"""Driver orchestration: tile -> chunks -> prefetch -> device -> drain.

Replaces ccdc/core.py.  The reference's shape is preserved — snap the point
to a tile, enumerate its chips, `partition_all(chunk_size, take(number,
chips))`, run each chunk with failure isolation, persist chip/pixel/segment
(core.py:78-124) — but execution is host-orchestrated TPU dispatch instead
of Spark jobs: chips are fetched by a host thread pool (INPUT_PARTITIONS
semantics), packed into device batches, run through the CCD kernel, and
drained to the store by an async writer so egress overlaps compute.

Failure handling is per-CHIP, not per-chunk: a chip that exhausts its
(jittered, budgeted) fetch retries is dead-lettered to quarantine.json and
its chunk completes without it; kernel/store errors still fail the chunk
as a backstop (core.py:115-124 semantics) but dead-letter its chips too.
Because store writes are keyed upserts, ``--resume`` (gated by
run_manifest.json, draining the quarantine first) repairs any gap
(SURVEY.md §5 durability model; docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import concurrent.futures as cf
import contextlib
import dataclasses
import itertools
import threading
import time
import traceback

import jax.numpy as jnp
import numpy as np

from firebird_tpu import faults as faultlib
from firebird_tpu import grid
from firebird_tpu import retry as retrylib
from firebird_tpu.ccd import format as ccdformat
from firebird_tpu.ccd import kernel
from firebird_tpu.config import Config
from firebird_tpu.driver import quarantine as qlib
from firebird_tpu.ingest import ChipmunkSource, FileSource, SyntheticSource, pack
from firebird_tpu.obs import Counters, jsonlog, logger
from firebird_tpu.obs import flightrec
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import profiling as obs_profiling
from firebird_tpu.obs import report as obs_report
from firebird_tpu.obs import server as obs_server
from firebird_tpu.obs import tracing
from firebird_tpu.obs import watchdog as obs_watchdog
from firebird_tpu.store import AsyncWriter, open_store
from firebird_tpu.utils import dates as dt
from firebird_tpu.utils.fn import partition_all, take

# bfloat16 is deliberately absent: ordinal days (~730000) have a bf16 ulp of
# 4096 days, which would corrupt segment dates; bf16 belongs inside matmul
# precision hints, not the date-carrying compute dtype.
_DTYPES = {"float32": jnp.float32, "float64": jnp.float64}


def _process_index() -> int:
    """JAX process index for run identity; 0 when no backend is up."""
    import jax

    try:
        return jax.process_index()
    except Exception:
        return 0


# Lockstep sequence for run-id broadcast keys: every process of an SPMD
# fleet runs the same program, so the per-process counters agree (the
# same idiom as parallel.mesh._kv_seq).
_run_id_seq = itertools.count()


def fleet_run_id() -> str:
    """One run id for the WHOLE fleet launch.

    Single-process: a fresh id.  Multi-process: process 0 mints it and
    broadcasts through the jax.distributed coordination-service KV store,
    so every host's JSON log lines, report shard, and /progress payload
    carry the SAME id — the cross-host log join is one grep, not an
    out-of-band host table."""
    rid = jsonlog.new_run_id()
    try:
        import jax

        if jax.process_count() <= 1:
            return rid
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            return rid
        seq = next(_run_id_seq)
        if jax.process_index() == 0:
            client.key_value_set(f"fb/run_id/{seq}", rid)
            return rid
        return client.blocking_key_value_get(f"fb/run_id/{seq}", 60_000)
    except Exception:
        return rid           # a broken broadcast degrades to per-host ids


def _mesh_ready() -> bool:
    """The /readyz mesh half: True when no distributed mesh is expected
    (no coordinator configured), or when jax.distributed is actually up.
    An operator who exported JAX_COORDINATOR_ADDRESS but whose bring-up
    failed keeps /readyz at 503 instead of lying."""
    import os

    if not os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return True
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def record_topology_metrics() -> None:
    """(Re-)record the fleet topology gauges on the CURRENT registry.

    init_distributed sets them at bring-up, but the drivers reset the
    registry per run — so every run re-records them here or /metrics and
    the fleet report would silently lose the topology."""
    import jax

    try:
        obs_metrics.gauge(
            "mesh_processes",
            help="jax.distributed process count").set(jax.process_count())
        obs_metrics.gauge(
            "mesh_global_devices",
            help="global device count").set(len(jax.devices()))
    except Exception:
        pass                   # no backend yet: nothing to record


def start_ops(cfg: Config, run_id: str, kind: str, *, chips_total: int,
              counters, run_block: dict, quarantine=None, breaker=None,
              fleet=None, alerts=None, streamops=None):
    """Bring up the run's live ops surface (shared by both drivers).

    Registers the run context for JSON logs, clears stale report shards
    from a previous run in a reused artifact directory, starts the stall
    watchdog when ``cfg.stall_sec`` asks for one, publishes a
    :class:`~firebird_tpu.obs.server.RunStatus` for the module-level
    progress hooks, and binds the HTTP endpoint ONLY when
    ``cfg.ops_port`` is set — the default run binds no port.  Returns
    (status, server, watchdog); tear down with :func:`stop_ops`.  If the
    port bind fails, everything already started is torn down before the
    error propagates — a half-up ops surface must not outlive the raise.
    """
    import os

    jsonlog.set_run_context(run_id=run_id, process_index=_process_index())
    obs_report.clear_stale_artifacts(cfg)
    record_topology_metrics()
    watchdog = None
    server = None
    try:
        # Crash flight recorder (FIREBIRD_FLIGHTREC ring size; 0 off):
        # armed for the run so an unhandled exception, watchdog stall,
        # or SIGTERM leaves postmortem.json next to the store.
        if cfg.flightrec > 0:
            flightrec.arm(flightrec.postmortem_path(cfg),
                          ring=cfg.flightrec, run_id=run_id,
                          fingerprint=qlib.config_fingerprint(cfg))
        # On-demand device profiler: POST /profile windows land next to
        # the store; FIREBIRD_PROFILE=<seconds> arms an automatic window
        # at the first dispatched batch.  Memory-backend runs have no
        # artifact dir and get no profiler (the endpoint answers 503).
        profiler = None
        art_dir = qlib._artifact_dir(cfg)
        if art_dir is not None:
            profiler = obs_profiling.set_active(obs_profiling.DeviceProfiler(
                os.path.join(art_dir, "device_profile")))
            if cfg.profile > 0:
                profiler.arm_auto(cfg.profile)
        if cfg.stall_sec > 0:
            watchdog = obs_watchdog.Watchdog(cfg.stall_sec).start()
        status = obs_server.set_status(obs_server.RunStatus(
            run_id, kind, chips_total=chips_total, counters=counters,
            watchdog=watchdog, run=run_block, mesh_up=_mesh_ready(),
            pipeline_depth=cfg.pipeline_depth, quarantine=quarantine,
            breaker=breaker, profiler=profiler, slo_spec=cfg.slo,
            fleet=fleet, alerts=alerts, streamops=streamops))
        if cfg.ops_port > 0:
            server = obs_server.start_ops_server(cfg.ops_port, status,
                                                 host=cfg.ops_host)
    except Exception:
        stop_ops(server, watchdog)
        raise
    return status, server, watchdog


def stop_ops(server, watchdog) -> None:
    """Tear down :func:`start_ops` state; never raises — ops teardown
    must not mask a run's real outcome.  Called from the drivers'
    ``finally``: when the run is unwinding on an exception, the flight
    recorder dumps its postmortem BEFORE disarming (the excepthook would
    otherwise fire after the recorder is gone)."""
    import sys

    if sys.exc_info()[0] is not None:
        flightrec.dump_if_armed("unhandled_exception", sys.exc_info()[1])
    try:
        if server is not None:
            server.close()
        if watchdog is not None:
            watchdog.stop()
        obs_profiling.close_active()
    except Exception as e:
        logger("change-detection").error("ops teardown failed: %s", e)
    finally:
        obs_profiling.set_active(None)
        flightrec.disarm()
        obs_server.clear_status()
        jsonlog.clear_run_context()


def make_source(cfg: Config, kind: str | None = None):
    """Source factory (cfg.source_backend): chipmunk | synthetic | file."""
    kind = kind or cfg.source_backend
    if kind == "chipmunk":
        return ChipmunkSource(cfg.ard_url,
                              band_parallelism=cfg.band_parallelism,
                              timeout=cfg.http_timeout)
    if kind == "synthetic":
        from firebird_tpu.ccd.sensor import SENSORS

        return SyntheticSource(seed=0, sensor=SENSORS[cfg.synth_sensor])
    if kind == "file":
        return FileSource(cfg.source_path)
    raise ValueError(f"unknown source backend: {kind!r}")


def make_aux_source(cfg: Config, kind: str | None = None):
    kind = kind or cfg.source_backend
    if kind == "chipmunk":
        return ChipmunkSource(cfg.aux_url,
                              band_parallelism=cfg.band_parallelism,
                              timeout=cfg.http_timeout)
    return make_source(cfg, kind)


def robustness_setup(cfg: Config, run_id: str, *, source=None, store=None):
    """The drivers' shared graceful-degradation bring-up (ONE code path
    for batch and stream): the (usually absent) fault plan wraps the
    failure seams, one retry budget + ingest circuit breaker are shared
    by every retry site, the async writer retries store writes, and the
    dead-letter quarantine carries poisoned chips across runs.  With
    FIREBIRD_FAULTS unset the wrap_* calls return their argument
    unchanged — nothing on the hot path.

    Returns (source, store, writer, policy, breaker, quarantine)."""
    plan = faultlib.FaultPlan.from_config(cfg)
    source = faultlib.wrap_source(source or make_source(cfg), plan)
    store = faultlib.wrap_store(
        store or open_store(cfg.store_backend, cfg.store_path,
                            cfg.keyspace()), plan)
    budget = retrylib.RetryBudget(cfg.retry_budget)
    breaker = retrylib.make_breaker(cfg)
    policy = retrylib.RetryPolicy.for_ingest(cfg, budget=budget,
                                             breaker=breaker)
    writer = faultlib.wrap_writer(
        AsyncWriter(store, workers=cfg.writer_threads,
                    retry=retrylib.RetryPolicy.for_store(cfg,
                                                         budget=budget)),
        plan)
    quarantine = qlib.Quarantine.load(qlib.quarantine_path(cfg),
                                      run_id=run_id)
    return source, store, writer, policy, breaker, quarantine


def _pad_target(n_chips: int, pad_to: int | None, use_mesh: bool,
                n_dev: int) -> int:
    """THE batch pad-target rule, shared by stage_batch, detect_batch,
    and predict_batch_shape (the warm-compile shape prediction would
    silently drift from real dispatch padding if this were duplicated):
    at least ``pad_to`` chips, rounded up to a device-count multiple when
    sharded."""
    target = max(pad_to or 0, n_chips)
    if use_mesh:
        target = -n_dev * (-target // n_dev)
    return target


def _pad_batch(packed, target: int):
    """Pad a PackedChips batch to `target` chips (repeating the last chip);
    returns (padded, real_count)."""
    from firebird_tpu.ingest.packer import PackedChips

    C = packed.n_chips
    if C >= target:
        return packed, C
    pad = target - C
    rep = lambda a: np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
    return PackedChips(cids=rep(packed.cids), dates=rep(packed.dates),
                       spectra=rep(packed.spectra), qas=rep(packed.qas),
                       n_obs=rep(packed.n_obs), sensor=packed.sensor), C


def host_shard(cids: list) -> list:
    """This host's slice of a chip-id list under multi-host execution.

    CCDC is embarrassingly parallel over chips, so multi-host scaling is
    pure data decomposition: after parallel.init_distributed each process
    takes a strided slice and runs the normal per-host loop against its
    local devices; the keyed store upserts make the union of all hosts'
    writes identical to a single-host run (the reference instead scaled by
    adding Spark executors, README.rst:11 "2000 cores").  Single-process
    runs return the list unchanged.
    """
    import jax

    n = jax.process_count()
    if n <= 1:
        return cids
    i = jax.process_index()
    logger("change-detection").info(
        "multi-host: process %d/%d takes %d of %d chips",
        i, n, len(cids[i::n]), len(cids))
    return cids[i::n]


def estimate_obs(acquired: str, cfg: Config) -> int:
    """Conservative observation-count estimate for an acquired range:
    two-satellite 8-day effective cadence over the span, rounded/capped
    by the packer's own capacity rule (bucket_capacity — max_obs=0 means
    uncapped there, so the estimate must not treat it as a cap)."""
    from firebird_tpu.ingest.packer import bucket_capacity

    lo, hi = dt.acquired_range(acquired)
    t = (max(hi - lo, 0) // 8) + 8
    return bucket_capacity(t, max(cfg.obs_bucket, 1), cfg.max_obs)


def auto_chips_per_batch(cfg: Config, acquired: str, device=None) -> int:
    """Size the device batch from the accelerator's memory budget.

    VERDICT r1 weak #5: chips_per_batch was a static config while the
    working set scales with T.  With cfg.chips_per_batch <= 0 ("auto"),
    the driver fits  budget = 60% of the device's bytes_limit  against
    kernel.working_set_bytes(T_est) per chip.  Devices that report no
    memory stats (CPU) fall back to the static default.
    """
    import jax

    dev = device if device is not None else jax.local_devices()[0]
    try:
        stats = dev.memory_stats() or {}
    except Exception:
        stats = {}
    limit = stats.get("bytes_limit")
    fallback = Config.chips_per_batch
    if not limit:
        return fallback
    t_est = estimate_obs(acquired, cfg)
    dtype_bytes = 4 if cfg.dtype == "float32" else 8
    per = kernel.working_set_bytes(t_est, dtype_bytes=dtype_bytes)
    # Pipeline-depth residency: each in-flight batch beyond the one
    # computing pins its full-capacity result buffers until its drain
    # (the egress diet shrinks the wire, NOT this residency), so the
    # deeper default depth must shrink the batch, not blow HBM.
    per += (max(cfg.pipeline_depth, 1) - 1) * kernel.result_bytes(
        t_est, dtype_bytes=dtype_bytes)
    n = max(int(limit * 0.6 / per), 1)
    logger("change-detection").info(
        "auto chips_per_batch: T~%d, %.2f GB/chip (incl. depth-%d "
        "in-flight results) against %.1f GB device limit -> %d "
        "chips/batch", t_est, per / 1e9, cfg.pipeline_depth, limit / 1e9,
        n)
    return n


def resolve_batching(cfg: Config, acquired: str) -> Config:
    """cfg with chips_per_batch resolved (<= 0 means auto-size)."""
    if cfg.chips_per_batch > 0:
        return cfg
    return dataclasses.replace(
        cfg, chips_per_batch=auto_chips_per_batch(cfg, acquired))


# ---------------------------------------------------------------------------
# Compile-warm startup: persistent cache + background AOT of the batch shape
# ---------------------------------------------------------------------------

_cache_listener_installed = False
_warm_lock = threading.Lock()
_warm_thread: threading.Thread | None = None  # guarded-by: _warm_lock


def _install_cache_counters() -> None:
    """Count persistent compile-cache hits/misses into the run registry.

    jax records monitoring events on every persistent-cache lookup
    (``/jax/compilation_cache/cache_hits``) and write-back (``.../
    cache_misses``); the listener resolves the CURRENT metrics registry at
    event time, so per-run reports see their own counts even though the
    listener itself is registered once per process.  Attribution is
    best-effort across runs: the events carry no run identity, so a warm
    compile abandoned by a short run (the 5s join in the driver's
    finally) lands its hit/miss in whichever run is live when it finishes
    — bounded by warm_start's one-in-flight guard, and never wrong about
    the process-wide totals."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    try:
        from jax._src import monitoring

        def _on_event(event, **kw):
            if event == "/jax/compilation_cache/cache_hits":
                obs_metrics.counter(
                    "compile_cache_hits",
                    help="persistent XLA compile-cache hits").inc()
            elif event == "/jax/compilation_cache/cache_misses":
                obs_metrics.counter(
                    "compile_cache_misses",
                    help="persistent XLA compile-cache misses").inc()

        monitoring.register_event_listener(_on_event)
        # Idempotent once-latch; a duplicate listener from a racing
        # second run is harmless (both count the same events) and the
        # driver installs from one thread in practice.
        _cache_listener_installed = True  # firebird-lint: disable=ownership-global-mutation
    except Exception:
        pass         # older jax without the events: counters stay absent


def setup_compile_cache(cfg: Config) -> str | None:
    """Enable the persistent XLA compilation cache (FIREBIRD_COMPILE_CACHE
    / --compile-cache).  Compiled programs serialize to the directory, so
    the SECOND run of any shape deserializes instead of compiling — and
    the background :func:`warm_start` AOT compile of run N becomes the
    cache hit of run N+1's first dispatch.  Returns the cache path, or
    None when the config leaves the cache off."""
    if not cfg.compile_cache:
        return None
    import os

    import jax

    path = os.path.abspath(cfg.compile_cache)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache every compile: the sub-second CPU-smoke kernels must warm run
    # 2 as surely as a ten-minute TPU compile does.
    with contextlib.suppress(Exception):
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # Un-latch jax's once-per-process cache probe so enabling the cache
    # mid-process (after an unrelated first compile) still takes effect.
    with contextlib.suppress(Exception):
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    _install_cache_counters()
    logger("change-detection").info("persistent compile cache at %s", path)
    return path


def wire_avatar_dtypes() -> tuple:
    """The avatar dtype tuple warm_start AOT-compiles the wire signature
    with — ONE definition shared with the test pinning it against
    ``kernel.wire_args``' staged dtypes, because any drift makes every
    warm compile a silent cache miss (the AOT writes one key, the real
    dispatch looks up another)."""
    return (jnp.int32, jnp.int32, jnp.int16,
            jnp.dtype(kernel.wire_qa_dtype()))


def predict_batch_shape(cfg: Config, acquired: str) -> tuple[int, int, int]:
    """The steady-state padded dispatch shape a run is expected to
    compile: (C, T, wcap).  C mirrors detect_batch's padding (rounded to
    a device-count multiple when sharded); T is estimate_obs's bucketed
    estimate; wcap applies window_cap's rule to a dense 8-day acquisition
    grid.  A wrong guess wastes only the background compile — the
    persistent cache still warms the actual shape from run 1's own
    compile on every later run."""
    import jax

    from firebird_tpu.ccd import params

    n_dev = jax.local_device_count()
    use_mesh = cfg.device_sharding != "off" and n_dev > 1
    C = _pad_target(max(cfg.chips_per_batch, 1), None, use_mesh, n_dev)
    T = estimate_obs(acquired, cfg)
    lo, hi = dt.acquired_range(acquired)
    d = np.arange(lo, hi + 1, 8, dtype=np.int64)[:T]
    cap = params.MEOW_SIZE
    if d.size:
        hi_i = np.searchsorted(d, d + params.INIT_DAYS, side="right")
        cap = max(cap, int((hi_i - np.arange(d.size)).max()) + 1)
    wcap = min(-8 * (-cap // 8), T)
    return C, T, wcap


def warm_start(cfg: Config, acquired: str, sensor=None, dtype=None,
               donate: bool | None = None) -> threading.Thread | None:
    """AOT-lower/compile the predicted steady-state batch shape on a
    background thread, so the (multi-second) first XLA compile overlaps
    batch 0's HTTP fetch instead of serializing after it.

    Only runs when the persistent compilation cache is on: jit keeps its
    own in-memory table, so the AOT executable can only reach the first
    real dispatch *through* the cache (AOT writes the entry, the dispatch
    deserializes it).  A failed or mispredicted warm compile costs
    nothing but the background work.  Returns the started thread (join it
    to observe ``warm_compile_seconds``), or None when the cache is off
    or a previous warm compile is still running (no duplicate compiles).
    """
    if not cfg.compile_cache:
        return None
    import jax

    from firebird_tpu.ccd.sensor import LANDSAT_ARD

    sensor = sensor or LANDSAT_ARD
    dtype = dtype if dtype is not None else _DTYPES[cfg.dtype]
    # Match the program the steady-state loop will dispatch (detect_chunk
    # donates on accelerators only) — a warm compile of the wrong donation
    # variant would miss the cache at dispatch time.
    if donate is None:
        donate = _should_donate()
    kernel.ensure_x64(dtype)
    C, T, wcap = predict_batch_shape(cfg, acquired)
    B, P = sensor.n_bands, sensor.pixels
    # The all-integer wire signature (kernel.wire_args order): day
    # ordinals, per-chip counts, int16 spectra, uint8/uint16 QA.
    shapes = ((C, T), (C,), (C, B, P, T), (C, P, T))
    n_dev = jax.local_device_count()
    use_mesh = cfg.device_sharding != "off" and n_dev > 1
    # Metrics bind to THIS run's registry at start: a long warm compile
    # abandoned by a short run (5s join in the driver's finally) must not
    # record into whichever registry a LATER run has installed.
    reg = obs_metrics.get_registry()

    def _warm():
        try:
            with tracing.span("warm_compile", shape=(C, T, wcap)), \
                    obs_metrics.timer() as tm:
                if use_mesh:
                    from firebird_tpu.parallel import make_mesh
                    from firebird_tpu.parallel.mesh import \
                        aot_compile_sharded

                    aot_compile_sharded(
                        make_mesh(devices=jax.local_devices()), dtype,
                        wcap, sensor, shapes, donate=donate,
                        compact=cfg.compact)
                else:
                    avatars = tuple(
                        jax.ShapeDtypeStruct(s, d) for s, d in zip(
                            shapes, wire_avatar_dtypes()))
                    kernel.aot_compile(avatars, dtype=dtype, wcap=wcap,
                                       sensor=sensor, donate=donate,
                                       compact=cfg.compact)
            reg.histogram("warm_compile_seconds").observe(tm.elapsed)
            reg.counter("warm_compiles",
                        help="background AOT compiles completed").inc()
        except Exception as e:
            # Best-effort: the run proceeds cold; first dispatch compiles.
            logger("change-detection").warning(
                "warm-start compile failed (run proceeds cold): %s", e)

    global _warm_thread
    with _warm_lock:
        if _warm_thread is not None and _warm_thread.is_alive():
            logger("change-detection").info(
                "warm-start: previous warm compile still in flight; "
                "not starting another")
            return None
        _warm_thread = threading.Thread(
            target=_warm, name="firebird-warm-compile", daemon=True)
        _warm_thread.start()
        return _warm_thread


def _with_retries(cfg: Config, log, what: str, fn, policy=None):
    """Run fn() under the driver's transient-failure policy: the reference
    delegated these to Spark's task retry; here a blip on one fetch must
    not fail the whole chunk.  The real loop lives in
    :class:`firebird_tpu.retry.RetryPolicy` (decorrelated-jitter backoff,
    injectable sleep, optional shared budget + circuit breaker); callers
    without a run-scoped ``policy`` get a one-off built from
    ``cfg.fetch_retries``.  Raises the last error when retries run out."""
    if policy is None:
        policy = retrylib.RetryPolicy(cfg.fetch_retries)
    return policy.run(log, what, fn)


def fetch(x, y, outdir: str, acquired: str | None = None,
          number: int = 2500, aux: bool = False,
          cfg: Config | None = None, source=None,
          aux_source=None) -> tuple[int, int]:
    """Mirror a tile's chips from the configured source into a FileSource
    directory (.npz per chip) for offline reruns and fixture building.

    The write side of ingest's FileSource: fetch once over the network,
    then run any number of campaigns with FIREBIRD_SOURCE=file against the
    local archive.  Uses the driver's fetch retries and INPUT_PARTITIONS
    parallelism.  Chips that exhaust their retries are dead-lettered to
    ``<outdir>/quarantine.json`` (error class + attempt history) so a
    partial archive mirror is resumable like a partial store: rerun the
    same fetch and only the manifest's chips are missing work.  Returns
    (chips written, chips attempted).
    """
    import os

    cfg = cfg or Config.from_env()
    acquired = acquired or dt.default_acquired()
    log = logger("timeseries")
    plan = faultlib.FaultPlan.from_config(cfg)
    source = faultlib.wrap_source(source or make_source(cfg), plan)
    aux_source = aux_source or (make_aux_source(cfg) if aux else None)
    if aux_source is not None:
        aux_source = faultlib.wrap_source(aux_source, plan)
    os.makedirs(outdir, exist_ok=True)
    sink = FileSource(outdir)
    policy = retrylib.RetryPolicy.for_ingest(
        cfg, budget=retrylib.RetryBudget(cfg.retry_budget),
        breaker=retrylib.make_breaker(cfg))
    quarantine = qlib.Quarantine.load(
        os.path.join(outdir, "quarantine.json"))

    tile = grid.tile(x=x, y=y)
    cids = list(take(number, grid.chips(tile)))
    log.info("fetch: tile h=%s v=%s -> %s (%d chips, acquired %s, aux=%s)",
             tile["h"], tile["v"], outdir, len(cids), acquired, aux)

    def one(xy):
        # Chip and aux retry independently: a written chip is never
        # re-fetched because the aux side flaked.
        try:
            _with_retries(cfg, log, f"chip ({xy[0]},{xy[1]}) fetch",
                          lambda: sink.save_chip(
                              source.chip(xy[0], xy[1], acquired)),
                          policy=policy)
        except Exception as e:
            log.error("chip (%s,%s) failed: %s", xy[0], xy[1], e)
            quarantine.record(xy, e, attempts=cfg.fetch_retries + 1,
                              stage="fetch")
            return 0
        quarantine.discard(xy)       # a redeemed dead letter drains
        if aux_source is not None:
            try:
                _with_retries(cfg, log, f"aux ({xy[0]},{xy[1]}) fetch",
                              lambda: sink.save_aux(
                                  xy[0], xy[1],
                                  aux_source.aux(xy[0], xy[1], acquired)),
                              policy=policy)
            except Exception as e:
                log.error("aux (%s,%s) failed: %s — archive holds the "
                          "chip but no aux layers", xy[0], xy[1], e)
        return 1

    with cf.ThreadPoolExecutor(
            max_workers=max(cfg.input_parallelism, 1)) as ex:
        n = sum(ex.map(one, cids))
    failed = len(cids) - n
    log.info("fetch complete: %d/%d chips written, %d failed%s",
             n, len(cids), failed,
             f" (dead letters in {quarantine.path})" if failed else "")
    return n, len(cids)


def _should_donate() -> bool:
    """Donate staged inputs on accelerators only: on the CPU backend the
    HBM-footprint argument is moot, and the donated jit twin would just
    double-compile every shape the (CPU) test suite already caches."""
    import jax

    return jax.default_backend() in ("tpu", "gpu")


@dataclasses.dataclass
class StagedBatch:
    """A device-staged input batch (the prefetch thread's product): the
    kernel argument tuple already resident under the run's sharding, plus
    the padded host-side PackedChips the drain/recompute path still
    needs.  ``wcap`` is the (cross-host-agreed, sharded case) window cap
    the staged args were prepared for."""

    packed: object             # padded PackedChips (host arrays)
    args: tuple                # device arrays, wire dtypes
    n_real: int
    mesh: object | None        # the local data mesh when sharded
    wcap: int


def stage_batch(packed, dtype, sharding: str = "auto",
                pad_to: int | None = None) -> StagedBatch:
    """Pad and device_put one batch under the run's sharding — the H2D
    half of :func:`detect_batch`, run on the prefetch thread so batch
    i+1's transfer overlaps batch i's compute and the main thread only
    dispatches.  Blocks until the transfer lands (the *prefetch* thread
    eats the wait), records ``pipeline_stage_seconds``, the
    ``wire_h2d_bytes`` counter, and the h2d ``transfer`` span leg."""
    import jax

    from firebird_tpu.ccd import kernel as k

    n_dev = jax.local_device_count()
    use_mesh = sharding != "off" and n_dev > 1
    padded, real = _pad_batch(
        packed, _pad_target(packed.n_chips, pad_to, use_mesh, n_dev))
    with tracing.span("stage", chips=real), obs_metrics.timer() as tm:
        # The `transfer` span leg (leg=h2d; its d2h twin wraps the drain's
        # bulk fetch) makes transfer-vs-compute overlap directly readable
        # off the host trace: a healthy pipeline shows h2d transfer spans
        # riding the prefetch thread UNDER the main thread's dispatch gap.
        with tracing.span("transfer", leg="h2d", chips=real):
            if use_mesh:
                from firebird_tpu.parallel import make_mesh
                from firebird_tpu.parallel.mesh import stage_sharded

                mesh = make_mesh(devices=jax.local_devices())
                args, wcap = stage_sharded(padded, mesh, dtype)
            else:
                mesh = None
                args = k.stage_packed(padded, dtype)
                wcap = k.window_cap(padded)
    obs_metrics.histogram("pipeline_stage_seconds").observe(tm.elapsed)
    obs_metrics.counter(
        "wire_h2d_bytes",
        help="bytes staged host->device (all-integer packed inputs)").inc(
        int(sum(getattr(a, "nbytes", 0) for a in args)))
    return StagedBatch(packed=padded, args=args, n_real=real, mesh=mesh,
                       wcap=wcap)


def detect_batch(packed, dtype, sharding: str = "auto",
                 pad_to: int | None = None, check_capacity: bool = False,
                 max_segments: int | None = None,
                 staged: StagedBatch | None = None, donate: bool = False,
                 compact: bool | None = None):
    """Run the CCD kernel over a packed batch on every local device.

    Single device (or sharding='off'): plain jit dispatch.  Multiple local
    devices (the normal TPU-VM topology): the chip axis is sharded over a
    data mesh of this process's local devices — in multi-host runs each
    process does the same over its own chips (driver host_shard), so the
    two data-parallel levels compose: hosts split the tile, local devices
    split each host's batches.  A single *globally* sharded batch is the
    library path (parallel.mesh.detect_sharded), not the driver loop.

    Batches are padded (repeating the last chip) up to `pad_to` — and to a
    multiple of the device count when sharded — so a chunk's ragged final
    batch reuses the same compiled kernel shape as its full batches; padded
    results are dropped by the caller via the returned real count.

    With ``staged`` (a :class:`StagedBatch` from :func:`stage_batch`) the
    arrays are already device-resident — this call only dispatches.
    ``donate=True`` frees the staged wire inputs at dispatch (honored only
    with ``check_capacity=False``; a donated recompute re-stages from
    ``staged.packed``'s host arrays).
    """
    import jax

    from firebird_tpu.ccd import kernel as k

    # The default check_capacity=False keeps the dispatch asynchronous
    # (no device sync on this thread); the drain thread — which fetches
    # results anyway — detects segment-capacity overflow and re-runs the
    # batch through this same function with the check on (drain_batch).
    kw = dict(check_capacity=check_capacity, compact=compact)
    if max_segments is not None:
        kw["max_segments"] = max_segments
    if staged is not None:
        if staged.mesh is None:
            return k.detect_packed(staged.packed, dtype=dtype,
                                   staged=staged.args, donate=donate,
                                   **kw), staged.n_real
        from firebird_tpu.parallel.mesh import detect_sharded

        return detect_sharded(staged.packed, staged.mesh, dtype=dtype,
                              staged=(staged.args, staged.wcap),
                              donate=donate, **kw), staged.n_real

    n_dev = jax.local_device_count()
    use_mesh = sharding != "off" and n_dev > 1
    padded, real = _pad_batch(
        packed, _pad_target(packed.n_chips, pad_to, use_mesh, n_dev))
    if not use_mesh:
        return k.detect_packed(padded, dtype=dtype, **kw), real
    from firebird_tpu.parallel import make_mesh
    from firebird_tpu.parallel.mesh import detect_sharded

    mesh = make_mesh(devices=jax.local_devices())
    return detect_sharded(padded, mesh, dtype=dtype, **kw), real


def fetch_results(seg, worst: int | None = None):
    """The ONE bulk device->host fetch per batch: ``jax.device_get`` of
    the whole batched result, collapsing the old per-chip, per-field
    ``chip_slice(to_host=True)`` pattern (~C x fields D2H round trips per
    batch) into a single transfer sweep.

    With ``FIREBIRD_WIRE_EGRESS`` (default on) and a float32 result, the
    ChipSegments is first packed ON DEVICE into int-coded tables sliced
    to the batch's observed segment depth (``kernel.pack_egress``) and
    decoded back host-side (``format.decode_egress``) — identical host
    arrays, a fraction of the bytes on the wire (docs/ROOFLINE.md "Wire
    budget").  ``worst`` is the caller's capacity probe (max segments
    any pixel closed) when it already paid that sync; None probes here.
    Records ``pipeline_d2h_seconds``, the ``wire_d2h_bytes`` counter,
    and the d2h ``transfer`` span leg; returns a host-array
    ChipSegments."""
    import jax

    payload, decode_T = seg, None
    if kernel.wire_egress_enabled() and seg.seg_meta.dtype == jnp.float32:
        if worst is None:
            worst = int(np.asarray(seg.n_segments).max())
        s_eff = kernel.egress_bucket(worst, seg.seg_meta.shape[-2])
        payload = kernel.pack_egress(seg, s_eff)
        decode_T = seg.mask.shape[-1]
    nbytes = int(sum(getattr(v, "nbytes", 0)
                     for v in jax.tree_util.tree_leaves(payload)))
    with tracing.span("d2h", bytes=nbytes), obs_metrics.timer() as tm:
        with tracing.span("transfer", leg="d2h", bytes=nbytes):
            host = jax.device_get(payload)
    obs_metrics.histogram("pipeline_d2h_seconds").observe(tm.elapsed)
    obs_metrics.counter(
        "wire_d2h_bytes",
        help="bytes fetched device->host (batch results, int-coded and "
             "depth-sliced when the egress diet is on)").inc(nbytes)
    if decode_T is not None:
        host = ccdformat.decode_egress(host, decode_T)
    return host


def write_batch_frames(packed, host_seg, n_real, *, writer, counters=None):
    """Format + queue one drained batch's frames — the shared egress tail
    of both drivers: ``format.batch_frames`` builds the three tables
    across the chip axis in one numpy pass, split back into the existing
    keyed per-chip writes, so the segment frame still lands last per chip
    (the resume invariant)."""
    P = host_seg.n_segments.shape[1]
    for c, (cid, frames) in enumerate(
            ccdformat.batch_frames(packed, host_seg, n_real)):
        for table in ("chip", "pixel", "segment"):
            # keyed: one chip's frames drain in order, so the segment
            # frame lands last (the resume invariant)
            writer.write(table, frames[table], key=cid)
        if counters is not None:
            counters.add("chips")
            counters.add("pixels", P)
            counters.add("segments", int(host_seg.n_segments[c].sum()))


def drain_batch(seg, packed, n_real, *, writer, counters, dtype=None,
                sharding: str = "auto", pad_to: int | None = None,
                compact: bool | None = None, ctx=None):
    """Fetch one batch's results to the host, format, and queue writes
    (the egress half of ref core.detect, core.py:69-72) — results cross
    D2H as one bulk :func:`fetch_results` transfer and format through the
    vectorized :func:`write_batch_frames` path.

    ``ctx`` is the batch's :class:`~firebird_tpu.obs.tracing.TraceContext`
    — this function runs on the drain executor, so the context must
    cross the thread hop explicitly; everything below (spans, the queued
    writes, the drain histogram's exemplar, log lines) parents to it.

    Also the capacity backstop for the driver's asynchronous dispatch
    (detect_batch defaults check_capacity=False): if any pixel closed
    more segments than the result buffers hold, the batch is recomputed
    here through the same (sharded-aware) dispatch with the capacity
    check on — rare enough that the synchronous re-run does not matter."""
    cap = seg.seg_meta.shape[-2]                   # [.., P, S, 6] -> S
    with tracing.activate(ctx):
        with tracing.span("drain", chips=n_real), obs_metrics.timer() as tm:
            # Capacity probe BEFORE the bulk fetch: n_segments alone is a
            # few hundred KB, so an overflowed batch never pays a
            # full-result transfer whose buffers are about to be discarded
            # (and the d2h telemetry counts only the one real bulk fetch).
            worst = int(np.asarray(seg.n_segments).max())
            if worst > cap:
                logger("pyccd").info(
                    "segment capacity %d overflowed on drain (deepest pixel "
                    "closed %d); recomputing the batch", cap, worst)
                obs_metrics.counter("capacity_redispatches").inc()
                seg, _ = detect_batch(packed, dtype or seg.seg_meta.dtype,
                                      sharding, pad_to=pad_to,
                                      check_capacity=True, compact=compact,
                                      max_segments=min(
                                          2 * cap,
                                          kernel.capacity_bound(packed)))
            host = fetch_results(seg, worst=worst)
            # Occupancy telemetry: the event loop's per-round active/paid
            # lane capture feeds kernel_round_active_fraction and the
            # compaction counters (results are on the host anyway).
            kernel.record_occupancy(host)
            write_batch_frames(packed, host, n_real, writer=writer,
                               counters=counters)
        obs_metrics.histogram("pipeline_drain_seconds").observe(tm.elapsed)
        # In-context completion line: with FIREBIRD_LOG_FORMAT=json this
        # carries the batch id, joining the drain to its spans/exemplars.
        logger("change-detection").debug(
            "batch drained: %d chips in %.3fs", n_real, tm.elapsed)
    # Forward-progress beat: a drained batch is the watchdog's liveness
    # unit and /progress's batches_done tick (no-op when no run registered).
    obs_server.batch_done(n_real)


def detect_chunk(cids, *, source, writer, acquired, cfg, counters, log,
                 policy=None, quarantine=None):
    """Run change detection for one chunk of chip ids (ref core.detect,
    core.py:53-75): ingest -> pack -> stage -> kernel -> chip/pixel/segment
    writes.

    Zero-stall pipeline: the prefetch thread fetches, packs, AND stages
    (H2D under the run's sharding) batch i+1 while batch i is on the
    device — the main thread only dispatches — and a drain thread
    bulk-fetches/formats batch i-1's results while batch i computes.
    Staged wire inputs are donated to the dispatch (freed on device once
    consumed), which is what lets the in-flight bound be a configurable
    ``cfg.pipeline_depth`` instead of a hard 2 without pinning every
    batch's inputs alongside its results.

    Per-chip failure isolation: a chip that exhausts its fetch retries is
    dead-lettered to ``quarantine`` (quarantine.json) and DROPPED from its
    batch — the remaining chips pack, dispatch, and land normally (the old
    behavior lost the whole chunk, driver/core.py pre-PR4).  ``policy`` is
    the run's shared :class:`~firebird_tpu.retry.RetryPolicy` (jitter,
    budget, ingest breaker).  Returns the chip ids actually processed."""
    log.info("finding ccd segments for %d chips", len(cids))
    dtype = _DTYPES[cfg.dtype]
    batches = list(partition_all(cfg.chips_per_batch, cids))
    # Pad a ragged final batch onto the full-batch compiled shape only when
    # a full batch exists to share it with; a single small batch would pay
    # the padding compute for no compile reuse.
    pad_to = cfg.chips_per_batch if len(batches) > 1 else None
    depth = max(cfg.pipeline_depth, 1)

    # Separate single-worker executors: the prefetch slot must not steal
    # the chip-level workers (INPUT_PARTITIONS semantics) or a 1-worker
    # pool would deadlock on the nested map; the drain slot keeps one
    # batch's egress overlapping the next batch's compute.
    with cf.ThreadPoolExecutor(
            max_workers=max(cfg.input_parallelism, 1)) as chips_ex, \
            cf.ThreadPoolExecutor(max_workers=1) as prefetch_ex, \
            cf.ThreadPoolExecutor(max_workers=1) as drain_ex:

        def fetch_one(xy, ctx=None):
            # The chip pool's threads are outside the prefetch thread's
            # context scope — the batch context crosses this hop
            # explicitly too, so per-chip latency exemplars and failure
            # log lines carry the batch id.
            with tracing.activate(ctx):
                try:
                    with obs_metrics.timer() as tm:
                        chip = _with_retries(
                            cfg, log, f"chip ({xy[0]},{xy[1]}) fetch",
                            lambda: source.chip(xy[0], xy[1], acquired),
                            policy=policy)
                except Exception as e:
                    # Per-chip isolation: dead-letter the poisoned chip
                    # and let the rest of the batch proceed — `--resume`
                    # drains the quarantine once the cause clears.
                    log.error(
                        "chip (%s,%s) failed after retries (%s: %s); "
                        "quarantined — its chunk continues without it",
                        xy[0], xy[1], type(e).__name__, e)
                    if quarantine is not None:
                        quarantine.record(xy, e,
                                          attempts=cfg.fetch_retries + 1)
                    return None
                obs_metrics.histogram(
                    "ingest_chip_seconds").observe(tm.elapsed)
                return chip

        # ONE TraceContext per batch, minted here and carried EXPLICITLY
        # across the three thread hops (prefetch stage -> main-thread
        # dispatch -> drain executor -> writer queue): every span, JSON
        # log line, and histogram exemplar those threads record parents
        # to the same <run_id>/b<seq> id.
        run_id = jsonlog.get_run_context().get("run_id")
        ctxs = [tracing.TraceContext(tracing.new_batch_id(run_id),
                                     run_id=run_id) for _ in batches]

        def prepare_batch(bids, ctx):
            """fetch -> pack -> device staging, all on the prefetch
            thread: by the time the main thread picks the batch up, its
            arrays are already resident under the run's sharding.
            Returns (surviving chip ids, StagedBatch), or None when every
            chip of the batch was quarantined."""
            with tracing.activate(ctx):
                with tracing.span("fetch", chips=len(bids)), \
                        obs_metrics.timer() as tm:
                    chips = list(chips_ex.map(
                        lambda xy: fetch_one(xy, ctx), bids))
                obs_metrics.histogram(
                    "pipeline_fetch_seconds").observe(tm.elapsed)
                keep = [(cid, ch) for cid, ch in zip(bids, chips)
                        if ch is not None]
                if not keep:
                    return None
                with tracing.span("pack", chips=len(keep)), \
                        obs_metrics.timer() as tm:
                    packed = pack([ch for _, ch in keep],
                                  bucket=cfg.obs_bucket,
                                  max_obs=cfg.max_obs)
                obs_metrics.histogram(
                    "pipeline_pack_seconds").observe(tm.elapsed)
                return [cid for cid, _ in keep], \
                    stage_batch(packed, dtype, cfg.device_sharding,
                                pad_to=pad_to)

        nxt = prefetch_ex.submit(prepare_batch, batches[0], ctxs[0]) \
            if batches else None
        drains: list[cf.Future] = []
        processed: list = []
        for i in range(len(batches)):
            # Fence-loss fast abort (fleet jobs): a NonRetryable error
            # pending in the writer means every further write will
            # reject — stop paying for batches whose output cannot land
            # instead of discovering it at the final flush.
            err = getattr(writer, "peek_error", lambda: None)()
            if isinstance(err, retrylib.NonRetryable):
                raise err
            obs_server.set_stage("fetch")
            prep = nxt.result()
            nxt = (prefetch_ex.submit(prepare_batch, batches[i + 1],
                                      ctxs[i + 1])
                   if i + 1 < len(batches) else None)
            if prep is None:
                continue                 # whole batch quarantined
            kept, staged = prep
            # The dispatch span measures enqueue time, not device compute
            # (check_capacity=False keeps it async); compute shows up as
            # the gap before the matching drain span closes.
            obs_server.set_stage("dispatch")
            with tracing.activate(ctxs[i]):
                with tracing.span("dispatch", chips=staged.n_real), \
                        obs_metrics.timer() as tm:
                    seg, n_real = detect_batch(staged.packed, dtype,
                                               cfg.device_sharding,
                                               pad_to=pad_to, staged=staged,
                                               donate=_should_donate(),
                                               compact=cfg.compact)
                obs_metrics.histogram(
                    "pipeline_dispatch_seconds").observe(tm.elapsed)
            # /readyz flips here: mesh up + first batch dispatched means
            # compile/bring-up are behind us and the run is steady-state.
            obs_server.batch_dispatched()
            drains.append(drain_ex.submit(
                drain_batch, seg, staged.packed, n_real, writer=writer,
                counters=counters, dtype=dtype,
                sharding=cfg.device_sharding, pad_to=pad_to,
                compact=cfg.compact, ctx=ctxs[i]))
            processed.extend(kept)
            # Bound in-flight batches to cfg.pipeline_depth (the one
            # computing + depth-1 draining): input donation frees each
            # batch's staged wire buffers at dispatch, so depth only pins
            # result buffers — but unbounded depth would still exhaust
            # HBM, hence the config.
            while len(drains) > depth - 1:
                drains.pop(0).result()
        for f in drains:
            f.result()
    return processed


def run_chunk(chunk, *, source, writer, acquired, cfg, counters, log,
              policy=None, quarantine=None, reraise=False):
    """One chunk end-to-end — detect, flush, redeem dead letters — with
    the chunk-level failure backstop.  THE unit of fleet work: the batch
    driver's per-chunk loop body and a fleet ``detect`` job
    (fleet/worker.py) are this same function, so quarantine semantics
    cannot drift between single-process and fleet execution.

    ``reraise=False`` (the driver loop) swallows the chunk failure after
    dead-lettering its chips (core.py:115-124 semantics — later chunks
    continue); ``reraise=True`` (a fleet job) re-raises so the queue's
    attempt accounting sees the failure.  A ``NonRetryable`` error
    (fencing rejection) always propagates WITHOUT dead-lettering: the
    job's chips are a successor's responsibility, not owed work.
    Returns the chip ids processed ([] on a swallowed failure)."""
    try:
        processed = detect_chunk(
            chunk, source=source, writer=writer, acquired=acquired,
            cfg=cfg, counters=counters, log=log, policy=policy,
            quarantine=quarantine)
        obs_server.set_stage("flush")
        writer.flush()  # a chunk counts once its rows landed
        if quarantine is not None:
            quarantine.discard_many(processed)  # redeemed letters
        return processed
    except retrylib.NonRetryable:
        raise
    except Exception as e:
        # Chunk-level failure isolation (core.py:115-124) is the
        # BACKSTOP behind per-chip quarantine (ingest failures never
        # reach here anymore): a kernel or store error still fails the
        # chunk, but its chips are dead-lettered so `--resume` (or a
        # re-delivered fleet job) knows exactly what is owed instead of
        # rediscovering it by store diff.
        obs_metrics.counter("chunk_failures").inc()
        log.error("chunk failed (%d chips): %s", len(chunk), e)
        if quarantine is not None:
            held = quarantine.chip_ids()
            quarantine.record_many(
                [c for c in chunk
                 if tuple(int(v) for v in c) not in held],
                e, attempts=1, stage="chunk")
        if reraise:
            raise
        traceback.print_exc()
        return []


def changedetection(x, y, acquired: str | None = None, number: int = 2500,
                    chunk_size: int = 2500, cfg: Config | None = None,
                    source=None, store=None, resume: bool = False):
    """Run change detection for a tile and save results (ref
    core.changedetection, core.py:78-124).

    Args mirror the reference CLI: tile point (x, y), ISO8601 acquired
    range, number of chips (testing), chunk size (failure-isolation
    granularity).  ``resume=True`` skips chips whose segments are already
    stored (the segment table is written last per chip, so presence
    implies completeness) — the explicit restart the reference only got
    implicitly from rerunning idempotent upserts over a whole tile.  The
    run manifest (run_manifest.json) makes resume REFUSE on a mismatched
    acquired range and warn on a changed config fingerprint instead of
    silently mixing results, and chips dead-lettered to quarantine.json
    by a previous run drain first (docs/ROBUSTNESS.md).

    Returns the tuple of chip ids processed successfully.
    """
    cfg = cfg or Config.from_env()
    acquired = acquired or dt.default_acquired()
    cfg = resolve_batching(cfg, acquired)
    log = logger("change-detection")
    counters = Counters()
    # Run identity: ONE id (broadcast fleet-wide) correlates every
    # host's JSON log lines, spans, /progress payloads, and report
    # shards.  Context is set immediately — the setup log lines (tile
    # geometry, resume accounting) must already carry the id; start_ops
    # re-sets it with the process index once the backend is up.
    run_id = fleet_run_id()
    jsonlog.set_run_context(run_id=run_id)
    # Run-scoped telemetry: a fresh registry so the report reflects THIS
    # run.  (The span tracer starts below, right before the try/finally
    # that guarantees its stop — a setup failure here must not leak an
    # active process-global tracer into later runs.)
    obs_metrics.reset_registry()
    # Compile-warm startup (FIREBIRD_COMPILE_CACHE): persistent cache on,
    # then AOT-compile the predicted batch shape in the background so the
    # first XLA compile overlaps batch-0 fetch instead of following it.
    setup_compile_cache(cfg)
    warm = warm_start(cfg, acquired)

    # Refuse-or-warn BEFORE building anything: a resume against a
    # different acquired range must not interleave date windows (and must
    # not leave a half-built writer behind when it refuses).
    if resume:
        qlib.check_resume(cfg, acquired=acquired, log=log)

    source, store, writer, policy, breaker, quarantine = robustness_setup(
        cfg, run_id, source=source, store=store)

    tile = grid.tile(x=x, y=y)
    cids = list(take(number, grid.chips(tile)))
    cids = host_shard(cids)
    skipped: tuple = ()
    if resume:
        # Key on the segment table: it is written LAST per chip through the
        # FIFO writer, so its presence implies the chip/pixel rows landed
        # too.
        have = store.chip_ids("segment")
        # Dead letters whose chips actually landed (quarantined at chunk
        # granularity but persisted before the failure) drain right away.
        quarantine.discard_many(have)
        todo = [c for c in cids if c not in have]
        skipped = tuple(c for c in cids if c in have)
        # Drain the quarantine FIRST: the chips we already know we owe
        # sort to the front of the todo list (stable, so tile order is
        # otherwise preserved).
        qids = quarantine.chip_ids()
        todo.sort(key=lambda c: tuple(int(v) for v in c) not in qids)
        cids = todo
        log.info("resume: %d chips already stored, %d to do (%d draining "
                 "from quarantine first)", len(skipped), len(cids),
                 len(qids))
    else:
        qlib.write_manifest(cfg, acquired=acquired, run_id=run_id,
                            tile=tile)
    chunks = list(partition_all(chunk_size, cids))
    log.info("tile h=%s v=%s: %d chips in %d chunks (acquired %s)",
             tile["h"], tile["v"], len(cids), len(chunks), acquired)

    # Live ops surface: run context for JSON logs, /progress status,
    # optional watchdog + HTTP endpoint (no port bound unless asked).
    run_block = dict(kind="changedetection", run_id=run_id,
                     host=jsonlog.HOST, process_id=_process_index(),
                     tile_h=tile["h"], tile_v=tile["v"], acquired=acquired,
                     chips=len(cids), chunks=len(chunks),
                     resumed=len(skipped))
    _, ops_srv, watchdog = start_ops(
        cfg, run_id, "changedetection", chips_total=len(cids),
        counters=counters, run_block=run_block, quarantine=quarantine,
        breaker=breaker)

    # Opt-in tracing (cfg.profile_dir): the whole run captures a JAX
    # profiler trace viewable in TensorBoard/Perfetto — the tracing
    # subsystem the reference lacked (SURVEY.md §5).
    if cfg.profile_dir:
        import jax

        prof = jax.profiler.trace(cfg.profile_dir)
    else:
        prof = contextlib.nullcontext()

    tracer = tracing.start(run_id=run_id) \
        if tracing.wants_trace(cfg.trace) else None
    done: list = []
    # Rate clock starts at the first productive moment, not Counters()
    # construction — setup/backend idle must not deflate *_per_sec.
    counters.start()
    try:
        with prof:
            for chunk in chunks:
                done.extend(run_chunk(
                    chunk, source=source, writer=writer,
                    acquired=acquired, cfg=cfg, counters=counters,
                    log=log, policy=policy, quarantine=quarantine))
    finally:
        obs_server.set_stage("finalize")
        writer.close()
        # Collect the warm-compile counters for the report when the
        # background compile already finished (a still-compiling warm
        # thread of a short run is abandoned, not awaited).
        if warm is not None:
            warm.join(timeout=5.0)
        snap = counters.snapshot()
        log.info("change-detection complete: %s", snap)
        if len(quarantine):
            run_block["chips_quarantined"] = len(quarantine)
            log.warning(
                "%d chips in quarantine (%s) — rerun with --resume to "
                "drain them once the cause clears", len(quarantine),
                quarantine.path or "in-memory: memory store backend")
        if tracer is not None:
            tracing.stop()
        paths = obs_report.finish_run(
            cfg, tracer=tracer, run_counters=snap, run=run_block)
        if paths:
            log.info("observability artifacts: %s", paths)
        # Server goes down LAST so /progress and /report serve the final
        # state for as long as the process allows.
        obs_server.set_stage("done")
        stop_ops(ops_srv, watchdog)

    return tuple(skipped) + tuple(done)


def classification(x, y, msday: int, meday: int, acquired: str | None = None,
                   cfg: Config | None = None, source=None, aux_source=None,
                   store=None):
    """Train on the 3x3 tile neighborhood, classify the tile, persist
    predictions + the trained model (ref core.classification, core.py:156-251
    — including the predict/save path the reference left commented out)."""
    try:
        from firebird_tpu.rf import pipeline as rf_pipeline
    except ImportError as e:
        raise RuntimeError(
            "classification requires the firebird_tpu.rf module, which is "
            "not available in this build") from e

    cfg = cfg or Config.from_env()
    acquired = acquired or dt.default_acquired()
    store = store or open_store(cfg.store_backend, cfg.store_path,
                                cfg.keyspace())
    return rf_pipeline.classify_tile(
        x=x, y=y, msday=msday, meday=meday, acquired=acquired, cfg=cfg,
        source=source or make_source(cfg),
        aux_source=aux_source or make_aux_source(cfg),
        store=store)
