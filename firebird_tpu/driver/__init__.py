from firebird_tpu.driver import core

__all__ = ["core"]
