"""Durable, append-only alert log: one record per confirmed break.

The reference system only replays the archive — a detected break lands
in Cassandra and waits for the next product run (PAPER.md §0).  This
module is the producer half of the near-real-time alerting loop
(ROADMAP item 5): the streaming driver appends one durable record the
moment a tail break confirms (``StreamState.break_day`` 0→>0), and the
feed side (alerts/feed.py) pushes it to subscribers within seconds —
the durable-event-log + subscriber-feed architecture big astronomical
survey pipelines use for transient alerts (PAPERS.md).

Design rules, inherited from the fleet queue (fleet/queue.py — the same
no-external-services deployment weight):

- **sqlite next to the store.**  ``alerts.db`` via :func:`alert_db_path`
  (the fleet.db placement rule); WAL so the serving layer's readers and
  the stream's writer coexist.
- **Monotonic cursor.**  The rowid IS the cursor: ``since(cursor)``
  returns records with ``id > cursor`` in id order, so a consumer that
  remembers its last id never misses or re-reads a record.
- **Exactly-once emission.**  Records are UNIQUE on
  ``(px, py, break_day)``: a stream resume re-applying the same
  acquisitions, or a fleet re-delivering a stream job, re-emits the
  same logical alert and the log ignores it (``alert_deduped_total``).
  A pixel whose repair lands and whose tail breaks AGAIN carries a new
  ``break_day`` — a genuinely new alert, not a duplicate.
- **Durable subscriber cursors.**  Webhook subscribers live in the same
  database with their delivery cursor; delivery crash-resumes from the
  cursor, never from "the beginning" or "now".

The fanout plane (alerts/fanout.py, docs/ALERTS.md "Fanout plane")
adds three sharded structures on top, all migrated in with the same
guarded-ALTER discipline as the ``trace`` column:

- alerts carry their chip's base **quadkey** (``qk``) so shard rollup
  is a ``substr()`` group-by — the shard key is a quadkey prefix and
  can change width without restamping the log.
- ``subscription_cells`` maps covering quadkey cells -> subscriber ids
  (alerts/subindex.py), turning audience resolution into an O(levels)
  cell lookup; subscribers gain an exact AOI for the post-filter plus
  a delivery policy (immediate | digest | batch) and parking state.
- ``fanout_cursors`` holds per-(subscriber, shard) forward-only
  delivery cursors — every alert belongs to exactly one shard, so the
  per-shard cursors compose to the same exactly-once contract the flat
  cursor gives, while letting shard jobs drain independently.
"""

from __future__ import annotations

import datetime
import os
import sqlite3
import threading
import time

from firebird_tpu.alerts import subindex
from firebird_tpu.obs import metrics as obs_metrics

ALERT_SCHEMA = "firebird-alert-log/1"

# A since() page bound: cursor pagination makes any depth reachable,
# one page must not balloon a response or an SSE write burst.
MAX_PAGE = 10_000

# Per-subscriber delivery policies (docs/ALERTS.md "Fanout plane"):
# immediate POSTs every page as it lands, digest coalesces a window
# into one summary POST, batch bounds each POST to max_n records.
MODES = ("immediate", "digest", "batch")


def alert_db_path(cfg) -> str | None:
    """The alert log for a config: ``cfg.alert_db`` when set, else
    ``alerts.db`` next to the results store (the fleet.db placement
    rule).  None — alerting disabled — for the memory backend without
    an explicit path: unlike the fleet queue this is an optional side
    product, so no-location degrades to off rather than raising."""
    if cfg.alert_db:
        return cfg.alert_db
    from firebird_tpu.driver import quarantine as qlib

    d = qlib._artifact_dir(cfg)
    return None if d is None else os.path.join(d, "alerts.db")


def _now_iso() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def _validate_policy(mode: str, window_sec, max_n) -> None:
    if mode not in MODES:
        raise ValueError(
            f"delivery mode must be one of {MODES}, got {mode!r}")
    if mode == "digest" and (window_sec is None or float(window_sec) <= 0):
        raise ValueError(
            f"digest mode needs window_sec > 0, got {window_sec!r}")
    if mode == "batch" and (max_n is None or int(max_n) < 1):
        raise ValueError(f"batch mode needs max_n >= 1, got {max_n!r}")


class AlertLog:
    """The durable alert log + subscriber registry.  Thread-safe within
    a process (one guarded connection) and process-safe across the
    stream writer and serve readers (WAL + short transactions)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._con = sqlite3.connect(  # guarded-by: _lock
            path, timeout=60, isolation_level=None,
            check_same_thread=False)
        self._create()
        # Depth tracked incrementally: one COUNT(*) at open, then +=
        # per append — a per-append full-table count would make hot-path
        # emission O(total log size).  Other writers' appends are
        # invisible to this tally; status()/count() stay exact.
        self._depth = self.count()  # guarded-by: _lock (int += only)
        # Chip -> base quadkey memo: records arrive chip-batched, the
        # projection math need not re-run per record.
        self._qk_cache: dict[tuple[int, int], str | None] = {}

    def _create(self) -> None:
        with self._lock:
            con = self._con
            con.execute("PRAGMA journal_mode=WAL")
            con.execute("PRAGMA synchronous=NORMAL")
            con.execute("BEGIN IMMEDIATE")
            try:
                con.execute(
                    "CREATE TABLE IF NOT EXISTS alerts ("
                    " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                    " cx INTEGER NOT NULL, cy INTEGER NOT NULL,"
                    " px INTEGER NOT NULL, py INTEGER NOT NULL,"
                    " break_day REAL NOT NULL,"
                    " score REAL, magnitude REAL,"
                    " run_id TEXT, detected_at TEXT, trace TEXT,"
                    " UNIQUE (px, py, break_day))")
                # Guarded ALTERs, the trace-column precedent: pre-fanout
                # logs also lack qk (the chip's base quadkey stamped at
                # append; NULL for off-domain chips and for rows older
                # than the migration — both fan out through the legacy
                # whole-log deliverer only).
                cols = {row[1] for row in con.execute(
                    "PRAGMA table_info(alerts)")}
                if "trace" not in cols:
                    con.execute("ALTER TABLE alerts ADD COLUMN trace TEXT")
                if "qk" not in cols:
                    con.execute("ALTER TABLE alerts ADD COLUMN qk TEXT")
                con.execute(
                    "CREATE INDEX IF NOT EXISTS idx_alerts_chip "
                    "ON alerts (cx, cy)")
                con.execute(
                    "CREATE TABLE IF NOT EXISTS subscribers ("
                    " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                    " url TEXT NOT NULL UNIQUE,"
                    " cursor INTEGER NOT NULL DEFAULT 0,"
                    " created TEXT, last_ok TEXT,"
                    " failures INTEGER NOT NULL DEFAULT 0)")
                # Fanout-plane subscriber columns: exact AOI (NULL =
                # global) for the post-filter behind the cell index,
                # delivery policy, and failure-parking state.
                scols = {row[1] for row in con.execute(
                    "PRAGMA table_info(subscribers)")}
                for col, typ in (
                        ("aoi_minx", "REAL"), ("aoi_miny", "REAL"),
                        ("aoi_maxx", "REAL"), ("aoi_maxy", "REAL"),
                        ("mode", "TEXT NOT NULL DEFAULT 'immediate'"),
                        ("window_sec", "REAL"), ("max_n", "INTEGER"),
                        ("parked_until", "REAL"), ("park_delay", "REAL")):
                    if col not in scols:
                        con.execute(f"ALTER TABLE subscribers "
                                    f"ADD COLUMN {col} {typ}")
                con.execute(
                    "CREATE TABLE IF NOT EXISTS subscription_cells ("
                    " cell TEXT NOT NULL, sub_id INTEGER NOT NULL,"
                    " PRIMARY KEY (cell, sub_id)) WITHOUT ROWID")
                con.execute(
                    "CREATE INDEX IF NOT EXISTS idx_cells_sub "
                    "ON subscription_cells (sub_id)")
                # Subscribers from before the cell index registered no
                # AOI — give them the root cell so they stay global
                # audience, exactly as they behaved pre-migration.
                con.execute(
                    "INSERT OR IGNORE INTO subscription_cells (cell, "
                    "sub_id) SELECT '', id FROM subscribers WHERE id "
                    "NOT IN (SELECT sub_id FROM subscription_cells)")
                con.execute(
                    "CREATE TABLE IF NOT EXISTS fanout_cursors ("
                    " sub_id INTEGER NOT NULL, shard TEXT NOT NULL,"
                    " cursor INTEGER NOT NULL DEFAULT 0, last_sent REAL,"
                    " PRIMARY KEY (sub_id, shard)) WITHOUT ROWID")
                # The shard drain's straggler probe (rows behind a job's
                # window start) walks this instead of the PK.
                con.execute(
                    "CREATE INDEX IF NOT EXISTS idx_fanout_shard "
                    "ON fanout_cursors (shard, cursor)")
                # Forward-only per-shard drained watermark: everything
                # at or below it was ATTEMPTED for the whole audience
                # (pinned cursor rows track who is still behind), so a
                # duplicate job over a covered window is a no-op and a
                # row-less subscriber reads as caught-up-through-it.
                con.execute(
                    "CREATE TABLE IF NOT EXISTS fanout_shards ("
                    " shard TEXT PRIMARY KEY,"
                    " drained INTEGER NOT NULL DEFAULT 0) WITHOUT ROWID")
                con.execute(
                    "CREATE TABLE IF NOT EXISTS meta ("
                    " key TEXT PRIMARY KEY, value TEXT)")
                con.execute(
                    "INSERT OR IGNORE INTO meta (key, value) "
                    "VALUES ('schema', ?)", (ALERT_SCHEMA,))
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise

    # -- producer side ------------------------------------------------------

    def append(self, records, *, run_id: str | None = None,
               trace: str | None = None) -> tuple[int, int]:
        """Append alert records in ONE transaction; returns (inserted,
        deduped).  Each record: dict with cx, cy, px, py, break_day and
        optional score / magnitude.  Records whose (px, py, break_day)
        key already exists are ignored — stream resume and fleet
        re-delivery are exactly-once.  ``trace`` stamps the causal trace
        id (obs/tracing.py wire format) on every record that doesn't
        carry its own, so the alert row joins the fleet's cross-process
        telemetry chain all the way out to webhook delivery."""
        records = list(records)
        if not records:
            return 0, 0
        now = _now_iso()
        inserted = 0
        for r in records:
            key = (int(r["cx"]), int(r["cy"]))
            if key not in self._qk_cache:
                self._qk_cache[key] = subindex.base_quadkey(*key)
        with self._lock:
            con = self._con
            con.execute("BEGIN IMMEDIATE")
            try:
                for r in records:
                    cur = con.execute(
                        "INSERT OR IGNORE INTO alerts (cx, cy, px, py, "
                        "break_day, score, magnitude, run_id, detected_at,"
                        " trace, qk) VALUES "
                        "(?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                        (int(r["cx"]), int(r["cy"]), int(r["px"]),
                         int(r["py"]), float(r["break_day"]),
                         float(r.get("score", 1.0)),
                         float(r.get("magnitude", 0.0)), run_id, now,
                         r.get("trace", trace),
                         self._qk_cache[(int(r["cx"]), int(r["cy"]))]))
                    inserted += cur.rowcount
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise
            self._depth += inserted
            depth = self._depth
        deduped = len(records) - inserted
        if inserted:
            obs_metrics.counter(
                "alert_emitted_total",
                help="confirmed-break alerts appended to the durable "
                     "log").inc(inserted)
        if deduped:
            obs_metrics.counter(
                "alert_deduped_total",
                help="alert re-emissions ignored by the (pixel, "
                     "break_day) unique key (resume / re-delivery)").inc(
                deduped)
        obs_metrics.gauge(
            "alert_log_depth",
            help="total records in the durable alert log (as this "
                 "writer has seen it)").set(depth)
        return inserted, deduped

    # -- consumer side ------------------------------------------------------

    def since(self, cursor: int = 0, *, limit: int = 1000,
              bbox=None, t0=None, t1=None) -> list[dict]:
        """Records with ``id > cursor`` in id order (the resume
        contract).  ``bbox`` is (minx, miny, maxx, maxy) over the pixel
        projection coords; ``t0``/``t1`` are ISO dates bounding
        ``break_day``."""
        from firebird_tpu.utils import dates as dt

        limit = max(1, min(int(limit), MAX_PAGE))
        sql = ("SELECT id, cx, cy, px, py, break_day, score, magnitude, "
               "run_id, detected_at, trace FROM alerts WHERE id > ?")
        args: list = [int(cursor)]
        if bbox is not None:
            minx, miny, maxx, maxy = (float(v) for v in bbox)
            sql += " AND px >= ? AND px <= ? AND py >= ? AND py <= ?"
            args += [minx, maxx, miny, maxy]
        if t0 is not None:
            sql += " AND break_day >= ?"
            args.append(float(dt.to_ordinal(t0)))
        if t1 is not None:
            sql += " AND break_day <= ?"
            args.append(float(dt.to_ordinal(t1)))
        sql += " ORDER BY id LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._con.execute(sql, args).fetchall()
        out = []
        for (rid, cx, cy, px, py, bday, score, mag, run_id,
             detected_at, trace) in rows:
            out.append({
                "id": int(rid), "cx": int(cx), "cy": int(cy),
                "px": int(px), "py": int(py),
                "break_day": float(bday),
                "break_date": dt.to_iso(int(bday)),
                "score": score, "magnitude": mag,
                "run_id": run_id, "detected_at": detected_at,
                "trace": trace})
        return out

    def latest_cursor(self) -> int:
        with self._lock:
            row = self._con.execute("SELECT MAX(id) FROM alerts").fetchone()
        return int(row[0]) if row and row[0] is not None else 0

    def count(self) -> int:
        with self._lock:
            return int(self._con.execute(
                "SELECT COUNT(*) FROM alerts").fetchone()[0])

    # -- subscribers --------------------------------------------------------

    def subscribe(self, url: str, *, cursor: int | None = None,
                  aoi=None, mode: str = "immediate",
                  window_sec: float | None = None,
                  max_n: int | None = None,
                  max_cells: int | None = None) -> int:
        """Register a webhook subscriber; returns its id.  Idempotent on
        url (re-registering keeps the existing durable cursor but
        REPLACES the AOI, covering cells, and delivery policy).  A new
        subscriber's cursor defaults to 0 — full catch-up from the log's
        beginning; pass ``cursor`` to start elsewhere (e.g.
        ``latest_cursor()`` for new-alerts-only).  ``aoi`` is an exact
        (minx, miny, maxx, maxy) projection bbox (None = global),
        decomposed into at most ``max_cells`` covering quadkey cells in
        the subscription index; ``mode``/``window_sec``/``max_n`` pick
        the delivery policy (docs/ALERTS.md "Fanout plane")."""
        return self.subscribe_many(
            [{"url": url, "cursor": cursor, "aoi": aoi, "mode": mode,
              "window_sec": window_sec, "max_n": max_n}],
            max_cells=max_cells)[0]

    def subscribe_many(self, entries, *,
                       max_cells: int | None = None) -> list[int]:
        """Bulk :meth:`subscribe` — one transaction for the whole list
        (the 1M-subscriber loadtest's registration path).  Each entry is
        a dict with ``url`` and optional ``cursor`` / ``aoi`` / ``mode``
        / ``window_sec`` / ``max_n``.  Returns ids in entry order."""
        budget = subindex.MAX_CELLS if max_cells is None else int(max_cells)
        prepared = []
        for e in entries:
            url = e.get("url")
            if not url or "://" not in url:
                raise ValueError(
                    f"subscriber url must be absolute, got {url!r}")
            mode = e.get("mode") or "immediate"
            window_sec, max_n = e.get("window_sec"), e.get("max_n")
            _validate_policy(mode, window_sec, max_n)
            aoi = e.get("aoi")
            if aoi is not None:
                aoi = tuple(float(v) for v in aoi)
            cells = [""] if aoi is None else subindex.cover_bbox(aoi, budget)
            prepared.append((url, int(e.get("cursor") or 0), aoi, mode,
                             window_sec, max_n, cells))
        ids: list[int] = []
        now = _now_iso()
        with self._lock:
            con = self._con
            con.execute("BEGIN IMMEDIATE")
            try:
                for url, cur0, aoi, mode, window_sec, max_n, cells \
                        in prepared:
                    minx, miny, maxx, maxy = aoi or (None,) * 4
                    con.execute(
                        "INSERT INTO subscribers (url, cursor, created, "
                        "aoi_minx, aoi_miny, aoi_maxx, aoi_maxy, mode, "
                        "window_sec, max_n) "
                        "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?) "
                        "ON CONFLICT (url) DO UPDATE SET "
                        "aoi_minx = excluded.aoi_minx, "
                        "aoi_miny = excluded.aoi_miny, "
                        "aoi_maxx = excluded.aoi_maxx, "
                        "aoi_maxy = excluded.aoi_maxy, "
                        "mode = excluded.mode, "
                        "window_sec = excluded.window_sec, "
                        "max_n = excluded.max_n",
                        (url, cur0, now, minx, miny, maxx, maxy, mode,
                         window_sec, max_n))
                    sid = int(con.execute(
                        "SELECT id FROM subscribers WHERE url = ?",
                        (url,)).fetchone()[0])
                    con.execute("DELETE FROM subscription_cells "
                                "WHERE sub_id = ?", (sid,))
                    con.executemany(
                        "INSERT OR IGNORE INTO subscription_cells "
                        "(cell, sub_id) VALUES (?, ?)",
                        [(c, sid) for c in cells])
                    ids.append(sid)
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise
        return ids

    def subscribers(self) -> list[dict]:
        latest = self.latest_cursor()
        with self._lock:
            rows = self._con.execute(
                "SELECT id, url, cursor, created, last_ok, failures, "
                "aoi_minx, aoi_miny, aoi_maxx, aoi_maxy, mode, "
                "window_sec, max_n, parked_until "
                "FROM subscribers ORDER BY id").fetchall()
        return [{"id": int(i), "url": u, "cursor": int(c),
                 "lag": max(latest - int(c), 0), "created": cr,
                 "last_ok": ok, "failures": int(f),
                 "aoi": None if x0 is None else (x0, y0, x1, y1),
                 "mode": m, "window_sec": w, "max_n": n,
                 "parked_until": p}
                for i, u, c, cr, ok, f, x0, y0, x1, y1, m, w, n, p
                in rows]

    # -- audience resolution (the quadkey subscription index) ---------------

    def audience(self, px: float, py: float) -> list[int]:
        """Subscriber ids whose AOI contains projection point
        (px, py), resolved through the subscription-cell index: one
        ``cell IN (O(levels) quadkeys)`` probe plus the exact-AOI
        post-filter — cost independent of subscriber count (the
        sublinearity the fanout loadtest measures).  Defined for
        in-domain points; off-domain points see global subscribers
        only (their alerts carry no quadkey)."""
        cells = subindex.point_cells(px, py)
        t0 = time.perf_counter()
        marks = ",".join("?" * len(cells))
        with self._lock:
            rows = self._con.execute(
                f"SELECT DISTINCT s.id FROM subscription_cells c "
                f"JOIN subscribers s ON s.id = c.sub_id "
                f"WHERE c.cell IN ({marks}) AND (s.aoi_minx IS NULL OR "
                f"(s.aoi_minx <= ? AND ? <= s.aoi_maxx AND "
                f"s.aoi_miny <= ? AND ? <= s.aoi_maxy)) ORDER BY s.id",
                (*cells, float(px), float(px), float(py),
                 float(py))).fetchall()
        obs_metrics.histogram(
            "audience_resolve_seconds",
            help="alert audience resolution through the quadkey "
                 "subscription index (per alert point)").observe(
            time.perf_counter() - t0)
        return [int(r[0]) for r in rows]

    def audience_brute(self, px: float, py: float) -> list[int]:
        """The pre-index audience answer: a full bbox scan of every
        subscriber.  The property test pins audience() == this; the
        loadtest times it as the O(subscribers) contrast."""
        with self._lock:
            rows = self._con.execute(
                "SELECT id FROM subscribers WHERE aoi_minx IS NULL OR "
                "(aoi_minx <= ? AND ? <= aoi_maxx AND aoi_miny <= ? "
                "AND ? <= aoi_maxy) ORDER BY id",
                (float(px), float(px), float(py),
                 float(py))).fetchall()
        return [int(r[0]) for r in rows]

    # -- shard plane (fanout rollup + drain queries) ------------------------

    def shards_since(self, cursor: int, prefix_len: int) -> list[dict]:
        """The shards with quadkey-stamped alerts past ``cursor``:
        ``[{shard, since, upto, count}]`` where ``upto`` is the shard's
        max alert id and ``since`` echoes the watermark the group-by
        started from — one rollup group-by, the unit the coordinator
        turns into ``fanout`` fleet jobs (the drain needs ``since`` to
        tell stragglers from caught-up subscribers)."""
        with self._lock:
            rows = self._con.execute(
                "SELECT substr(qk, 1, ?) AS s, MAX(id), COUNT(*) "
                "FROM alerts WHERE id > ? AND qk IS NOT NULL "
                "GROUP BY s ORDER BY s",
                (int(prefix_len), int(cursor))).fetchall()
        return [{"shard": s, "since": int(cursor), "upto": int(mx),
                 "count": int(n)}
                for s, mx, n in rows]

    def alerts_for_shard(self, shard: str, *, after: int = 0,
                         upto: int, limit: int = 1000) -> list[dict]:
        """The shard's alert records with ``after < id <= upto`` in id
        order — the drain page of one fanout job (same record shape as
        :meth:`since`, plus ``qk``)."""
        from firebird_tpu.utils import dates as dt

        limit = max(1, min(int(limit), MAX_PAGE))
        with self._lock:
            rows = self._con.execute(
                "SELECT id, cx, cy, px, py, break_day, score, magnitude,"
                " run_id, detected_at, trace, qk FROM alerts "
                "WHERE id > ? AND id <= ? AND qk IS NOT NULL "
                "AND substr(qk, 1, ?) = ? ORDER BY id LIMIT ?",
                (int(after), int(upto), len(shard), shard,
                 limit)).fetchall()
        return [{"id": int(rid), "cx": int(cx), "cy": int(cy),
                 "px": int(px), "py": int(py), "break_day": float(bday),
                 "break_date": dt.to_iso(int(bday)), "score": score,
                 "magnitude": mag, "run_id": run_id,
                 "detected_at": detected_at, "trace": trace, "qk": qk}
                for (rid, cx, cy, px, py, bday, score, mag, run_id,
                     detected_at, trace, qk) in rows]

    def shard_subscribers(self, shard: str) -> list[dict]:
        """The subscribers a shard's fanout job must serve — any
        subscriber with a covering cell inside the shard's subtree
        (``LIKE shard%``) or on its ancestor chain (coarse and global
        cells), each joined with its durable per-shard fanout cursor."""
        rows = self.shard_subscriber_rows(shard)
        return [{"id": int(i), "url": u,
                 "aoi": None if x0 is None else (x0, y0, x1, y1),
                 "mode": m, "window_sec": w, "max_n": n,
                 "parked_until": p, "failures": int(f),
                 "cursor": int(c), "last_sent": ls}
                for i, u, x0, y0, x1, y1, m, w, n, p, f, c, ls in rows]

    def shard_subscriber_rows(self, shard: str) -> list[tuple]:
        """:meth:`shard_subscribers` as raw ``(id, url, aoi_minx,
        aoi_miny, aoi_maxx, aoi_maxy, mode, window_sec, max_n,
        parked_until, failures, cursor, last_sent)`` tuples — the shard
        drain turns tens of thousands of these into numpy columns, and
        building a dict per subscriber first is measurable CPU at that
        scale.

        The subtree arm is an explicit ``[shard, shard+1)`` range on
        the ``(cell, sub_id)`` primary key, UNIONed with equality
        probes for the ancestor cells: a single ``LIKE-or-IN``
        predicate makes sqlite abandon the index for a full scan of
        the cell table — the difference between O(shard) and O(every
        cell of every subscriber) per fanout job."""
        prefixes = subindex.shard_prefixes(shard)
        # Quadkey digits are 0-3, so bumping the last digit bounds the
        # subtree ("01" -> ["01", "02")) without overflow.
        hi = shard[:-1] + chr(ord(shard[-1]) + 1)
        sub = ("SELECT sub_id FROM subscription_cells "
               "WHERE cell >= ? AND cell < ?")
        args: list = [shard, shard, hi]
        if prefixes:
            sub += (" UNION SELECT sub_id FROM subscription_cells "
                    f"WHERE cell IN ({','.join('?' * len(prefixes))})")
            args += prefixes
        with self._lock:
            return self._con.execute(
                self._SUB_ROW_SELECT
                + f"WHERE s.id IN ({sub}) ORDER BY s.id",
                args).fetchall()

    # One fanout job's candidate set: the window alerts' cell audience
    # plus the shard's stragglers.  Cost is O(audience + stragglers) —
    # never O(shard subscribers), which is the point of the cell index.
    _SUB_ROW_SELECT = (
        "SELECT s.id, s.url, s.aoi_minx, s.aoi_miny, "
        "s.aoi_maxx, s.aoi_maxy, s.mode, s.window_sec, s.max_n, "
        "s.parked_until, s.failures, "
        "COALESCE(fc.cursor, 0), fc.last_sent "
        "FROM subscribers s "
        "LEFT JOIN fanout_cursors fc "
        "ON fc.sub_id = s.id AND fc.shard = ? ")

    def audience_for_cells(self, cells) -> list[int]:
        """DISTINCT subscriber ids holding any of ``cells`` — the
        batched audience probe of one fanout job's alert window (the
        union of every window alert's prefix chain, deduplicated by the
        caller).  Covering cells over-approximate AOIs, so the drain
        still applies the exact vectorised bbox filter; this only
        bounds WHOM it looks at."""
        out: set = set()
        cells = list(cells)
        with self._lock:
            for i in range(0, len(cells), 500):
                chunk = cells[i:i + 500]
                rows = self._con.execute(
                    "SELECT DISTINCT sub_id FROM subscription_cells "
                    f"WHERE cell IN ({','.join('?' * len(chunk))})",
                    chunk).fetchall()
                out.update(int(r[0]) for r in rows)
        return sorted(out)

    def shard_straggler_rows(self, shard: str, since: int) -> list[tuple]:
        """``(sub_id, cursor)`` for the shard's cursor rows still behind
        ``since`` (a job's window start): held digests, parked/failed
        subscribers, and partial advances from a killed worker.  A
        cursor row only EXISTS while its subscriber is mid-catch-up
        (clean completion deletes it — see advance_fanout_many), so
        this stays small however many subscribers the shard has."""
        with self._lock:
            return self._con.execute(
                "SELECT sub_id, cursor FROM fanout_cursors "
                "WHERE shard = ? AND cursor < ?",
                (shard, int(since))).fetchall()

    def subscriber_rows_by_id(self, ids, shard: str) -> list[tuple]:
        """The :meth:`shard_subscriber_rows` tuple shape for an explicit
        id set (a drain's audience-union-stragglers candidates), joined
        with the per-``shard`` fanout cursor — except the cursor column
        is ``-1`` when NO row exists (the drain must tell "caught up
        through the shard watermark, no row" from "pinned at 0").
        ``ids`` must be sorted for the result to be id-ordered."""
        ids = [int(i) for i in ids]
        out: list[tuple] = []
        sel = self._SUB_ROW_SELECT.replace("COALESCE(fc.cursor, 0)",
                                           "COALESCE(fc.cursor, -1)")
        with self._lock:
            for i in range(0, len(ids), 500):
                chunk = ids[i:i + 500]
                out.extend(self._con.execute(
                    sel
                    + f"WHERE s.id IN ({','.join('?' * len(chunk))}) "
                      "ORDER BY s.id",
                    [shard, *chunk]).fetchall())
        return out

    def shard_drained(self, shard: str) -> int:
        """The shard's forward-only drained watermark (0 if never
        drained): alert ids at or below it have been offered to their
        whole audience — whoever is still behind has a pinned cursor
        row saying so."""
        with self._lock:
            row = self._con.execute(
                "SELECT drained FROM fanout_shards WHERE shard = ?",
                (shard,)).fetchone()
        return int(row[0]) if row else 0

    def set_shard_drained(self, shard: str, since: int,
                          upto: int) -> None:
        """Advance the shard's drained watermark — forward-only (a
        zombie worker finishing a stale job cannot undo its successor)
        AND contiguous: the covered window must START at or below the
        current watermark.  Jobs over successive windows of one shard
        can run concurrently; if the newer window completes first, its
        ``upto`` must not mark the older, still-in-flight window
        covered — a SIGKILL there would silently lose it."""
        since, upto = int(since), int(upto)
        with self._lock:
            if since <= 0:
                # Contiguity is trivially satisfied from the log's
                # start; this is also the only path that may CREATE
                # the shard's row.
                self._con.execute(
                    "INSERT INTO fanout_shards (shard, drained) "
                    "VALUES (?, ?) ON CONFLICT (shard) DO UPDATE SET "
                    "drained = excluded.drained "
                    "WHERE excluded.drained > fanout_shards.drained",
                    (shard, upto))
            else:
                self._con.execute(
                    "UPDATE fanout_shards SET drained = ? "
                    "WHERE shard = ? AND drained < ? AND drained >= ?",
                    (upto, shard, upto, since))

    def fanout_cursor(self, sub_id: int, shard: str) -> int:
        with self._lock:
            row = self._con.execute(
                "SELECT cursor FROM fanout_cursors WHERE sub_id = ? "
                "AND shard = ?", (int(sub_id), shard)).fetchone()
        return int(row[0]) if row else 0

    def advance_fanout(self, sub_id: int, shard: str, cursor: int, *,
                       sent_at: float | None = None) -> None:
        """Move a (subscriber, shard) fanout cursor FORWARD — same
        no-rewind rule as :meth:`advance`, so a zombie fanout worker
        finishing a stale job cannot undo its successor.  ``sent_at``
        marks an actual 2xx POST: it stamps the digest window's
        last-sent time and unparks/heals the subscriber (a cursor-only
        advance — e.g. a page the AOI filtered to nothing — touches
        neither)."""
        with self._lock:
            con = self._con
            con.execute("BEGIN IMMEDIATE")
            try:
                con.execute(
                    "INSERT INTO fanout_cursors (sub_id, shard, cursor, "
                    "last_sent) VALUES (?, ?, ?, ?) "
                    "ON CONFLICT (sub_id, shard) DO UPDATE SET "
                    "cursor = excluded.cursor, last_sent = "
                    "COALESCE(excluded.last_sent, fanout_cursors."
                    "last_sent) WHERE excluded.cursor > "
                    "fanout_cursors.cursor",
                    (int(sub_id), shard, int(cursor), sent_at))
                if sent_at is not None:
                    con.execute(
                        "UPDATE subscribers SET failures = 0, "
                        "parked_until = NULL, park_delay = NULL, "
                        "last_ok = ? WHERE id = ?",
                        (_now_iso(), int(sub_id)))
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise

    def advance_fanout_many(self, shard: str, advances,
                            completes=()) -> None:
        """Batch fanout-cursor advance in ONE transaction: ``advances``
        holds ``(sub_id, cursor)`` pairs (cursor-only — pins a held
        digest or a failed subscriber so the straggler probe can find
        it) and/or ``(sub_id, cursor, sent_at)`` triples
        (2xx-acknowledged deliveries — stamps the digest window's
        last-sent time and heals failures/parking, exactly like
        :meth:`advance_fanout`).  Same forward-only rule throughout; a
        per-subscriber transaction each would dominate the drain.

        ``completes`` lists subscribers whose drain finished CLEAN to
        the job's bound: their cursor rows are DELETED — no row means
        "caught up; only the audience probe need ever visit me again".
        A zombie's late advance can re-insert a stale row, which the
        next job re-drains into receiver-deduplicated re-POSTs and
        deletes again — at-least-once POSTs, exactly-once records."""
        rows = []
        healed = []
        for adv in advances:
            sub_id, cursor = int(adv[0]), int(adv[1])
            sent_at = adv[2] if len(adv) > 2 else None
            rows.append((sub_id, shard, cursor, sent_at))
            if sent_at is not None:
                healed.append(sub_id)
        if not rows and not completes:
            return
        with self._lock:
            con = self._con
            con.execute("BEGIN IMMEDIATE")
            try:
                if rows:
                    con.executemany(
                        "INSERT INTO fanout_cursors (sub_id, shard, "
                        "cursor, last_sent) VALUES (?, ?, ?, ?) "
                        "ON CONFLICT (sub_id, shard) DO UPDATE SET "
                        "cursor = excluded.cursor, last_sent = "
                        "COALESCE(excluded.last_sent, fanout_cursors."
                        "last_sent) WHERE excluded.cursor > "
                        "fanout_cursors.cursor", rows)
                if healed:
                    now = _now_iso()
                    con.executemany(
                        "UPDATE subscribers SET failures = 0, "
                        "parked_until = NULL, park_delay = NULL, "
                        "last_ok = ? WHERE id = ?",
                        [(now, s) for s in healed])
                if completes:
                    con.executemany(
                        "DELETE FROM fanout_cursors WHERE sub_id = ? "
                        "AND shard = ?",
                        [(int(s), shard) for s in completes])
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise

    def rollup_cursor(self) -> int:
        """The global rollup watermark: every quadkey-stamped alert at
        or below it has been covered by an enqueued fanout job."""
        with self._lock:
            row = self._con.execute(
                "SELECT value FROM meta WHERE key = "
                "'fanout_rollup_cursor'").fetchone()
        return int(row[0]) if row else 0

    def set_rollup_cursor(self, cursor: int) -> None:
        with self._lock:
            self._con.execute(
                "INSERT INTO meta (key, value) VALUES "
                "('fanout_rollup_cursor', ?) ON CONFLICT (key) DO "
                "UPDATE SET value = excluded.value WHERE "
                "CAST(excluded.value AS INTEGER) > "
                "CAST(meta.value AS INTEGER)", (int(cursor),))

    def advance(self, sub_id: int, cursor: int) -> None:
        """Move a subscriber's durable delivery cursor FORWARD (a crashed
        deliverer restarting with stale state cannot rewind a successor's
        progress — the fencing discipline, cursor-shaped)."""
        with self._lock:
            self._con.execute(
                "UPDATE subscribers SET cursor = ?, last_ok = ?, "
                "failures = 0 WHERE id = ? AND cursor < ?",
                (int(cursor), _now_iso(), int(sub_id), int(cursor)))

    def record_failure(self, sub_id: int, *,
                       park_after: int | None = None,
                       base: float = 5.0, cap: float = 300.0,
                       rng=None, clock=time.time) -> float | None:
        """Count a delivery failure; with ``park_after`` set, park the
        subscriber under decorrelated backoff once it hits that many
        CONSECUTIVE failures (``retry.decorrelated_delay`` — the
        drivers' jitter, subscriber-shaped), so one dead endpoint never
        stalls its shard.  Returns the park delay when parking happened,
        else None.  Any delivery success (``advance`` /
        ``advance_fanout(sent_at=...)``) heals: failures reset, park
        cleared."""
        from firebird_tpu import retry as retrylib

        with self._lock:
            con = self._con
            con.execute("BEGIN IMMEDIATE")
            try:
                con.execute(
                    "UPDATE subscribers SET failures = failures + 1 "
                    "WHERE id = ?", (int(sub_id),))
                delay = None
                if park_after is not None:
                    row = con.execute(
                        "SELECT failures, park_delay FROM subscribers "
                        "WHERE id = ?", (int(sub_id),)).fetchone()
                    if row and int(row[0]) >= int(park_after):
                        delay = retrylib.decorrelated_delay(
                            float(row[1] or 0.0), base=base, cap=cap,
                            rng=rng)
                        con.execute(
                            "UPDATE subscribers SET parked_until = ?, "
                            "park_delay = ? WHERE id = ?",
                            (clock() + delay, delay, int(sub_id)))
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise
        return delay

    def unsubscribe(self, sub_id: int) -> bool:
        with self._lock:
            cur = self._con.execute(
                "DELETE FROM subscribers WHERE id = ?", (int(sub_id),))
        return cur.rowcount > 0

    # -- operator surface ---------------------------------------------------

    def status(self) -> dict:
        """The alerts view: log depth, latest cursor, per-subscriber
        delivery lag — rendered by ``firebird status`` and the
        ``/progress`` alerts block."""
        now = time.time()
        with self._lock:
            cells = int(self._con.execute(
                "SELECT COUNT(*) FROM subscription_cells").fetchone()[0])
            by_mode = {m: int(n) for m, n in self._con.execute(
                "SELECT mode, COUNT(*) FROM subscribers GROUP BY mode")}
            parked = int(self._con.execute(
                "SELECT COUNT(*) FROM subscribers WHERE parked_until "
                "IS NOT NULL AND parked_until > ?", (now,)).fetchone()[0])
        return {
            "path": self.path,
            "depth": self.count(),
            "latest_cursor": self.latest_cursor(),
            "subscribers": self.subscribers(),
            "fanout": {
                "cells": cells,
                "by_mode": by_mode,
                "parked": parked,
                "rollup_cursor": self.rollup_cursor(),
            },
        }

    def close(self) -> None:
        with self._lock:
            self._con.close()
