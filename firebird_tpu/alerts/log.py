"""Durable, append-only alert log: one record per confirmed break.

The reference system only replays the archive — a detected break lands
in Cassandra and waits for the next product run (PAPER.md §0).  This
module is the producer half of the near-real-time alerting loop
(ROADMAP item 5): the streaming driver appends one durable record the
moment a tail break confirms (``StreamState.break_day`` 0→>0), and the
feed side (alerts/feed.py) pushes it to subscribers within seconds —
the durable-event-log + subscriber-feed architecture big astronomical
survey pipelines use for transient alerts (PAPERS.md).

Design rules, inherited from the fleet queue (fleet/queue.py — the same
no-external-services deployment weight):

- **sqlite next to the store.**  ``alerts.db`` via :func:`alert_db_path`
  (the fleet.db placement rule); WAL so the serving layer's readers and
  the stream's writer coexist.
- **Monotonic cursor.**  The rowid IS the cursor: ``since(cursor)``
  returns records with ``id > cursor`` in id order, so a consumer that
  remembers its last id never misses or re-reads a record.
- **Exactly-once emission.**  Records are UNIQUE on
  ``(px, py, break_day)``: a stream resume re-applying the same
  acquisitions, or a fleet re-delivering a stream job, re-emits the
  same logical alert and the log ignores it (``alert_deduped_total``).
  A pixel whose repair lands and whose tail breaks AGAIN carries a new
  ``break_day`` — a genuinely new alert, not a duplicate.
- **Durable subscriber cursors.**  Webhook subscribers live in the same
  database with their delivery cursor; delivery crash-resumes from the
  cursor, never from "the beginning" or "now".
"""

from __future__ import annotations

import datetime
import os
import sqlite3
import threading

from firebird_tpu.obs import metrics as obs_metrics

ALERT_SCHEMA = "firebird-alert-log/1"

# A since() page bound: cursor pagination makes any depth reachable,
# one page must not balloon a response or an SSE write burst.
MAX_PAGE = 10_000


def alert_db_path(cfg) -> str | None:
    """The alert log for a config: ``cfg.alert_db`` when set, else
    ``alerts.db`` next to the results store (the fleet.db placement
    rule).  None — alerting disabled — for the memory backend without
    an explicit path: unlike the fleet queue this is an optional side
    product, so no-location degrades to off rather than raising."""
    if cfg.alert_db:
        return cfg.alert_db
    from firebird_tpu.driver import quarantine as qlib

    d = qlib._artifact_dir(cfg)
    return None if d is None else os.path.join(d, "alerts.db")


def _now_iso() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


class AlertLog:
    """The durable alert log + subscriber registry.  Thread-safe within
    a process (one guarded connection) and process-safe across the
    stream writer and serve readers (WAL + short transactions)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._con = sqlite3.connect(  # guarded-by: _lock
            path, timeout=60, isolation_level=None,
            check_same_thread=False)
        self._create()
        # Depth tracked incrementally: one COUNT(*) at open, then +=
        # per append — a per-append full-table count would make hot-path
        # emission O(total log size).  Other writers' appends are
        # invisible to this tally; status()/count() stay exact.
        self._depth = self.count()  # guarded-by: _lock (int += only)

    def _create(self) -> None:
        with self._lock:
            con = self._con
            con.execute("PRAGMA journal_mode=WAL")
            con.execute("PRAGMA synchronous=NORMAL")
            con.execute("BEGIN IMMEDIATE")
            try:
                con.execute(
                    "CREATE TABLE IF NOT EXISTS alerts ("
                    " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                    " cx INTEGER NOT NULL, cy INTEGER NOT NULL,"
                    " px INTEGER NOT NULL, py INTEGER NOT NULL,"
                    " break_day REAL NOT NULL,"
                    " score REAL, magnitude REAL,"
                    " run_id TEXT, detected_at TEXT, trace TEXT,"
                    " UNIQUE (px, py, break_day))")
                # Pre-telemetry logs lack the trace column; adding it is
                # the only schema migration this log has ever needed, so
                # a guarded ALTER beats a schema-version dance.
                cols = {row[1] for row in con.execute(
                    "PRAGMA table_info(alerts)")}
                if "trace" not in cols:
                    con.execute("ALTER TABLE alerts ADD COLUMN trace TEXT")
                con.execute(
                    "CREATE INDEX IF NOT EXISTS idx_alerts_chip "
                    "ON alerts (cx, cy)")
                con.execute(
                    "CREATE TABLE IF NOT EXISTS subscribers ("
                    " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                    " url TEXT NOT NULL UNIQUE,"
                    " cursor INTEGER NOT NULL DEFAULT 0,"
                    " created TEXT, last_ok TEXT,"
                    " failures INTEGER NOT NULL DEFAULT 0)")
                con.execute(
                    "CREATE TABLE IF NOT EXISTS meta ("
                    " key TEXT PRIMARY KEY, value TEXT)")
                con.execute(
                    "INSERT OR IGNORE INTO meta (key, value) "
                    "VALUES ('schema', ?)", (ALERT_SCHEMA,))
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise

    # -- producer side ------------------------------------------------------

    def append(self, records, *, run_id: str | None = None,
               trace: str | None = None) -> tuple[int, int]:
        """Append alert records in ONE transaction; returns (inserted,
        deduped).  Each record: dict with cx, cy, px, py, break_day and
        optional score / magnitude.  Records whose (px, py, break_day)
        key already exists are ignored — stream resume and fleet
        re-delivery are exactly-once.  ``trace`` stamps the causal trace
        id (obs/tracing.py wire format) on every record that doesn't
        carry its own, so the alert row joins the fleet's cross-process
        telemetry chain all the way out to webhook delivery."""
        records = list(records)
        if not records:
            return 0, 0
        now = _now_iso()
        inserted = 0
        with self._lock:
            con = self._con
            con.execute("BEGIN IMMEDIATE")
            try:
                for r in records:
                    cur = con.execute(
                        "INSERT OR IGNORE INTO alerts (cx, cy, px, py, "
                        "break_day, score, magnitude, run_id, detected_at,"
                        " trace) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                        (int(r["cx"]), int(r["cy"]), int(r["px"]),
                         int(r["py"]), float(r["break_day"]),
                         float(r.get("score", 1.0)),
                         float(r.get("magnitude", 0.0)), run_id, now,
                         r.get("trace", trace)))
                    inserted += cur.rowcount
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise
            self._depth += inserted
            depth = self._depth
        deduped = len(records) - inserted
        if inserted:
            obs_metrics.counter(
                "alert_emitted_total",
                help="confirmed-break alerts appended to the durable "
                     "log").inc(inserted)
        if deduped:
            obs_metrics.counter(
                "alert_deduped_total",
                help="alert re-emissions ignored by the (pixel, "
                     "break_day) unique key (resume / re-delivery)").inc(
                deduped)
        obs_metrics.gauge(
            "alert_log_depth",
            help="total records in the durable alert log (as this "
                 "writer has seen it)").set(depth)
        return inserted, deduped

    # -- consumer side ------------------------------------------------------

    def since(self, cursor: int = 0, *, limit: int = 1000,
              bbox=None, t0=None, t1=None) -> list[dict]:
        """Records with ``id > cursor`` in id order (the resume
        contract).  ``bbox`` is (minx, miny, maxx, maxy) over the pixel
        projection coords; ``t0``/``t1`` are ISO dates bounding
        ``break_day``."""
        from firebird_tpu.utils import dates as dt

        limit = max(1, min(int(limit), MAX_PAGE))
        sql = ("SELECT id, cx, cy, px, py, break_day, score, magnitude, "
               "run_id, detected_at, trace FROM alerts WHERE id > ?")
        args: list = [int(cursor)]
        if bbox is not None:
            minx, miny, maxx, maxy = (float(v) for v in bbox)
            sql += " AND px >= ? AND px <= ? AND py >= ? AND py <= ?"
            args += [minx, maxx, miny, maxy]
        if t0 is not None:
            sql += " AND break_day >= ?"
            args.append(float(dt.to_ordinal(t0)))
        if t1 is not None:
            sql += " AND break_day <= ?"
            args.append(float(dt.to_ordinal(t1)))
        sql += " ORDER BY id LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._con.execute(sql, args).fetchall()
        out = []
        for (rid, cx, cy, px, py, bday, score, mag, run_id,
             detected_at, trace) in rows:
            out.append({
                "id": int(rid), "cx": int(cx), "cy": int(cy),
                "px": int(px), "py": int(py),
                "break_day": float(bday),
                "break_date": dt.to_iso(int(bday)),
                "score": score, "magnitude": mag,
                "run_id": run_id, "detected_at": detected_at,
                "trace": trace})
        return out

    def latest_cursor(self) -> int:
        with self._lock:
            row = self._con.execute("SELECT MAX(id) FROM alerts").fetchone()
        return int(row[0]) if row and row[0] is not None else 0

    def count(self) -> int:
        with self._lock:
            return int(self._con.execute(
                "SELECT COUNT(*) FROM alerts").fetchone()[0])

    # -- subscribers --------------------------------------------------------

    def subscribe(self, url: str, *, cursor: int | None = None) -> int:
        """Register a webhook subscriber; returns its id.  Idempotent on
        url (re-registering keeps the existing durable cursor).  A new
        subscriber's cursor defaults to 0 — full catch-up from the log's
        beginning; pass ``cursor`` to start elsewhere (e.g.
        ``latest_cursor()`` for new-alerts-only)."""
        if not url or "://" not in url:
            raise ValueError(f"subscriber url must be absolute, got {url!r}")
        with self._lock:
            con = self._con
            con.execute("BEGIN IMMEDIATE")
            try:
                con.execute(
                    "INSERT OR IGNORE INTO subscribers (url, cursor, "
                    "created) VALUES (?, ?, ?)",
                    (url, int(cursor or 0), _now_iso()))
                sid = con.execute(
                    "SELECT id FROM subscribers WHERE url = ?",
                    (url,)).fetchone()[0]
                con.execute("COMMIT")
            except BaseException:
                con.execute("ROLLBACK")
                raise
        return int(sid)

    def subscribers(self) -> list[dict]:
        latest = self.latest_cursor()
        with self._lock:
            rows = self._con.execute(
                "SELECT id, url, cursor, created, last_ok, failures "
                "FROM subscribers ORDER BY id").fetchall()
        return [{"id": int(i), "url": u, "cursor": int(c),
                 "lag": max(latest - int(c), 0), "created": cr,
                 "last_ok": ok, "failures": int(f)}
                for i, u, c, cr, ok, f in rows]

    def advance(self, sub_id: int, cursor: int) -> None:
        """Move a subscriber's durable delivery cursor FORWARD (a crashed
        deliverer restarting with stale state cannot rewind a successor's
        progress — the fencing discipline, cursor-shaped)."""
        with self._lock:
            self._con.execute(
                "UPDATE subscribers SET cursor = ?, last_ok = ?, "
                "failures = 0 WHERE id = ? AND cursor < ?",
                (int(cursor), _now_iso(), int(sub_id), int(cursor)))

    def record_failure(self, sub_id: int) -> None:
        with self._lock:
            self._con.execute(
                "UPDATE subscribers SET failures = failures + 1 "
                "WHERE id = ?", (int(sub_id),))

    def unsubscribe(self, sub_id: int) -> bool:
        with self._lock:
            cur = self._con.execute(
                "DELETE FROM subscribers WHERE id = ?", (int(sub_id),))
        return cur.rowcount > 0

    # -- operator surface ---------------------------------------------------

    def status(self) -> dict:
        """The alerts view: log depth, latest cursor, per-subscriber
        delivery lag — rendered by ``firebird status`` and the
        ``/progress`` alerts block."""
        return {
            "path": self.path,
            "depth": self.count(),
            "latest_cursor": self.latest_cursor(),
            "subscribers": self.subscribers(),
        }

    def close(self) -> None:
        with self._lock:
            self._con.close()
