"""Near-real-time change alerting (ROADMAP item 5, docs/ALERTS.md).

The loop that turns archive replay into a live land-change monitor:
the streaming driver appends confirmed tail breaks to a durable alert
log (alerts/log.py), the serving layer feeds them to consumers by
cursor pull, SSE push, and webhooks (alerts/feed.py), the fanout plane
shards delivery over the quadkey subscription index into idempotent
fleet jobs (alerts/subindex.py + alerts/fanout.py), and the flagged
pixels schedule their own cold-path batch repair on the fleet queue
(alerts/repair.py).
"""

from firebird_tpu.alerts.fanout import (FanoutCoordinator, FanoutDeliverer,
                                        rollup)
from firebird_tpu.alerts.feed import AlertFeed, WebhookDeliverer
from firebird_tpu.alerts.log import AlertLog, alert_db_path
from firebird_tpu.alerts.repair import repair_chip, schedule_repairs

__all__ = [
    "AlertFeed",
    "AlertLog",
    "FanoutCoordinator",
    "FanoutDeliverer",
    "WebhookDeliverer",
    "alert_db_path",
    "repair_chip",
    "rollup",
    "schedule_repairs",
]
