"""Automatic cold-path repair: broken pixels become fleet jobs.

A stream-confirmed break freezes the pixel (``StreamState.needs_batch``)
until a full batch rerun re-initializes a fresh segment after the break
— before this module that was a COUNT in the stream summary an operator
had to notice and act on.  Now the streaming driver rolls the flagged
pixels up per chip and enqueues idempotent ``repair`` jobs on the PR 9
fleet queue (fleet/plan.enqueue_repairs — at most one open job per
chip), and any ``firebird fleet work`` worker executes them through
:func:`repair_chip`:

- batch re-detection of the chip over the job's full acquired range,
  republished through the normal keyed-upsert save path (so the repair
  is byte-identical to what a scheduled cold-path rerun would write,
  magnitudes included);
- a FRESH stream checkpoint seeded from the batch result — break_day
  clears, the pixel is live again, and a SECOND break on the repaired
  tail alerts under its new break_day (the (pixel, break_day) dedup key
  treats it as a new event, not a duplicate).
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.obs import logger

log = logger("alerts")


def schedule_repairs(cfg, needs: dict, *, acquired: str,
                     run_id: str | None = None) -> list[int]:
    """Enqueue repair jobs for ``needs`` ({(cx, cy): flagged pixels});
    returns the new job ids.  Opens the config's fleet queue; a config
    with no file-backed queue location (memory store, no
    FIREBIRD_FLEET_DB) schedules nothing — the count-only summary still
    reports the debt."""
    from firebird_tpu.fleet.plan import enqueue_repairs
    from firebird_tpu.fleet.queue import FleetQueue, queue_path

    chips = {c: n for c, n in needs.items() if n > 0}
    if not chips:
        return []
    try:
        path = queue_path(cfg)
    except ValueError as e:
        log.warning("repair scheduling skipped: %s", e)
        return []
    queue = FleetQueue(path, lease_sec=cfg.fleet_lease_sec)
    try:
        return enqueue_repairs(queue, chips, acquired=acquired,
                               max_attempts=cfg.fleet_max_attempts,
                               run_id=run_id)
    finally:
        queue.close()


def repair_chip(cfg, cid, acquired: str, *, source=None, store=None,
                fence_guard=None) -> dict:
    """Cold-path repair of ONE chip: batch re-detection + fresh stream
    checkpoint.  Returns a summary (pixels re-flagged after the rerun is
    normally 0 — a still-breaking tail re-alerts on its next stream
    update instead).

    ``fence_guard``: zero-arg callable invoked immediately before the
    checkpoint save; the fleet worker passes a fence check that raises
    StaleFence so a zombie whose lease lapsed cannot overwrite a LIVE
    checkpoint with its stale seed (store writes are fenced by
    FencedStore; the .npz is the other output).  The check-then-write
    window is one atomic rename wide — the FencedStore discipline."""
    import jax.numpy as jnp

    from firebird_tpu import retry as retrylib
    from firebird_tpu.ccd import kernel
    from firebird_tpu.ccd.incremental import StreamState
    from firebird_tpu.driver import core as dcore
    from firebird_tpu.driver import stream as sdrv
    from firebird_tpu.ingest import pack
    from firebird_tpu.store import AsyncWriter, open_store
    from firebird_tpu.streamops import statestore as sstore_mod

    cx, cy = int(cid[0]), int(cid[1])
    source = source or dcore.make_source(cfg)
    own_store = store is None
    if store is None:
        store = open_store(cfg.store_backend, cfg.store_path,
                           cfg.keyspace())
    writer = AsyncWriter(store, retry=retrylib.RetryPolicy.for_store(cfg))
    try:
        chip = source.chip(cx, cy, acquired)
        if not chip.dates.shape[0]:
            raise ValueError(
                f"repair of chip ({cx},{cy}): no acquisitions in "
                f"{acquired}")
        packed = pack([chip], bucket=cfg.obs_bucket, max_obs=cfg.max_obs)
        # Synchronous single-chip dispatch, capacity check ON — the
        # stream bootstrap's kernel contract, so the republished rows
        # and the reseeded checkpoint match what a bootstrap would have
        # produced over the same range.
        seg, n_real = dcore.detect_batch(
            packed, jnp.float32, "off", check_capacity=True,
            compact=cfg.compact)
        host = dcore.fetch_results(seg)
        dcore.write_batch_frames(packed, host, n_real, writer=writer)
        one = kernel.chip_slice(host, 0)
        st = StreamState.from_chip(one)
        sday, curqa = sdrv._tail_identity(one)
        T = int(packed.n_obs[0])
        side = dict(sday=sday, curqa=curqa,
                    anchor=np.float64(packed.dates[0][0]),
                    horizon=np.float64(packed.dates[0][T - 1]))
        if fence_guard is not None:
            fence_guard()
        # Same checkpoint store as the stream driver (packed by default
        # — streamops/statestore.py): the check-then-write window is
        # one atomic slot publish wide, the FencedStore discipline.
        sstore = sstore_mod.open_statestore(cfg)
        try:
            sstore.save((cx, cy), st, side)
        finally:
            sstore.close()
        writer.flush()
        # Cross-process coherence (serve/changefeed.py): a repair
        # republishes the chip's segment rows but clears the break, so
        # no alert record announces it — the product_writes feed is how
        # serve replicas learn to drop their cached frames/rasters/
        # pyramid tiles for this chip.  Appended AFTER the flush: a
        # replica applying the record reads the repaired rows.
        from firebird_tpu.serve.changefeed import append_product_writes

        append_product_writes(cfg, "segment", [(cx, cy)])
        summary = {"chip": [cx, cy],
                   "obs": T,
                   "active": int(np.asarray(st.active).sum()),
                   "still_flagged": int(np.asarray(st.needs_batch).sum())}
        log.info("repaired chip (%d,%d): %s", cx, cy, summary)
        return summary
    finally:
        writer.close()
        if own_store:
            store.close()
