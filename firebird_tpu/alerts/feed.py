"""Alert feed: the consumer side of the durable alert log.

Three delivery modes over one cursor contract (alerts/log.py — the
record id is the cursor):

- **Pull** (``/v1/alerts?since=&bbox=&t0=&t1=``): a page of records past
  the caller's cursor plus the page's new cursor — poll-and-remember.
- **Push, SSE** (``/v1/alerts/stream``): a long-lived
  ``text/event-stream`` response where every event carries the record
  id as the SSE ``id:`` field, so a reconnecting client resumes with
  ``since=<last id>`` and misses nothing.  Mounted in serve/api.py over
  the shared httpd streaming support.
- **Push, webhooks**: registered subscriber URLs receive JSON batches
  POSTed by :class:`WebhookDeliverer`; each subscriber's durable cursor
  (in the alert db) advances only after a 2xx, so delivery crash-resumes
  from exactly the first undelivered record.  Transient delivery
  failures retry under the shared :class:`~firebird_tpu.retry.RetryPolicy`
  (decorrelated jitter — the batch drivers' machinery, not a bespoke
  loop).

docs/ALERTS.md has the record schema, cursor semantics, webhook
contract, and failure matrix.
"""

from __future__ import annotations

import json
import threading
import time
from firebird_tpu import retry as retrylib
from firebird_tpu.alerts.log import AlertLog
from firebird_tpu.obs import logger
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import spool as obs_spool
from firebird_tpu.obs import tracing

log = logger("alerts")

# Records per webhook POST: bounds one delivery's payload; the cursor
# makes multi-batch catch-up seamless.
WEBHOOK_BATCH = 500


def parse_bbox(raw: str):
    """``"minx,miny,maxx,maxy"`` -> 4-tuple of floats."""
    parts = raw.split(",")
    if len(parts) != 4:
        raise ValueError(f"bbox must be minx,miny,maxx,maxy, got {raw!r}")
    return tuple(float(p) for p in parts)


# Keep-alive connection pool for webhook POSTs, one per (thread,
# scheme, host): a delivery burst POSTs the same few endpoints
# thousands of times, and a fresh TCP connection per request triples
# the per-POST cost.  Thread-local because http.client connections
# are not thread-safe; deliverers are long-lived threads/processes.
_conn_pool = threading.local()


def _default_post(url: str, body: bytes, timeout: float) -> int:
    import http.client
    import urllib.parse

    u = urllib.parse.urlsplit(url)
    key = (u.scheme, u.netloc)
    conns = getattr(_conn_pool, "conns", None)
    if conns is None:
        conns = _conn_pool.conns = {}
    path = u.path or "/"
    if u.query:
        path += "?" + u.query
    for attempt in (0, 1):
        conn = conns.get(key)
        if conn is None:
            cls = (http.client.HTTPSConnection if u.scheme == "https"
                   else http.client.HTTPConnection)
            conn = conns[key] = cls(u.netloc, timeout=timeout)
        try:
            conn.request("POST", path, body,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            r.read()
            # A 4xx/5xx is an ANSWER, not a transport failure: return
            # the code so the cursor-hold branch handles it instead of
            # the retry loop hammering a permanent 404.
            return r.status
        except (http.client.HTTPException, OSError):
            # A stale kept-alive connection (server closed it between
            # bursts) fails exactly once: retry on a fresh one, and
            # only surface the second, genuine transport failure.
            conn.close()
            conns.pop(key, None)
            if attempt:
                raise
    raise AssertionError("unreachable")


class AlertFeed:
    """The serving layer's view of the alert log: pull pages, feed
    status, and an optional background webhook deliverer."""

    def __init__(self, alog: AlertLog, cfg=None, *, post=None, sleep=None):
        from firebird_tpu.config import Config

        self.log = alog
        self.cfg = cfg or Config.from_env()
        self.deliverer = WebhookDeliverer(alog, self.cfg, post=post,
                                          sleep=sleep)

    def pull(self, since: int = 0, *, limit: int = 1000, bbox=None,
             t0=None, t1=None) -> dict:
        """One page past ``since``: the records, the page's new cursor
        (== ``since`` when empty), and the log's latest cursor so a
        client can tell "caught up" from "more pages"."""
        recs = self.log.since(since, limit=limit, bbox=bbox, t0=t0, t1=t1)
        return {
            "alerts": recs,
            "cursor": recs[-1]["id"] if recs else int(since),
            "latest": self.log.latest_cursor(),
        }

    def status(self) -> dict:
        s = self.log.status()
        s["webhook_retries"] = obs_metrics.counter(
            "alert_webhook_retries",
            help="transient webhook-delivery failures retried").value
        return s

    def close(self) -> None:
        self.deliverer.stop()
        self.log.close()


class WebhookDeliverer:
    """Durable-cursor webhook delivery: for each subscriber, POST the
    records past its cursor in batches; advance the cursor only on 2xx.

    ``deliver_once`` is the synchronous unit (tests and the soak drive
    it directly); ``start``/``stop`` run it on a background poll thread
    for ``firebird serve``.  ``post`` is injectable for tests."""

    def __init__(self, alog: AlertLog, cfg, *, poll_sec: float = 1.0,
                 post=None, sleep=None):
        self.log = alog
        self.cfg = cfg
        self.poll_sec = float(poll_sec)
        self._post = post or _default_post
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # The drivers' transient-failure machinery, webhook-flavored —
        # but only ONE inline retry: the poll loop re-sweeps every
        # subscriber each tick anyway, so deep per-sweep backoff would
        # just let a dead receiver starve the healthy ones (delivery is
        # serial per sweep).  Transport errors only; a 4xx/5xx answer
        # comes back as a status code and holds the cursor instead.
        self.policy = retrylib.RetryPolicy(
            1, base=0.5, cap=2.0, sleep=sleep,
            counter_name="alert_webhook_retries",
            counter_help="transient webhook-delivery failures retried")

    def deliver_once(self, *, batch: int = WEBHOOK_BATCH,
                     max_batches: int | None = None) -> int:
        """One delivery sweep over every subscriber; returns records
        delivered.  A subscriber whose POST exhausts its retries keeps
        its cursor (and its place in line next sweep) — one dead
        receiver must not wedge the others.  ``max_batches`` caps the
        POSTs per subscriber per sweep (the soak uses it to leave a
        deliberate backlog for a successor incarnation to catch up)."""
        delivered = 0
        now = time.time()
        for sub in self.log.subscribers():
            # Head-of-line guard: a subscriber parked after consecutive
            # failures is skipped (cursor held) until its decorrelated
            # backoff elapses — one dead endpoint costs the sweep one
            # row check, not its retry budget every tick.
            if sub.get("parked_until") is not None \
                    and float(sub["parked_until"]) > now:
                obs_metrics.counter(
                    "alert_webhook_skipped_parked_total",
                    help="webhook sweep subscriber visits skipped while "
                         "parked after consecutive failures").inc()
                continue
            sent = 0
            while max_batches is None or sent < max_batches:
                recs = self.log.since(sub["cursor"], limit=batch)
                if not recs:
                    break
                sent += 1
                body = json.dumps({
                    "schema": "firebird-alert-webhook/1",
                    "cursor": recs[-1]["id"],
                    "alerts": recs,
                }).encode()
                # The causal chain's last hop: the batch's distinct trace
                # ids (stamped at append time) ride the deliver span and
                # the per-trace delivered marks, closing the scene ->
                # webhook path in the collected fleet trace.
                traces = sorted({r["trace"] for r in recs
                                 if r.get("trace")})
                dctx = tracing.from_wire(traces[0]) \
                    if len(traces) == 1 else None
                try:
                    with tracing.activate(dctx), tracing.span(
                            "deliver", subscriber=sub["id"],
                            records=len(recs)):
                        status = self.policy.run(
                            log, f"webhook {sub['url']}",
                            lambda b=body, u=sub["url"]: self._post(
                                u, b, self.cfg.alert_webhook_timeout))
                except Exception as e:
                    self._failed(sub, f"{type(e).__name__}: {e}")
                    break
                if not 200 <= int(status) < 300:
                    self._failed(sub, f"answered {status}")
                    break
                cursor = recs[-1]["id"]
                self.log.advance(sub["id"], cursor)
                sub = dict(sub, cursor=cursor)
                delivered += len(recs)
                for tr in traces:
                    obs_spool.mark("alert_delivered", trace=tr,
                                   subscriber=sub["id"], cursor=cursor)
                obs_metrics.counter(
                    "alert_webhook_delivered_total",
                    help="alert records delivered to webhook "
                         "subscribers (2xx-acknowledged)").inc(len(recs))
        return delivered

    def _failed(self, sub: dict, why: str) -> None:
        """One abandoned batch: count the failure and — once the
        subscriber hits ``fanout_park_after`` consecutive failures —
        park it under decorrelated backoff (the fanout plane's parking
        knobs; a 2xx heals).  The cursor always holds."""
        self.log.record_failure(
            sub["id"], park_after=self.cfg.fanout_park_after,
            base=self.cfg.fanout_park_base_sec,
            cap=self.cfg.fanout_park_cap_sec)
        obs_metrics.counter(
            "alert_webhook_failures_total",
            help="webhook batches abandoned after retries "
                 "(cursor held; redelivered next sweep)").inc()
        log.warning("webhook %s delivery failed (%s); cursor held at "
                    "%d", sub["url"], why, sub["cursor"])

    def start(self) -> "WebhookDeliverer":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="firebird-alert-webhooks",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_sec):
            try:
                self.deliver_once()
            except Exception as e:
                # The poll loop must survive a corrupt subscriber row or
                # a transient db error — delivery is retried next tick.
                log.error("webhook sweep failed (%s: %s)",
                          type(e).__name__, e)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
