"""Sharded alert fanout: rollup -> fleet jobs -> per-subscriber drain.

The delivery plane behind millions of subscribers (ROADMAP item 5,
docs/ALERTS.md "Fanout plane").  The flat WebhookDeliverer sweeps every
subscriber from one loop; this module splits delivery along the quadkey
shard key so it rides the fleet's elastic, crash-tolerant machinery:

- **Rollup** (:func:`rollup`): group the alerts past the durable rollup
  watermark by shard (``substr(qk, 1, prefix_len)`` — one SQL group-by,
  AlertLog.shards_since) and enqueue one idempotent ``fanout`` job per
  shard on the FleetQueue (plan.enqueue_fanout skips shards whose open
  job already covers the watermark).  :class:`FanoutCoordinator` runs
  this on a poll thread inside ``firebird serve``.
- **Drain** (:class:`FanoutDeliverer`): a fleet worker executing a
  shard's job loads the job window's alerts ONCE, resolves the
  window's audience through the quadkey cell index (plus the shard's
  straggler cursor rows), and serves each candidate from its durable
  per-(subscriber, shard) cursor — AOI post-filter, delivery policy
  (immediate | digest | batch), parking — POSTing under the webhook
  contract.  Cursors are forward-only (AlertLog.advance_fanout) and
  exist only mid-catch-up (a clean completion deletes the row; a held
  digest or failure pins it), so worker SIGKILL, lease re-delivery,
  and zombie/successor overlap re-deliver from the cursor without
  rewinding: at-least-once POSTs whose record ids give the receiver
  exactly-once records — the same contract the flat deliverer has,
  now per shard.  Webhook effects cannot be fenced (an HTTP POST is
  not a conditional write), which is why idempotence lives in the
  cursor + record-id contract rather than the queue's fencing tokens.

One shard job is O(window audience + stragglers + window alerts): the
quadkey index already paid the audience-resolution cost at
registration, so the drain never scans subscribers — a million quiet
subscriptions cost a burst nothing.
"""

from __future__ import annotations

import datetime
import json
import threading
import time

from firebird_tpu import retry as retrylib
from firebird_tpu.alerts import subindex
from firebird_tpu.alerts.feed import WEBHOOK_BATCH, _default_post
from firebird_tpu.alerts.log import MAX_PAGE, AlertLog
from firebird_tpu.obs import logger
from firebird_tpu.obs import metrics as obs_metrics

log = logger("fanout")


def _parse_ts(iso: str | None) -> float | None:
    if not iso:
        return None
    try:
        return datetime.datetime.fromisoformat(iso).timestamp()
    except ValueError:
        return None


class FanoutDeliverer:
    """Drains one shard's fanout job: the job window's cell-index
    audience plus the shard's stragglers advance from their durable
    per-shard cursors to the job's ``upto`` bound.  Synchronous and
    re-runnable — the fleet worker's ``fanout`` handler is one
    :meth:`drain_shard` call.  ``post``/``sleep``/``clock``/``rng``
    are injectable for tests."""

    def __init__(self, alog: AlertLog, cfg, *, post=None, sleep=None,
                 clock=time.time, rng=None):
        self.log = alog
        self.cfg = cfg
        self.clock = clock
        self.rng = rng
        self._post = post or _default_post
        # Same shallow transient-retry stance as the flat deliverer:
        # the shard job itself re-delivers on failure, so deep inline
        # backoff would only stall the rest of the shard.
        self.policy = retrylib.RetryPolicy(
            1, base=0.5, cap=2.0, sleep=sleep,
            counter_name="alert_webhook_retries",
            counter_help="transient webhook-delivery failures retried")

    # -- one shard ----------------------------------------------------------

    # Acknowledged-chunk cursor advances accumulate and flush every
    # this-many delivered subscribers (and at drain end): one durable
    # transaction per flush instead of one per POST.  A SIGKILL between
    # POST and flush redelivers at most this window's chunks — the
    # receiver's record-id dedup absorbs them (the documented
    # at-least-once-POST contract), and on a busy shard the per-chunk
    # transactions would otherwise dominate the drain.
    FLUSH_EVERY = 64

    def drain_shard(self, shard: str, upto: int, *, since: int = 0,
                    batch: int = WEBHOOK_BATCH) -> int:
        """Serve one fanout job: the alerts in the shard's window
        (``since`` — the rollup watermark the job rolled from — up to
        ``upto``) go to the job's CANDIDATES, the union of

        - the window's cell audience (AlertLog.audience_for_cells over
          every window alert's quadkey prefix chain), and
        - the shard's stragglers (cursor rows behind ``since`` —
          held digests, parked/failed subscribers, partial advances
          from a killed worker), caught up from their cursors.

        Cost is O(audience + stragglers + window alerts), never
        O(shard subscribers): a cursor row only exists mid-catch-up
        (clean completion deletes it), so a quiet subscriber costs
        nothing after registration.  Returns records delivered
        (counted once per subscriber).  Parked subscribers are skipped
        (their pinned row holds; a later job redelivers); one
        subscriber's failure parks it and moves on — never stalls the
        shard."""
        upto, since = int(upto), int(since)
        # The shard's drained watermark supersedes the job's stamped
        # window start: a re-rolled or duplicate job over a covered
        # window shrinks to the uncovered remainder (usually nothing).
        since = max(since, self.log.shard_drained(shard))
        stragglers = self.log.shard_straggler_rows(shard, since)
        floor = min((int(c) for _, c in stragglers), default=since)
        if floor >= upto:
            return 0
        alerts: list[dict] = []
        cur = floor
        while True:
            page = self.log.alerts_for_shard(shard, after=cur, upto=upto,
                                             limit=MAX_PAGE)
            if not page:
                break
            alerts.extend(page)
            cur = page[-1]["id"]
        strag_ids = [int(s) for s, _ in stragglers]
        if not alerts:
            # Window already covered (e.g. a duplicate job): nothing is
            # pending for the stragglers either — catch their rows up
            # cursor-only (not retire: a digest row's last_sent is its
            # window clock, and this path cannot see modes).
            self.log.advance_fanout_many(
                shard, [(s, upto) for s in strag_ids], [])
            self.log.set_shard_drained(shard, since, upto)
            return 0
        cells: set = set()
        for a in alerts:
            qk = a["qk"]
            for i in range(len(qk) + 1):
                cells.add(qk[:i])
        cand = self.log.audience_for_cells(cells)
        if strag_ids:
            cand = sorted(set(cand).union(strag_ids))
        rows = self.log.subscriber_rows_by_id(cand, shard)
        # An unsubscribe can orphan a straggler's cursor row; drop it.
        dangling = set(strag_ids) - {int(r[0]) for r in rows}
        if dangling:
            self.log.advance_fanout_many(shard, [], sorted(dangling))
        if not rows:
            # A window with no audience is drained by definition.
            self.log.set_shard_drained(shard, since, upto)
            return 0
        # Columns: (id, url, aoi_minx, aoi_miny, aoi_maxx, aoi_maxy,
        # mode, window_sec, max_n, parked_until, failures, cursor,
        # last_sent) — see AlertLog.subscriber_rows_by_id.
        # The (candidate x alert) match is one chunked boolean matrix —
        # even a per-subscriber numpy slice (let alone a Python bbox
        # test per pair) measurably dominates a busy drain.
        import numpy as np

        ids = np.array([a["id"] for a in alerts], dtype=np.int64)
        pxs = np.array([a["px"] for a in alerts], dtype=np.float64)
        pys = np.array([a["py"] for a in alerts], dtype=np.float64)
        # Each record is serialised ONCE per job; payload bodies are
        # assembled from these fragments (a regional alert lands in
        # hundreds of payloads — re-dumping it per subscriber is
        # measurable CPU across a burst).
        enc = [json.dumps(a) for a in alerts]
        sid = np.array([r[0] for r in rows], dtype=np.int64)
        # Cursor -1 means NO catch-up row: the subscriber is caught up
        # through the shard's drained watermark (retirement's
        # invariant), so its effective cursor is the window start —
        # never 0, which would re-deliver the covered past.
        curs_raw = np.array([r[11] for r in rows], dtype=np.int64)
        has_row = curs_raw >= 0
        curs = np.where(has_row, curs_raw, since)
        inf = float("inf")
        minx = np.array([-inf if r[2] is None else r[2] for r in rows])
        miny = np.array([-inf if r[3] is None else r[3] for r in rows])
        maxx = np.array([inf if r[4] is None else r[4] for r in rows])
        maxy = np.array([inf if r[5] is None else r[5] for r in rows])
        parked = np.array([0.0 if r[9] is None else float(r[9])
                           for r in rows])
        now = self.clock()
        parked_mask = parked > now
        n_parked = int(parked_mask.sum())
        if n_parked:
            obs_metrics.counter(
                "fanout_skipped_parked_total",
                help="shard-drain subscriber visits skipped because "
                     "the subscriber is parked after consecutive "
                     "failures").inc(n_parked)
        active = (curs < upto) & ~parked_mask
        # A digest subscriber's row is its window clock (last_sent says
        # when the previous digest went out): it is NEVER auto-deleted,
        # only pinned/advanced — retiring it would let the next burst
        # flush inside a still-open window.
        is_digest = np.array([r[6] == "digest" for r in rows],
                             dtype=bool)
        # Candidates already past the bound carry leftover rows (a
        # zombie's late re-insert, a crash between final ack and row
        # delete): complete them so the rows drop.
        stale = ~parked_mask & ~active & has_row & ~is_digest
        delivered = 0
        # A parked candidate with NO row must be pinned at its
        # effective cursor before the watermark covers this window —
        # otherwise its alerts vanish behind it while it backs off.
        advances: list = [(int(s), since)
                          for s in sid[parked_mask & ~has_row]]
        completes: list = list(sid[stale].tolist())  # rows to delete
        pending_subs = 0
        # Bound the boolean matrix at ~4M cells whatever the alert
        # window's size — a backlogged shard must not trade the Python
        # loop for an allocation spike.
        chunk = max(256, min(8192, 4_000_000 // len(alerts)))
        for s0 in range(0, len(rows), chunk):
            s1 = min(s0 + chunk, len(rows))
            act = active[s0:s1]
            if not act.any():
                continue
            m = ((ids[None, :] > curs[s0:s1, None])
                 & (pxs[None, :] >= minx[s0:s1, None])
                 & (pxs[None, :] <= maxx[s0:s1, None])
                 & (pys[None, :] >= miny[s0:s1, None])
                 & (pys[None, :] <= maxy[s0:s1, None])
                 & act[:, None])
            hit = m.any(axis=1)
            # Nothing in the window concerns these candidates: whatever
            # catch-up row brought them here is settled — delete it.
            completes.extend(sid[s0:s1][
                act & ~hit & has_row[s0:s1] & ~is_digest[s0:s1]
            ].tolist())
            # A no-hit digest row instead catches up cursor-only
            # (last_sent untouched) so it stops reading as a straggler.
            advances.extend(
                (int(s), upto) for s in sid[s0:s1][
                    act & ~hit & has_row[s0:s1] & is_digest[s0:s1]])
            for k in np.nonzero(hit)[0]:
                r = rows[s0 + int(k)]
                # The EFFECTIVE cursor (no-row sentinel already mapped
                # to the window start): pins written from it must never
                # rewind a subscriber to the covered past.
                sub = {"id": int(r[0]), "url": r[1], "mode": r[6],
                       "window_sec": r[7], "max_n": r[8],
                       "failures": int(r[10]),
                       "cursor": int(curs[s0 + int(k)]),
                       "last_sent": r[12]}
                mi = np.nonzero(m[k])[0]
                delivered += self._deliver_sub(
                    shard, sub, [alerts[j] for j in mi],
                    [enc[j] for j in mi], upto, batch, advances,
                    completes)
                pending_subs += 1
                if pending_subs >= self.FLUSH_EVERY:
                    self.log.advance_fanout_many(shard, advances,
                                                 completes)
                    advances, completes, pending_subs = [], [], 0
        self.log.advance_fanout_many(shard, advances, completes)
        # The whole window was offered to its whole audience (anyone
        # still behind holds a pinned row): advance the watermark so a
        # duplicate job no-ops and future no-row candidates start here.
        # (Contiguity-guarded — see set_shard_drained: a newer window
        # completing ahead of an in-flight older one must not cover it.)
        self.log.set_shard_drained(shard, since, upto)
        return delivered

    def _deliver_sub(self, shard: str, sub: dict, matched: list[dict],
                     enc: list[str], upto: int, batch: int,
                     advances: list, completes: list) -> int:
        """One subscriber's drain to ``upto``: policy-shaped POSTs with
        the cursor advanced past each acknowledged chunk, then the
        catch-up row retired via ``completes`` once everything matched
        is out — nothing else in the window concerns this subscriber,
        and with no row left only the audience probe ever visits it
        again.  A held digest or a failure instead PINS the row at the
        current cursor so the straggler probe finds it, and a FLUSHED
        digest keeps its row too (advanced to ``upto``): last_sent is
        the digest window's clock.  ``enc`` holds
        the matched records pre-serialised (one json.dumps per record
        per job, however many payloads it lands in); advances land on
        ``advances`` for the caller's batched flush (see FLUSH_EVERY),
        not as per-chunk transactions."""
        mode = sub["mode"] or "immediate"
        if mode == "digest":
            window = float(sub["window_sec"] or 0.0)
            last = sub["last_sent"]
            if last is not None and self.clock() - float(last) < window:
                # Window still open: pin the cursor row so a later
                # job's straggler probe flushes the digest once the
                # window elapses.
                advances.append((sub["id"], sub["cursor"]))
                return 0
            chunks = [list(range(len(matched)))]
            schema = "firebird-alert-digest/1"
        else:
            size = batch if mode == "immediate" \
                else max(1, min(int(sub["max_n"]), batch))
            chunks = [list(range(i, min(i + size, len(matched))))
                      for i in range(0, len(matched), size)]
            schema = "firebird-alert-webhook/1"
        sent = 0
        for i, chunk in enumerate(chunks):
            cursor = upto if i == len(chunks) - 1 \
                else matched[chunk[-1]]["id"]
            body = ('{"schema": "%s", "shard": "%s", "cursor": %d, '
                    '"count": %d, "alerts": [%s]}'
                    % (schema, shard, cursor, len(chunk),
                       ", ".join(enc[j] for j in chunk))).encode()
            try:
                status = self.policy.run(
                    log, f"fanout {sub['url']}",
                    lambda b=body, u=sub["url"]: self._post(
                        u, b, self.cfg.alert_webhook_timeout))
            except Exception as e:
                self._flush_then_fail(shard, advances, completes, sub,
                                      f"{type(e).__name__}: {e}")
                return sent
            if not 200 <= int(status) < 300:
                self._flush_then_fail(shard, advances, completes, sub,
                                      f"answered {status}")
                return sent
            now = self.clock()
            advances.append((sub["id"], cursor, now))
            sent += len(chunk)
            obs_metrics.counter(
                "fanout_delivered_total",
                help="alert records delivered by shard fanout jobs "
                     "(2xx-acknowledged)").inc(len(chunk))
            oldest = min((t for t in (_parse_ts(matched[j].get(
                "detected_at")) for j in chunk) if t is not None),
                default=None)
            if oldest is not None:
                obs_metrics.histogram(
                    "alert_delivery_lag_seconds",
                    help="alert age at fanout delivery (append to "
                         "2xx-acknowledged POST, per chunk's oldest "
                         "record)").observe(max(now - oldest, 0.0))
        if mode != "digest":
            # Fully served: retire the catch-up row.  A digest row
            # stays — its last_sent is the window clock for the next
            # burst (the final advance above left it at ``upto``).
            completes.append(sub["id"])
        return sent

    def _flush_then_fail(self, shard: str, advances: list,
                         completes: list, sub: dict, why: str) -> None:
        """Pin the failed subscriber's cursor row (so the straggler
        probe redelivers it) and flush the pending advances BEFORE
        recording the failure: the batch may heal this subscriber for
        chunks acknowledged earlier in this very drain, and healing
        must not wipe the failure that just happened."""
        advances.append((sub["id"], sub["cursor"]))
        self.log.advance_fanout_many(shard, advances, completes)
        advances.clear()
        completes.clear()
        self._failed(sub, why)

    def _failed(self, sub: dict, why: str) -> None:
        delay = self.log.record_failure(
            sub["id"], park_after=self.cfg.fanout_park_after,
            base=self.cfg.fanout_park_base_sec,
            cap=self.cfg.fanout_park_cap_sec, rng=self.rng,
            clock=self.clock)
        obs_metrics.counter(
            "fanout_failures_total",
            help="fanout POSTs abandoned after retries (cursor held; "
                 "redelivered by a later job)").inc()
        if delay is not None:
            obs_metrics.counter(
                "fanout_parked_total",
                help="subscribers parked under decorrelated backoff "
                     "after consecutive delivery failures").inc()
        log.warning(
            "fanout to %s failed (%s); cursor held%s", sub["url"], why,
            f", parked {delay:.1f}s" if delay is not None else "")


# -- rollup (alerts -> fanout jobs) -----------------------------------------


def rollup(alog: AlertLog, queue, cfg, *, run_id: str | None = None,
           clock=time.time) -> list[int]:
    """One rollup pass: turn the quadkey-stamped alerts past the
    durable watermark into per-shard ``fanout`` jobs; returns the new
    job ids.  The watermark advances only AFTER the jobs are enqueued —
    a crash between group-by and enqueue re-rolls the same alerts, and
    the open-job skip plus forward-only delivery cursors make the
    duplicate harmless (at-least-once rollup, exactly-once records)."""
    from firebird_tpu.fleet import plan

    start = alog.rollup_cursor()
    shards = alog.shards_since(start, cfg.fanout_shard_prefix)
    if not shards:
        return []
    ids = plan.enqueue_fanout(queue, shards, run_id=run_id,
                              rolled_at=clock())
    alog.set_rollup_cursor(max(s["upto"] for s in shards))
    return ids


class FanoutCoordinator:
    """The standing rollup loop ``firebird serve`` runs next to the
    webhook deliverer: poll the log, enqueue shard jobs, let the fleet
    deliver.  Crash-safe by construction — all state is the durable
    watermark + queue."""

    def __init__(self, alog: AlertLog, queue, cfg, *,
                 run_id: str | None = None):
        self.log = alog
        self.queue = queue
        self.cfg = cfg
        self.run_id = run_id
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def poll_once(self) -> list[int]:
        return rollup(self.log, self.queue, self.cfg,
                      run_id=self.run_id)

    def start(self) -> "FanoutCoordinator":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="firebird-fanout-rollup",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.fanout_poll_sec):
            try:
                self.poll_once()
            except Exception as e:
                # The rollup loop must outlive transient db/queue
                # hiccups — the watermark makes the next tick resume.
                log.error("fanout rollup failed (%s: %s)",
                          type(e).__name__, e)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
