"""Quadkey subscription index: spatial audience resolution in O(levels).

The flat subscriber table scales delivery O(subscribers) per alert — a
bbox test against every registered row.  This module is the spatial
half of the fanout plane (docs/ALERTS.md "Fanout plane"): a subscriber
AOI is decomposed ONCE, at registration, into a small covering set of
quadkey cells (serve/pyramid.py's Bing-style scheme over the Albers
chip grid — base level Z_BASE, one base tile == one chip), and an
alert point resolves its audience by looking up the O(Z_BASE) quadkeys
on its ancestor chain instead of scanning subscribers:

- **Registration** (:func:`cover_bbox`): descend from the AOI's deepest
  single ancestor tile, emitting a tile when it is fully inside the
  bbox or the cell budget is reached — CONUS-wide AOIs register a few
  COARSE cells, chip-sized AOIs one BASE cell, and every AOI costs at
  most ``max_cells`` index rows regardless of area.
- **Resolution** (:func:`point_cells`): the alert pixel's base quadkey
  and every prefix of it (root included).  A covering cell contains the
  point iff it IS one of those prefixes, so audience lookup is one
  ``cell IN (12 quadkeys)`` probe of the ``subscription_cells`` table —
  independent of subscriber count.

Covering cells may overhang the exact bbox (partial base cells, budget
coalescing), so resolution post-filters candidates against the exact
AOI stored on the subscriber row; the contract — index audience ==
brute-force bbox scan — is pinned by tests/test_fanout.py's property
test.  The root quadkey is the empty string: a subscriber with NO AOI
registers the root cell and matches everywhere (every point's prefix
chain starts at "").

Points or AOIs outside the quadkey domain (off the CONUS chip grid's
[0, 2**Z_BASE) index range) cannot be spatially indexed: such AOIs get
no cells (they contain no indexable point) and such alerts resolve to
root-cell (global) subscribers only — the same answer the pyramid
gives (it cannot address those chips either).
"""

from __future__ import annotations

# Deepest quadkey level (== serve.pyramid.Z_BASE; one base tile is one
# chip).  Redeclared here so config validation and the alert log do not
# drag the pyramid's numpy/raster stack into import time — pinned equal
# by tests/test_fanout.py.
Z_BASE = 11

# Default AOI covering budget (FIREBIRD_FANOUT_MAX_CELLS): the most
# index rows one registration may cost.  64 coarse-to-base cells cover
# any rectangle with < one tile-width of overhang per edge.
MAX_CELLS = 64


def base_quadkey(cx: float, cy: float) -> str | None:
    """The base-level quadkey of chip (cx, cy) — the alert log stamps
    this on every record so shard rollup is a substr() group-by.  None
    for chips outside the quadkey domain (they fan out through the
    legacy whole-log deliverer only)."""
    from firebird_tpu.serve import pyramid as pyr

    try:
        x, y = pyr.tile_of_chip(cx, cy)
    except ValueError:
        return None
    return pyr.quadkey(Z_BASE, x, y)


def point_cells(px: float, py: float) -> list[str]:
    """Every quadkey whose tile contains projection point (px, py):
    the base tile's quadkey and all its prefixes, root ("") first —
    the O(levels) lookup set of audience resolution.  Out-of-domain
    points degrade to the root cell alone (global subscribers)."""
    from firebird_tpu.serve import pyramid as pyr

    try:
        x, y = pyr.tile_for_point(px, py, Z_BASE)
    except ValueError:
        return [""]
    qk = pyr.quadkey(Z_BASE, x, y)
    return [qk[:i] for i in range(Z_BASE + 1)]


def _extent(z: int, x: int, y: int) -> tuple[float, float, float, float]:
    from firebird_tpu.serve import pyramid as pyr

    e = pyr.tile_extent(z, x, y)
    return e["ulx"], e["lry"], e["lrx"], e["uly"]     # minx,miny,maxx,maxy


def cover_bbox(bbox, max_cells: int = MAX_CELLS) -> list[str]:
    """A covering quadkey cell set for projection bbox (minx, miny,
    maxx, maxy): at most ``max_cells`` cells whose union contains every
    in-domain point of the bbox.  Cells are emitted coarse where the
    bbox fully contains a tile (or the budget forces coalescing) and at
    the base level otherwise — the overhang is post-filtered at
    resolution time by the exact AOI.  Empty when the bbox misses the
    quadkey domain entirely."""
    from firebird_tpu import grid
    from firebird_tpu.serve import pyramid as pyr

    minx, miny, maxx, maxy = (float(v) for v in bbox)
    if minx > maxx or miny > maxy:
        raise ValueError(f"bbox must be minx,miny,maxx,maxy with "
                         f"min <= max, got {bbox!r}")
    if max_cells < 4:
        raise ValueError(f"max_cells must be >= 4, got {max_cells}")
    dminx, dminy, dmaxx, dmaxy = _extent(0, 0, 0)
    if minx > dmaxx or maxx < dminx or miny > dmaxy or maxy < dminy:
        return []
    # Clamp the corner chip indices into the domain, then start the
    # descent at the corners' deepest common ancestor — a chip-sized
    # AOI costs ~Z_BASE quadkey digits of shared prefix, not a walk
    # from the root.
    g = grid.CONUS.chip
    lim = (1 << Z_BASE) - 1
    h0, v0 = grid.grid_pt(max(minx, dminx), min(maxy, dmaxy), g)
    h1, v1 = grid.grid_pt(min(maxx, dmaxx), max(miny, dminy), g)
    h0, v0 = min(max(h0, 0), lim), min(max(v0, 0), lim)
    h1, v1 = min(max(h1, 0), lim), min(max(v1, 0), lim)
    qk0 = pyr.quadkey(Z_BASE, h0, v0)
    qk1 = pyr.quadkey(Z_BASE, h1, v1)
    n = 0
    while n < Z_BASE and qk0[n] == qk1[n]:
        n += 1
    z0, x0, y0 = pyr.tile_from_quadkey(qk0[:n])
    out: list[str] = []
    queue: list[tuple[int, int, int]] = [(z0, x0, y0)]
    while queue:
        z, x, y = queue.pop()
        tminx, tminy, tmaxx, tmaxy = _extent(z, x, y)
        if tminx > maxx or tmaxx < minx or tminy > maxy or tmaxy < miny:
            continue
        inside = (tminx >= minx and tmaxx <= maxx
                  and tminy >= miny and tmaxy <= maxy)
        # Budget rule: emitting this tile COARSE (overhang and all)
        # keeps the total at most max_cells; splitting must leave room
        # for this tile's four children plus everything still queued.
        if inside or z == Z_BASE \
                or len(out) + len(queue) + 4 > max_cells:
            out.append(pyr.quadkey(z, x, y))
        else:
            queue.extend(pyr.children(z, x, y))
    return sorted(out)


def shard_of(qk: str, prefix_len: int) -> str:
    """The fanout shard of a base quadkey: its leading ``prefix_len``
    digits (the quadkey-prefix shard key — docs/ALERTS.md)."""
    return qk[:max(int(prefix_len), 0)]


def shard_prefixes(shard: str) -> list[str]:
    """The PROPER prefixes of a shard key, root first — the coarse
    cells whose subscribers also belong to the shard (a CONUS-wide
    cell at z=1 intersects every deeper shard under it).  The shard
    itself and its descendants match by ``LIKE shard || '%'``."""
    return [shard[:i] for i in range(len(shard))]


def aoi_contains(aoi, px: float, py: float) -> bool:
    """Exact post-filter: True when ``aoi`` (a 4-tuple or None) is
    global or contains the point — the closed-interval rule the alert
    log's ``since(bbox=...)`` filter uses."""
    if aoi is None:
        return True
    minx, miny, maxx, maxy = aoi
    return minx <= px <= maxx and miny <= py <= maxy
