"""jax-hotpath rules: no host syncs or Python branching in traced code.

The contract: code that executes under a ``jax.jit`` trace, a ``lax``
control-flow body, or a Pallas kernel must stay on the device.  A
``.item()`` / ``device_get`` / ``np.asarray`` on a tracer either crashes
at trace time or — worse — silently forces a host sync per dispatch; a
Python ``if`` on a traced value bakes one branch into the compiled
program.  And the jit/AOT seam has its own drift mode (the PR 6
near-bug): ``kernel.aot_compile`` passes static kwargs to ``.lower()``
by hand, so a static added to ``_WIRE_STATICS`` but not to the AOT call
site makes every warm-start compile key miss silently.

Traced code is found statically, per module:

- functions decorated with ``jax.jit`` / ``partial(jax.jit, ...)``,
- functions wrapped by a ``name = jax.jit(fn, ...)`` assignment,
- functions (or lambdas) passed to ``lax`` control flow
  (``while_loop``/``cond``/``scan``/``fori_loop``/``switch``),
  ``pallas_call``, ``vmap``/``pmap``/``shard_map``/``checkpoint``,
- functions defined inside, or called by bare name from, any of the
  above (transitive, same module).

For directly-jitted functions the ``static_argnames``/``static_argnums``
set is resolved (including through a module-level tuple like
``_WIRE_STATICS``), so branching on a *static* argument is — correctly —
not a finding.  Transitively-traced helpers have unknown staticness and
only get the unambiguous host-sync checks; branching there is the
developer's call (document with a suppression if a checker ever grows
into it).
"""

from __future__ import annotations

import ast

from firebird_tpu.analysis.engine import LintContext, SourceFile, rule

# Call wrappers whose function-valued arguments execute traced.
TRACING_WRAPPERS = {"while_loop", "cond", "scan", "fori_loop", "switch",
                    "pallas_call", "vmap", "pmap", "shard_map",
                    "checkpoint", "remat"}

# Zero-arg attribute calls that force (or imply) a device->host sync.
HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}

# Attribute accesses on a traced value that are static at trace time —
# branching on these is legitimate shape/dtype dispatch, not a traced
# branch.
STATIC_VALUE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}

# numpy conversion entry points that materialize their argument on host.
NP_CONVERTERS = {"asarray", "array", "copy", "ascontiguousarray"}

CASTS = {"float", "int", "bool"}


class TracedFn:
    """One function body that executes under a trace."""

    def __init__(self, node, reason: str, static: set[str] | None,
                 statics_known: bool):
        self.node = node                    # FunctionDef / Lambda
        self.reason = reason                # "jit" | "wrapper" | "reach"
        self.static = static or set()
        # True when the static-arg set is authoritative (a jit site we
        # resolved, or a control-flow body where every param is traced).
        self.statics_known = statics_known

    @property
    def params(self) -> set[str]:
        a = self.node.args
        names = [p.arg for p in
                 list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)

    @property
    def traced_params(self) -> set[str]:
        return self.params - self.static


class ModuleScan:
    """Per-module alias/def/jit-site inventory."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.np_aliases: set[str] = set()
        self.jit_names: set[str] = set()          # from jax import jit
        self.defs: dict[str, ast.AST] = {}        # name -> innermost def
        self.str_tuples: dict[str, tuple[str, ...]] = {}
        # wrapped function name -> [(statics or None, call node)]
        self.jit_sites: dict[str, list] = {}
        # assigned wrapper name -> statics (from `w = jax.jit(f, ...)`)
        self.wrapper_statics: dict[str, set[str] | None] = {}
        self._scan_imports()
        self._scan_defs()
        self._scan_tuples()

    def _scan_imports(self) -> None:
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        self.np_aliases.add(a.asname or "numpy")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "jit":
                            self.jit_names.add(a.asname or "jit")

    def _scan_defs(self) -> None:
        for node in ast.walk(self.src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node

    def _scan_tuples(self) -> None:
        for node in self.src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                elts = node.value.elts
                if elts and all(isinstance(e, ast.Constant)
                                and isinstance(e.value, str) for e in elts):
                    self.str_tuples[node.targets[0].id] = tuple(
                        e.value for e in elts)

    # -- jit expression recognition ----------------------------------------

    def is_jit_expr(self, node: ast.AST) -> bool:
        """``jax.jit`` (or an imported ``jit``) as a bare reference."""
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return True
        return isinstance(node, ast.Name) and node.id in self.jit_names

    def jit_call_statics(self, call: ast.Call,
                         fn_node=None) -> set[str] | None:
        """The static-arg name set a ``jax.jit(...)`` call declares, or
        None when it cannot be resolved statically."""
        statics: set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                names = self._resolve_names(kw.value)
                if names is None:
                    return None
                statics |= names
            elif kw.arg == "static_argnums":
                if fn_node is None:
                    return None
                nums = self._resolve_nums(kw.value)
                if nums is None:
                    return None
                a = fn_node.args
                pos = [p.arg for p in list(a.posonlyargs) + list(a.args)]
                for i in nums:
                    if 0 <= i < len(pos):
                        statics.add(pos[i])
                    else:
                        return None
        return statics

    def _resolve_names(self, node: ast.AST) -> set[str] | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return {node.value}
        if isinstance(node, (ast.Tuple, ast.List)):
            out = set()
            for e in node.elts:
                got = self._resolve_names(e)
                if got is None:
                    return None
                out |= got
            return out
        if isinstance(node, ast.Name) and node.id in self.str_tuples:
            return set(self.str_tuples[node.id])
        return None

    @staticmethod
    def _resolve_nums(node: ast.AST) -> list[int] | None:
        try:
            v = ast.literal_eval(node)
        except (ValueError, SyntaxError):
            return None
        if isinstance(v, int):
            return [v]
        if isinstance(v, (tuple, list)) \
                and all(isinstance(i, int) for i in v):
            return list(v)
        return None

    def decorator_statics(self, fn) -> tuple[bool, set[str] | None]:
        """(is_jitted, statics) for a function's decorator list."""
        for dec in fn.decorator_list:
            if self.is_jit_expr(dec):
                return True, set()
            if isinstance(dec, ast.Call):
                if self.is_jit_expr(dec.func):
                    return True, self.jit_call_statics(dec, fn)
                # functools.partial(jax.jit, ...) / partial(jax.jit, ...)
                f = dec.func
                is_partial = (isinstance(f, ast.Name) and f.id == "partial") \
                    or (isinstance(f, ast.Attribute) and f.attr == "partial")
                if is_partial and dec.args \
                        and self.is_jit_expr(dec.args[0]):
                    return True, self.jit_call_statics(dec, fn)
        return False, None


def _collect_traced(scan: ModuleScan) -> dict[int, TracedFn]:
    """id(def-node) -> TracedFn for every traced body in the module."""
    traced: dict[int, TracedFn] = {}

    def add(node, reason, static, known):
        if id(node) not in traced:
            traced[id(node)] = TracedFn(node, reason, static, known)
            return True
        return False

    # 1. decorated defs
    for fn in scan.defs.values():
        jitted, statics = scan.decorator_statics(fn)
        if jitted:
            add(fn, "jit", statics, statics is not None)

    # 2. jax.jit(fn, ...) call sites + wrapper-name assignments
    for node in ast.walk(scan.src.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and scan.is_jit_expr(node.value.func):
            call = node.value
            wrapped = call.args[0] if call.args else None
            fn = scan.defs.get(wrapped.id) \
                if isinstance(wrapped, ast.Name) else None
            statics = scan.jit_call_statics(call, fn)
            scan.wrapper_statics[node.targets[0].id] = statics
            if isinstance(wrapped, ast.Name):
                scan.jit_sites.setdefault(wrapped.id, []).append(
                    (statics, call))
                if fn is not None:
                    add(fn, "jit", statics, statics is not None)

    # 3. control-flow / pallas wrapper arguments: every param is traced
    for node in ast.walk(scan.src.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in TRACING_WRAPPERS:
            continue
        cands = list(node.args)
        for a in list(cands):
            if isinstance(a, (ast.Tuple, ast.List)):
                cands.extend(a.elts)
        for a in cands:
            if isinstance(a, ast.Lambda):
                add(a, "wrapper", set(), True)
            elif isinstance(a, ast.Name) and a.id in scan.defs:
                add(scan.defs[a.id], "wrapper", set(), True)

    # 4. transitive closure: nested defs and same-module callees of
    #    traced bodies are traced (unknown staticness: host-sync only).
    work = list(traced.values())
    while work:
        tf = work.pop()
        for node in ast.walk(tf.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not tf.node:
                if add(node, "reach", None, False):
                    work.append(traced[id(node)])
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in scan.defs:
                callee = scan.defs[node.func.id]
                if add(callee, "reach", None, False):
                    work.append(traced[id(callee)])
    return traced


# -- per-body checks --------------------------------------------------------

def _own_nodes(fn) -> list[ast.AST]:
    """Statements of ``fn`` excluding nested function/lambda bodies (they
    are traced bodies of their own and checked separately)."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        out.append(n)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def _subtree_names(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _branch_names(test: ast.AST) -> set[str]:
    """Names in a branch test that would make it a traced branch —
    occurrences under static accessors (``.shape``/``.dtype``/...,
    ``len()``, ``isinstance()``, ``is``/``is not`` comparisons) pruned."""
    if isinstance(test, ast.Attribute) and test.attr in STATIC_VALUE_ATTRS:
        return set()
    if isinstance(test, ast.Call) and isinstance(test.func, ast.Name) \
            and test.func.id in ("len", "isinstance", "hasattr",
                                 "getattr", "callable"):
        return set()
    if isinstance(test, ast.Compare) \
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
        return set()
    names = {test.id} if isinstance(test, ast.Name) else set()
    for child in ast.iter_child_nodes(test):
        names |= _branch_names(child)
    return names


def _check_body(ctx: LintContext, src: SourceFile, scan: ModuleScan,
                tf: TracedFn) -> None:
    traced_params = tf.traced_params
    for node in _own_nodes(tf.node):
        # host syncs ----------------------------------------------------
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in HOST_SYNC_ATTRS and not node.args:
                    ctx.emit("hotpath-host-sync", src, node.lineno,
                             f".{f.attr}() inside traced code forces a "
                             "device->host sync")
                    continue
                if f.attr == "device_get":
                    ctx.emit("hotpath-host-sync", src, node.lineno,
                             "device_get inside traced code forces a "
                             "device->host transfer")
                    continue
                if isinstance(f.value, ast.Name) \
                        and f.value.id in scan.np_aliases \
                        and f.attr in NP_CONVERTERS \
                        and node.args \
                        and _subtree_names(node.args[0]) & traced_params:
                    ctx.emit("hotpath-host-sync", src, node.lineno,
                             f"np.{f.attr} on a traced argument "
                             "materializes it on host (use jnp)")
                    continue
            elif isinstance(f, ast.Name):
                if f.id == "device_get":
                    ctx.emit("hotpath-host-sync", src, node.lineno,
                             "device_get inside traced code forces a "
                             "device->host transfer")
                    continue
                if tf.statics_known and f.id in CASTS \
                        and len(node.args) == 1 \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in traced_params:
                    ctx.emit("hotpath-host-sync", src, node.lineno,
                             f"{f.id}() on traced argument "
                             f"{node.args[0].id!r} concretizes a tracer")
                    continue
        # traced branches -----------------------------------------------
        if tf.statics_known and isinstance(node, (ast.If, ast.While)):
            hits = _branch_names(node.test) & traced_params
            if hits:
                ctx.emit("hotpath-traced-branch", src, node.lineno,
                         "Python branch on traced argument(s) "
                         f"{', '.join(sorted(hits))} — use lax.cond/"
                         "jnp.where or declare the arg static")


# -- statics drift ----------------------------------------------------------

def _check_statics(ctx: LintContext, src: SourceFile,
                   scan: ModuleScan) -> None:
    # (a) every jit site wrapping the same function agrees on statics
    for name, sites in sorted(scan.jit_sites.items()):
        known = [(s, c) for s, c in sites if s is not None]
        if len(known) > 1:
            first, _ = known[0]
            for statics, call in known[1:]:
                if statics != first:
                    ctx.emit(
                        "hotpath-statics-drift", src, call.lineno,
                        f"jit of {name!r} declares statics "
                        f"{sorted(statics)} but an earlier site "
                        f"declares {sorted(first)} — AOT cache keys "
                        "will miss")
        # (b) declared statics must be real parameters
        fn = scan.defs.get(name)
        if fn is None:
            continue
        params = TracedFn(fn, "jit", set(), True).params
        for statics, call in known:
            ghost = statics - params
            if ghost:
                ctx.emit("hotpath-statics-drift", src, call.lineno,
                         f"static_argnames {sorted(ghost)} are not "
                         f"parameters of {name!r}")

    # (c) .lower(...) AOT call sites pass exactly the wrapper's statics
    for node in ast.walk(src.tree):
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))):
            continue
        local_env: dict[str, list[str]] = {}
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                v = stmt.value
                if isinstance(v, ast.Name):
                    local_env[stmt.targets[0].id] = [v.id]
                elif isinstance(v, ast.IfExp) \
                        and isinstance(v.body, ast.Name) \
                        and isinstance(v.orelse, ast.Name):
                    local_env[stmt.targets[0].id] = [v.body.id,
                                                     v.orelse.id]
        for call in ast.walk(node):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "lower"
                    and isinstance(call.func.value, ast.Name)):
                continue
            base = call.func.value.id
            cands = local_env.get(base, [base])
            statics_sets = [scan.wrapper_statics[c] for c in cands
                            if c in scan.wrapper_statics]
            if len(statics_sets) != len(cands) \
                    or any(s is None for s in statics_sets):
                continue   # not (all) jit wrappers, or unresolvable
            want = statics_sets[0]
            if any(s != want for s in statics_sets[1:]):
                continue   # drift already reported at the jit sites
            got = {kw.arg for kw in call.keywords if kw.arg is not None}
            if got != want:
                missing = sorted(want - got)
                extra = sorted(got - want)
                detail = "; ".join(
                    p for p in (f"missing {missing}" if missing else "",
                                f"extra {extra}" if extra else "") if p)
                ctx.emit("hotpath-statics-drift", src, call.lineno,
                         f"AOT .lower() kwargs disagree with the jit "
                         f"wrapper's static set ({detail}) — the warm "
                         "entry will never match a real dispatch")


@rule("jax-hotpath", {
    "hotpath-host-sync":
        "host-sync call (.item/device_get/np.asarray/float-cast) inside "
        "traced code",
    "hotpath-traced-branch":
        "Python if/while on a traced (non-static) argument inside "
        "traced code",
    "hotpath-statics-drift":
        "jit/AOT static-arg sets disagree (or name ghost parameters)",
})
def check_hotpath(ctx: LintContext) -> None:
    for src in ctx.sources:
        scan = ModuleScan(src)
        traced = _collect_traced(scan)
        for tf in traced.values():
            _check_body(ctx, src, scan, tf)
        if scan.jit_sites or scan.wrapper_statics:
            _check_statics(ctx, src, scan)
