"""thread-ownership rules: ``# guarded-by: <lock>`` annotated state.

The prefetch/drain/writer/watchdog/serve threads share mutable state
(writer error slots, watchdog beat records, serve flight tables, cache
LRU maps, the warm-compile singleton) that until this PR was guarded
only by convention — the lock discipline lived in comments a refactor
could silently break.  The convention is now machine-checked:

- ``self._attr = ...  # guarded-by: _lock`` in ``__init__`` (or a
  class-body annotation) declares that every later access of
  ``self._attr`` — read or write — must happen inside a
  ``with self._lock:`` block.  Methods whose name ends in ``_locked``,
  or whose ``def`` line carries its own ``# guarded-by: <lock>``
  annotation, are caller-holds-the-lock helpers and are exempt (their
  call sites are checked instead, being ordinary accesses).
- ``_global = ...  # guarded-by: _lock`` at module level declares that
  every *mutation* of the global from function code must happen inside
  ``with _lock:``.  Reads are deliberately not checked: swapping or
  reading one reference is atomic under the GIL, and the repo's
  hot-path pattern (obs.server.current, native._load's double-checked
  fast path) reads lock-free on purpose — the lock orders writers.
- Any *unannotated* module-global mutation (``global x; x = ...``)
  from inside a function is flagged unless it happens under some
  ``with`` lock: the driver's prefetch/drain/watchdog threads can reach
  most module code, so an unsynchronized global latch is a data race
  until someone either takes a lock, annotates the global, or
  suppresses the line with a reason.
"""

from __future__ import annotations

import ast

from firebird_tpu.analysis.engine import LintContext, SourceFile, rule

# A with-held lock, as (scope, name): ("self", "_lock") for
# ``with self._lock:``; ("mod", "_lock") for ``with _lock:``.
Lock = tuple


def _withitem_locks(node: ast.With) -> set[Lock]:
    locks: set[Lock] = set()
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
                and e.value.id == "self":
            locks.add(("self", e.attr))
        elif isinstance(e, ast.Name):
            locks.add(("mod", e.id))
    return locks


def _def_line_annotation(src: SourceFile, fn) -> str | None:
    """A ``# guarded-by:`` annotation on the signature lines of ``fn`` —
    strictly BEFORE the first body statement's line, or an annotation on
    a method's first statement would exempt the whole method instead of
    declaring that statement's lock.  (A one-line ``def f(): stmt`` has
    no separate signature line; only the def line itself counts then.)"""
    first_body = fn.body[0].lineno if fn.body else fn.lineno + 1
    for line in range(fn.lineno, max(first_body, fn.lineno + 1)):
        if line in src.guarded_by:
            return src.guarded_by[line]
    return None


def _stmt_annotation(src: SourceFile, stmt) -> str | None:
    """A ``# guarded-by:`` annotation anywhere on ``stmt``'s physical
    lines — a black-wrapped assignment puts the comment on the
    continuation line, not ``stmt.lineno``."""
    end = getattr(stmt, "end_lineno", None) or stmt.lineno
    for line in range(stmt.lineno, end + 1):
        if line in src.guarded_by:
            return src.guarded_by[line]
    return None


def _annotated_attrs(src: SourceFile, cls: ast.ClassDef) -> dict[str, str]:
    """attr name -> lock name, from annotated ``self.x = ...`` lines in
    ``__init__`` and annotated class-body assignments."""
    out: dict[str, str] = {}
    for stmt in cls.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            lock = _stmt_annotation(src, stmt)
            if lock is not None:
                tgt = stmt.targets[0] if isinstance(stmt, ast.Assign) \
                    else stmt.target
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = lock
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and stmt.name == "__init__":
            for node in ast.walk(stmt):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                lock = _stmt_annotation(src, node)
                if lock is None:
                    continue
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        out[t.attr] = lock
    return out


def _annotated_globals(src: SourceFile) -> dict[str, str]:
    out: dict[str, str] = {}
    for stmt in src.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            lock = _stmt_annotation(src, stmt)
            if lock is not None:
                tgt = stmt.targets[0] if isinstance(stmt, ast.Assign) \
                    else stmt.target
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = lock
    return out


class _ScopeWalker:
    """Walk a function body tracking the set of with-held locks, calling
    ``visit(node, locks)`` on every node.  A closure launched on a
    thread holds no caller lock, so a nested def either resets the lock
    context (``nested="reset"``, the class-attr checker: methods are the
    only defs visited) or is skipped outright (``nested="skip"``, the
    global checker: every def — nested included — is visited on its own
    walk, so descending here would double-report)."""

    def __init__(self, visit, nested: str = "reset"):
        self.visit = visit
        self.nested = nested

    def walk(self, fn, locks: frozenset = frozenset()) -> None:
        for stmt in fn.body:
            self._walk(stmt, locks)

    def _walk(self, node: ast.AST, locks: frozenset) -> None:
        self.visit(node, locks)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if self.nested == "reset":
                self.walk(node, frozenset())
            return
        if isinstance(node, ast.Lambda):
            if self.nested == "reset":
                self._walk(node.body, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locks | _withitem_locks(node)
            for item in node.items:
                self._walk(item.context_expr, locks)
            for stmt in node.body:
                self._walk(stmt, inner)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, locks)


def _check_class(ctx: LintContext, src: SourceFile,
                 cls: ast.ClassDef) -> None:
    attrs = _annotated_attrs(src, cls)
    if not attrs:
        return
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if method.name == "__init__":
            continue
        held = _def_line_annotation(src, method)
        if held is not None or method.name.endswith("_locked"):
            continue     # caller-holds-the-lock helper: sites are checked

        def visit(node, locks, method=method):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and node.attr in attrs \
                    and ("self", attrs[node.attr]) not in locks:
                ctx.emit(
                    "ownership-unguarded-attr", src, node.lineno,
                    f"{cls.name}.{method.name} touches self.{node.attr} "
                    f"(guarded-by {attrs[node.attr]}) outside "
                    f"`with self.{attrs[node.attr]}:`")

        _ScopeWalker(visit).walk(method)


def _check_globals(ctx: LintContext, src: SourceFile) -> None:
    annotated = _annotated_globals(src)
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # Only THIS function's own `global` statements: a nested def's
        # declaration must not leak out, or the outer function's locals
        # of the same name get flagged as unlocked global mutations.
        declared: set[str] = set()
        stack = list(fn.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, ast.Global):
                declared.update(sub.names)
            stack.extend(ast.iter_child_nodes(sub))
        if not declared:
            continue

        def visit(node, locks, fn=fn, declared=declared):
            targets: list[ast.Name] = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                # tuple-unpack targets: `a, b = ...`
                for t in node.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        targets.extend(e for e in t.elts
                                       if isinstance(e, ast.Name))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                    and isinstance(node.target, ast.Name):
                targets = [node.target]
            for t in targets:
                if t.id not in declared:
                    continue
                lock = annotated.get(t.id)
                if lock is not None:
                    if ("mod", lock) not in locks:
                        ctx.emit(
                            "ownership-unguarded-global", src, node.lineno,
                            f"{fn.name} mutates module global {t.id!r} "
                            f"(guarded-by {lock}) outside "
                            f"`with {lock}:`")
                elif not locks:
                    ctx.emit(
                        "ownership-global-mutation", src, node.lineno,
                        f"{fn.name} mutates module global {t.id!r} with "
                        "no lock held — annotate it `# guarded-by: "
                        "<lock>`, take a lock, or suppress with a "
                        "reason")

        _ScopeWalker(visit, nested="skip").walk(fn)


@rule("thread-ownership", {
    "ownership-unguarded-attr":
        "guarded-by annotated attribute accessed outside its lock",
    "ownership-unguarded-global":
        "guarded-by annotated module global mutated outside its lock",
    "ownership-global-mutation":
        "unannotated module global mutated from a function with no "
        "lock held",
})
def check_ownership(ctx: LintContext) -> None:
    for src in ctx.sources:
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                _check_class(ctx, src, node)
        _check_globals(ctx, src)
