"""metrics-contract rules: obs instruments vs naming, help, and docs.

The obs registry (obs/metrics.py) is get-or-create by name from ~50
call sites across 15 modules — nothing ever forced a new instrument to
(a) survive Prometheus exposition (``PROM_LINE_RE``), (b) carry a
``# HELP`` body, or (c) land in the docs' metric tables.  These rules
close all three loops, both directions: every registered instrument
must be documented, and every metric a docs table declares must still
have a registration site (so a renamed counter cannot leave a stale
table row behind).

Registration sites are AST call sites of ``counter(...)`` /
``gauge(...)`` / ``histogram(...)`` — the module helpers, the
``obs_metrics.*`` aliases, and registry-method calls alike.  Dynamic
names (f-strings like ``f"stream_{k}"``) become ``stream_*`` patterns
and match docs wildcards (``stream_*``) or placeholder spellings
(``faults_injected_<scope>``, ``serve_requests_{segments,pixel}``).
"""

from __future__ import annotations

import ast
import re

from firebird_tpu.analysis.engine import LintContext, rule

METRICS_MODULE = "firebird_tpu/obs/metrics.py"

# Mirrors obs.metrics._prom_name's input expectations: what the
# sanitizer would have to rewrite is what we reject at the source.
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# Files whose metric tables / code spans document instruments.  A metric
# may be documented in any of them; table rows in any of them must
# resolve to a live registration.
DOC_FILES = ("docs/OBSERVABILITY.md", "docs/ROBUSTNESS.md",
             "docs/SERVING.md", "docs/ROOFLINE.md")

_KINDS = {"counter", "gauge", "histogram"}


class Site:
    def __init__(self, kind: str, name: str, dynamic: bool,
                 src, line: int, has_help: bool):
        self.kind = kind
        self.name = name          # literal name, or the '*' pattern
        self.dynamic = dynamic
        self.src = src
        self.line = line
        self.has_help = has_help


def _name_arg(node: ast.Call) -> tuple[str, bool] | None:
    """(name_or_pattern, dynamic) from the call's first argument."""
    if not node.args:
        return None
    a = node.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, False
    if isinstance(a, ast.JoinedStr):
        parts = [str(v.value) if isinstance(v, ast.Constant) else "*"
                 for v in a.values]
        return "".join(parts), True
    return None


def collect_sites(ctx: LintContext) -> list[Site]:
    sites = []
    for src in ctx.sources:
        if not src.relpath.startswith("firebird_tpu/"):
            continue
        if src.relpath == METRICS_MODULE:
            continue  # the registry's own plumbing, not instrumentation
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            kind = None
            if isinstance(f, ast.Name) and f.id in _KINDS:
                kind = f.id
            elif isinstance(f, ast.Attribute) and f.attr in _KINDS:
                kind = f.attr
            if kind is None:
                continue
            named = _name_arg(node)
            if named is None:
                continue
            name, dynamic = named
            has_help = any(k.arg == "help" and not (
                isinstance(k.value, ast.Constant) and k.value.value is None)
                for k in node.keywords)
            sites.append(Site(kind, name, dynamic, src, node.lineno,
                              has_help))
    return sites


# -- docs parsing -----------------------------------------------------------

_CODE_SPAN_RE = re.compile(r"`([^`]+)`")
_BRACE_RE = re.compile(r"\{([^{}]*)\}")
_TABLE_KIND_RE = re.compile(r"^(counter|gauge|histogram)s?\b")
_METRIC_TOKEN_RE = re.compile(r"^[a-z][a-z0-9_*]*$")


def _expand(token: str) -> list[str]:
    """Expand doc spellings into match patterns: ``{a,b}`` alternates
    (including the empty alternate of ``{,_x}``), ``<placeholder>`` and
    literal ``*`` wildcards."""
    token = re.sub(r"<[^<>]+>", "*", token)
    m = _BRACE_RE.search(token)
    if not m:
        return [token]
    head, tail = token[:m.start()], token[m.end():]
    out = []
    for alt in m.group(1).split(","):
        out.extend(_expand(head + alt + tail))
    return out


def doc_patterns(ctx: LintContext) -> dict[str, tuple[str, int]]:
    """Every metric-ish pattern the docs mention anywhere (code spans):
    pattern -> (file, line).  The "is it documented" direction."""
    out: dict[str, tuple[str, int]] = {}
    for rel in DOC_FILES:
        text = ctx.read_text(rel)
        if text is None:
            continue
        for i, line in enumerate(text.splitlines(), start=1):
            for span in _CODE_SPAN_RE.findall(line):
                for tok in _expand(span.strip()):
                    if _METRIC_TOKEN_RE.fullmatch(tok):
                        out.setdefault(tok, (rel, i))
    return out


def doc_table_metrics(ctx: LintContext) -> dict[str, tuple[str, int]]:
    """Metrics DECLARED by a docs table (rows whose second column is a
    counter/gauge/histogram kind): pattern -> (file, line).  The reverse
    direction — these must all resolve to a live registration site."""
    out: dict[str, tuple[str, int]] = {}
    for rel in DOC_FILES:
        text = ctx.read_text(rel)
        if text is None:
            continue
        for i, line in enumerate(text.splitlines(), start=1):
            if not line.lstrip().startswith("|"):
                continue
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if len(cells) < 2 or not _TABLE_KIND_RE.match(cells[1]):
                continue
            for span in _CODE_SPAN_RE.findall(cells[0]):
                for tok in _expand(span.strip()):
                    if _METRIC_TOKEN_RE.fullmatch(tok):
                        out.setdefault(tok, (rel, i))
    return out


def help_catalog(ctx: LintContext) -> set[str]:
    """Keys of obs.metrics.METRIC_HELP (exact names and glob patterns)
    — the central # HELP fallback a site-less instrument may rely on."""
    src = ctx.source(METRICS_MODULE)
    if src is None:
        return set()
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "METRIC_HELP" \
                and isinstance(node.value, ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return set()


def _pattern_match(pattern: str, name: str) -> bool:
    """Glob-ish match where '*' spans word characters; tried both ways
    so a dynamic site (stream_*) matches a docs wildcard (stream_*)."""
    if pattern == name:
        return True
    rex = re.escape(pattern).replace(r"\*", r"[a-z0-9_]+")
    if re.fullmatch(rex, name):
        return True
    rex2 = re.escape(name).replace(r"\*", r"[a-z0-9_]+")
    return re.fullmatch(rex2, pattern) is not None


@rule("metrics-contract", {
    "metric-name":
        "instrument name breaks the Prometheus naming contract",
    "metric-total-suffix":
        "non-counter instrument named *_total (masquerades as a counter)",
    "metric-help":
        "instrument never registered with help text at any call site",
    "metric-undocumented":
        "registered instrument missing from the docs' metric tables/spans",
    "metric-doc-stale":
        "docs table declares a metric with no registration site left",
})
def check_metrics(ctx: LintContext) -> None:
    sites = collect_sites(ctx)
    if not sites:
        return
    docs = doc_patterns(ctx)
    tables = doc_table_metrics(ctx)
    catalog = help_catalog(ctx)

    by_name: dict[tuple[str, str], list[Site]] = {}
    for s in sites:
        by_name.setdefault((s.kind, s.name), []).append(s)

    for (kind, name), group in sorted(by_name.items()):
        first = min(group, key=lambda s: (s.src.relpath, s.line))
        bare = name.replace("*", "x")
        if not NAME_RE.fullmatch(bare) or "__" in bare \
                or bare.endswith("_"):
            ctx.emit("metric-name", first.src, first.line,
                     f"{kind} {name!r} would not survive Prometheus "
                     "exposition (want ^[a-z][a-z0-9_]*$, no '__', no "
                     "trailing '_')")
            continue
        if kind != "counter" and name.endswith("_total"):
            ctx.emit("metric-total-suffix", first.src, first.line,
                     f"{kind} {name!r} ends in _total — that suffix is "
                     "the counter convention (obs.metrics._prom_name)")
        if not any(s.has_help for s in group) \
                and not any(_pattern_match(p, name) for p in catalog):
            ctx.emit("metric-help", first.src, first.line,
                     f"{kind} {name!r} has no help text: pass help= at "
                     "a registration site or add an "
                     "obs.metrics.METRIC_HELP entry")
        if not any(_pattern_match(p, name) for p in docs):
            ctx.emit("metric-undocumented", first.src, first.line,
                     f"{kind} {name!r} is not mentioned in any of "
                     f"{', '.join(DOC_FILES)}")

    # Reverse: every table-declared metric still has a registration.
    live = [s.name for s in sites]
    for pat, (rel, line) in sorted(tables.items()):
        if not any(_pattern_match(pat, p) or _pattern_match(p, pat)
                   for p in live):
            ctx.emit("metric-doc-stale", rel, line,
                     f"docs table declares {pat!r} but no code "
                     "registers it")


# ---------------------------------------------------------------------------
# Span names: call sites vs obs.report.SPAN_NAMES vs the docs span table
# ---------------------------------------------------------------------------

REPORT_MODULE = "firebird_tpu/obs/report.py"
SPAN_DOC_FILE = "docs/OBSERVABILITY.md"


def collect_span_sites(ctx: LintContext) -> list[Site]:
    """Every ``tracing.span("name", ...)`` call site (literal or
    f-string first arg) outside the tracer's own module."""
    sites = []
    for src in ctx.sources:
        if not src.relpath.startswith("firebird_tpu/"):
            continue
        if src.relpath == "firebird_tpu/obs/tracing.py":
            continue  # the span() factory itself
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not ((isinstance(f, ast.Name) and f.id == "span")
                    or (isinstance(f, ast.Attribute) and f.attr == "span")):
                continue
            named = _name_arg(node)
            if named is None:
                continue  # Match.span() etc: no literal name argument
            name, dynamic = named
            sites.append(Site("span", name, dynamic, src, node.lineno,
                              False))
    return sites


def _report_tuple(ctx: LintContext, var: str) -> dict[str, int]:
    """A literal tuple-of-strings assignment in obs/report.py parsed
    from source (the KNOBS pattern): name -> line, empty when absent."""
    src = ctx.source(REPORT_MODULE)
    if src is None:
        return {}
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == var \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return {e.value: e.lineno for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return {}


def doc_span_table(ctx: LintContext) -> dict[str, tuple[str, int]]:
    """Rows of the OBSERVABILITY.md span table (second cell literally
    ``span``): name -> (file, line)."""
    out: dict[str, tuple[str, int]] = {}
    text = ctx.read_text(SPAN_DOC_FILE)
    if text is None:
        return out
    for i, line in enumerate(text.splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 2 or cells[1] != "span":
            continue
        for tok in _CODE_SPAN_RE.findall(cells[0]):
            tok = tok.strip()
            if _METRIC_TOKEN_RE.fullmatch(tok):
                out.setdefault(tok, (SPAN_DOC_FILE, i))
    return out


@rule("metrics-contract", {
    "span-unregistered":
        "span call site uses a name missing from obs.report.SPAN_NAMES",
    "span-dead":
        "SPAN_NAMES declares a span with no call site left",
    "span-undocumented":
        "declared span missing from the OBSERVABILITY.md span table",
    "span-doc-stale":
        "docs span table row with no SPAN_NAMES entry behind it",
})
def check_spans(ctx: LintContext) -> None:
    """Span names agree three ways — call sites, the SPAN_NAMES catalog
    (which DRIVER_SPAN_NAMES must subset), and the docs span table —
    in both directions, the metric-table pattern: a new span cannot
    ship undocumented and a renamed one cannot leave a stale row."""
    declared = _report_tuple(ctx, "SPAN_NAMES")
    if not declared:
        return  # fixture repos without the catalog don't enforce spans
    sites = collect_span_sites(ctx)
    docs = doc_span_table(ctx)

    seen: set[str] = set()
    for s in sites:
        if s.name in seen:
            continue
        seen.add(s.name)
        if s.dynamic:
            if not any(_pattern_match(s.name, d) for d in declared):
                ctx.emit("span-unregistered", s.src, s.line,
                         f"dynamic span name {s.name!r} matches no "
                         "SPAN_NAMES entry (obs/report.py)")
            continue
        if s.name not in declared:
            ctx.emit("span-unregistered", s.src, s.line,
                     f"span {s.name!r} is not declared in "
                     "obs.report.SPAN_NAMES (obs/report.py)")

    live = {s.name for s in sites}
    for name, line in sorted(declared.items()):
        if not any(_pattern_match(name, n) or _pattern_match(n, name)
                   for n in live):
            ctx.emit("span-dead", REPORT_MODULE, line,
                     f"SPAN_NAMES declares {name!r} but no call site "
                     "opens that span")
        if not any(_pattern_match(p, name) for p in docs):
            ctx.emit("span-undocumented", REPORT_MODULE, line,
                     f"span {name!r} is missing from the "
                     f"{SPAN_DOC_FILE} span table")
    # DRIVER_SPAN_NAMES is the driver-stage subset of the catalog — an
    # entry outside SPAN_NAMES means the two tuples drifted apart.
    for name, line in sorted(_report_tuple(ctx,
                                           "DRIVER_SPAN_NAMES").items()):
        if name not in declared:
            ctx.emit("span-unregistered", REPORT_MODULE, line,
                     f"DRIVER_SPAN_NAMES entry {name!r} is not in "
                     "SPAN_NAMES")
    for pat, (rel, line) in sorted(docs.items()):
        if not any(_pattern_match(pat, n) or _pattern_match(n, pat)
                   for n in declared):
            ctx.emit("span-doc-stale", rel, line,
                     f"docs span table declares {pat!r} but "
                     "SPAN_NAMES has no such span")


# ---------------------------------------------------------------------------
# SLO objectives: obs/slo.py OBJECTIVES + default specs vs live metrics
# ---------------------------------------------------------------------------

SLO_MODULE = "firebird_tpu/obs/slo.py"

_SPEC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*")


def slo_objectives(ctx: LintContext) -> dict[str, tuple[str, list, int]]:
    """The ``OBJECTIVES`` literal parsed from obs/slo.py source:
    objective name -> (kind, [metric names], line).  A tuple metric
    field (a histogram fallback chain, or a ratio's numerator/
    denominator pair) contributes every member."""
    src = ctx.source(SLO_MODULE)
    if src is None:
        return {}
    out: dict[str, tuple[str, list, int]] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "OBJECTIVES"
                and isinstance(node.value, ast.Dict)):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            if not (isinstance(v, ast.Tuple) and len(v.elts) >= 2):
                continue
            kind = v.elts[0].value \
                if isinstance(v.elts[0], ast.Constant) else ""
            met = v.elts[1]
            if isinstance(met, ast.Constant) and isinstance(met.value,
                                                            str):
                names = [met.value]
            elif isinstance(met, (ast.Tuple, ast.List)):
                names = [e.value for e in met.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)]
            else:
                names = []
            out[k.value] = (str(kind), names, k.lineno)
    return out


def _spec_literal(ctx: LintContext, var: str) -> tuple[str, int] | None:
    """A module-level string-constant assignment in obs/slo.py
    (implicit concatenation folds to one Constant): (value, line)."""
    src = ctx.source(SLO_MODULE)
    if src is None:
        return None
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == var \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            return node.value.value, node.lineno
    return None


@rule("metrics-contract", {
    "slo-metric-unknown":
        "SLO objective reads a metric no call site registers",
    "slo-spec-unknown":
        "SLO spec names an objective missing from OBJECTIVES",
})
def check_slo_objectives(ctx: LintContext) -> None:
    """The SLO layer's names agree with the metric registry: every
    OBJECTIVES metric (histogram/gauge/ratio kinds; watchdog fields are
    report-block keys, not registry instruments) must have a live
    registration site or a METRIC_HELP entry — a typo'd objective
    metric silently evaluates as no-data forever, which the no-data-is-
    zero-burn budget rule would hide indefinitely.  And every objective
    name the default specs (DEFAULT_SPEC, DEFAULT_BUDGET_SPEC) mention
    must exist in OBJECTIVES."""
    objectives = slo_objectives(ctx)
    if not objectives:
        return  # fixture repos without the SLO module don't enforce
    live = [s.name for s in collect_sites(ctx)]
    catalog = help_catalog(ctx)
    for name, (kind, metric_names, line) in sorted(objectives.items()):
        if kind == "watchdog":
            continue
        for m in metric_names:
            if not any(_pattern_match(p, m) for p in live) \
                    and not any(_pattern_match(p, m) for p in catalog):
                ctx.emit("slo-metric-unknown", SLO_MODULE, line,
                         f"objective {name!r} reads metric {m!r} but "
                         "no call site registers it and METRIC_HELP "
                         "has no entry — it would evaluate as no-data "
                         "forever")
    for var in ("DEFAULT_SPEC", "DEFAULT_BUDGET_SPEC"):
        lit = _spec_literal(ctx, var)
        if lit is None:
            continue
        spec, line = lit
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            m = _SPEC_NAME_RE.match(entry)
            if m is None or m.group(0) not in objectives:
                ctx.emit("slo-spec-unknown", SLO_MODULE, line,
                         f"{var} entry {entry!r} names no OBJECTIVES "
                         "key")
