"""``python -m firebird_tpu.analysis`` — the firebird-lint entry point
(`make lint` uses this form so it works without the console script)."""

import sys

from firebird_tpu.analysis.engine import main

if __name__ == "__main__":
    sys.exit(main())
