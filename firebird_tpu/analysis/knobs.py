"""knob-registry rules: FIREBIRD_* env vars vs the config.KNOBS registry.

The failure mode this family kills: a knob is added in some module as a
raw ``os.environ.get`` (quick, works), never grows a Config field or a
doc line, and six months later nobody can say whether setting it still
does anything.  At PR 7 time the repo had 52 ``FIREBIRD_*`` knobs read
from 10+ modules with 10 undocumented — exactly the drift these rules
now fail CI on.

Everything is derived from source: the registry is parsed out of
``firebird_tpu/config.py`` (the ``KNOBS`` literal), reads are AST
``os.environ`` / ``os.getenv`` call sites, documentation presence is a
scan of ``README.md`` + ``docs/*.md``, and aliveness additionally counts
shell expansions in ``tools/*.sh`` and the ``Makefile`` — so the linter
works unchanged on the hermetic fixture repos the test suite builds.
"""

from __future__ import annotations

import ast
import glob
import os
import re

from firebird_tpu.analysis.engine import LintContext, SourceFile, rule

KNOB_RE = re.compile(r"\bFIREBIRD_[A-Z0-9]+(?:_[A-Z0-9]+)*\b")

CONFIG_PATH = "firebird_tpu/config.py"


class KnobDecl:
    def __init__(self, name: str, field=None, readers=(), internal=False,
                 line: int = 0):
        self.name = name
        self.field = field
        self.readers = tuple(readers)
        self.internal = internal
        self.line = line


def registry_span(src: SourceFile) -> tuple[int, int]:
    """Line range of the ``KNOBS = (...)`` assignment, or (0, -1).

    Knob name literals inside the registry itself must not count as
    "references" — otherwise declaring a knob would satisfy the
    dead-knob and from_env-reads-it checks by construction."""
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "KNOBS":
            return node.lineno, node.end_lineno or node.lineno
    return 0, -1


def parse_registry(src: SourceFile) -> dict[str, KnobDecl]:
    """Extract the ``KNOBS = (Knob(...), ...)`` literal from config.py.

    Each ``Knob(...)`` call must carry constant (literal-evaluable)
    keywords — the registry is data, and keeping it data is what lets a
    fixture repo's registry be parsed without importing it.
    """
    out: dict[str, KnobDecl] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "KNOBS"):
            continue
        for call in ast.walk(node.value):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)
                    and call.func.id == "Knob"):
                continue
            kw = {}
            for k in call.keywords:
                try:
                    kw[k.arg] = ast.literal_eval(k.value)
                except ValueError:
                    continue  # non-literal argument: ignore that field
            if "name" in kw:
                out[kw["name"]] = KnobDecl(
                    kw["name"], field=kw.get("field"),
                    readers=kw.get("readers", ()),
                    internal=bool(kw.get("internal", False)),
                    line=call.lineno)
    return out


def _is_environ_expr(node: ast.AST) -> bool:
    """True for expressions ending in ``environ`` (os.environ, a bare
    ``environ`` import, bench.py's ``_os.environ``)."""
    return (isinstance(node, ast.Attribute) and node.attr == "environ") \
        or (isinstance(node, ast.Name) and node.id == "environ")


def env_reads(src: SourceFile):
    """Yield ``(knob_name, lineno)`` for every env READ of a FIREBIRD_*
    literal: ``environ.get/.setdefault``, ``os.getenv``, and
    ``environ[...]`` subscript loads.  Stores/deletes/pops are harness
    configuration of child code, not reads, and stay unflagged."""
    for node in ast.walk(src.tree):
        name = None
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("get",
                                                           "setdefault") \
                    and _is_environ_expr(f.value):
                name = _const_knob(node.args[0]) if node.args else None
            elif (isinstance(f, ast.Attribute) and f.attr == "getenv") \
                    or (isinstance(f, ast.Name) and f.id == "getenv"):
                name = _const_knob(node.args[0]) if node.args else None
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and _is_environ_expr(node.value):
            name = _const_knob(node.slice)
        if name:
            yield name, node.lineno


def _const_knob(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and KNOB_RE.fullmatch(node.value):
        return node.value
    return None


def env_knob_reads(src: SourceFile):
    """Yield ``(knob_name, lineno)`` for every ``env_knob("FIREBIRD_X")``
    call site.  env_knob raises KeyError on an unregistered name at
    RUNTIME — these sites must be validated at lint time too, or a knob
    rename that misses one env_knob caller ships a lint-clean repo that
    crashes on its first hot-path read."""
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_env_knob = (isinstance(f, ast.Name) and f.id == "env_knob") \
            or (isinstance(f, ast.Attribute) and f.attr == "env_knob")
        if is_env_knob and node.args:
            name = _const_knob(node.args[0])
            if name:
                yield name, node.lineno


def knob_literals(src: SourceFile):
    """Every FIREBIRD_* string constant in the file (aliveness scan:
    env_knob() calls, bench fold arguments, test-free references)."""
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in KNOB_RE.finditer(node.value):
                yield m.group(0), node.lineno


def doc_files(ctx: LintContext) -> list[str]:
    """Operator-facing docs: README.md + docs/*.md (repo-relative).
    Root planning files (ISSUE/ROADMAP/CHANGES/...) are meta, not docs."""
    out = []
    if os.path.exists(os.path.join(ctx.root, "README.md")):
        out.append("README.md")
    for p in sorted(glob.glob(os.path.join(ctx.root, "docs", "*.md"))):
        out.append("/".join(["docs", os.path.basename(p)]))
    return out


def _doc_mentions(ctx: LintContext) -> dict[str, tuple[str, int]]:
    """knob name -> (doc file, first line mentioning it)."""
    found: dict[str, tuple[str, int]] = {}
    for rel in doc_files(ctx):
        text = ctx.read_text(rel) or ""
        for i, line in enumerate(text.splitlines(), start=1):
            for m in KNOB_RE.finditer(line):
                found.setdefault(m.group(0), (rel, i))
    return found


def _shell_mentions(ctx: LintContext) -> set[str]:
    names: set[str] = set()
    paths = glob.glob(os.path.join(ctx.root, "tools", "*.sh"))
    mk = os.path.join(ctx.root, "Makefile")
    if os.path.exists(mk):
        paths.append(mk)
    for p in paths:
        with open(p, encoding="utf-8", errors="replace") as f:
            names.update(m.group(0) for m in KNOB_RE.finditer(f.read()))
    return names


@rule("knob-registry", {
    "knob-unregistered-read":
        "os.environ read of a FIREBIRD_* var absent from config.KNOBS",
    "knob-reader-drift":
        "registered knob read outside config.py and its declared readers",
    "knob-undocumented":
        "registered non-internal knob missing from README.md/docs/*.md",
    "knob-doc-stale":
        "FIREBIRD_* var named in the docs but absent from config.KNOBS",
    "knob-dead":
        "registered knob with no remaining read or reference anywhere",
    "knob-config-field":
        "knob declares a Config field that config.py does not implement",
    "knob-no-registry":
        "firebird_tpu/config.py has no parseable KNOBS registry",
})
def check_knobs(ctx: LintContext) -> None:
    cfg = ctx.source(CONFIG_PATH)
    if cfg is None:
        return  # not a firebird repo layout; nothing to check
    registry = parse_registry(cfg)
    if not registry:
        ctx.emit("knob-no-registry", cfg, 1,
                 "config.py defines no KNOBS = (Knob(...), ...) literal")
        return

    # Config class attributes + env literals in config.py (field rule).
    # Literals inside the KNOBS registry itself are declarations, not
    # references — exclude them or dead-knob detection can never fire.
    config_attrs = _config_attrs(cfg)
    lo, hi = registry_span(cfg)
    config_lits = {n for n, ln in knob_literals(cfg)
                   if not lo <= ln <= hi}

    referenced: set[str] = set(config_lits)
    for src in ctx.sources:
        is_config = src.relpath == CONFIG_PATH
        for name, line in env_reads(src):
            decl = registry.get(name)
            if decl is None:
                ctx.emit("knob-unregistered-read", src, line,
                         f"{name} read from the environment but not "
                         "registered in config.KNOBS")
                continue
            if not is_config and src.relpath not in decl.readers:
                ctx.emit("knob-reader-drift", src, line,
                         f"{name} read directly here but config.KNOBS "
                         f"declares readers {list(decl.readers) or '[]'} "
                         "— route through Config.from_env / "
                         "config.env_knob or declare this module")
        for name, line in env_knob_reads(src):
            if name not in registry:
                ctx.emit("knob-unregistered-read", src, line,
                         f"env_knob({name!r}) names a knob absent from "
                         "config.KNOBS — this raises KeyError at "
                         "runtime")
        if not is_config:     # config.py handled above (span-excluded)
            referenced.update(n for n, _ in knob_literals(src))
    referenced |= _shell_mentions(ctx)

    docs = _doc_mentions(ctx)
    for name, (rel, line) in sorted(docs.items()):
        if name not in registry:
            ctx.emit("knob-doc-stale", rel, line,
                     f"{name} appears in the docs but is not registered "
                     "in config.KNOBS")

    for name, decl in sorted(registry.items()):
        if not decl.internal and name not in docs:
            ctx.emit("knob-undocumented", cfg, decl.line,
                     f"{name} is registered but never mentioned in "
                     "README.md or docs/*.md")
        if name not in referenced:
            ctx.emit("knob-dead", cfg, decl.line,
                     f"{name} is registered but nothing reads or "
                     "references it anymore")
        if decl.field is not None:
            if decl.field not in config_attrs:
                ctx.emit("knob-config-field", cfg, decl.line,
                         f"{name} declares Config field "
                         f"{decl.field!r} which Config does not define")
            elif name not in config_lits:
                ctx.emit("knob-config-field", cfg, decl.line,
                         f"{name} declares Config field {decl.field!r} "
                         "but from_env never reads the env var")


def _config_attrs(cfg: SourceFile) -> set[str]:
    attrs: set[str] = set()
    for node in cfg.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    attrs.add(stmt.target.id)
    return attrs
