"""firebird-lint: repo-native static analysis (docs/STATIC_ANALYSIS.md).

Four AST-checked contract families over the codebase itself:

- **jax-hotpath** — no host syncs or Python branching on traced values
  inside jitted/pallas code, and jit static-arg sets that agree with
  ``ccd.kernel._WIRE_STATICS``.
- **knob-registry** — every ``FIREBIRD_*`` env read routes through the
  ``config.KNOBS`` registry, is documented, and is actually read
  somewhere (dead-knob detection).
- **metrics-contract** — obs instruments satisfy the Prometheus naming
  rules, carry help text, and match the docs' metric tables both ways.
- **thread-ownership** — ``# guarded-by: <lock>`` annotated shared state
  is only touched under its lock.

Run with ``firebird lint``, ``make lint``, or
``python -m firebird_tpu.analysis``.  Stdlib ``ast`` only — importing
this package never imports jax.
"""

from firebird_tpu.analysis.engine import (Baseline, Finding, LintResult,
                                          RULE_DOCS, families, main,
                                          run_lint)

__all__ = ["Baseline", "Finding", "LintResult", "RULE_DOCS", "families",
           "main", "run_lint"]
