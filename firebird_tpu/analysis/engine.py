"""firebird-lint engine: findings, suppressions, baseline, and the runner.

The repo's correctness rests on cross-cutting contracts that no unit test
can see whole: jit static-arg sets vs ``_WIRE_STATICS``, ``FIREBIRD_*``
knobs vs the config registry and the docs, obs instruments vs the
OBSERVABILITY.md tables, and lock-guarded shared state across the
prefetch/drain/writer/serve threads.  DrJAX (PAPERS.md) makes the point
for JAX programs — the parallel structure is statically analyzable — and
this package applies it to the host program too: every contract is an
AST-checkable invariant, so it is checked in CI (``firebird lint`` /
``make lint``) instead of in review.

Machinery (this module; the rule families live in sibling modules):

- :class:`Finding` — one violation: rule id, file, line, message.
- **Suppressions** — ``# firebird-lint: disable=<rule>[,<rule>...]`` on
  the offending line silences those rules for that line;
  ``# firebird-lint: disable-file=<rule>`` anywhere in a file silences a
  rule for the whole file.  Every suppression is counted in the summary
  so a suppression-heavy file is visible.
- **Baseline** — a committed JSON file of grandfathered finding
  fingerprints (rule|path|message, line-independent so findings survive
  unrelated edits).  ``firebird lint`` fails only on findings NOT in the
  baseline; ``--update-baseline`` rewrites it from the current state.
- **JSON summary** — ``--json`` writes a machine-readable report that
  bench.py folds into round artifacts next to the chaos/serve/compact
  smokes.

Rules register through :func:`rule`; the runner parses each source file
once and hands every rule the same :class:`LintContext`.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re

BASELINE_SCHEMA = "firebird-lint-baseline/1"
REPORT_SCHEMA = "firebird-lint-report/1"

# Directories/files never scanned: tests seed deliberate violations as
# fixtures, __pycache__ is bytecode, __graft_entry__ is harness glue.
EXCLUDE_PARTS = ("__pycache__", "tests", ".git", "deploy")
EXCLUDE_FILES = ("__graft_entry__.py",)

_SUPPRESS_RE = re.compile(
    r"#\s*firebird-lint:\s*(disable|disable-file)=([A-Za-z0-9_,-]+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str        # repo-relative, '/'-separated
    line: int
    message: str

    @property
    def fingerprint(self) -> str:
        """Line-independent identity for the baseline: unrelated edits
        move line numbers constantly, but (rule, file, message) is stable
        until the finding itself is fixed or duplicated."""
        return f"{self.rule}|{self.path}|{self.message}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


class SourceFile:
    """One parsed source file shared by every rule (parse once)."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree: ast.Module | None = None
        # line -> set of rule ids disabled on that line; "*-file" entries
        # land in file_disabled.
        self.line_disabled: dict[int, set[str]] = {}
        self.file_disabled: set[str] = set()
        # line -> lock name from a `# guarded-by: <lock>` annotation
        # (the thread-ownership convention; parsed here so the comment
        # syntax has exactly one parser).
        self.guarded_by: dict[int, str] = {}
        for i, ln in self._comments():
            m = _SUPPRESS_RE.search(ln)
            if m:
                rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
                if m.group(1) == "disable-file":
                    self.file_disabled |= rules
                else:
                    self.line_disabled.setdefault(i, set()).update(rules)
            g = _GUARDED_RE.search(ln)
            if g:
                self.guarded_by[i] = g.group(1)

    def _comments(self):
        """(line, comment_text) for every REAL comment token — a string
        literal quoting the suppression syntax (help text, a docstring
        documenting it) must not disable rules.  Falls back to a raw
        line scan when the file does not tokenize (it will fail to parse
        and surface as a parse-error finding anyway)."""
        import io
        import tokenize

        out = []
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out.append((tok.start[0], tok.string))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return [(i, ln) for i, ln in enumerate(self.lines, start=1)
                    if "#" in ln]
        return out

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=self.relpath)
        return self._tree

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disabled:
            return True
        return rule in self.line_disabled.get(line, set())


class LintContext:
    """Everything a rule needs: the parsed python sources, the repo root
    (for docs and shell scripts), and a Finding factory that applies
    suppressions at emit time."""

    def __init__(self, root: str, sources: list[SourceFile]):
        self.root = root
        self.sources = sources
        self.by_path = {s.relpath: s for s in sources}
        self.findings: list[Finding] = []
        self.suppressed_count = 0

    def source(self, relpath: str) -> SourceFile | None:
        return self.by_path.get(relpath)

    def read_text(self, relpath: str) -> str | None:
        """A non-python repo file (docs, shell scripts); None if absent."""
        p = os.path.join(self.root, relpath)
        if not os.path.exists(p):
            return None
        with open(p, encoding="utf-8") as f:
            return f.read()

    def emit(self, rule: str, src: SourceFile | str, line: int,
             message: str) -> None:
        path = src.relpath if isinstance(src, SourceFile) else src
        sf = src if isinstance(src, SourceFile) else self.by_path.get(src)
        if sf is not None and sf.suppressed(rule, line):
            self.suppressed_count += 1
            return
        self.findings.append(Finding(rule, path, line, message))


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

# family -> [(rule_prefix_doc, fn)]; each fn(ctx) emits via ctx.emit.
_CHECKERS: dict[str, list] = {}
# rule id -> one-line description (the `--list-rules` catalog; docs'
# rule table is generated from the same declarations).
RULE_DOCS: dict[str, str] = {}


def rule(family: str, rules: dict[str, str]):
    """Register a checker function under ``family``, declaring the rule
    ids it may emit (id -> one-line description)."""

    def deco(fn):
        _CHECKERS.setdefault(family, []).append(fn)
        for rid, doc in rules.items():
            RULE_DOCS[rid] = doc
        return fn

    return deco


def families() -> list[str]:
    return sorted(_CHECKERS)


def _load_families() -> None:
    # Import side effect registers the checkers; deferred so engine.py
    # itself is importable by the rule modules without a cycle.
    from firebird_tpu.analysis import (hotpath, knobs,  # noqa: F401
                                       metrics_contract, ownership)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

class Baseline:
    """Grandfathered findings: fingerprint -> count.

    Counts (not a set) so two identical findings in one file — same rule,
    same message — are two baseline slots: fixing one of them is progress
    the linter can see.
    """

    def __init__(self, counts: dict[str, int] | None = None):
        self.counts = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("schema") != BASELINE_SCHEMA:
            raise ValueError(f"unrecognized baseline schema in {path}: "
                             f"{doc.get('schema')!r}")
        return cls(doc.get("findings", {}))

    def save(self, path: str, findings: list[Finding]) -> None:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
        doc = {"schema": BASELINE_SCHEMA,
               "findings": dict(sorted(counts.items()))}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        self.counts = counts

    def split(self, findings: list[Finding]) -> tuple[list[Finding],
                                                      list[Finding]]:
        """(new, known): each baseline slot absorbs at most its count."""
        budget = dict(self.counts)
        new, known = [], []
        for f in findings:
            fp = f.fingerprint
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                known.append(f)
            else:
                new.append(f)
        return new, known

    def __len__(self) -> int:
        return sum(self.counts.values())


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

def discover(root: str) -> list[str]:
    """Repo-relative python files the linter scans."""
    out = []
    for base, dirs, names in os.walk(root):
        rel = os.path.relpath(base, root)
        parts = [] if rel == "." else rel.split(os.sep)
        if any(p in EXCLUDE_PARTS or p.startswith(".") for p in parts):
            dirs[:] = []
            continue
        dirs[:] = [d for d in dirs
                   if d not in EXCLUDE_PARTS and not d.startswith(".")]
        for n in sorted(names):
            if n.endswith(".py") and n not in EXCLUDE_FILES:
                out.append("/".join(parts + [n]) if parts else n)
    return sorted(out)


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]          # post-suppression, pre-baseline
    new: list[Finding]               # not absorbed by the baseline
    known: list[Finding]             # absorbed by the baseline
    suppressed: int
    files_scanned: int
    parse_errors: list[Finding]
    # Findings before any --rules filter: what --update-baseline must
    # record, or refreshing one family would silently drop every other
    # family's grandfathered slots.
    all_findings: list[Finding] = dataclasses.field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.new and not self.parse_errors

    def summary(self) -> dict:
        per_rule: dict[str, int] = {}
        for f in self.findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        return {
            "schema": REPORT_SCHEMA,
            "clean": self.clean,
            "files_scanned": self.files_scanned,
            "findings": len(self.findings),
            "new": [str(f) for f in self.new],
            "new_count": len(self.new),
            "baselined_count": len(self.known),
            "suppressed_count": self.suppressed,
            "per_rule": dict(sorted(per_rule.items())),
            "parse_errors": [str(f) for f in self.parse_errors],
        }


def run_lint(root: str, baseline: Baseline | None = None,
             only: list[str] | None = None) -> LintResult:
    """Run every registered rule family over the repo at ``root``.

    ``only`` filters to rule families or individual rule ids (glob
    patterns accepted: ``knob-*``).
    """
    _load_families()
    sources, parse_errors = [], []
    paths = discover(root)
    for relpath in paths:
        try:
            src = SourceFile(root, relpath)
            src.tree  # parse now: a syntax error is itself a finding
        except (SyntaxError, UnicodeDecodeError) as e:
            line = getattr(e, "lineno", 0) or 0
            parse_errors.append(Finding("parse-error", relpath, line,
                                        f"cannot parse: {e}"))
            continue
        sources.append(src)
    ctx = LintContext(root, sources)
    for family in families():
        for fn in _CHECKERS[family]:
            fn(ctx)
    all_findings = sorted(ctx.findings,
                          key=lambda f: (f.path, f.line, f.rule, f.message))
    findings = all_findings
    if only:
        findings = [f for f in findings
                    if _selected(f.rule, only)
                    or _selected(_rule_family(f.rule), only)]
    base = baseline or Baseline()
    new, known = base.split(findings)
    return LintResult(findings=findings, new=new, known=known,
                      suppressed=ctx.suppressed_count,
                      files_scanned=len(sources),
                      parse_errors=parse_errors,
                      all_findings=all_findings)


_FAMILY_PREFIX = {"jax-hotpath": "hotpath-", "knob-registry": "knob-",
                  "metrics-contract": "metric-",
                  "thread-ownership": "ownership-"}


def _rule_family(rule_id: str) -> str:
    for fam, prefix in _FAMILY_PREFIX.items():
        if rule_id.startswith(prefix):
            return fam
    return rule_id


def _selected(name: str, only: list[str]) -> bool:
    return any(fnmatch.fnmatch(name, pat) for pat in only)


# ---------------------------------------------------------------------------
# CLI (argparse — stdlib-only so `python -m firebird_tpu.analysis` needs
# nothing installed; `firebird lint` delegates here)
# ---------------------------------------------------------------------------

def default_root() -> str:
    """The repo root: the directory holding the firebird_tpu package."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="firebird lint",
        description="AST contract checker: jax hot-path, FIREBIRD_* "
                    "knobs, obs metrics, thread ownership "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--root", default=default_root(),
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <root>/lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding as new")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--json", dest="json_path", default=None,
                    help="write the machine-readable summary here "
                         "(bench.py folds it into round artifacts)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule families or rule ids "
                         "(globs ok), e.g. 'knob-*,metrics-contract'")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only the summary line")
    args = ap.parse_args(argv)

    if args.list_rules:
        _load_families()
        for rid in sorted(RULE_DOCS):
            print(f"{rid}: {RULE_DOCS[rid]}")
        return 0

    bpath = args.baseline or os.path.join(args.root, "lint_baseline.json")
    baseline = Baseline() if args.no_baseline else Baseline.load(bpath)
    only = ([p.strip() for p in args.rules.split(",") if p.strip()]
            if args.rules else None)
    result = run_lint(args.root, baseline=baseline, only=only)

    if args.update_baseline:
        if result.parse_errors:
            # An unparseable file ran zero rules: the findings snapshot
            # is incomplete, and grandfathering it would hide that until
            # the next plain run (likely post-commit, in CI).
            for f in result.parse_errors:
                print(str(f))
            print("baseline NOT updated: fix the parse error(s) first")
            return 1
        # Always from the unfiltered findings: a --rules run still
        # rewrites the WHOLE baseline, never just the selected family.
        baseline.save(bpath, result.all_findings)
        print(f"baseline updated: {len(result.all_findings)} finding(s) "
              f"recorded in {bpath}")
        if args.json_path:
            # Re-split against the just-saved baseline so a --json
            # report written alongside the update reflects the NEW
            # state (everything absorbed), not the stale pre-update
            # split bench would otherwise fold as current evidence.
            result.new, result.known = baseline.split(result.findings)
            _write_json(args.json_path, result)
        return 0

    if not args.quiet:
        for f in result.parse_errors:
            print(str(f))
        for f in result.new:
            print(str(f))
    status = "clean" if result.clean else "FAILED"
    print(f"firebird-lint: {status} — {result.files_scanned} files, "
          f"{len(result.new)} new, {len(result.known)} baselined, "
          f"{result.suppressed} suppressed")
    if args.json_path:
        _write_json(args.json_path, result)
    return 0 if result.clean else 1


def _write_json(path: str, result: LintResult) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(result.summary(), f, indent=1)
        f.write("\n")
