"""SLO tracking: declared objectives evaluated against live histograms.

The obs stack records every latency but nothing *judges* them — an
operator watching ``/progress`` must remember what "healthy" looks like
for each number.  This module makes the objectives declarations: a spec
(``FIREBIRD_SLO`` / ``Config.slo``) names each objective and its
target, evaluation reads the SAME metric snapshots the report and
``/metrics`` expose, and the verdict is served live at ``/slo``
(obs/server.py) and summarized in every ``obs_report.json`` (fleet
merges re-evaluate over the merged histograms).

Objectives (the spec grammar is ``name=target;name=target``; targets
are seconds):

``batch_p95``
    p95 of ``pipeline_drain_seconds`` — the steady-state batch wall
    time as the drain thread sees it (device wait + egress; dispatch is
    asynchronous so this histogram is where a slow batch shows up).
``serve_p99``
    p99 of ``serve_request_seconds`` — the query layer's tail latency,
    admission wait included.
``freshness``
    Seconds since the last drained batch (the watchdog's
    ``last_beat_age_sec``) — the liveness half of an alerting-grade
    freshness promise: results are at most this stale.
``changefeed_lag``
    The ``serve_changefeed_lag_seconds`` gauge — how far behind the
    write feed a serve replica's cache-coherence loop ran at its last
    poll (docs/SERVING.md's staleness bound, measured).

An objective whose metric has no data reports ``ok: null`` ("no_data")
rather than passing or failing — a serve SLO must not fail a batch run
that never served a request.  ``FIREBIRD_SLO=0`` disables evaluation.

Error budgets (the durable half, over obs/series.py history): a budget
spec (``FIREBIRD_SLO_BUDGET``) declares target ratios over rolling
windows — ``alert_freshness<60@99.9/28d`` reads "the p95 source metric
stays under 60s for 99.9% of observations over 28 days".  Evaluation
replays the series store's merged per-host history (fleet verdicts are
re-derived from summed per-source deltas, never one host's percentile)
into three windows: the full budget window (exhaustion: bad >
(1-target) x total) and a fast+slow burn-rate pair (paging signal: both
windows burning >= ``FIREBIRD_SLO_BURN`` at once — the multi-window
rule that filters blips without missing slow leaks).  A window with no
data contributes ZERO burn — not a violation, not credit — and is named
in ``empty_windows`` so an operator can tell "healthy" from "blind".
Budget-state transitions append durably to ``slo_events.jsonl`` next to
the series rings.
"""

from __future__ import annotations

import json
import os
import time

DEFAULT_SPEC = ("batch_p95=30;serve_p99=2;freshness=600;"
                "alert_freshness=60;changefeed_lag=10;drain_eta=3600")

# The default error budgets (FIREBIRD_SLO_BUDGET): the alerting-grade
# freshness promise, the serve tail, and the black-box prober's failure
# ratio.  Objectives whose metrics never report (no prober running, no
# serve replica) contribute zero burn — a batch-only deployment is
# "no data", never "burned".
DEFAULT_BUDGET_SPEC = ("alert_freshness<60@99.9/28d;"
                       "serve_p99<2@99/7d;probe_errors@99/1d;"
                       "fanout_p99<30@99/7d")

# Multi-window burn-rate defaults (FIREBIRD_SLO_FAST_SEC /
# FIREBIRD_SLO_SLOW_SEC / FIREBIRD_SLO_BURN): page when the error rate
# runs >= 14.4x the budget rate over BOTH the 5-minute and 1-hour
# windows — at that burn a 28d budget dies in under 2 days, fast enough
# to matter, and the slow window filters one-batch blips.
DEFAULT_FAST_SEC = 300.0
DEFAULT_SLOW_SEC = 3600.0
DEFAULT_BURN = 14.4

BUDGET_EVENTS_FILE = "slo_events.jsonl"
BUDGET_EVENT_SCHEMA = "firebird-slo-event/1"

# name -> (kind, metric/field, stat, description)
OBJECTIVES = {
    "batch_p95": ("histogram", "pipeline_drain_seconds", "p95",
                  "steady-state batch seconds (device wait + egress, p95)"),
    "serve_p99": ("histogram", "serve_request_seconds", "p99",
                  "serve /v1 request seconds (admission wait incl., p99)"),
    "freshness": ("watchdog", "last_beat_age_sec", None,
                  "seconds since the last drained batch"),
    # The alerting-grade promise (docs/ALERTS.md, docs/STREAMING.md): a
    # new acquisition's confirmed break is VISIBLE on the alert feed
    # within the target.  The metric field is a fallback CHAIN: the
    # watcher-fed end-to-end histogram (scene publish time -> durable
    # alert append, acquisition_to_alert_seconds) judges when it has
    # data; runs without a watcher (manual `firebird stream`) fall back
    # to the stream-local alert_visible_seconds leg (per-chip ingest
    # start -> durable commit) rather than reporting no_data.
    "alert_freshness": ("histogram",
                        ("acquisition_to_alert_seconds",
                         "alert_visible_seconds"), "p95",
                        "scene publish (or stream ingest start) -> "
                        "alert-visible seconds (p95)"),
    # The replica-coherence promise (docs/SERVING.md): a serve replica
    # applies a changefeed record — and so stops serving stale cached
    # answers for the touched chips — within the target.  The gauge is
    # the age of the newest record the last poll applied (0 = caught
    # up), so the objective judges the serving staleness bound the
    # replica fleet actually ran at.
    "changefeed_lag": ("gauge", "serve_changefeed_lag_seconds", None,
                       "replica changefeed apply lag seconds "
                       "(newest-applied record age at last poll)"),
    # The elastic-fleet promise (docs/ROBUSTNESS.md "Elastic
    # operation"): at the capacity the supervisor is running, the open
    # batch backlog drains within the target.  The gauge is the
    # supervisor's per-tick open-work / trailing-ack-rate estimate; a
    # run with no supervisor has no gauge and reports no_data.
    "drain_eta": ("gauge", "queue_drain_eta_seconds", None,
                  "estimated seconds to drain the open batch backlog "
                  "at the observed ack rate"),
    # The black-box view (obs/prober.py): outage detection must not
    # depend on the sick process reporting itself, so these judge what
    # an outside canary measured — serve latency from a real GET, the
    # scene-drop -> SSE-alert round trip, the webhook sink round trip,
    # and the all-surfaces failure ratio (a "ratio" kind divides two
    # counters; its value/target are fractions, not seconds).
    "probe_p99": ("histogram", "probe_serve_seconds", "p99",
                  "black-box serve GET seconds as the canary prober "
                  "measured them (p99)"),
    "probe_alert": ("histogram", "probe_alert_seconds", "p95",
                    "black-box scene drop -> SSE alert seconds (p95)"),
    "probe_webhook": ("histogram", "probe_webhook_seconds", "p95",
                      "black-box scene drop -> webhook sink seconds "
                      "(p95)"),
    "probe_errors": ("ratio", ("probe_failures", "probe_attempts"), None,
                     "black-box probe failure ratio (failed probes / "
                     "attempted probes, all surfaces)"),
    # The fanout promise (docs/ALERTS.md "Fanout plane"): a rolled-up
    # shard of new alerts is DRAINED — every shard subscriber's cursor
    # at the job's bound — within the target.  The histogram is
    # observed by the fleet worker's fanout handler (rollup stamp ->
    # drain done); deployments with no fanout jobs report no_data.
    "fanout_p99": ("histogram", "fanout_completion_seconds", "p99",
                   "alert rollup -> shard fanout drained seconds (p99)"),
}


def parse_spec(spec: str) -> list[tuple[str, float]]:
    """``"batch_p95=30;serve_p99=2"`` -> [(name, target), ...].

    Raises ValueError on unknown objective names or unparseable targets
    — Config validates at construction (the FIREBIRD_FAULTS fail-fast
    rationale: a typo'd spec silently evaluating nothing is worse than
    a crash at bring-up).
    """
    out: list[tuple[str, float]] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, target = part.partition("=")
        name = name.strip()
        if not sep:
            raise ValueError(f"SLO objective {part!r} is not name=target")
        if name not in OBJECTIVES:
            raise ValueError(
                f"unknown SLO objective {name!r}; known: "
                f"{sorted(OBJECTIVES)}")
        try:
            t = float(target)
        except ValueError as e:
            raise ValueError(
                f"SLO target {target!r} for {name!r} is not a number"
            ) from e
        if t <= 0:
            raise ValueError(f"SLO target for {name!r} must be > 0, got {t}")
        out.append((name, t))
    return out


def evaluate_snapshot(metrics: dict, watchdog: dict | None = None,
                      spec: str | None = None) -> dict:
    """Evaluate the spec against a metrics *snapshot* (the JSON form —
    ``MetricsRegistry.snapshot()`` or a report's ``metrics`` block, so
    live endpoints, per-host shards, and merged fleet reports all
    evaluate identically).  ``watchdog`` is a watchdog snapshot for the
    freshness objective (None: no_data).

    Returns ``{"spec", "ok", "violations", "objectives": [...]}`` —
    ``ok`` is True only when no evaluated objective is violated
    (no_data objectives neither pass nor fail).
    """
    if spec is None or spec == "":
        spec = DEFAULT_SPEC
    if spec == "0":
        return {"spec": "0", "ok": True, "violations": 0, "objectives": []}
    objectives = []
    violations = 0
    hists = (metrics or {}).get("histograms", {})
    for name, target in parse_spec(spec):
        kind, key, stat, desc = OBJECTIVES[name]
        value = None
        if kind == "histogram":
            # A tuple key is a fallback chain: the first histogram with
            # observations judges the objective (alert_freshness above).
            for key in (key if isinstance(key, tuple) else (key,)):
                h = hists.get(key) or {}
                if h.get("count", 0) > 0:
                    value = h.get(stat)
                    break
        elif kind == "gauge":
            # An absent gauge is no_data (a batch run with no serve
            # replica must not pass or fail the coherence objective).
            value = ((metrics or {}).get("gauges") or {}).get(key)
        elif kind == "ratio":
            # Two cumulative counters; zero attempts is no_data (a run
            # with no prober must not pass or fail the probe ratio).
            ctr = (metrics or {}).get("counters") or {}
            den = float(ctr.get(key[1], 0) or 0)
            if den > 0:
                value = min(float(ctr.get(key[0], 0) or 0), den) / den
        else:                            # watchdog field
            if watchdog is not None:
                value = watchdog.get(key)
        ok = None if value is None else bool(value <= target)
        if ok is False:
            violations += 1
        obj = {"name": name, "target_sec": target, "value_sec": value,
               "ok": ok, "description": desc}
        if kind == "histogram":
            obj["metric"] = key
            obj["stat"] = stat
            # Exemplars turn a violated latency objective into a lead:
            # the exact batch/span ids behind the slowest observations.
            ex = (hists.get(key) or {}).get("exemplars")
            if ex and ok is False:
                obj["exemplars"] = ex
        objectives.append(obj)
    return {"spec": spec, "ok": violations == 0, "violations": violations,
            "objectives": objectives}


# ---------------------------------------------------------------------------
# Error budgets: multi-window burn rates over the durable series store
# ---------------------------------------------------------------------------

_WINDOW_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def _parse_window(raw: str, part: str) -> float:
    raw = raw.strip()
    unit = _WINDOW_UNITS.get(raw[-1:].lower())
    num = raw[:-1] if unit else raw
    try:
        sec = float(num) * (unit or 1.0)
    except ValueError as e:
        raise ValueError(
            f"budget window {raw!r} in {part!r} is not "
            "<number>[s|m|h|d]") from e
    if sec <= 0:
        raise ValueError(f"budget window in {part!r} must be > 0")
    return sec


def parse_budget_spec(spec: str) -> list[dict]:
    """``"alert_freshness<60@99.9/28d;probe_errors@99/1d"`` -> budget
    objective dicts.  Grammar per part: ``name[<threshold]@target/window``
    — threshold (seconds) is required for histogram/gauge objectives
    (what counts as a bad observation), forbidden for ratio objectives
    (bad/total are the two counters themselves); target is the good
    percentage (0 < target < 100); window is ``<number>[s|m|h|d]``.

    Raises ValueError on unknown names, watchdog-kind objectives (a
    point-in-time liveness field has no per-observation history to
    budget), or malformed parts — Config validates at construction
    (the parse_spec fail-fast rationale).
    """
    out: list[dict] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        head, sep, rest = part.partition("@")
        if not sep:
            raise ValueError(
                f"budget {part!r} is not name[<threshold]@target/window")
        name, tsep, thr_raw = head.partition("<")
        name = name.strip()
        if name not in OBJECTIVES:
            raise ValueError(
                f"unknown budget objective {name!r}; known: "
                f"{sorted(OBJECTIVES)}")
        kind, key, stat, desc = OBJECTIVES[name]
        if kind == "watchdog":
            raise ValueError(
                f"budget objective {name!r} is watchdog-kind — a "
                "liveness field has no observation history to budget")
        threshold = None
        if tsep:
            try:
                threshold = float(thr_raw)
            except ValueError as e:
                raise ValueError(
                    f"budget threshold {thr_raw!r} in {part!r} is not "
                    "a number") from e
            if threshold <= 0:
                raise ValueError(
                    f"budget threshold in {part!r} must be > 0")
        if kind == "ratio" and threshold is not None:
            raise ValueError(
                f"budget {part!r}: ratio objective {name!r} takes no "
                "<threshold (its counters already split bad/total)")
        if kind != "ratio" and threshold is None:
            raise ValueError(
                f"budget {part!r} needs a <threshold: what counts as "
                f"a bad {kind} observation")
        target_raw, wsep, window_raw = rest.partition("/")
        if not wsep:
            raise ValueError(
                f"budget {part!r} is missing its /window")
        try:
            target_pct = float(target_raw)
        except ValueError as e:
            raise ValueError(
                f"budget target {target_raw!r} in {part!r} is not a "
                "number") from e
        if not 0.0 < target_pct < 100.0:
            raise ValueError(
                f"budget target in {part!r} must be a percentage in "
                f"(0, 100), got {target_pct}")
        out.append({"name": name, "kind": kind, "metric": key,
                    "stat": stat, "threshold": threshold,
                    "target_pct": target_pct,
                    "target": target_pct / 100.0,
                    "window_sec": _parse_window(window_raw, part),
                    "description": desc})
    return out


def _pick_resolution(window_sec: float, resolutions) -> int:
    """The coarsest series resolution that still gives a window >= ~4
    buckets — fast windows read the 10s ring, 28d budgets the 1h one."""
    floor = min(resolutions)
    cands = [r for r in resolutions
             if r <= max(window_sec / 4.0, floor)]
    return max(cands) if cands else floor


def _window_stats(points: list, budget: dict, t0: float,
                  t1: float) -> dict:
    """bad/total over one window from merged per-source deltas.  An
    empty window (no source reported the metric inside it) is
    ``empty: True`` with zero bad and zero total — the no-data-is-
    zero-burn rule (it must neither page nor bank credit)."""
    from firebird_tpu.obs import series as series_mod

    kind, key = budget["kind"], budget["metric"]
    bad = total = 0.0
    empty = True
    if kind == "histogram":
        for m in (key if isinstance(key, tuple) else (key,)):
            win = series_mod.hist_window(points, m, t0, t1)
            if win is not None and win["count"] > 0:
                total = float(win["count"])
                bad = series_mod.hist_over_threshold(
                    win, budget["threshold"])
                empty = False
                break
    elif kind == "gauge":
        samples = series_mod.gauge_samples(points, key, t0, t1)
        if samples:
            total = float(len(samples))
            bad = float(sum(1 for (_, _, v) in samples
                            if v > budget["threshold"]))
            empty = False
    else:                                # ratio: (bad, total) counters
        den = series_mod.counter_window(points, key[1], t0, t1)
        if den is not None and den > 0:
            num = series_mod.counter_window(points, key[0], t0, t1) or 0.0
            total = float(den)
            bad = min(float(num), total)
            empty = False
    ratio = (bad / total) if total > 0 else None
    return {"t0": t0, "t1": t1, "sec": round(t1 - t0, 3),
            "total": total, "bad": bad, "error_ratio": ratio,
            "empty": empty}


def evaluate_budgets(directory: str, spec: str | None = None, *,
                     now: float | None = None,
                     fast_sec: float = DEFAULT_FAST_SEC,
                     slow_sec: float = DEFAULT_SLOW_SEC,
                     burn_threshold: float = DEFAULT_BURN,
                     resolutions=None) -> dict:
    """Evaluate the budget spec against the series rings under
    ``directory`` (obs/series.py).  Every number is re-derived from
    summed per-source deltas across EVERY host's points — the fleet
    verdict, never one process's self-report.

    Per budget: the full rolling window decides exhaustion (cumulative
    bad > (1-target) x total), and the fast/slow burn-window pair
    decides ``burning`` (BOTH >= ``burn_threshold``).  ``ok`` is None
    when every window was empty (no data -> zero burn), False on
    exhaustion or burning, True otherwise; ``empty_windows`` names the
    windows that had no data.
    """
    from firebird_tpu.obs import series as series_mod

    if spec is None or spec == "":
        spec = DEFAULT_BUDGET_SPEC
    if spec == "0":
        return {"spec": "0", "ok": True, "violations": 0, "budgets": []}
    if now is None:
        now = time.time()
    if resolutions is None:
        resolutions = series_mod.RESOLUTIONS
    budgets = []
    violations = 0
    srcs: set = set()
    for b in parse_budget_spec(spec):
        windows: dict = {}
        burn_raw: dict = {}
        for wname, wsec in (("window", b["window_sec"]),
                            ("fast", fast_sec), ("slow", slow_sec)):
            res = _pick_resolution(wsec, resolutions)
            # Two extra buckets of lookback feed the pre-window
            # baseline the cumulative-delta math needs.
            points = series_mod.read_points(
                directory, res, now - wsec - 2 * res, now)
            srcs.update(p.get("src") for p in points)
            w = _window_stats(points, b, now - wsec, now)
            w["resolution_sec"] = res
            if w["error_ratio"] is None:
                burn_raw[wname] = None
                w["burn_rate"] = None
            else:
                # The paging decision below compares the UNROUNDED
                # ratio; rounding is display-only (a window burning at
                # 14.3996x must not page a 14.4 threshold).
                burn_raw[wname] = (w["error_ratio"]
                                   / max(1.0 - b["target"], 1e-9))
                w["burn_rate"] = round(burn_raw[wname], 3)
            windows[wname] = w
        full = windows["window"]
        allowed = (1.0 - b["target"]) * full["total"]
        exhausted = (not full["empty"]) and full["bad"] > allowed
        burning = (not windows["fast"]["empty"]
                   and not windows["slow"]["empty"]
                   and burn_raw["fast"] >= burn_threshold
                   and burn_raw["slow"] >= burn_threshold)
        empty_names = [n for n in ("window", "fast", "slow")
                       if windows[n]["empty"]]
        ok = None if len(empty_names) == 3 else \
            not (exhausted or burning)
        if ok is False:
            violations += 1
        budgets.append({
            "name": b["name"], "kind": b["kind"],
            "metric": b["metric"], "threshold": b["threshold"],
            "target_pct": b["target_pct"],
            "window_sec": b["window_sec"],
            "description": b["description"],
            "total": full["total"], "bad": full["bad"],
            "allowed_bad": round(allowed, 6),
            "budget_spent": (round(full["bad"] / allowed, 4)
                             if allowed > 0 else None),
            "exhausted": exhausted, "burning": burning,
            "fast_burn": windows["fast"]["burn_rate"],
            "slow_burn": windows["slow"]["burn_rate"],
            "empty_windows": empty_names, "ok": ok,
            "windows": windows,
        })
    return {"spec": spec, "evaluated_at": now,
            "fast_sec": fast_sec, "slow_sec": slow_sec,
            "burn_threshold": burn_threshold,
            "sources": sorted(s for s in srcs if s),
            "ok": violations == 0, "violations": violations,
            "budgets": budgets}


# -- durable budget-state events --------------------------------------------

def budget_events_path(directory: str) -> str:
    return os.path.join(directory, BUDGET_EVENTS_FILE)


def read_budget_events(directory: str) -> list[dict]:
    """Every parseable budget event under ``directory``, append order.
    Torn tail lines are skipped (the spool reader's rule)."""
    out: list[dict] = []
    try:
        with open(budget_events_path(directory)) as f:
            for line in f:
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue            # torn tail line
                if isinstance(doc, dict) and doc.get("name"):
                    out.append(doc)
    except OSError:
        pass
    return out


def _budget_state(b: dict) -> str:
    if b.get("exhausted"):
        return "exhausted"
    if b.get("burning"):
        return "burning"
    return "no_data" if b.get("ok") is None else "ok"


def record_budget_events(directory: str, verdict: dict,
                         now: float | None = None) -> list[dict]:
    """Append one durable event per budget whose state CHANGED into or
    out of trouble (exhausted/burning) since the last recorded event —
    flush-per-line JSONL next to the series rings, so exhaustion
    survives every process that witnessed it.  ok <-> no_data flaps are
    not recorded (a quiet fleet is not an incident timeline).  Returns
    the appended events; I/O trouble degrades to none appended."""
    last: dict = {}
    for ev in read_budget_events(directory):
        last[ev["name"]] = ev.get("state")
    appended = []
    trouble = ("exhausted", "burning")
    for b in verdict.get("budgets", ()):
        state = _budget_state(b)
        prev = last.get(b["name"])
        if state == prev or (state not in trouble
                             and prev not in trouble):
            continue
        appended.append({
            "kind": "budget_event", "schema": BUDGET_EVENT_SCHEMA,
            "t": time.time() if now is None else float(now),
            "name": b["name"], "state": state, "prev": prev,
            "bad": b["bad"], "total": b["total"],
            "allowed_bad": b["allowed_bad"],
            "window_sec": b["window_sec"],
            "fast_burn": b["fast_burn"], "slow_burn": b["slow_burn"]})
    if appended:
        try:
            os.makedirs(directory, exist_ok=True)
            with open(budget_events_path(directory), "a") as f:
                for ev in appended:
                    f.write(json.dumps(ev, separators=(",", ":")) + "\n")
                    f.flush()
        except OSError:
            return []    # degraded telemetry, never a crashed evaluator
    return appended


def evaluate_and_record(directory: str, spec: str | None = None,
                        **kwargs) -> dict:
    """:func:`evaluate_budgets` + :func:`record_budget_events`; the
    verdict gains ``events_appended``."""
    verdict = evaluate_budgets(directory, spec, **kwargs)
    verdict["events_appended"] = record_budget_events(
        directory, verdict, now=verdict.get("evaluated_at"))
    return verdict
