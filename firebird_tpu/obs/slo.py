"""SLO tracking: declared objectives evaluated against live histograms.

The obs stack records every latency but nothing *judges* them — an
operator watching ``/progress`` must remember what "healthy" looks like
for each number.  This module makes the objectives declarations: a spec
(``FIREBIRD_SLO`` / ``Config.slo``) names each objective and its
target, evaluation reads the SAME metric snapshots the report and
``/metrics`` expose, and the verdict is served live at ``/slo``
(obs/server.py) and summarized in every ``obs_report.json`` (fleet
merges re-evaluate over the merged histograms).

Objectives (the spec grammar is ``name=target;name=target``; targets
are seconds):

``batch_p95``
    p95 of ``pipeline_drain_seconds`` — the steady-state batch wall
    time as the drain thread sees it (device wait + egress; dispatch is
    asynchronous so this histogram is where a slow batch shows up).
``serve_p99``
    p99 of ``serve_request_seconds`` — the query layer's tail latency,
    admission wait included.
``freshness``
    Seconds since the last drained batch (the watchdog's
    ``last_beat_age_sec``) — the liveness half of an alerting-grade
    freshness promise: results are at most this stale.
``changefeed_lag``
    The ``serve_changefeed_lag_seconds`` gauge — how far behind the
    write feed a serve replica's cache-coherence loop ran at its last
    poll (docs/SERVING.md's staleness bound, measured).

An objective whose metric has no data reports ``ok: null`` ("no_data")
rather than passing or failing — a serve SLO must not fail a batch run
that never served a request.  ``FIREBIRD_SLO=0`` disables evaluation.
"""

from __future__ import annotations

DEFAULT_SPEC = ("batch_p95=30;serve_p99=2;freshness=600;"
                "alert_freshness=60;changefeed_lag=10;drain_eta=3600")

# name -> (kind, metric/field, stat, description)
OBJECTIVES = {
    "batch_p95": ("histogram", "pipeline_drain_seconds", "p95",
                  "steady-state batch seconds (device wait + egress, p95)"),
    "serve_p99": ("histogram", "serve_request_seconds", "p99",
                  "serve /v1 request seconds (admission wait incl., p99)"),
    "freshness": ("watchdog", "last_beat_age_sec", None,
                  "seconds since the last drained batch"),
    # The alerting-grade promise (docs/ALERTS.md, docs/STREAMING.md): a
    # new acquisition's confirmed break is VISIBLE on the alert feed
    # within the target.  The metric field is a fallback CHAIN: the
    # watcher-fed end-to-end histogram (scene publish time -> durable
    # alert append, acquisition_to_alert_seconds) judges when it has
    # data; runs without a watcher (manual `firebird stream`) fall back
    # to the stream-local alert_visible_seconds leg (per-chip ingest
    # start -> durable commit) rather than reporting no_data.
    "alert_freshness": ("histogram",
                        ("acquisition_to_alert_seconds",
                         "alert_visible_seconds"), "p95",
                        "scene publish (or stream ingest start) -> "
                        "alert-visible seconds (p95)"),
    # The replica-coherence promise (docs/SERVING.md): a serve replica
    # applies a changefeed record — and so stops serving stale cached
    # answers for the touched chips — within the target.  The gauge is
    # the age of the newest record the last poll applied (0 = caught
    # up), so the objective judges the serving staleness bound the
    # replica fleet actually ran at.
    "changefeed_lag": ("gauge", "serve_changefeed_lag_seconds", None,
                       "replica changefeed apply lag seconds "
                       "(newest-applied record age at last poll)"),
    # The elastic-fleet promise (docs/ROBUSTNESS.md "Elastic
    # operation"): at the capacity the supervisor is running, the open
    # batch backlog drains within the target.  The gauge is the
    # supervisor's per-tick open-work / trailing-ack-rate estimate; a
    # run with no supervisor has no gauge and reports no_data.
    "drain_eta": ("gauge", "queue_drain_eta_seconds", None,
                  "estimated seconds to drain the open batch backlog "
                  "at the observed ack rate"),
}


def parse_spec(spec: str) -> list[tuple[str, float]]:
    """``"batch_p95=30;serve_p99=2"`` -> [(name, target), ...].

    Raises ValueError on unknown objective names or unparseable targets
    — Config validates at construction (the FIREBIRD_FAULTS fail-fast
    rationale: a typo'd spec silently evaluating nothing is worse than
    a crash at bring-up).
    """
    out: list[tuple[str, float]] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, target = part.partition("=")
        name = name.strip()
        if not sep:
            raise ValueError(f"SLO objective {part!r} is not name=target")
        if name not in OBJECTIVES:
            raise ValueError(
                f"unknown SLO objective {name!r}; known: "
                f"{sorted(OBJECTIVES)}")
        try:
            t = float(target)
        except ValueError as e:
            raise ValueError(
                f"SLO target {target!r} for {name!r} is not a number"
            ) from e
        if t <= 0:
            raise ValueError(f"SLO target for {name!r} must be > 0, got {t}")
        out.append((name, t))
    return out


def evaluate_snapshot(metrics: dict, watchdog: dict | None = None,
                      spec: str | None = None) -> dict:
    """Evaluate the spec against a metrics *snapshot* (the JSON form —
    ``MetricsRegistry.snapshot()`` or a report's ``metrics`` block, so
    live endpoints, per-host shards, and merged fleet reports all
    evaluate identically).  ``watchdog`` is a watchdog snapshot for the
    freshness objective (None: no_data).

    Returns ``{"spec", "ok", "violations", "objectives": [...]}`` —
    ``ok`` is True only when no evaluated objective is violated
    (no_data objectives neither pass nor fail).
    """
    if spec is None or spec == "":
        spec = DEFAULT_SPEC
    if spec == "0":
        return {"spec": "0", "ok": True, "violations": 0, "objectives": []}
    objectives = []
    violations = 0
    hists = (metrics or {}).get("histograms", {})
    for name, target in parse_spec(spec):
        kind, key, stat, desc = OBJECTIVES[name]
        value = None
        if kind == "histogram":
            # A tuple key is a fallback chain: the first histogram with
            # observations judges the objective (alert_freshness above).
            for key in (key if isinstance(key, tuple) else (key,)):
                h = hists.get(key) or {}
                if h.get("count", 0) > 0:
                    value = h.get(stat)
                    break
        elif kind == "gauge":
            # An absent gauge is no_data (a batch run with no serve
            # replica must not pass or fail the coherence objective).
            value = ((metrics or {}).get("gauges") or {}).get(key)
        else:                            # watchdog field
            if watchdog is not None:
                value = watchdog.get(key)
        ok = None if value is None else bool(value <= target)
        if ok is False:
            violations += 1
        obj = {"name": name, "target_sec": target, "value_sec": value,
               "ok": ok, "description": desc}
        if kind == "histogram":
            obj["metric"] = key
            obj["stat"] = stat
            # Exemplars turn a violated latency objective into a lead:
            # the exact batch/span ids behind the slowest observations.
            ex = (hists.get(key) or {}).get("exemplars")
            if ex and ok is False:
                obj["exemplars"] = ex
        objectives.append(obj)
    return {"spec": spec, "ok": violations == 0, "violations": violations,
            "objectives": objectives}
