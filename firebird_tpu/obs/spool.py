"""Durable per-process telemetry spool: the fleet telemetry plane's disk leg.

The obs stack through PR 8 is per-process and per-run: the span tracer
exports at run END, the metrics registry dies with its process, and the
flight recorder dumps only on signals it can catch — a SIGKILLed fleet
worker (the autoscaler's last resort, fleet/supervisor.py) takes its
telemetry with it.  The spool closes that gap: every fleet-role process
appends its span/mark events and periodic metric-registry snapshots to
a bounded ring of JSONL segment files next to the store, flushed per
line, so whatever survives the process is already on disk for
``firebird trace collect`` (obs/collect.py) and ``firebird top``.

Design points (docs/OBSERVABILITY.md "Fleet telemetry plane"):

- **Bounded.**  ``FIREBIRD_TELEMETRY`` events per segment times
  ``FIREBIRD_TELEMETRY_SEGMENTS`` segment files per process; a full
  segment seals and the ring truncate-reopens the oldest.  A standing
  watcher cannot grow telemetry without bound.
- **Crash-safe.**  Append-only JSON lines, ``flush()`` per event: the
  data reaches the OS before the next span runs, so SIGKILL loses at
  most the line being formatted.  No fsync — a host power loss may drop
  the tail, which is telemetry-grade acceptable (the flight recorder +
  postmortem path owns crash forensics).
- **Self-describing.**  Every segment opens with a header line stamping
  pid/role/run_id/host, so the collector needs no side index and a
  stray segment from a dead pid still attributes correctly.
- **Zero-cost disarmed.**  Arming installs the spool as a tracing span
  sink (tracing.set_spool); disarmed, ``tracing.span()`` keeps its
  one-global-read no-op gate and :func:`mark` is one module read + None
  check — the FIREBIRD_TELEMETRY=0 hot path is byte-identical to the
  pre-spool one.
"""

from __future__ import annotations

import json
import os
import threading
import time

from firebird_tpu.obs import jsonlog
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import tracing

SPOOL_SCHEMA = "firebird-telemetry-spool/1"

# Segment file name: spool.<role>.<pid>.<segment>.jsonl — the glob the
# collector scans.  role/pid in the NAME (not only the header) lets
# `firebird top` group files without parsing every segment.
SPOOL_GLOB = "spool.*.jsonl"

# Fleet roles that arm by default (cli.py): the standing multi-process
# fleet whose telemetry would otherwise die with each process.
FLEET_ROLES = ("watcher", "worker", "supervisor", "deliverer", "serve",
               "prober")


def spool_dir(cfg) -> str | None:
    """The spool directory for a config: ``cfg.telemetry_dir`` when
    set, else ``telemetry/`` next to the results store (the
    quarantine.json placement rule — None for the memory backend, which
    has no cross-process 'next to')."""
    if cfg.telemetry_dir:
        return cfg.telemetry_dir
    from firebird_tpu.driver import quarantine as qlib

    d = qlib._artifact_dir(cfg)
    return None if d is None else os.path.join(d, "telemetry")


class TelemetrySpool:
    """One process's append-only telemetry spool (a bounded segment
    ring).  Thread-safe: span exits arrive from every pipeline thread."""

    def __init__(self, directory: str, role: str, run_id: str | None = None,
                 *, events_per_segment: int = 4096, segments: int = 4,
                 snapshot_sec: float = 5.0):
        if events_per_segment < 1:
            raise ValueError("events_per_segment must be >= 1, got "
                             f"{events_per_segment}")
        if segments < 2:
            raise ValueError(f"segments must be >= 2, got {segments}")
        self.dir = directory
        self.role = role
        self.run_id = run_id
        self.pid = os.getpid()
        self.events_per_segment = int(events_per_segment)
        self.segments = int(segments)
        self.snapshot_sec = float(snapshot_sec)
        self._lock = threading.Lock()
        self._seg = 0          # guarded-by: _lock
        self._n = 0            # guarded-by: _lock
        self._f = None         # guarded-by: _lock
        self._last_snap = 0.0  # guarded-by: _lock
        self._dropped = 0      # guarded-by: _lock (I/O errors, not ring)
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self._open_segment(0)

    # -- segment ring ------------------------------------------------------

    def segment_path(self, seg: int) -> str:
        return os.path.join(
            self.dir, f"spool.{self.role}.{self.pid}.{seg}.jsonl")

    def _open_segment(self, seg: int) -> None:
        # guarded-by: _lock (callers hold it)
        if self._f is not None:
            self._f.close()
        self._seg = seg
        self._n = 0
        self._f = open(self.segment_path(seg), "w")
        header = {"kind": "header", "schema": SPOOL_SCHEMA,
                  "pid": self.pid, "role": self.role,
                  "run_id": self.run_id, "host": jsonlog.HOST,
                  "segment": seg, "t": time.time()}
        self._f.write(json.dumps(header, separators=(",", ":")) + "\n")
        self._f.flush()

    def _write(self, doc: dict) -> None:
        line = json.dumps(doc, separators=(",", ":"), default=str)
        with self._lock:
            if self._f is None:      # closed: late span from a worker
                return               # thread during teardown
            try:
                if self._n >= self.events_per_segment:
                    self._open_segment((self._seg + 1) % self.segments)
                self._f.write(line + "\n")
                self._f.flush()
                self._n += 1
            except OSError:
                # Disk trouble must degrade telemetry, never the
                # pipeline writing it (the alert-log unavailable rule).
                self._dropped += 1

    # -- event feeds -------------------------------------------------------

    def span_event(self, name: str, dur_s: float,
                   trace: str | None) -> None:
        """The tracing span sink (tracing.set_spool): one closed span.
        Wall-clock start is derived (now - dur) so the collector can
        place spans from different processes on one absolute axis."""
        t1 = time.time()
        self._write({"kind": "span", "name": name,
                     "t0": t1 - dur_s, "dur": dur_s, "trace": trace,
                     "tid": threading.get_ident(),
                     "thread": threading.current_thread().name})
        self._maybe_snapshot(t1)

    def mark(self, name: str, *, trace: str | None = None,
             t: float | None = None, **attrs) -> None:
        """An instant event — the cross-process causal-chain joints
        (scene_enqueued, job_claimed, alert_appended, alert_delivered)
        the critical-path breakdown is computed from."""
        doc = {"kind": "mark", "name": name, "t": time.time()
               if t is None else float(t), "trace": trace,
               "tid": threading.get_ident()}
        if attrs:
            doc["attrs"] = attrs
        self._write(doc)

    def _maybe_snapshot(self, now: float) -> None:
        with self._lock:
            due = now - self._last_snap >= self.snapshot_sec
            if due:
                self._last_snap = now
        if due:
            self.snapshot()

    def snapshot(self) -> None:
        """Write one metric-registry snapshot line (counters, gauges,
        histogram bucket counts — the mergeable form, so `firebird top`
        and the collector re-derive fleet percentiles exactly as the
        obs_report merge policy does)."""
        self._write({"kind": "snap", "t": time.time(),
                     "metrics": obs_metrics.get_registry().snapshot()})

    def status(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "role": self.role, "pid": self.pid,
                    "segment": self._seg, "events": self._n,
                    "dropped": self._dropped}

    def close(self) -> None:
        try:
            self.snapshot()   # final registry state for the collector
        finally:
            with self._lock:
                if self._f is not None:
                    self._f.close()
                    self._f = None


# ---------------------------------------------------------------------------
# Module-level arm/disarm (the flightrec pattern): one spool per process
# ---------------------------------------------------------------------------

_spool: TelemetrySpool | None = None


def arm(cfg, role: str, run_id: str | None = None) -> TelemetrySpool | None:
    """Arm the process spool for ``role`` and install it as the tracing
    span sink.  No-ops (returns the existing spool) when already armed;
    returns None when disabled (FIREBIRD_TELEMETRY=0) or the store has
    no file-backed 'next to'."""
    global _spool
    if _spool is not None:
        return _spool
    if cfg.telemetry <= 0:
        return None
    d = spool_dir(cfg)
    if d is None:
        return None
    sp = TelemetrySpool(
        d, role, run_id, events_per_segment=cfg.telemetry,
        segments=cfg.telemetry_segments,
        snapshot_sec=cfg.telemetry_snapshot_sec)
    # Single-reference swap from the process-owning thread (cli
    # bring-up); mark() reads the reference once.
    _spool = sp  # firebird-lint: disable=ownership-global-mutation
    tracing.set_spool(sp)
    return sp


def disarm() -> None:
    """Close the process spool and uninstall the span sink."""
    global _spool
    sp = _spool
    # See arm(): single-reference swap, process-owning thread only.
    _spool = None  # firebird-lint: disable=ownership-global-mutation
    tracing.set_spool(None)
    if sp is not None:
        sp.close()


def active() -> TelemetrySpool | None:
    return _spool


def mark(name: str, *, trace: str | None = None, t: float | None = None,
         **attrs) -> None:
    """Record an instant event on the armed spool; one module read +
    None check when disarmed (safe on any hot path)."""
    sp = _spool
    if sp is not None:
        sp.mark(name, trace=trace, t=t, **attrs)
