"""Durable metric history: spool snapshots -> fixed-resolution rings.

The telemetry spool (obs/spool.py) already persists periodic metric-
registry snapshots per process, but each spool is a short ring scoped to
one pid — history dies with segment rotation and a restart starts a new
file set.  This module is the history leg the SLO plane needs: an
append-only time-series store next to the spools, downsampling every
snapshot into fixed-resolution rings (10s / 5min / 1h) that survive
process death, SIGKILL, and reader restarts — the substrate for
``firebird top`` sparklines, ``/metrics/history``, and the error-budget
burn-rate windows in obs/slo.py.

Design points (the spool's discipline, re-applied to the read side):

- **Reader-side ingestion.**  Points are written by whoever *reads* the
  spools (``firebird slo`` / ``firebird top`` / the ops endpoint / the
  prober loop), never by the pipeline hot path — FIREBIRD_TELEMETRY=0
  keeps its zero-cost guarantee because no snapshots exist to ingest.
- **Snapshot clocks only.**  A point's bucket is derived from the
  wall-clock ``t`` the *emitting* process stamped on its snap line —
  never the ingesting reader's clock (the PR 15 park-expiry bug was
  exactly such a clock-domain mix; a reader on a skewed host must not
  re-time another host's history).
- **Bounded rings, crash-safe lines.**  One segment ring per
  resolution per ingesting pid (``series.<res>.<pid>.<seg>.jsonl``),
  ``flush()`` per line, OSError degrades to a drop counter.  A full
  segment truncate-reopens the oldest; a torn tail line is skipped by
  readers.  A (re)opened store RESUMES its newest on-disk segment in
  append mode — truncation only ever happens when the ring genuinely
  wraps, so reopening never destroys a prior incarnation's durable
  points.  Ring files whose entire content has aged past their
  resolution's retention are garbage-collected at open, so dead
  incarnations (cron runs, killed fleets) do not grow the directory
  without bound.
- **Idempotent.**  Re-ingesting the same spools is a no-op: a bucket
  already holding a point at the same or newer snapshot time is
  skipped, and live-bucket refreshes are throttled to ``res/8`` so the
  coarse rings keep their retention (counters are cumulative, so a
  skipped tail snapshot just lands in the next bucket's delta).

Retention math (documented in docs/OBSERVABILITY.md): a ring holds
``FIREBIRD_SERIES x FIREBIRD_SERIES_SEGMENTS`` lines shared by every
source process; one bucket costs 1 line when closed plus at most 8
throttled refreshes while live, so a ring of N lines retains at least
``N x res / 9`` seconds of history per source, typically ``~N x res``.
"""

from __future__ import annotations

import glob
import json
import os
import threading

from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import spool as spool_mod

SERIES_SCHEMA = "firebird-metric-series/1"

# Ring file name: series.<resolution-sec>.<ingesting-pid>.<segment>.jsonl
SERIES_GLOB = "series.*.jsonl"

# Fixed downsampling resolutions (seconds per bucket): sparkline-grade,
# burn-window-grade, and budget-window-grade history.
RESOLUTIONS = (10, 300, 3600)

# A live (newest) bucket accepts a refreshed point at most this often,
# in fractions of the resolution — bounds lines-per-bucket so the
# coarse rings keep their retention (module docstring math).
_LIVE_REFRESH_FRACTION = 8


def series_dir(cfg) -> str | None:
    """The series directory for a config: ``cfg.series_dir`` when set,
    else ``series/`` inside the telemetry spool directory (None when
    the spool has no home — the memory backend)."""
    if getattr(cfg, "series_dir", ""):
        return cfg.series_dir
    d = spool_mod.spool_dir(cfg)
    return None if d is None else os.path.join(d, "series")


def _compact(metrics: dict) -> dict:
    """The point payload: counters + gauges verbatim, histograms
    reduced to the mergeable cumulative form (count/sum/buckets —
    percentiles re-derive from bucket deltas, never stored)."""
    out = {"counters": dict(metrics.get("counters") or {}),
           "gauges": dict(metrics.get("gauges") or {}),
           "histograms": {}}
    for name, h in (metrics.get("histograms") or {}).items():
        out["histograms"][name] = {
            "count": h.get("count", 0), "sum": h.get("sum", 0.0),
            "bucket_bounds": list(h.get("bucket_bounds") or ()),
            "bucket_counts": list(h.get("bucket_counts") or ())}
    return out


class SeriesStore:
    """One ingesting process's series writer: per-resolution segment
    rings plus the dedup state that makes re-ingestion idempotent.
    Thread-safe (the ops endpoint and a CLI loop may share one)."""

    def __init__(self, directory: str, *, points_per_segment: int = 512,
                 segments: int = 4, resolutions=RESOLUTIONS):
        if points_per_segment < 1:
            raise ValueError("points_per_segment must be >= 1, got "
                             f"{points_per_segment}")
        if segments < 2:
            raise ValueError(f"segments must be >= 2, got {segments}")
        self.dir = directory
        self.pid = os.getpid()
        self.points_per_segment = int(points_per_segment)
        self.segments = int(segments)
        self.resolutions = tuple(int(r) for r in resolutions)
        # Lock order: _ingest_lock (whole-batch atomicity for
        # concurrent ingesters sharing one instance) outside _lock
        # (rings + dedup state).
        self._ingest_lock = threading.Lock()
        self._lock = threading.Lock()
        self._rings: dict = {}      # guarded-by: _lock  res -> {seg,n,f}
        self._state: dict = {}      # guarded-by: _lock  (res,src) -> (b,t)
        self._dropped = 0           # guarded-by: _lock
        self._gc_removed = 0        # guarded-by: _lock
        os.makedirs(directory, exist_ok=True)
        self._load_state()

    # -- segment rings -----------------------------------------------------

    def segment_path(self, res: int, seg: int) -> str:
        return os.path.join(
            self.dir, f"series.{int(res)}.{self.pid}.{seg}.jsonl")

    def _open_segment(self, res: int, seg: int, *, append: bool = False,
                      n: int = 0):
        # guarded-by: _lock (callers hold it)
        ring = self._rings.setdefault(res, {"seg": 0, "n": 0, "f": None})
        if ring["f"] is not None:
            ring["f"].close()
        ring["seg"], ring["n"] = seg, n
        ring["f"] = open(self.segment_path(res, seg),
                         "a" if append else "w")
        if not append:
            header = {"kind": "header", "schema": SERIES_SCHEMA,
                      "pid": self.pid, "res": int(res), "segment": seg}
            ring["f"].write(json.dumps(header, separators=(",", ":"))
                            + "\n")
            ring["f"].flush()
        return ring

    def _resume_point(self, res: int) -> tuple | None:
        """Where this pid's ring resumes after a (re)open: the newest
        existing segment (by last point time, then mtime) and its
        occupied line count, or None when no segment exists yet.
        Resuming in APPEND mode is what keeps a re-opened store — same
        process, same pid — from truncating a prior incarnation's
        durable points; a segment is only ever truncated when the ring
        genuinely wraps onto it."""
        # guarded-by: _lock (callers hold it)
        best_key, best = None, None
        for seg in range(self.segments):
            path = self.segment_path(res, seg)
            try:
                mtime = os.path.getmtime(path)
                n, last_t = 0, float("-inf")
                with open(path) as f:
                    for line in f:
                        try:
                            doc = json.loads(line)
                        except ValueError:
                            n += 1      # a torn line still fills a slot
                            continue
                        if isinstance(doc, dict) \
                                and doc.get("kind") == "pt":
                            n += 1
                            last_t = max(last_t,
                                         float(doc.get("t", 0.0)))
            except OSError:
                continue
            key = (last_t, mtime, seg)
            if best_key is None or key > best_key:
                best_key, best = key, (seg, n)
        return best

    def _write(self, res: int, doc: dict) -> None:
        line = json.dumps(doc, separators=(",", ":"), default=str)
        with self._lock:
            try:
                ring = self._rings.get(res)
                if ring is None or ring["f"] is None:
                    resume = self._resume_point(res)
                    if resume is None:
                        ring = self._open_segment(res, 0)
                    elif resume[1] >= self.points_per_segment:
                        # The resumed segment is already full: a
                        # genuine ring wrap, the one case where
                        # truncating the next slot is correct.
                        ring = self._open_segment(
                            res, (resume[0] + 1) % self.segments)
                    else:
                        ring = self._open_segment(
                            res, resume[0], append=True, n=resume[1])
                elif ring["n"] >= self.points_per_segment:
                    ring = self._open_segment(
                        res, (ring["seg"] + 1) % self.segments)
                ring["f"].write(line + "\n")
                ring["f"].flush()
                ring["n"] += 1
            except OSError:
                # Disk trouble degrades history, never the reader
                # writing it (the spool's own rule).
                self._dropped += 1

    def _load_state(self) -> None:
        """Rebuild the dedup state from EVERY pid's rings on disk (so a
        restarted ingester — or a second one — never re-appends points
        an earlier incarnation already durably wrote), then
        garbage-collect ring files whose whole content has aged out of
        their resolution's retention."""
        with self._lock:
            files = _scan_files(self.dir)
            for pts in files.values():
                for pt in pts:
                    key = (pt["res"], pt["src"])
                    cur = self._state.get(key)
                    cand = (pt["b"], pt["t"])
                    if cur is None or cand > cur:
                        self._state[key] = cand
            self._gc_locked(files)

    def _gc_locked(self, files: dict) -> None:
        """Reclaim dead incarnations' ring files.  Each ingesting pid
        (every cron ``firebird slo`` run, every killed fleet) leaves up
        to resolutions x segments files behind; without collection the
        directory — and every ``_read_raw`` walk over it — grows
        without bound.  A file whose NEWEST point predates its
        resolution's whole-ring retention (``points_per_segment x
        segments x res`` seconds) can no longer serve any window the
        ring itself would have retained, so it is unlinked.  Staleness
        is judged against the newest point at the same resolution —
        the emitters' clock domain, never this reader's wall clock
        (historic spools must stay replayable) — and never touches
        this pid's own files (they may be live open handles)."""
        # guarded-by: _lock (called at open, before any ring opens)
        res_newest: dict = {}
        stamped: dict = {}
        for path, pts in files.items():
            name = _parse_ring_name(path)
            if name is None or not pts:
                continue        # foreign file / header-only segment
            newest = max(float(p.get("t", 0.0)) for p in pts)
            stamped[path] = (name, newest)
            res = name[0]
            res_newest[res] = max(res_newest.get(res, newest), newest)
        for path, ((res, pid, _seg), newest) in stamped.items():
            if pid == self.pid:
                continue
            horizon = self.points_per_segment * self.segments * res
            if newest < res_newest[res] - horizon:
                try:
                    os.remove(path)
                    self._gc_removed += 1
                except OSError:
                    pass

    # -- ingestion ---------------------------------------------------------

    def ingest_events(self, events: list) -> int:
        """Downsample spool snap events into the rings.  Buckets key on
        each snap line's own wall-clock ``t`` — the emitting process's
        clock, NEVER this reader's (clock-domain rule, module
        docstring).  Returns the number of points written.  The whole
        batch runs under the ingest lock, so concurrent ingesters
        sharing one instance (the threaded ops endpoint) cannot
        interleave their dedup checks and double-write points."""
        with self._ingest_lock:
            return self._ingest_events_locked(events)

    def _ingest_events_locked(self, events: list) -> int:
        # guarded-by: _ingest_lock
        # Batch pre-group: per (res, src, bucket) keep only the
        # newest-t snapshot, then walk buckets in order so a closed
        # bucket lands exactly one line (its final cumulative state).
        best: dict = {}
        for ev in events:
            if ev.get("kind") != "snap" or ev.get("pid") is None:
                continue
            t = float(ev["t"])
            src = f"{ev.get('role')}:{ev.get('pid')}"
            for res in self.resolutions:
                key = (res, src, int(t // res))
                cur = best.get(key)
                if cur is None or t > cur[0]:
                    best[key] = (t, ev)
        written = 0
        for (res, src, b), (t, ev) in sorted(
                best.items(), key=lambda kv: (kv[0][0], kv[0][1],
                                              kv[0][2], kv[1][0])):
            with self._lock:
                last = self._state.get((res, src))
            if last is not None:
                last_b, last_t = last
                if b < last_b or (b == last_b
                                  and t < last_t + res / _LIVE_REFRESH_FRACTION):
                    continue      # immutable past / throttled live bucket
            self._write(res, {"kind": "pt", "res": res, "b": b,
                              "t": t, "src": src,
                              "m": _compact(ev.get("metrics") or {})})
            with self._lock:
                self._state[(res, src)] = (b, t)
            written += 1
        return written

    def ingest_spools(self, spool_directory: str | None = None) -> int:
        """Ingest every spool snapshot under ``spool_directory``
        (default: the parent of this series dir — the spool/series
        co-location rule)."""
        from firebird_tpu.obs import collect as obs_collect

        d = spool_directory or os.path.dirname(self.dir.rstrip("/"))
        return self.ingest_events(obs_collect.snap_events(d))

    # -- queries -----------------------------------------------------------

    def points(self, res: int, t0: float | None = None,
               t1: float | None = None) -> list:
        return read_points(self.dir, res, t0, t1)

    def status(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "pid": self.pid,
                    "resolutions": list(self.resolutions),
                    "sources": sorted({s for _, s in self._state}),
                    "dropped": self._dropped,
                    "gc_removed": self._gc_removed}

    def close(self) -> None:
        with self._lock:
            for ring in self._rings.values():
                if ring["f"] is not None:
                    ring["f"].close()
                    ring["f"] = None


def open_store(cfg) -> SeriesStore | None:
    """A SeriesStore for a config, or None when history is disabled
    (``FIREBIRD_SERIES=0`` / ``FIREBIRD_TELEMETRY=0``) or homeless (no
    file-backed artifact dir) — the zero-cost path writes nothing."""
    if getattr(cfg, "series", 0) <= 0 or cfg.telemetry <= 0:
        return None
    d = series_dir(cfg)
    if d is None:
        return None
    return SeriesStore(d, points_per_segment=cfg.series,
                       segments=cfg.series_segments)


# ---------------------------------------------------------------------------
# Read side: any process can query the rings without a writer instance
# ---------------------------------------------------------------------------

def _parse_ring_name(path: str) -> tuple | None:
    """``(res, pid, seg)`` from a ring file name
    (``series.<res>.<pid>.<seg>.jsonl``), or None for anything else
    the glob happened to match."""
    parts = os.path.basename(path).split(".")
    if len(parts) != 5 or parts[0] != "series" or parts[4] != "jsonl":
        return None
    try:
        return int(parts[1]), int(parts[2]), int(parts[3])
    except ValueError:
        return None


def _scan_files(directory: str) -> dict:
    """path -> parseable point lines for every ring file under
    ``directory`` (all pids, all segments); torn tail lines skipped,
    not fatal."""
    out: dict = {}
    for path in sorted(glob.glob(os.path.join(directory, SERIES_GLOB))):
        pts: list = []
        try:
            with open(path) as f:
                for line in f:
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue            # torn tail line
                    if not isinstance(doc, dict) \
                            or doc.get("kind") != "pt":
                        continue
                    pts.append(doc)
        except OSError:
            continue
        out[path] = pts
    return out


def _read_raw(directory: str) -> list:
    """Every parseable point line under ``directory``."""
    out: list = []
    for pts in _scan_files(directory).values():
        out.extend(pts)
    return out


def read_points(directory: str, res: int, t0: float | None = None,
                t1: float | None = None) -> list:
    """Retained points at one resolution within ``(t0, t1]``, deduped
    keep-latest per (bucket, source), sorted by snapshot time.  Reads
    every ingester's rings — two collectors ingesting concurrently
    still query as one history."""
    best: dict = {}
    for pt in _read_raw(directory):
        if pt.get("res") != int(res):
            continue
        t = float(pt.get("t", 0.0))
        if (t0 is not None and t <= t0) or (t1 is not None and t > t1):
            continue
        key = (pt.get("b"), pt.get("src"))
        cur = best.get(key)
        if cur is None or t > float(cur.get("t", 0.0)):
            best[key] = pt
    return sorted(best.values(), key=lambda p: (p["t"], str(p["src"])))


def sources(points: list) -> list:
    return sorted({p.get("src") for p in points})


def _by_src(points: list) -> dict:
    out: dict = {}
    for p in points:
        out.setdefault(p.get("src"), []).append(p)
    return out       # read_points order is time-sorted already


# -- windowed aggregates (the burn-rate substrate) --------------------------
#
# Counters and histogram bucket counts are CUMULATIVE per source
# process, so a window's activity is the delta between its edge points,
# summed per source and only then across sources — the fleet view is
# re-derived from merged host series, never one host's percentile.

def counter_window(points: list, name: str, t0: float,
                   t1: float) -> float | None:
    """Sum-over-sources of each source's counter delta across
    ``(t0, t1]``.  The baseline is the source's last point at or before
    ``t0`` (a source born inside the window baselines at zero — its
    whole cumulative count happened since start).  None when NO source
    has a point inside the window (an empty window is 'no data', never
    zero activity — obs/slo.py's no-data-is-zero-burn rule needs the
    distinction)."""
    total = None
    for pts in _by_src(points).values():
        inside = [p for p in pts if t0 < p["t"] <= t1]
        if not inside:
            continue
        before = [p for p in pts if p["t"] <= t0]
        base = (before[-1]["m"].get("counters") or {}).get(name, 0.0) \
            if before else 0.0
        end = (inside[-1]["m"].get("counters") or {}).get(name, 0.0)
        total = (total or 0.0) + max(float(end) - float(base), 0.0)
    return total


def hist_window(points: list, name: str, t0: float, t1: float) -> dict | None:
    """Merged histogram activity across ``(t0, t1]``: summed per-source
    deltas of count / sum / bucket_counts (same bounds).  None when no
    source has in-window data for the metric."""
    out = None
    for pts in _by_src(points).values():
        inside = [p for p in pts
                  if t0 < p["t"] <= t1 and name in p["m"]["histograms"]]
        if not inside:
            continue
        end = inside[-1]["m"]["histograms"][name]
        before = [p for p in pts
                  if p["t"] <= t0 and name in p["m"]["histograms"]]
        base = before[-1]["m"]["histograms"][name] if before else None
        bounds = list(end.get("bucket_bounds") or ())
        counts = [float(c) for c in (end.get("bucket_counts") or ())]
        n, s = float(end.get("count", 0)), float(end.get("sum", 0.0))
        if base is not None \
                and list(base.get("bucket_bounds") or ()) == bounds:
            bc = base.get("bucket_counts") or ()
            counts = [max(c - float(b), 0.0)
                      for c, b in zip(counts, bc)]
            n = max(n - float(base.get("count", 0)), 0.0)
            s = s - float(base.get("sum", 0.0))
        if out is None:
            out = {"count": 0.0, "sum": 0.0, "bucket_bounds": bounds,
                   "bucket_counts": [0.0] * len(counts)}
        if out["bucket_bounds"] == bounds \
                and len(out["bucket_counts"]) == len(counts):
            out["bucket_counts"] = [a + b for a, b
                                    in zip(out["bucket_counts"], counts)]
        out["count"] += n
        out["sum"] += s
    return out


def hist_over_threshold(win: dict, threshold: float) -> float:
    """Observations above ``threshold`` in a :func:`hist_window` result:
    total count minus the cumulative count of buckets whose upper bound
    is <= threshold (bucket granularity — the same quantization the
    percentile estimates already live with)."""
    under = 0.0
    for bound, c in zip(win.get("bucket_bounds") or (),
                        win.get("bucket_counts") or ()):
        if float(bound) <= threshold:
            under += float(c)
    return max(float(win.get("count", 0)) - under, 0.0)


def gauge_samples(points: list, name: str, t0: float,
                  t1: float) -> list:
    """Every in-window gauge sample as ``(t, src, value)`` — budget
    math counts bad samples over total samples."""
    out = []
    for p in points:
        if not (t0 < p["t"] <= t1):
            continue
        v = (p["m"].get("gauges") or {}).get(name)
        if v is not None:
            out.append((p["t"], p.get("src"), float(v)))
    return out


def bucket_series(points: list, name: str, kind: str,
                  res: int) -> list:
    """Per-bucket values for sparklines: counters render as per-bucket
    deltas (activity), gauges as the fleet-merged sample, histograms as
    per-bucket observation counts.  Returns ``[(bucket, value), ...]``
    in bucket order; buckets with no data are absent (the renderer
    decides how to show gaps)."""
    by_bucket: dict = {}
    for p in points:
        by_bucket.setdefault(int(p["b"]), []).append(p)
    out = []
    for b in sorted(by_bucket):
        t1 = (b + 1) * int(res)
        t0 = b * int(res)
        if kind == "gauge":
            vals = [v for (_, _, v)
                    in gauge_samples(points, name, t0, t1)]
            if vals:
                out.append((b, obs_metrics.merge_gauge_values(name, vals)))
        elif kind == "histogram":
            win = hist_window(points, name, t0, t1)
            if win is not None:
                out.append((b, win["count"]))
        else:
            v = counter_window(points, name, t0, t1)
            if v is not None:
                out.append((b, v))
    return out
