"""On-demand device profiling: windowed jax.profiler captures mid-run.

The host span tracer (obs/tracing.py) says *that* a batch was slow; it
cannot say *where on the device* the time went — and every bench round
r01-r05 ran blind on exactly that question (the reference had the same
gap: no throughput numbers anywhere, PAPER.md §6).  The LASP CPU→GPU
port (PAPERS.md) attributes every optimization step to profiler-measured
kernel phases *before* touching code; this module makes that workflow a
one-request operation on a live run:

- ``FIREBIRD_PROFILE=<seconds>`` (``Config.profile``) arms an automatic
  window that starts at the run's FIRST dispatched batch — steady-state
  kernels, not bring-up compile noise.
- ``POST /profile?seconds=N`` on the ops endpoint (obs/server.py)
  captures a window on demand at any point mid-run.

Each window wraps ``jax.profiler.start_trace``/``stop_trace`` around a
bounded wait and writes the standard XLA/TensorBoard artifact
(``.trace.json.gz`` + xplane) under ``<store dir>/device_profile/
window_<n>/`` — linkable from the run's other artifacts, loadable in
Perfetto/TensorBoard.  The Chrome-trace half is then parsed for
**per-phase device-time attribution**: event durations bucketed into
the CCD loop's phases (fit / monitor / compaction) by kernel-name
pattern, folded into ``obs_report.json`` (``profile`` block — structure
always present, zeros allowed on backends whose op names match nothing)
and from there into bench artifacts.

``Config.profile_dir`` (FIREBIRD_PROFILE_DIR) remains the whole-run
capture; this module is the *windowed* complement a multi-hour run
needs (a full-run device trace of a tile run is gigabytes).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import threading
import time

from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import tracing

# Kernel-name patterns -> CCD event-loop phase.  Matched as lowercase
# substrings against every trace event name; first phase wins, anything
# unmatched lands in "other".  Zeros are legitimate (the lax CPU path
# fuses phases into opaque while-loop ops) — the structure is the
# contract, the split fills in where the lowering preserves names
# (Pallas kernels, named HLO ops on TPU).
PHASE_PATTERNS = (
    ("fit", ("lasso", "gram", "cd_step", "lstsq", "fit")),
    ("monitor", ("monitor", "score", "peek", "tmask")),
    ("compaction", ("compact", "permut", "scatter", "cumsum", "sort")),
)
PHASES = tuple(name for name, _ in PHASE_PATTERNS) + ("other",)


def empty_attribution(source: str = "none") -> dict:
    out = {f"{p}_ms": 0.0 for p in PHASES}
    out.update({"total_ms": 0.0, "events": 0, "source": source})
    return out


def attribute_phases(trace_dir: str) -> dict:
    """Per-phase device-time split of a captured window.

    Walks the window directory for the ``.trace.json.gz`` files jax's
    profiler writes (``plugins/profile/<ts>/<host>.trace.json.gz``),
    sums complete-event durations by PHASE_PATTERNS, and returns the
    attribution dict (milliseconds).  Unreadable/absent traces return
    the zero structure with ``source`` saying why.
    """
    paths = sorted(glob.glob(os.path.join(trace_dir, "**",
                                          "*.trace.json.gz"),
                             recursive=True))
    if not paths:
        return empty_attribution("no-trace-files")
    out = empty_attribution("trace")
    for path in paths:
        try:
            with gzip.open(path, "rt", errors="replace") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        for ev in doc.get("traceEvents", ()):
            if not isinstance(ev, dict) or ev.get("ph") != "X":
                continue
            dur_ms = float(ev.get("dur", 0.0)) / 1e3
            name = str(ev.get("name", "")).lower()
            phase = "other"
            for p, pats in PHASE_PATTERNS:
                if any(s in name for s in pats):
                    phase = p
                    break
            out[f"{phase}_ms"] += dur_ms
            out["total_ms"] += dur_ms
            out["events"] += 1
    for p in PHASES:
        out[f"{p}_ms"] = round(out[f"{p}_ms"], 3)
    out["total_ms"] = round(out["total_ms"], 3)
    return out


class ProfilerBusy(RuntimeError):
    """A capture window is already in flight (jax allows one trace at a
    time per process)."""


class DeviceProfiler:
    """Windowed device-trace capture for one run.

    ``outdir`` is the artifact root (``<store dir>/device_profile``);
    each window writes ``window_<n>/`` under it.  One window at a time —
    jax.profiler is a process singleton.
    """

    def __init__(self, outdir: str):
        self.outdir = os.path.abspath(outdir)
        self._lock = threading.Lock()
        self._busy = False  # guarded-by: _lock
        self._n = 0  # guarded-by: _lock
        self._windows: list[dict] = []  # guarded-by: _lock
        self._auto_seconds = 0.0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- capture -------------------------------------------------------------

    def window(self, seconds: float, block: bool = False) -> dict:
        """Start one capture window of ``seconds`` (bounded 0.05..600).
        Raises :class:`ProfilerBusy` when one is already in flight.
        ``block=True`` runs the capture synchronously (tests, tools);
        the default returns immediately and captures on a daemon thread.
        """
        seconds = min(max(float(seconds), 0.05), 600.0)
        with self._lock:
            if self._busy:
                raise ProfilerBusy("a profile window is already capturing")
            self._busy = True
            n = self._n
            self._n += 1
        info = {"window": n, "seconds": seconds,
                "dir": os.path.join(self.outdir, f"window_{n:02d}"),
                # UTC with designator — the written_at/generated_at
                # convention, so windows correlate across artifacts.
                "started_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime())}
        if block:
            self._capture(info)
            return info
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._capture, args=(info,),
            name="firebird-profile", daemon=True)
        self._thread.start()
        return info

    def _capture(self, info: dict) -> None:
        try:
            import jax

            os.makedirs(info["dir"], exist_ok=True)
            with tracing.span("profile", seconds=info["seconds"]):
                jax.profiler.start_trace(info["dir"])
                try:
                    # Interruptible wait: close() ends an in-flight
                    # window early instead of leaking a started trace.
                    self._stop.wait(info["seconds"])
                finally:
                    jax.profiler.stop_trace()
            info["attribution"] = attribute_phases(info["dir"])
            info["trace_files"] = len(glob.glob(
                os.path.join(info["dir"], "**", "*"), recursive=True))
            obs_metrics.counter(
                "profile_windows",
                help="on-demand device-profile windows captured").inc()
        except Exception as e:
            # A broken profiler (unsupported backend, concurrent trace)
            # must cost the operator a diagnosable record, not the run.
            info["error"] = f"{type(e).__name__}: {e}"
            info["attribution"] = empty_attribution("error")
            from firebird_tpu.obs import logger
            logger("change-detection").warning(
                "device-profile window failed: %s", info["error"])
        finally:
            with self._lock:
                self._windows.append(info)
                self._busy = False

    # -- FIREBIRD_PROFILE auto window ---------------------------------------

    def arm_auto(self, seconds: float) -> None:
        """Arm a one-shot window that starts at the first dispatched
        batch (obs/server.py's ``batch_dispatched`` hook) — steady-state
        kernels, not bring-up compile."""
        with self._lock:
            self._auto_seconds = float(seconds)

    def maybe_start_auto(self) -> None:
        with self._lock:
            seconds, self._auto_seconds = self._auto_seconds, 0.0
        if seconds > 0:
            try:
                self.window(seconds)
            except ProfilerBusy:
                pass

    # -- reads / teardown ----------------------------------------------------

    def summary(self) -> dict:
        """The report's ``profile`` block: windows so far + device-time
        totals across them (structure matches :func:`report_block`)."""
        with self._lock:
            windows = [dict(w) for w in self._windows]
            busy = self._busy
        # Provenance must survive aggregation: 'trace' only when a
        # window REALLY parsed trace files — every-window-failed reports
        # 'error' (all zeros + 'trace' would mask a broken profiler as a
        # healthy capture that attributed nothing).
        sources = {w.get("attribution", {}).get("source") for w in windows}
        device_time = empty_attribution(
            "trace" if "trace" in sources
            else "error" if ("error" in sources
                             or "no-trace-files" in sources)
            else "none")
        for w in windows:
            a = w.get("attribution")
            if not a:
                continue
            for p in PHASES:
                device_time[f"{p}_ms"] = round(
                    device_time[f"{p}_ms"] + a.get(f"{p}_ms", 0.0), 3)
            device_time["total_ms"] = round(
                device_time["total_ms"] + a.get("total_ms", 0.0), 3)
            device_time["events"] += a.get("events", 0)
        return {"windows": windows, "in_flight": busy,
                "device_time": device_time, "dir": self.outdir}

    def close(self, timeout: float = 10.0) -> None:
        """End any in-flight window early and collect it — called before
        the report is written so a run's last window is never lost."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
        self._thread = None


# ---------------------------------------------------------------------------
# Process-global slot (one run's profiler; obs/report reads it)
# ---------------------------------------------------------------------------

# Mutated by start_ops/stop_ops on the run-owning thread; readers grab
# the reference once (the obs/server._status discipline).
_active: DeviceProfiler | None = None


def set_active(prof: DeviceProfiler | None) -> DeviceProfiler | None:
    global _active
    _active = prof  # firebird-lint: disable=ownership-global-mutation
    return prof


def active() -> DeviceProfiler | None:
    return _active


def close_active() -> None:
    """Flush an in-flight window (never raises) — obs.report.finish_run
    calls this before building the report so the artifact carries the
    final window's attribution."""
    prof = _active
    if prof is not None:
        try:
            prof.close()
        except Exception:
            pass


def report_block() -> dict:
    """The obs_report ``profile`` block — ALWAYS structurally present
    (the acceptance contract: zeros allowed, structure never absent)."""
    prof = _active
    if prof is None:
        return {"windows": [], "in_flight": False,
                "device_time": empty_attribution("none"), "dir": None}
    return prof.summary()
