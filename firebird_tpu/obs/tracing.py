"""Span tracer: nested, thread-aware, Chrome-trace/Perfetto JSON export.

``span("fetch", chip=cid)`` wraps any pipeline stage; spans nest naturally
(Chrome's trace viewer stacks complete events by interval containment per
thread), and each OS thread renders as its own track, so the driver's
prefetch/pack/dispatch/drain overlap is visually inspectable — the
host-orchestration counterpart of the XLA trace ``profile_dir`` captures
(driver/core.py).

Disabled cost is one module-attribute read and a ``None`` check per span:
no tracer installed means ``span()`` returns a shared no-op context
manager and records nothing.  Enable per run with FIREBIRD_TRACE (see
resolve_path) or programmatically via ``start()``/``stop()``.

Export is the Chrome trace-event JSON format (``{"traceEvents": [...]}``,
"X" complete events with microsecond timestamps) — loads directly in
Perfetto (ui.perfetto.dev) and chrome://tracing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import os
import threading
import time


class _NullSpan:
    """Shared no-op span: tracing disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


# ---------------------------------------------------------------------------
# Cross-thread trace propagation: the per-batch/per-request TraceContext
# ---------------------------------------------------------------------------
#
# The pipeline's unit of work crosses FOUR threads (prefetch stage ->
# main-thread dispatch -> drain executor -> writer worker), so a
# thread-local alone cannot correlate one batch's spans and log lines.
# The drivers therefore mint ONE TraceContext per batch (per request in
# serve/api.py) and carry it EXPLICITLY across each thread hop; each
# thread activates it around the work it does for that batch, and
# everything recorded while it is active — spans (the ``batch`` arg),
# JSON log lines (obs/jsonlog.py), histogram exemplars
# (obs/metrics.py), flight-recorder events (obs/flightrec.py) — parents
# to the same batch id.

@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One unit of work's identity: ``batch_id`` is globally unique
    (``<run_id>/b<seq>`` in the drivers, ``req-<hex>`` in serve)."""

    batch_id: str
    run_id: str | None = None


class _Tls(threading.local):
    ctx: TraceContext | None = None
    last_span_id: int = 0


_tls = _Tls()

# Span ids are minted process-wide (not per tracer) so exemplars and
# flight-recorder events can reference spans even when no tracer runs.
_span_ids = itertools.count(1)
_batch_seq = itertools.count()


def new_batch_id(run_id: str | None) -> str:
    """Mint the next batch id for a run: ``<run_id>/b<seq>`` (seq is
    process-wide, so ids stay unique across chunks and drivers)."""
    return f"{run_id or 'run'}/b{next(_batch_seq)}"


# ---------------------------------------------------------------------------
# Cross-PROCESS trace propagation (the fleet telemetry plane)
# ---------------------------------------------------------------------------
# A trace id travels between processes as a plain string: the watcher
# stamps it into fleet-queue job payloads (key ``trace``), workers adopt
# it, alert rows persist it, and serve accepts it as an inbound
# X-Firebird-Trace header.  Wire ids are validated against WIRE_RE
# before adoption — a job payload and an HTTP header are both untrusted
# inputs, and an unbounded id would flow into log lines and sqlite rows.

TRACE_KEY = "trace"

import re as _re  # noqa: E402  (scoped import, stdlib only)

WIRE_RE = _re.compile(r"^[A-Za-z0-9._:/\-]{1,160}$")


def to_wire(ctx: TraceContext | None) -> str | None:
    """The propagable form of a context (its batch id), or None."""
    return None if ctx is None else ctx.batch_id


def from_wire(trace, run_id: str | None = None) -> TraceContext | None:
    """Adopt a trace id that arrived from another process (queue
    payload, HTTP header).  None — or None-return on a malformed id —
    means the caller mints its own context instead."""
    if not isinstance(trace, str) or WIRE_RE.match(trace) is None:
        return None
    return TraceContext(trace, run_id=run_id)


def current_context() -> TraceContext | None:
    """The TraceContext active on THIS thread (None outside any unit of
    work)."""
    return _tls.ctx


@contextlib.contextmanager
def activate(ctx: TraceContext | None):
    """Make ``ctx`` the calling thread's active context for the block.
    ``None`` is accepted (no-op) so call sites can thread an optional
    context without branching."""
    prev = _tls.ctx
    if ctx is not None:
        _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


def exemplar() -> dict | None:
    """The histogram-exemplar payload for the current thread: the active
    batch id plus the most recently closed span's id — "the slow p99
    sample WAS this batch/span".  None outside any context (histograms
    then record no exemplar)."""
    ctx = _tls.ctx
    if ctx is None:
        return None
    out = {"batch": ctx.batch_id}
    if _tls.last_span_id:
        out["span_id"] = _tls.last_span_id
    return out


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0", "_ctx")

    def __init__(self, tracer: "Tracer | None", name: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._ctx = _tls.ctx
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        sid = next(_span_ids)
        _tls.last_span_id = sid
        args = self._args
        ctx = self._ctx
        if ctx is not None:
            args = dict(args, batch=ctx.batch_id, span_id=sid)
        else:
            args = dict(args, span_id=sid) if args else {"span_id": sid}
        if self._tracer is not None:
            self._tracer._record(self._name, self._t0, dur, args)
        rec = _recorder
        if rec is not None:
            rec.span_event(self._name, dur * 1e3,
                           ctx.batch_id if ctx is not None else None)
        sp = _spool
        if sp is not None:
            sp.span_event(self._name, dur,
                          ctx.batch_id if ctx is not None else None)
        return False


class Tracer:
    """Collects complete ("X") trace events; thread-safe.

    Timestamps are microseconds relative to the tracer's epoch; OS thread
    idents map to small sequential tids with ``thread_name`` metadata so
    Perfetto tracks are readable (MainThread, ThreadPoolExecutor-0_0, ...).
    """

    def __init__(self, run_id: str | None = None):
        # Run correlation: the trace artifact carries the same run_id as
        # the JSON logs, /progress, and the report run block (otherData
        # plus a process_name metadata track label in Perfetto).
        self.run_id = run_id
        self._lock = threading.Lock()
        self._events: list[dict] = []
        # tids assign through a threading.local, NOT by OS thread ident:
        # CPython reuses idents after a thread exits (the driver spins up
        # fresh executors per chunk), which would put a later thread's
        # spans on a dead thread's track under its stale name.
        self._local = threading.local()
        self._n_tids = 0
        self._epoch = time.perf_counter()

    def _tid(self) -> int:
        tid = getattr(self._local, "tid", None)
        if tid is None:
            tid = self._local.tid = self._n_tids
            self._n_tids += 1
            self._events.append({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": threading.current_thread().name}})
        return tid

    def _record(self, name: str, t0: float, dur: float, args: dict) -> None:
        ev = {"name": name, "ph": "X", "pid": 0,
              "ts": (t0 - self._epoch) * 1e6, "dur": dur * 1e6}
        if args:
            ev["args"] = {k: (v if isinstance(v, (int, float, bool))
                              else str(v)) for k, v in args.items()}
        with self._lock:
            ev["tid"] = self._tid()
            self._events.append(ev)

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def to_chrome_trace(self) -> dict:
        with self._lock:
            events = list(self._events)
        other = {"producer": "firebird_tpu.obs.tracing"}
        if self.run_id:
            other["run_id"] = self.run_id
            events = [{"name": "process_name", "ph": "M", "pid": 0,
                       "tid": 0, "args": {"name": f"run {self.run_id}"}}] \
                + events
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": other}

    def save(self, path: str) -> str:
        """Write the Chrome-trace JSON (atomic tmp+rename)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def summary(self) -> dict:
        """Per-span-name aggregate: count and total/mean/max milliseconds
        (the obs_report.json span table)."""
        with self._lock:
            events = [e for e in self._events if e.get("ph") == "X"]
        out: dict[str, dict] = {}
        for e in events:
            s = out.setdefault(e["name"],
                               {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
            ms = e["dur"] / 1e3
            s["count"] += 1
            s["total_ms"] += ms
            s["max_ms"] = max(s["max_ms"], ms)
        for s in out.values():
            s["mean_ms"] = s["total_ms"] / s["count"]
            for k in ("total_ms", "max_ms", "mean_ms"):
                s[k] = round(s[k], 3)
        return out


_active: Tracer | None = None

# The crash flight recorder's span feed (obs/flightrec.py installs it
# while armed): spans record into the per-thread event rings even when
# no tracer is running, so a postmortem bundle has recent spans to show.
_recorder = None


def set_recorder(rec) -> None:
    """Install/clear the flight-recorder span sink (None clears)."""
    global _recorder
    # Single-reference swap from the run-owning thread (arm/disarm);
    # span exits read the reference once.
    _recorder = rec  # firebird-lint: disable=ownership-global-mutation


# The durable telemetry spool's span feed (obs/spool.py installs it
# while armed): a parallel sink to the flight recorder — the recorder
# keeps a crash-dump ring in memory, the spool appends to disk so a
# SIGKILLed process's spans survive for `firebird trace collect`.
_spool = None


def set_spool(sp) -> None:
    """Install/clear the telemetry-spool span sink (None clears)."""
    global _spool
    # Single-reference swap from the process-owning thread (spool
    # arm/disarm); span exits read the reference once.
    _spool = sp  # firebird-lint: disable=ownership-global-mutation


def active() -> Tracer | None:
    return _active


def start(tracer: Tracer | None = None,
          run_id: str | None = None) -> Tracer:
    """Install ``tracer`` (or a fresh one) as the process-global span sink
    and return it.  Spans from any thread land in the active tracer.
    ``run_id`` stamps the exported trace for fleet-log correlation."""
    global _active
    # Single-reference swap from the run-owning thread; span() reads the
    # reference once, so torn state is impossible under the GIL.
    _active = tracer or Tracer(run_id=run_id)  # firebird-lint: disable=ownership-global-mutation
    if run_id and _active.run_id is None:
        _active.run_id = run_id
    return _active


def stop() -> Tracer | None:
    """Uninstall and return the active tracer (None if none installed)."""
    global _active
    # See start(): single-reference swap, run-owning thread only.
    t, _active = _active, None  # firebird-lint: disable=ownership-global-mutation
    return t


def span(name: str, **args):
    """A span against the active tracer (and the armed flight recorder
    and telemetry spool); a shared no-op when all three are off."""
    t = _active
    if t is None and _recorder is None and _spool is None:
        return _NULL_SPAN
    return _Span(t, name, args)


def wants_trace(trace: str) -> bool:
    """FIREBIRD_TRACE gate: ""/"0" off (matching the 0-disables
    convention of FIREBIRD_METRICS and FIREBIRD_OBS_REPORT), anything
    else on."""
    return trace not in ("", "0")


def resolve_path(trace: str, store_path: str,
                 default_name: str = "trace.json") -> str:
    """Resolve the FIREBIRD_TRACE value to an output file.

    "1" (just "turn it on") writes ``<store dir>/<default_name>`` next to
    the store; a directory path appends ``default_name``; anything else is
    the literal output file.
    """
    if trace == "1":
        return os.path.join(
            os.path.dirname(os.path.abspath(store_path)), default_name)
    if os.path.isdir(trace) or trace.endswith(os.sep):
        return os.path.join(trace, default_name)
    return trace
