"""Per-run report artifact: obs_report.json build, write, and validation.

One JSON document per pipeline run — metrics snapshot (counters, gauges,
latency histograms), span summary table, and run identity — written next
to the results store so soak/bench tooling can fold it into round
artifacts (tools/soak_report.py, bench.py) and operators can diff runs
without scraping logs.  ``validate_report``/``validate_trace`` are the
shared schema checks used by ``make obs-smoke`` and the test suite.
"""

from __future__ import annotations

import datetime
import json
import os

SCHEMA = "firebird-obs-report/1"

# Stage keys a driver run is expected to populate (the obs-smoke contract):
# ingest, kernel, and store latencies.  Kept here — not in the smoke tool —
# so the driver tests and the Makefile target assert the same contract.
DRIVER_STAGE_HISTOGRAMS = (
    "ingest_chip_seconds",
    "pipeline_fetch_seconds",
    "pipeline_pack_seconds",
    "pipeline_stage_seconds",
    "pipeline_dispatch_seconds",
    "pipeline_drain_seconds",
    "pipeline_d2h_seconds",
    "store_write_seconds",
    "store_flush_seconds",
    "kernel_first_call_seconds",
)
DRIVER_SPAN_NAMES = ("fetch", "pack", "stage", "dispatch", "drain", "d2h",
                     "transfer")

# THE span-name catalog: every tracing.span(...) call site in the
# codebase must use a name declared here, and every declared name must
# still have a call site — firebird-lint's span-name rules check both
# directions against this literal AND the OBSERVABILITY.md span table
# (the metric-table pattern), so a new span cannot ship undocumented
# and a renamed one cannot leave a stale row.  Keep it a literal tuple:
# the linter parses it from source.
SPAN_NAMES = (
    "alert",
    "d2h",
    "deliver",
    "dispatch",
    "drain",
    "fetch",
    "first_dispatch",
    "fleet_job",
    "pack",
    "probe_cycle",
    "profile",
    "publish",
    "stage",
    "step",
    "store_flush",
    "store_write",
    "transfer",
    "warm_compile",
    "watch_poll",
)


def build_report(*, registry=None, tracer=None, run: dict | None = None,
                 run_counters: dict | None = None) -> dict:
    """Assemble the report dict from live objects (no I/O)."""
    from firebird_tpu.obs import metrics as m
    from firebird_tpu.obs import profiling
    from firebird_tpu.obs import server as obs_server
    from firebird_tpu.obs import slo as slomod

    reg = registry if registry is not None else m.get_registry()
    metrics = reg.snapshot()
    # SLO + device-profile blocks are structurally ALWAYS present (the
    # obs-smoke contract): no-data objectives report ok=null, a run
    # without profile windows reports the zero attribution.
    st = obs_server.current()
    wd_snap = None
    spec = None
    if st is not None:
        spec = getattr(st, "slo_spec", None)
        if st.watchdog is not None:
            wd_snap = st.watchdog.snapshot()
    rep = {
        "schema": SCHEMA,
        "generated_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "run": run or {},
        "metrics": metrics,
        "spans": tracer.summary() if tracer is not None else {},
        "slo": slomod.evaluate_snapshot(metrics, watchdog=wd_snap,
                                        spec=spec),
        "profile": profiling.report_block(),
    }
    if run_counters:
        rep["run_counters"] = run_counters
    return rep


def write_report(path: str, **kw) -> dict:
    """build_report + atomic write (tmp+rename); returns the report."""
    rep = build_report(**kw)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=1)
    os.replace(tmp, path)
    return rep


def validate_report(rep: dict) -> None:
    """Raise ValueError unless ``rep`` is a structurally valid report."""
    if not isinstance(rep, dict):
        raise ValueError("report is not a JSON object")
    if rep.get("schema") != SCHEMA:
        raise ValueError(f"report schema {rep.get('schema')!r} != {SCHEMA!r}")
    met = rep.get("metrics")
    if not isinstance(met, dict):
        raise ValueError("report has no metrics snapshot")
    for kind in ("counters", "gauges", "histograms"):
        if not isinstance(met.get(kind), dict):
            raise ValueError(f"metrics snapshot missing {kind!r}")
    for name, h in met["histograms"].items():
        if not isinstance(h, dict) or "count" not in h:
            raise ValueError(f"histogram {name!r} snapshot malformed")
        if h["count"] > 0 and not all(k in h for k in ("p50", "p95", "p99")):
            raise ValueError(f"histogram {name!r} missing percentiles")
    if not isinstance(rep.get("spans"), dict):
        raise ValueError("report has no span summary")


def validate_trace(trace: dict) -> None:
    """Raise ValueError unless ``trace`` is valid Chrome-trace JSON (the
    subset Perfetto's JSON importer requires)."""
    if not isinstance(trace, dict) \
            or not isinstance(trace.get("traceEvents"), list):
        raise ValueError("trace is not {'traceEvents': [...]} JSON")
    for ev in trace["traceEvents"]:
        if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev:
            raise ValueError(f"malformed trace event: {ev!r}")
        if ev["ph"] == "X" and not ("ts" in ev and "dur" in ev):
            raise ValueError(f"complete event missing ts/dur: {ev!r}")


def validate_driver_artifacts(trace: dict, rep: dict) -> None:
    """The full obs-smoke contract over a driver run's two artifacts —
    schema validity plus the stage-key coverage — shared by ``make
    obs-smoke`` (tools/obs_smoke.py) and the driver smoke test so the
    contract cannot drift between them.  Raises ValueError."""
    validate_trace(trace)
    names = {e.get("name") for e in trace["traceEvents"]}
    missing = [n for n in DRIVER_SPAN_NAMES if n not in names]
    if missing:
        raise ValueError(f"trace missing span names {missing}")
    validate_report(rep)
    hists = rep["metrics"]["histograms"]
    missing = [k for k in DRIVER_STAGE_HISTOGRAMS
               if k not in hists or hists[k]["count"] < 1]
    if missing:
        raise ValueError(f"report missing stage histograms {missing}")


def default_report_path(store_path: str) -> str:
    """obs_report.json next to the results store."""
    return os.path.join(os.path.dirname(os.path.abspath(store_path)),
                        "obs_report.json")


# ---------------------------------------------------------------------------
# Multi-host aggregation: per-process shards -> one fleet report
# ---------------------------------------------------------------------------

def shard_report_path(path: str, process_index: int) -> str:
    """Per-process shard next to the fleet report:
    obs_report.json -> obs_report.host<N>.json."""
    root, ext = os.path.splitext(path)
    return f"{root}.host{int(process_index)}{ext or '.json'}"


def _process_info() -> tuple[int, int]:
    """(process_count, process_index); (1, 0) when jax/distributed is not
    up — report emission must never require an initialized backend."""
    try:
        import jax

        return jax.process_count(), jax.process_index()
    except Exception:
        return 1, 0


def clear_stale_artifacts(cfg) -> None:
    """Run-start cleanup for reused report directories (rolling soak).

    Merge-time shard discovery is by filename, so a shard left by a
    PREVIOUS run in the same directory would satisfy the wait loop
    instantly and contaminate the new fleet report with stale counters.
    Every process therefore deletes its OWN shard before doing any work,
    and process 0 also drops the old merged report — by the time any
    process can *write* a new shard (a full detect pass later), every
    peer has long since passed this point (all of them crossed the
    jax.distributed bring-up barrier before their run began).  Never
    raises: cleanup must not fail a run over a read-only artifact dir.
    """
    try:
        path = run_report_path(cfg)
        if path is None:
            return
        n_proc, proc_idx = _process_info()
        if n_proc <= 1:
            return
        stale = [shard_report_path(path, proc_idx)]
        if proc_idx == 0:
            stale.append(path)
        for p in stale:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
    except OSError:
        pass


def merge_reports(reports: list[dict]) -> dict:
    """Combine per-host report shards into one fleet report.

    Merge policy (declared with the metric kinds in obs/metrics.py):
    counters sum; histogram bucket counts add and percentiles recompute
    from the merged buckets; gauges combine per
    ``metrics.gauge_merge_policy`` (sum/max/min by name); span tables sum
    counts/totals and keep the fleet max; run_counters sum, with
    ``elapsed_sec`` as the fleet max (wall time, not CPU time) and the
    ``*_per_sec`` rates recomputed against it.
    """
    from firebird_tpu.obs import metrics as m

    if not reports:
        raise ValueError("no report shards to merge")
    out = {
        "schema": SCHEMA,
        "generated_at": max(r.get("generated_at", "") for r in reports),
        "run": dict(reports[0].get("run", {})),
    }
    mets = [r.get("metrics", {}) for r in reports]
    counters: dict = {}
    for met in mets:
        for k, v in met.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
    gauges: dict = {}
    for name in sorted({k for met in mets for k in met.get("gauges", {})}):
        vals = [met["gauges"][name] for met in mets
                if name in met.get("gauges", {})]
        gauges[name] = m.merge_gauge_values(name, vals)
    hists: dict = {}
    for name in sorted({k for met in mets
                        for k in met.get("histograms", {})}):
        hists[name] = m.merge_histogram_snapshots(
            [met["histograms"][name] for met in mets
             if name in met.get("histograms", {})])
    out["metrics"] = {
        "elapsed_sec": max((met.get("elapsed_sec", 0.0) for met in mets),
                           default=0.0),
        "counters": counters, "gauges": gauges, "histograms": hists,
    }
    spans: dict = {}
    for r in reports:
        for name, s in (r.get("spans") or {}).items():
            t = spans.setdefault(name, {"count": 0, "total_ms": 0.0,
                                        "max_ms": 0.0})
            t["count"] += s.get("count", 0)
            t["total_ms"] += s.get("total_ms", 0.0)
            t["max_ms"] = max(t["max_ms"], s.get("max_ms", 0.0))
    for s in spans.values():
        s["mean_ms"] = round(s["total_ms"] / max(s["count"], 1), 3)
        s["total_ms"] = round(s["total_ms"], 3)
        s["max_ms"] = round(s["max_ms"], 3)
    out["spans"] = spans
    # SLO: RE-evaluated over the merged histograms (per-host verdicts
    # cannot be combined — a fleet p99 is not any host's p99); the first
    # shard's spec wins (every host of a fleet launch shares one config).
    from firebird_tpu.obs import slo as slomod

    specs = [r.get("slo", {}).get("spec") for r in reports
             if r.get("slo")]
    out["slo"] = slomod.evaluate_snapshot(
        out["metrics"], spec=specs[0] if specs else None)
    # Device-profile attribution sums across hosts; windows concatenate
    # (each already names its host-local artifact directory).
    from firebird_tpu.obs import profiling

    prof = {"windows": [], "in_flight": False,
            "device_time": profiling.empty_attribution("none"), "dir": None}
    sources = set()
    for r in reports:
        p = r.get("profile")
        if not p:
            continue
        prof["windows"].extend(p.get("windows", ()))
        dt = p.get("device_time") or {}
        sources.add(dt.get("source"))
        for k, v in dt.items():
            if isinstance(v, (int, float)):
                prof["device_time"][k] = round(
                    prof["device_time"].get(k, 0) + v, 3)
    # Shard provenance survives the merge: any real capture -> 'trace';
    # otherwise any failed shard -> 'error' (a fleet whose every
    # profiler broke must not read as one that never profiled).
    if "trace" in sources:
        prof["device_time"]["source"] = "trace"
    elif "error" in sources:
        prof["device_time"]["source"] = "error"
    out["profile"] = prof
    rcs = [r["run_counters"] for r in reports if r.get("run_counters")]
    if rcs:
        merged: dict = {}
        elapsed = max(rc.get("elapsed_sec", 0.0) for rc in rcs)
        for rc in rcs:
            for k, v in rc.items():
                if k == "elapsed_sec" or k.endswith("_per_sec"):
                    continue
                merged[k] = merged.get(k, 0) + v
        for k in list(merged):
            if elapsed > 0:
                merged[f"{k}_per_sec"] = merged[k] / elapsed
        merged["elapsed_sec"] = elapsed
        out["run_counters"] = merged
    out["fleet"] = {
        "hosts": len(reports),
        "host_runs": [{k: r.get("run", {}).get(k)
                       for k in ("run_id", "host", "process_id", "chips")}
                      for r in reports],
    }
    return out


def merge_fleet_report(path: str, n_processes: int,
                       timeout: float | None = None,
                       poll_sec: float = 0.25) -> dict | None:
    """Process 0's half of the aggregation: wait (bounded) for every
    host's shard next to ``path``, merge whatever arrived, atomically
    write the fleet report to ``path``.  Returns the merged report, or
    None when not even one shard exists.  Hosts that never delivered are
    listed under ``fleet.missing`` rather than failing the merge — a
    crashed peer must not take down the survivors' telemetry."""
    import time as _time

    if timeout is None:
        from firebird_tpu.config import env_knob

        timeout = float(env_knob("FIREBIRD_OBS_MERGE_TIMEOUT"))
    paths = [shard_report_path(path, j) for j in range(n_processes)]
    deadline = _time.monotonic() + timeout
    while not all(os.path.exists(p) for p in paths) \
            and _time.monotonic() < deadline:
        _time.sleep(poll_sec)
    shards, missing = [], []
    for j, p in enumerate(paths):
        try:
            shards.append(json.load(open(p)))
        except (OSError, ValueError):
            missing.append(j)
    if not shards:
        return None
    rep = merge_reports(shards)
    rep["fleet"]["expected_hosts"] = n_processes
    if missing:
        rep["fleet"]["missing"] = missing
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=1)
    os.replace(tmp, path)
    return rep


def load_fleet_report(directory: str) -> dict | None:
    """The merged view of a run directory, for tooling (soak_report,
    bench).

    Prefers the fleet obs_report.json — UNLESS it recorded missing hosts
    whose shards have since landed (process 0's merge wait is one-shot
    at its run end; a host draining past FIREBIRD_OBS_MERGE_TIMEOUT
    writes its shard after the merge), in which case the shards on disk
    are re-merged so the late host's contribution is not undercounted
    forever.  When only shards exist (process 0 died before merging),
    they merge in memory.  None when the directory holds no report."""
    import glob as _glob

    shards = []
    for p in sorted(_glob.glob(
            os.path.join(directory, "obs_report.host*.json"))):
        try:
            shards.append(json.load(open(p)))
        except (OSError, ValueError):
            continue
    merged_path = os.path.join(directory, "obs_report.json")
    if os.path.exists(merged_path):
        try:
            merged = json.load(open(merged_path))
        except (OSError, ValueError):
            merged = None
        if merged is not None:
            fleet = merged.get("fleet") or {}
            stale = fleet.get("missing") and len(shards) > fleet.get(
                "hosts", 0)
            if not stale:
                return merged
    return merge_reports(shards) if shards else None


def run_report_path(cfg) -> str | None:
    """Where a driver run's report goes, or None to skip.

    cfg.obs_report: "0" never; a path always; "" auto — next to the store
    for file-backed backends, skipped for 'memory' (tests and embedded
    uses must not litter the CWD with artifacts nobody asked for).
    """
    if cfg.obs_report == "0":
        return None
    if cfg.obs_report:
        return cfg.obs_report
    if cfg.store_backend == "memory":
        return None
    return default_report_path(cfg.store_path)


def finish_run(cfg, *, tracer=None, run: dict | None = None,
               run_counters: dict | None = None) -> dict:
    """End-of-run artifact emission shared by the batch and streaming
    drivers: save the tracer's Chrome trace (when one ran) and write
    obs_report.json per cfg.obs_report policy.  Returns {artifact: path}
    for the paths actually written.  Never raises — a failed telemetry
    write must not fail a run whose results already landed."""
    from firebird_tpu.obs import logger, profiling, tracing

    log = logger("change-detection")
    # Flush any in-flight device-profile window FIRST so the report's
    # profile block carries its attribution (never raises).
    profiling.close_active()
    out = {}
    # Independent try blocks: an unwritable trace path must not also
    # drop the report (or vice versa) when its own path is writable.
    try:
        if tracer is not None:
            out["trace"] = tracer.save(
                tracing.resolve_path(cfg.trace or "1", cfg.store_path))
    except OSError as e:
        log.error("trace write failed: %s", e)
    try:
        path = run_report_path(cfg)
        if path is not None:
            n_proc, proc_idx = _process_info()
            if n_proc <= 1:
                write_report(path, tracer=tracer, run=run,
                             run_counters=run_counters)
                out["report"] = path
            else:
                # Multi-host SPMD: every process writes its own shard
                # (obs_report.host<N>.json); process 0 then waits for the
                # fleet and merges into the single obs_report.json that
                # tooling reads — the per-process view PR 1 left behind
                # is preserved in the shards.
                shard = shard_report_path(path, proc_idx)
                write_report(shard, tracer=tracer, run=run,
                             run_counters=run_counters)
                out["report_shard"] = shard
                if proc_idx == 0:
                    merged = merge_fleet_report(
                        path, n_proc,
                        timeout=getattr(cfg, "obs_merge_timeout", None))
                    if merged is not None:
                        out["report"] = path
                        got = merged["fleet"]["hosts"]
                        if got < n_proc:
                            log.warning(
                                "fleet report merged %d/%d host shards "
                                "(missing hosts crashed or timed out)",
                                got, n_proc)
    except OSError as e:
        log.error("obs report write failed: %s", e)
    return out
