"""Per-run report artifact: obs_report.json build, write, and validation.

One JSON document per pipeline run — metrics snapshot (counters, gauges,
latency histograms), span summary table, and run identity — written next
to the results store so soak/bench tooling can fold it into round
artifacts (tools/soak_report.py, bench.py) and operators can diff runs
without scraping logs.  ``validate_report``/``validate_trace`` are the
shared schema checks used by ``make obs-smoke`` and the test suite.
"""

from __future__ import annotations

import datetime
import json
import os

SCHEMA = "firebird-obs-report/1"

# Stage keys a driver run is expected to populate (the obs-smoke contract):
# ingest, kernel, and store latencies.  Kept here — not in the smoke tool —
# so the driver tests and the Makefile target assert the same contract.
DRIVER_STAGE_HISTOGRAMS = (
    "ingest_chip_seconds",
    "pipeline_fetch_seconds",
    "pipeline_pack_seconds",
    "pipeline_dispatch_seconds",
    "pipeline_drain_seconds",
    "store_write_seconds",
    "store_flush_seconds",
    "kernel_first_call_seconds",
)
DRIVER_SPAN_NAMES = ("fetch", "pack", "dispatch", "drain")


def build_report(*, registry=None, tracer=None, run: dict | None = None,
                 run_counters: dict | None = None) -> dict:
    """Assemble the report dict from live objects (no I/O)."""
    from firebird_tpu.obs import metrics as m

    reg = registry if registry is not None else m.get_registry()
    rep = {
        "schema": SCHEMA,
        "generated_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "run": run or {},
        "metrics": reg.snapshot(),
        "spans": tracer.summary() if tracer is not None else {},
    }
    if run_counters:
        rep["run_counters"] = run_counters
    return rep


def write_report(path: str, **kw) -> dict:
    """build_report + atomic write (tmp+rename); returns the report."""
    rep = build_report(**kw)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=1)
    os.replace(tmp, path)
    return rep


def validate_report(rep: dict) -> None:
    """Raise ValueError unless ``rep`` is a structurally valid report."""
    if not isinstance(rep, dict):
        raise ValueError("report is not a JSON object")
    if rep.get("schema") != SCHEMA:
        raise ValueError(f"report schema {rep.get('schema')!r} != {SCHEMA!r}")
    met = rep.get("metrics")
    if not isinstance(met, dict):
        raise ValueError("report has no metrics snapshot")
    for kind in ("counters", "gauges", "histograms"):
        if not isinstance(met.get(kind), dict):
            raise ValueError(f"metrics snapshot missing {kind!r}")
    for name, h in met["histograms"].items():
        if not isinstance(h, dict) or "count" not in h:
            raise ValueError(f"histogram {name!r} snapshot malformed")
        if h["count"] > 0 and not all(k in h for k in ("p50", "p95", "p99")):
            raise ValueError(f"histogram {name!r} missing percentiles")
    if not isinstance(rep.get("spans"), dict):
        raise ValueError("report has no span summary")


def validate_trace(trace: dict) -> None:
    """Raise ValueError unless ``trace`` is valid Chrome-trace JSON (the
    subset Perfetto's JSON importer requires)."""
    if not isinstance(trace, dict) \
            or not isinstance(trace.get("traceEvents"), list):
        raise ValueError("trace is not {'traceEvents': [...]} JSON")
    for ev in trace["traceEvents"]:
        if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev:
            raise ValueError(f"malformed trace event: {ev!r}")
        if ev["ph"] == "X" and not ("ts" in ev and "dur" in ev):
            raise ValueError(f"complete event missing ts/dur: {ev!r}")


def validate_driver_artifacts(trace: dict, rep: dict) -> None:
    """The full obs-smoke contract over a driver run's two artifacts —
    schema validity plus the stage-key coverage — shared by ``make
    obs-smoke`` (tools/obs_smoke.py) and the driver smoke test so the
    contract cannot drift between them.  Raises ValueError."""
    validate_trace(trace)
    names = {e.get("name") for e in trace["traceEvents"]}
    missing = [n for n in DRIVER_SPAN_NAMES if n not in names]
    if missing:
        raise ValueError(f"trace missing span names {missing}")
    validate_report(rep)
    hists = rep["metrics"]["histograms"]
    missing = [k for k in DRIVER_STAGE_HISTOGRAMS
               if k not in hists or hists[k]["count"] < 1]
    if missing:
        raise ValueError(f"report missing stage histograms {missing}")


def default_report_path(store_path: str) -> str:
    """obs_report.json next to the results store."""
    return os.path.join(os.path.dirname(os.path.abspath(store_path)),
                        "obs_report.json")


def run_report_path(cfg) -> str | None:
    """Where a driver run's report goes, or None to skip.

    cfg.obs_report: "0" never; a path always; "" auto — next to the store
    for file-backed backends, skipped for 'memory' (tests and embedded
    uses must not litter the CWD with artifacts nobody asked for).
    """
    if cfg.obs_report == "0":
        return None
    if cfg.obs_report:
        return cfg.obs_report
    if cfg.store_backend == "memory":
        return None
    return default_report_path(cfg.store_path)


def finish_run(cfg, *, tracer=None, run: dict | None = None,
               run_counters: dict | None = None) -> dict:
    """End-of-run artifact emission shared by the batch and streaming
    drivers: save the tracer's Chrome trace (when one ran) and write
    obs_report.json per cfg.obs_report policy.  Returns {artifact: path}
    for the paths actually written.  Never raises — a failed telemetry
    write must not fail a run whose results already landed."""
    from firebird_tpu.obs import logger, tracing

    log = logger("change-detection")
    out = {}
    # Independent try blocks: an unwritable trace path must not also
    # drop the report (or vice versa) when its own path is writable.
    try:
        if tracer is not None:
            out["trace"] = tracer.save(
                tracing.resolve_path(cfg.trace or "1", cfg.store_path))
    except OSError as e:
        log.error("trace write failed: %s", e)
    try:
        path = run_report_path(cfg)
        if path is not None:
            write_report(path, tracer=tracer, run=run,
                         run_counters=run_counters)
            out["report"] = path
    except OSError as e:
        log.error("obs report write failed: %s", e)
    return out
