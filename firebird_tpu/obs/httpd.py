"""Shared stdlib HTTP plumbing for the embedded servers.

Two subsystems embed a ThreadingHTTPServer on a daemon thread: the ops
surface (obs/server.py — /healthz /readyz /metrics /progress /report) and
the query/serving layer (serve/api.py — /v1/*).  Before this module each
carried its own copy of the byte-level send helpers and the
start/close/port lifecycle; the duplication is factored here so the two
servers cannot drift on the parts that must behave identically (HTTP/1.1
keep-alive framing, JSON error envelopes, daemon-thread shutdown).

- :class:`JsonHandler` — BaseHTTPRequestHandler with ``_send`` /
  ``_send_json``, access-log routing to the obs logger at DEBUG, and a
  ``do_GET`` that parses the URL once and dispatches to the subclass's
  ``_route(path, query)`` under the standard error envelope (a broken
  endpoint reports a 500 JSON body; it must never kill the server
  thread — the surface exists to diagnose trouble).  Long-lived
  chunk-less streaming responses (the ``/v1/alerts/stream`` SSE feed)
  go through ``_start_stream`` / ``_stream_event``: headers first, body
  incrementally, connection closed at the end — the only framing a
  response without a Content-Length can honestly offer.
- :class:`Httpd` — ThreadingHTTPServer with daemon worker threads, a
  ``port`` property (useful with port 0 ephemeral binds in tests and
  smokes), and ``start()``/``close()`` managing the serve_forever thread.
"""

from __future__ import annotations

import http.server
import json
import threading
from urllib.parse import parse_qs, urlsplit

from firebird_tpu.obs import tracing


class JsonHandler(http.server.BaseHTTPRequestHandler):
    """Request handler base: subclasses implement ``_route(path, query)``
    where ``query`` is the parse_qs dict (values are lists)."""

    server_version = "firebird/1"
    protocol_version = "HTTP/1.1"
    # Subsystem logger category for access lines (DEBUG, not stderr spam).
    log_category = "change-detection"

    def log_message(self, fmt, *args):
        from firebird_tpu.obs import logger
        logger(self.log_category).debug("http %s", fmt % args)

    def _send(self, code: int, body: bytes, ctype: str,
              headers: dict | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        headers = headers or {}
        for k, v in headers.items():
            self.send_header(k, str(v))
        # Trace propagation: a response produced under a TraceContext
        # (serve mints one per request) echoes its id, so a client can
        # join its slow call to server-side spans/exemplars/logs.
        ctx = tracing.current_context()
        if ctx is not None and "X-Firebird-Trace" not in headers:
            self.send_header("X-Firebird-Trace", ctx.batch_id)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj,
                   headers: dict | None = None) -> None:
        self._send(code, json.dumps(obj, default=str).encode(),
                   "application/json", headers)

    # -- long-lived / streaming responses (SSE) -----------------------------

    def _start_stream(self, ctype: str = "text/event-stream",
                      headers: dict | None = None) -> None:
        """Begin a long-lived response: headers go out now, the body is
        written incrementally by the caller, and the connection CLOSES
        when the handler returns — no Content-Length means HTTP/1.1
        keep-alive framing cannot survive this response, so advertising
        the close is what keeps clients in sync."""
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        headers = headers or {}
        for k, v in headers.items():
            self.send_header(k, str(v))
        ctx = tracing.current_context()
        if ctx is not None and "X-Firebird-Trace" not in headers:
            self.send_header("X-Firebird-Trace", ctx.batch_id)
        self.close_connection = True
        self.end_headers()

    def _stream_event(self, data: str, *, event: str | None = None,
                      event_id=None) -> bool:
        """Write one server-sent event; False when the client is gone
        (the caller's loop should end quietly — a consumer hanging up is
        the normal way an SSE session finishes)."""
        buf = []
        if event:
            buf.append(f"event: {event}")
        if event_id is not None:
            buf.append(f"id: {event_id}")
        for line in (data.splitlines() or [""]):
            buf.append(f"data: {line}")
        return self._stream_raw(("\n".join(buf) + "\n\n").encode())

    def _stream_comment(self, text: str = "keepalive") -> bool:
        """An SSE comment line — the keep-alive beat that lets both ends
        notice a dead peer between real events."""
        return self._stream_raw(f": {text}\n\n".encode())

    def _stream_raw(self, payload: bytes) -> bool:
        try:
            self.wfile.write(payload)
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False

    def do_GET(self):  # noqa: N802 (stdlib handler naming)
        self._dispatch_safely(self._route)

    def do_POST(self):  # noqa: N802 (stdlib handler naming)
        # Drain any request body first: leaving it unread desyncs the
        # HTTP/1.1 keep-alive stream for the client's next request.
        # Bodies past a sane bound aren't drained (nothing here takes a
        # payload) — the connection is closed after the response instead,
        # so a capped drain can never leave stray bytes to be parsed as
        # the next request line.
        try:
            n = int(self.headers.get("Content-Length") or 0)
            if n > (1 << 20):
                self.close_connection = True
            else:
                while n > 0:
                    chunk = self.rfile.read(min(n, 1 << 16))
                    if not chunk:
                        break
                    n -= len(chunk)
        except (ValueError, OSError):
            pass
        self._dispatch_safely(self._route_post)

    def _dispatch_safely(self, route) -> None:
        parts = urlsplit(self.path)
        try:
            route(parts.path, parse_qs(parts.query))
        except BrokenPipeError:
            pass                       # client went away mid-response
        except Exception as e:         # a broken endpoint must report, not
            # kill the serving thread
            try:
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def _route(self, path: str, query: dict) -> None:
        raise NotImplementedError

    def _route_post(self, path: str, query: dict) -> None:
        """Default POST surface: nothing accepts writes unless a
        subclass says so (the ops server's /profile does)."""
        self._send_json(405, {"error": f"POST not supported on {path!r}"})


class Httpd(http.server.ThreadingHTTPServer):
    """Threading HTTP server on a daemon thread; ``port`` is the bound
    port (useful when constructed with port 0 for an ephemeral bind)."""

    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog is 5: a replica fleet's
    # load generator opening ~100 keep-alive connections in one burst
    # overflows it and the excess see connection resets — a transport
    # error the client books against the SERVER.  128 absorbs any sane
    # connection storm; steady state is unaffected (keep-alive reuses).
    request_queue_size = 128
    thread_name = "firebird-httpd"

    def __init__(self, addr, handler_cls):
        super().__init__(addr, handler_cls)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> "Httpd":
        self._thread = threading.Thread(
            target=self.serve_forever, kwargs={"poll_interval": 0.25},
            name=self.thread_name, daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
