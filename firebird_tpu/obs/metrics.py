"""Metrics: counters, gauges, and fixed-bucket latency histograms.

The reference has no metrics system at all (SURVEY.md §5 — log4j lines are
its only signal).  This registry closes the gap with the three Prometheus
metric kinds, a text exposition (``prometheus()``) scrapable from a file or
pushed by an operator wrapper, and a JSON snapshot embedded in the per-run
``obs_report.json`` artifact (firebird_tpu.obs.report).

Instrumentation calls the module-level helpers (``counter("chips").inc()``,
``histogram("store_write_seconds").observe(dt)``) against a process-global
default registry — the pipeline stages live in different threads and
modules, and threading a registry handle through every seam would dwarf the
instrumentation itself.  FIREBIRD_METRICS=0 turns every recording call into
a no-op (the acceptance bar: disabled telemetry must cost <2% throughput;
all instrumented sites are per-batch/per-request, never per-pixel).
"""

from __future__ import annotations

import bisect
import os
import threading
import time

from firebird_tpu.obs import tracing as _tracing

# Exemplars kept per histogram: the slowest observations' trace
# identities (batch id + span id), so a hot p99 in a report links to the
# exact batch/trace that caused it instead of an anonymous bucket count.
EXEMPLAR_SLOTS = 4

# Fixed latency buckets (seconds): spans sub-millisecond packs up to
# multi-minute XLA compiles.  Fixed — not adaptive — so percentiles are
# comparable across runs and the exposition is a stable schema.
LATENCY_BUCKETS_SEC = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def metrics_enabled() -> bool:
    """FIREBIRD_METRICS gate: unset/1 on, 0/empty off.  Read per call so
    tests (and the bench overhead check) can flip it without reimports."""
    return os.environ.get("FIREBIRD_METRICS", "1") not in ("0", "")


class Counter:
    """Monotonic named counter."""

    def __init__(self, name: str, help: str | None = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0  # guarded-by: _lock

    def inc(self, n: int = 1) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-written value (queue depths, capacities)."""

    def __init__(self, name: str, help: str | None = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: _lock

    def set(self, v: float) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    Cumulative-bucket exposition matches Prometheus; ``quantile`` linearly
    interpolates inside the containing bucket (the overflow bucket reports
    the observed max — better than +Inf for a report meant to be read).
    """

    def __init__(self, name: str, buckets=LATENCY_BUCKETS_SEC,
                 help: str | None = None):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._min = float("inf")  # guarded-by: _lock
        self._max = float("-inf")  # guarded-by: _lock
        # Slowest-observation exemplars [(value, {batch, span_id}), ...],
        # descending, at most EXEMPLAR_SLOTS.
        self._exemplars: list = []  # guarded-by: _lock

    def observe(self, v: float) -> None:
        if not metrics_enabled():
            return
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        # Exemplar resolved OUTSIDE the lock (one thread-local read; None
        # when no TraceContext is active — e.g. registry unit tests).
        ex = _tracing.exemplar()
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if ex is not None and (len(self._exemplars) < EXEMPLAR_SLOTS
                                   or v > self._exemplars[-1][0]):
                self._exemplars.append((v, ex))
                self._exemplars.sort(key=lambda t: -t[0])
                del self._exemplars[EXEMPLAR_SLOTS:]

    def observe_many(self, values) -> None:
        """Bulk observe: vectorized binning + ONE lock acquisition for
        the whole array.  The per-batch occupancy feed
        (kernel.record_occupancy) delivers thousands of chip-round
        fractions from the driver's drain thread — per-value observe()
        calls there would serialize against every scraper."""
        if not metrics_enabled():
            return
        import numpy as np

        v = np.asarray(values, float).reshape(-1)
        if v.size == 0:
            return
        # side='left' matches observe()'s bisect_left binning exactly.
        binc = np.bincount(np.searchsorted(self.buckets, v, side="left"),
                           minlength=len(self.buckets) + 1)
        with self._lock:
            for i, c in enumerate(binc):
                self._counts[i] += int(c)
            self._sum += float(v.sum())
            self._count += v.size
            self._min = min(self._min, float(v.min()))
            self._max = max(self._max, float(v.max()))

    def quantile(self, q: float) -> float | None:
        with self._lock:
            counts, total = list(self._counts), self._count
            lo_obs, hi_obs = self._min, self._max
        if total == 0:
            return None
        target = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= target and c > 0:
                lo = self.buckets[i - 1] if i > 0 else min(lo_obs, self.buckets[0])
                hi = self.buckets[i] if i < len(self.buckets) else hi_obs
                frac = (target - seen) / c
                est = lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                # clamp to the observed range: bucket interpolation must
                # not report a percentile beyond any recorded value
                return min(max(est, lo_obs), hi_obs)
            seen += c
        return hi_obs

    def snapshot(self) -> dict:
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            out = {"count": self._count, "sum": self._sum,
                   "mean": self._sum / self._count,
                   "min": self._min, "max": self._max,
                   # Raw per-bucket counts (last = overflow) travel in the
                   # snapshot so per-host report shards stay mergeable —
                   # percentiles cannot be combined, bucket counts can
                   # (merge_histogram_snapshots).
                   "bucket_bounds": list(self.buckets),
                   "bucket_counts": list(self._counts)}
            if self._exemplars:
                out["exemplars"] = [dict(ex, value=round(v, 6))
                                    for v, ex in self._exemplars]
        out.update({"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                    "p99": self.quantile(0.99)})
        return out

    def cumulative_buckets(self) -> list[tuple[str, int]]:
        """[(le_label, cumulative_count), ...] ending with '+Inf'."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append((format(b, "g"), cum))
        out.append(("+Inf", cum + counts[-1]))
        return out


# Exposition format contract: every non-empty line is a HELP/TYPE comment
# or a `name{labels} value` sample.  Shared by tools/obs_smoke.py and the
# test suite so the scrape-format check cannot drift from the emitter.
import re as _re

PROM_LINE_RE = _re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+)$")


def _prom_name(name: str, kind: str | None = None) -> str:
    """Prometheus-sanitized metric name.  Counters get the conventional
    ``_total`` suffix exactly once — a counter already named ``*_total``
    (watchdog_stall_total) must not double up."""
    p = "firebird_" + _re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if kind == "counter" and not p.endswith("_total"):
        p += "_total"
    return p


# Central ``# HELP`` catalog for instruments registered at hot call
# sites where an inline ``help=`` kwarg would crowd the instrumentation
# (an inline help still wins; this is the fallback before the generic
# default).  Glob keys (``stream_*``) cover dynamically-named families.
# firebird-lint's metric-help rule accepts an instrument iff SOME
# registration site passes help= or its name matches an entry here — so
# a new instrument cannot ship help-less.
METRIC_HELP = {
    "kernel_first_call_seconds":
        "per-shape first kernel call wall time (~ XLA compile)",
    "kernel_dispatch_shapes":
        "distinct compiled kernel shapes dispatched this run",
    "warm_compile_seconds":
        "background AOT warm-start compile wall time",
    "pipeline_fetch_seconds": "per-batch source fetch wall time",
    "pipeline_pack_seconds": "per-batch dense packing wall time",
    "pipeline_stage_seconds": "per-batch H2D staging wall time",
    "pipeline_dispatch_seconds": "per-batch dispatch (enqueue) wall time",
    "pipeline_drain_seconds": "per-batch result drain wall time",
    "pipeline_d2h_seconds": "per-batch bulk device_get wall time",
    "ingest_chip_seconds": "per-chip source fetch wall time",
    "ingest_http_seconds": "chipmunk HTTP request wall time",
    "ingest_http_requests": "chipmunk HTTP requests issued",
    "ingest_bytes_in": "decoded ingest payload bytes",
    "capacity_redispatches":
        "batches re-dispatched at doubled segment capacity",
    "chunk_failures": "chunks abandoned by the per-chunk isolation",
    "fetch_retries": "chip fetches retried after transient errors",
    "store_write_seconds": "store backend write wall time",
    "store_flush_seconds": "writer flush (drain-all) wall time",
    "store_write_errors": "store writes that exhausted their retries",
    "store_write_retries": "store writes retried after transient errors",
    "store_queue_depth": "frames queued to the async writer",
    "objectstore_puts": "objects published (manifest commits)",
    "objectstore_gets": "object reads served",
    "objectstore_conflicts":
        "conditional puts that lost the generation race",
    "objectstore_torn_recoveries":
        "reads that fell back a generation past a torn newest object",
    "objectstore_scrubbed_chunks":
        "orphaned chunks reclaimed by the scrubber",
    "objectstore_retries":
        "transient object-store operation failures retried under the "
        "shared budget",
    "object_fence_rejected_total":
        "stale-fence conditional puts rejected at the object layer",
    "watchdog_stall_total": "stall episodes declared by the watchdog",
    "watchdog_recovered_total": "stalls cleared by a later batch beat",
    "watchdog_throughput_drop_total":
        "rolling-window throughput drop events",
    "stream_publish_seconds": "streaming update publish wall time",
    "stream_*": "per-run streaming driver summary values",
    "faults_injected_*": "injected faults by scope (chaos drills)",
    "serve_requests_segments": "/v1/segments requests served",
    "serve_requests_pixel": "/v1/pixel requests served",
    "serve_requests_product": "/v1/product requests served",
    "serve_requests_tile": "/v1/tile requests served",
    "serve_deadline_exceeded_total":
        "requests past their deadline (504)",
    "fleet_jobs_claimed": "fleet jobs claimed (leased) by workers",
    "fleet_jobs_acked": "fleet jobs completed and acked",
    "fleet_jobs_requeued":
        "fleet jobs returned to the queue (lease expiry or retryable "
        "failure)",
    "fleet_jobs_dead":
        "fleet jobs dead-lettered after their attempt budget",
    "fleet_jobs_lost":
        "jobs abandoned after lease loss (zombie fenced off its output)",
    "fleet_fence_rejected":
        "operations rejected for a stale fencing token",
    "fleet_lease_age_seconds": "age of this worker's current fleet lease",
    "fleet_job_seconds_*": "fleet job execution wall time by job type",
    "probe_attempts": "black-box probes resolved (all surfaces)",
    "probe_attempts_*": "black-box probes resolved, by surface",
    "probe_failures":
        "black-box probes failed (timeout, transport error, or 5xx)",
    "probe_failures_*": "black-box probe failures, by surface",
    "probe_etag_304":
        "probe conditional GETs answered 304 (ETag revalidation "
        "worked end to end)",
    "probe_serve_seconds":
        "black-box serve GET seconds (the outside view of /v1 latency)",
    "probe_alert_seconds":
        "black-box scene drop -> SSE alert visibility seconds",
    "probe_webhook_seconds":
        "black-box scene drop -> webhook delivery seconds",
}


def _catalog_help(name: str) -> str | None:
    h = METRIC_HELP.get(name)
    if h is not None:
        return h
    import fnmatch

    for pat, text in METRIC_HELP.items():
        if "*" in pat and fnmatch.fnmatch(name, pat):
            return text
    return None


def _help_text(m, kind: str) -> str:
    """# HELP body: the metric's declared help, the METRIC_HELP catalog
    entry, or a readable default."""
    return m.help or _catalog_help(m.name) \
        or f"firebird {kind} {m.name.replace('_', ' ')}"


class MetricsRegistry:
    """Named metric registry: get-or-create accessors, Prometheus text
    exposition, and a JSON-ready snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        # The three stores are mutated only inside _get (under _lock);
        # accessors pass the dict REFERENCE through, which is why they
        # are not guarded-by annotated — the linter checks lexical
        # with-scopes, not aliases (docs/STATIC_ANALYSIS.md).
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._once: set = set()  # guarded-by: _lock
        self._t0 = time.monotonic()

    def once(self, key) -> bool:
        """True exactly the first time ``key`` is seen on this registry —
        first-call capture (e.g. per-shape kernel compile time) scoped to
        the registry's lifetime, so every run's report records its own."""
        with self._lock:
            if key in self._once:
                return False
            self._once.add(key)
            return True

    def _get(self, store: dict, name: str, factory, help: str | None):
        with self._lock:
            m = store.get(name)
            if m is None:
                m = store[name] = factory(name)
            if help and not m.help:   # first declared help wins
                m.help = help
            return m

    def counter(self, name: str, help: str | None = None) -> Counter:
        return self._get(self._counters, name, Counter, help)

    def gauge(self, name: str, help: str | None = None) -> Gauge:
        return self._get(self._gauges, name, Gauge, help)

    def histogram(self, name: str, buckets=LATENCY_BUCKETS_SEC,
                  help: str | None = None) -> Histogram:
        return self._get(self._histograms, name,
                         lambda n: Histogram(n, buckets), help)

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        return {
            "elapsed_sec": time.monotonic() - self._t0,
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.snapshot() for n, h in sorted(hists.items())},
        }

    def prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._histograms.items())
        lines = []
        for name, c in counters:
            p = _prom_name(name, "counter")
            lines += [f"# HELP {p} {_help_text(c, 'counter')}",
                      f"# TYPE {p} counter", f"{p} {c.value}"]
        for name, g in gauges:
            p = _prom_name(name)
            lines += [f"# HELP {p} {_help_text(g, 'gauge')}",
                      f"# TYPE {p} gauge", f"{p} {format(g.value, 'g')}"]
        for name, h in hists:
            p = _prom_name(name)
            lines.append(f"# HELP {p} {_help_text(h, 'histogram')}")
            lines.append(f"# TYPE {p} histogram")
            for le, cum in h.cumulative_buckets():
                lines.append(f'{p}_bucket{{le="{le}"}} {cum}')
            snap = h.snapshot()
            lines.append(f"{p}_sum {format(snap.get('sum', 0.0), 'g')}")
            lines.append(f"{p}_count {snap['count']}")
        # An empty registry exposes nothing — not a lone blank line
        # (scrape format: every line is a comment or a sample).
        return "\n".join(lines) + "\n" if lines else ""


def prometheus_from_snapshot(snap: dict) -> str:
    """Rebuild the text exposition from a registry *snapshot* — a
    telemetry-spool ``snap`` line (obs/spool.py) or a fleet view merged
    from several (obs/collect.py): the scrape a SIGKILLed process can no
    longer serve.  Naming and format rules are shared with
    :meth:`MetricsRegistry.prometheus`; help text comes from the
    METRIC_HELP catalog (snapshots carry values, not per-instrument
    help declarations)."""

    def _help(name: str, kind: str) -> str:
        return _catalog_help(name) \
            or f"firebird {kind} {name.replace('_', ' ')}"

    lines: list[str] = []
    for name, v in sorted((snap.get("counters") or {}).items()):
        p = _prom_name(name, "counter")
        lines += [f"# HELP {p} {_help(name, 'counter')}",
                  f"# TYPE {p} counter", f"{p} {v}"]
    for name, v in sorted((snap.get("gauges") or {}).items()):
        p = _prom_name(name)
        lines += [f"# HELP {p} {_help(name, 'gauge')}",
                  f"# TYPE {p} gauge", f"{p} {format(v, 'g')}"]
    for name, h in sorted((snap.get("histograms") or {}).items()):
        p = _prom_name(name)
        lines.append(f"# HELP {p} {_help(name, 'histogram')}")
        lines.append(f"# TYPE {p} histogram")
        bounds = h.get("bucket_bounds") or ()
        counts = h.get("bucket_counts") or ()
        cum = 0
        for b, c in zip(bounds, counts):
            cum += c
            lines.append(f'{p}_bucket{{le="{format(b, "g")}"}} {cum}')
        overflow = counts[len(bounds)] if len(counts) > len(bounds) else 0
        lines.append(f'{p}_bucket{{le="+Inf"}} {cum + overflow}')
        lines.append(f"{p}_sum {format(h.get('sum', 0.0), 'g')}")
        lines.append(f"{p}_count {h.get('count', 0)}")
    return "\n".join(lines) + "\n" if lines else ""


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def reset_registry() -> MetricsRegistry:
    """Swap in a fresh default registry (test isolation; a run-scoped
    report should not carry a previous run's latencies)."""
    global _registry
    # Single-reference swap between runs (tests, driver run setup) while
    # no instrumented thread is live; readers grab the reference once.
    _registry = MetricsRegistry()  # firebird-lint: disable=ownership-global-mutation
    return _registry


def counter(name: str, help: str | None = None) -> Counter:
    return _registry.counter(name, help)


def gauge(name: str, help: str | None = None) -> Gauge:
    return _registry.gauge(name, help)


def histogram(name: str, buckets=LATENCY_BUCKETS_SEC,
              help: str | None = None) -> Histogram:
    return _registry.histogram(name, buckets, help)


# ---------------------------------------------------------------------------
# Multi-host merge policy (obs.report.merge_reports)
# ---------------------------------------------------------------------------
# Counters always sum across host shards and histogram bucket counts always
# add; gauges are last-written values, so each needs a declared combination.
# Prefix rules, first match wins; anything undeclared takes the default —
# "max" reads as "the worst host" for depth/backlog-style gauges, which is
# the operator-relevant view.
GAUGE_MERGE_POLICY: tuple[tuple[str, str], ...] = (
    ("stream_", "sum"),           # per-host stream summary counts add up
    ("store_queue_depth", "max"),  # worst backlog across the fleet
    ("mesh_", "max"),             # global topology, identical on every host
)
_GAUGE_MERGE_DEFAULT = "max"


def gauge_merge_policy(name: str) -> str:
    """'sum' | 'max' | 'min' for a gauge name under fleet merge."""
    for prefix, policy in GAUGE_MERGE_POLICY:
        if name.startswith(prefix):
            return policy
    return _GAUGE_MERGE_DEFAULT


def merge_gauge_values(name: str, values: list[float]) -> float:
    policy = gauge_merge_policy(name)
    if policy == "sum":
        return float(sum(values))
    if policy == "min":
        return float(min(values))
    return float(max(values))


def merge_histogram_snapshots(snaps: list[dict]) -> dict:
    """Combine per-host histogram snapshots into one fleet snapshot.

    When every live shard carries the same bucket bounds (the normal case
    — LATENCY_BUCKETS_SEC is a fixed schema precisely so runs compose),
    bucket counts add and the percentiles are *recomputed* from the merged
    buckets.  Shards without bucket data (older schema) or with mismatched
    bounds fall back to a count-weighted percentile average — labeled
    approximate, never silently wrong about count/sum/min/max, which merge
    exactly either way.
    """
    live = [s for s in snaps if s.get("count", 0) > 0]
    if not live:
        return {"count": 0}
    # Exemplars union across shards, slowest-first, re-bounded — a fleet
    # report's p99 exemplar should be the fleet's slowest batch.
    exemplars = sorted((e for s in live for e in s.get("exemplars", ())),
                       key=lambda e: -e.get("value", 0.0))[:EXEMPLAR_SLOTS]
    bounds = live[0].get("bucket_bounds")
    same = bounds is not None and \
        all(s.get("bucket_bounds") == bounds for s in live)
    if same:
        h = Histogram("merged", buckets=bounds)
        h._counts = [sum(s["bucket_counts"][i] for s in live)
                     for i in range(len(bounds) + 1)]
        h._count = sum(s["count"] for s in live)
        h._sum = float(sum(s["sum"] for s in live))
        h._min = min(s["min"] for s in live)
        h._max = max(s["max"] for s in live)
        out = h.snapshot()
        if exemplars:
            out["exemplars"] = exemplars
        return out
    total = sum(s["count"] for s in live)
    out = {"count": total, "sum": float(sum(s["sum"] for s in live)),
           "min": min(s["min"] for s in live),
           "max": max(s["max"] for s in live),
           "percentiles_approximate": True}
    out["mean"] = out["sum"] / total
    for q in ("p50", "p95", "p99"):
        vals = [(s[q], s["count"]) for s in live if s.get(q) is not None]
        out[q] = (sum(v * c for v, c in vals) / sum(c for _, c in vals)
                  if vals else None)
    if exemplars:
        out["exemplars"] = exemplars
    return out


class Counters:
    """Thread-safe run-scoped throughput counters (the original flat
    counter set; the driver logs its snapshot at run end).  Typical keys:
    chips, pixels, segments, bytes_in, bytes_out.

    The rate clock starts at the first ``add`` (or an explicit
    ``start()``), NOT at construction: the driver builds its Counters
    before source/store setup and XLA compilation, and dividing by that
    idle span deflated every ``*_per_sec`` rate — a 100s compile ahead of
    a 10s run read as a 10x slower pipeline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}  # guarded-by: _lock
        self._t0: float | None = None  # guarded-by: _lock

    def start(self) -> None:
        """Explicitly (re)start the rate clock — call at the moment the
        run's productive work begins; otherwise the first add starts it."""
        with self._lock:
            self._t0 = time.monotonic()

    def add(self, key: str, n: int = 1) -> None:
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic()
            self._counts[key] = self._counts.get(key, 0) + n

    def get(self, key: str) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def snapshot(self) -> dict:
        with self._lock:
            elapsed = (time.monotonic() - self._t0) \
                if self._t0 is not None else 0.0
            out = dict(self._counts)
        out["elapsed_sec"] = elapsed
        for k in list(out):
            if k != "elapsed_sec" and elapsed > 0:
                out[f"{k}_per_sec"] = out[k] / elapsed
        return out


class timer:
    """Context manager measuring wall time in seconds (``.elapsed``)."""

    def __enter__(self):
        self._t0 = time.monotonic()
        self.elapsed = 0.0
        return self

    def __exit__(self, *exc):
        self.elapsed = time.monotonic() - self._t0
        return False
