"""Crash flight recorder: per-thread event rings + postmortem.json.

A dead soak is today diagnosable only by rerunning it: the obs report
and trace are written at run *end*, so a run killed by SIGTERM, wedged
into a watchdog stall, or felled by an unhandled exception leaves
nothing but whatever stderr survived.  This module is the black box the
crash leaves behind: while armed (driver bring-up, ``FIREBIRD_FLIGHTREC``
ring size, default on) every thread keeps a bounded ring of its recent
events — spans (obs/tracing.py feeds them even when no tracer runs),
log lines (a handler on the ``firebird`` root logger), and driver
progress marks (stage changes, batch dispatch/done) — and on

- an **unhandled exception** (``sys.excepthook`` + ``threading.excepthook``,
  plus the drivers' own ``stop_ops`` exception check),
- a **watchdog stall** (obs/watchdog.py calls :func:`on_stall` when it
  declares one), or
- **SIGTERM** (handler installed while armed, main thread only)

a single ``postmortem.json`` bundle is written next to the results
store: the last N events per thread, the run's progress/degraded state
(breaker, quarantine, watchdog incl. throughput-drop events), the full
metrics snapshot (queue depths ride along as gauges), and the config
fingerprint — enough to say *where every thread was* without rerunning.

Cost while armed: one deque append per span/log/mark (deque appends are
GIL-atomic; no lock on the hot path), zero when disarmed (one global
read at each feed site).
"""

from __future__ import annotations

import collections
import datetime
import json
import logging
import os
import signal
import sys
import threading
import time
import traceback

from firebird_tpu.obs import tracing

SCHEMA = "firebird-postmortem/1"


def _now_iso() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


class _RingHandler(logging.Handler):
    """Feeds formatted-enough log records into the recorder's rings."""

    def __init__(self, rec: "FlightRecorder"):
        super().__init__(level=logging.DEBUG)
        self._rec = rec

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._rec.log_event(record.levelname, record.name,
                                record.getMessage())
        except Exception:
            pass                     # the black box must never crash a run


class FlightRecorder:
    """Bounded per-thread event rings + the postmortem dump.

    ``path`` is where ``postmortem.json`` lands (None keeps the rings
    in memory only — memory-backend runs, unit tests poking ``bundle``).
    """

    def __init__(self, path: str | None, ring: int = 128, *,
                 run_id: str = "", fingerprint: str = ""):
        self.path = path
        self.ring = max(int(ring), 1)
        self.run_id = run_id
        self.fingerprint = fingerprint
        self._lock = threading.Lock()
        self._rings: dict[str, collections.deque] = {}  # guarded-by: _lock
        self._local = threading.local()
        self._dumps = 0  # guarded-by: _lock
        self._reasons: list[str] = []  # guarded-by: _lock

    def _ring(self) -> collections.deque:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            name = threading.current_thread().name
            with self._lock:
                ring = self._rings.get(name)
                if ring is None:
                    ring = self._rings[name] = collections.deque(
                        maxlen=self.ring)
            self._local.ring = ring
        return ring

    def _append(self, ev: dict) -> None:
        ctx = tracing.current_context()
        if ctx is not None:
            ev["batch"] = ctx.batch_id
        ev["t"] = time.time()
        self._ring().append(ev)        # deque append: GIL-atomic

    # -- feeds (span hook installed via tracing.set_recorder) ---------------

    def span_event(self, name: str, dur_ms: float,
                   batch: str | None) -> None:
        ev = {"kind": "span", "name": name, "ms": round(dur_ms, 3)}
        if batch is not None:
            ev["batch"] = batch
        ev["t"] = time.time()
        self._ring().append(ev)

    def log_event(self, level: str, logger_name: str, message: str) -> None:
        self._append({"kind": "log", "level": level, "logger": logger_name,
                      "message": message[:500]})

    def mark(self, name: str, **fields) -> None:
        """A driver progress mark (stage change, batch dispatched/done)."""
        self._append({"kind": "mark", "name": name, **fields})

    # -- the bundle ----------------------------------------------------------

    def bundle(self, reason: str, exc: BaseException | None = None) -> dict:
        from firebird_tpu.obs import metrics as obs_metrics
        from firebird_tpu.obs import server as obs_server

        with self._lock:
            threads = {name: list(ring)
                       for name, ring in self._rings.items()}
            self._reasons.append(reason)
            reasons = list(self._reasons)
        out = {
            "schema": SCHEMA,
            "written_at": _now_iso(),
            "reason": reason,
            "reasons": reasons,
            "run_id": self.run_id,
            "config_fingerprint": self.fingerprint,
            "threads": threads,
            "live_threads": sorted(t.name for t in threading.enumerate()),
        }
        if exc is not None:
            out["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc)[:1200],
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__)[-20:],
            }
        # Best-effort context: a half-dead process must still dump what
        # it can — each block degrades independently.
        try:
            out["metrics"] = obs_metrics.get_registry().snapshot()
        except Exception:
            out["metrics"] = None
        try:
            st = obs_server.current()
            out["progress"] = st.progress() if st is not None else None
        except Exception:
            out["progress"] = None
        return out

    def dump(self, reason: str, exc: BaseException | None = None) -> dict:
        """Write the postmortem bundle (atomic tmp+rename) and return it.
        Multiple dumps in one run overwrite — the last state wins, with
        every trigger recorded under ``reasons``.  Never raises."""
        doc = self.bundle(reason, exc)
        with self._lock:
            self._dumps += 1
        if self.path is None:
            return doc
        try:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, self.path)
            from firebird_tpu.obs import metrics as obs_metrics
            obs_metrics.counter(
                "postmortems_written",
                help="postmortem.json bundles written by the flight "
                     "recorder").inc()
            from firebird_tpu.obs import logger
            logger("change-detection").error(
                "flight recorder: postmortem (%s) written to %s",
                reason, self.path)
        except Exception:
            pass                     # the black box must never crash a run
        return doc


# ---------------------------------------------------------------------------
# Process-global arming (driver bring-up; one recorder per run)
# ---------------------------------------------------------------------------

# Mutated only by arm()/disarm() from the run-owning thread; the feed
# sites read the one reference lock-free (same discipline as
# obs/server.py's _status).
_recorder: FlightRecorder | None = None
_prev_hooks: dict = {}


def active() -> FlightRecorder | None:
    return _recorder


def postmortem_path(cfg) -> str | None:
    """Where a run's postmortem.json lands: next to the results store
    (the quarantine/manifest rule), None for the memory backend."""
    from firebird_tpu.driver import quarantine as qlib

    d = qlib._artifact_dir(cfg)
    return None if d is None else os.path.join(d, "postmortem.json")


def arm(path: str | None, ring: int = 128, *, run_id: str = "",
        fingerprint: str = "") -> FlightRecorder:
    """Install a fresh recorder as the process flight recorder: span and
    log feeds attach, and the crash hooks (excepthook, threading
    excepthook, SIGTERM when on the main thread) chain to the previous
    handlers.  Re-arming replaces the previous recorder."""
    global _recorder
    if _recorder is not None:
        disarm()
    rec = FlightRecorder(path, ring, run_id=run_id, fingerprint=fingerprint)
    _recorder = rec  # firebird-lint: disable=ownership-global-mutation
    tracing.set_recorder(rec)

    handler = _RingHandler(rec)
    logging.getLogger("firebird").addHandler(handler)
    _prev_hooks["log_handler"] = handler

    prev_except = sys.excepthook

    def _excepthook(etype, value, tb):
        rec.dump("unhandled_exception", value)
        prev_except(etype, value, tb)

    sys.excepthook = _excepthook
    _prev_hooks["excepthook"] = prev_except

    prev_thread = threading.excepthook

    def _thread_excepthook(args):
        # SystemExit from a cleanly-stopped thread is not a crash.
        if args.exc_type is not SystemExit:
            rec.dump("unhandled_exception", args.exc_value)
        prev_thread(args)

    threading.excepthook = _thread_excepthook
    _prev_hooks["thread_excepthook"] = prev_thread

    if threading.current_thread() is threading.main_thread():
        try:
            prev_sig = signal.getsignal(signal.SIGTERM)

            def _on_sigterm(signum, frame):
                # The handler runs ON the main thread between bytecodes,
                # possibly while that thread holds a metrics/status lock
                # (Histogram.observe, RunStatus.batch_dispatched) that
                # bundle() needs — dumping inline could deadlock on a
                # non-reentrant lock our own paused frame owns.  Dump on
                # a helper thread with a bounded wait instead: the
                # common case (no lock held) completes in milliseconds;
                # the pathological case forfeits the bundle (the atomic
                # tmp+rename never lands a partial one) but the process
                # STILL dies with real SIGTERM semantics below.
                t = threading.Thread(target=rec.dump, args=("sigterm",),
                                     name="firebird-postmortem",
                                     daemon=True)
                t.start()
                t.join(timeout=10.0)
                # Restore and re-raise so the process dies with real
                # SIGTERM semantics (exit code 143, supervisors see it).
                signal.signal(signal.SIGTERM, prev_sig or signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_sigterm)
            _prev_hooks["sigterm"] = prev_sig
        except (ValueError, OSError):
            pass            # non-main thread / exotic platform: no signal
    return rec


def disarm() -> FlightRecorder | None:
    """Detach the recorder and restore every hook; returns it (rings
    intact) so a caller can still dump after disarming."""
    global _recorder
    rec = _recorder
    _recorder = None  # firebird-lint: disable=ownership-global-mutation
    tracing.set_recorder(None)
    handler = _prev_hooks.pop("log_handler", None)
    if handler is not None:
        logging.getLogger("firebird").removeHandler(handler)
    prev = _prev_hooks.pop("excepthook", None)
    if prev is not None:
        sys.excepthook = prev
    prev = _prev_hooks.pop("thread_excepthook", None)
    if prev is not None:
        threading.excepthook = prev
    if "sigterm" in _prev_hooks:
        prev = _prev_hooks.pop("sigterm")
        try:
            signal.signal(signal.SIGTERM, prev or signal.SIG_DFL)
        except (ValueError, OSError):
            pass
    return rec


# Module-level feed hooks: one global read + None check when disarmed —
# the obs/server progress hooks and watchdog call these unconditionally.

def mark(name: str, **fields) -> None:
    rec = _recorder
    if rec is not None:
        rec.mark(name, **fields)


def on_stall(age_sec: float, deadline_sec: float) -> None:
    """The watchdog's stall trigger: dump once per declared episode."""
    rec = _recorder
    if rec is not None:
        rec.dump("watchdog_stall")
        rec.mark("stall", age_sec=round(age_sec, 3),
                 deadline_sec=deadline_sec)


def dump_if_armed(reason: str, exc: BaseException | None = None) -> None:
    """The drivers' teardown check (stop_ops): when a run is unwinding on
    an exception, the bundle must be written BEFORE disarming."""
    rec = _recorder
    if rec is not None:
        rec.dump(reason, exc)
