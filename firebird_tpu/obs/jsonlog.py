"""Run-correlated structured logging: JSON lines + the run context.

The reference's log4j lines carry a category and a timestamp and nothing
else; joining a fleet's logs meant grepping hostnames out of Spark UI
screenshots.  Here every run mints a ``run_id`` (driver/core.py,
driver/stream.py) and registers it — with the JAX process index — in a
process-global run context, and the opt-in JSON formatter
(``FIREBIRD_LOG_FORMAT=json``, applied by ``obs.configure``) stamps every
log line with ``run_id`` / ``host`` / ``process_id`` / ``pid`` so a
multi-host SPMD run's interleaved logs are join-able by run and
attributable to a host without any out-of-band bookkeeping.

The same context feeds the ops server's ``/progress`` payload and the
report ``run`` block, so one identifier correlates logs, live endpoints,
and the post-hoc artifact.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time

from firebird_tpu.obs import tracing

HOST = socket.gethostname()

_lock = threading.Lock()
_context: dict = {"run_id": None, "process_index": None}


def new_run_id() -> str:
    """Mint a run id: coarse wall-clock prefix (sortable across a fleet)
    plus random suffix (collision-safe when hosts start in the same
    second)."""
    return f"{int(time.time()):x}-{os.urandom(4).hex()}"


def set_run_context(run_id: str | None = None,
                    process_index: int | None = None) -> None:
    """Install the current run's identity; every JSON log line and the
    ops endpoints read it.  Passing None leaves a field unchanged."""
    with _lock:
        if run_id is not None:
            _context["run_id"] = run_id
        if process_index is not None:
            _context["process_index"] = int(process_index)


def clear_run_context() -> None:
    with _lock:
        _context["run_id"] = None
        _context["process_index"] = None


def get_run_context() -> dict:
    with _lock:
        return dict(_context)


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts/level/logger/message plus the run
    correlation fields.  Values are whatever ``json.dumps`` can carry;
    anything else stringifies rather than crashing the log path."""

    def format(self, record: logging.LogRecord) -> str:
        ctx = get_run_context()
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.localtime(record.created))
                  + f".{int(record.msecs):03d}",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "host": HOST,
            "pid": record.process,
            "run_id": ctx["run_id"],
            "process_id": ctx["process_index"],
        }
        # Batch-scoped parent id: a line logged from inside a unit of
        # work (any thread that activated the batch's TraceContext —
        # prefetch, dispatch, drain, writer) joins to its spans and
        # exemplars on one key (obs/tracing.py).
        tctx = tracing.current_context()
        if tctx is not None:
            out["batch"] = tctx.batch_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def wants_json(env: dict | None = None) -> bool:
    """FIREBIRD_LOG_FORMAT gate: 'json' (case-insensitive) opts in; empty
    or 'text' keeps the ISO8601 line format."""
    e = os.environ if env is None else env
    return e.get("FIREBIRD_LOG_FORMAT", "").strip().lower() == "json"
