"""Stall watchdog: the pipeline's first automatic failure signal.

The reference pipeline had Spark's UI and task-retry accounting to tell a
wedged run from a slow one; host-orchestrated SPMD execution has neither
(PAPERS.md, DrJAX — no scheduler UI to fall back on).  This watchdog closes
the gap: the driver calls :meth:`Watchdog.beat` whenever a batch finishes
draining, and if no beat arrives within the configured deadline
(``FIREBIRD_STALL_SEC`` / ``Config.stall_sec``) the run is declared
stalled — ``/healthz`` flips to 503 (obs/server.py asks :attr:`stalled`)
and ``watchdog_stall_total`` increments, so a fleet supervisor can restart
the process instead of letting a multi-hour tile run hang silently.

A later beat clears the stall (``watchdog_recovered_total``): transient
wedges — a slow capacity-retry recompile, a raster-service brownout that
the fetch retries eventually absorb — self-heal without operator action.

Beyond the binary stall, beats feed a rolling throughput window: when the
recent batch rate drops below ``drop_frac`` of the window's baseline rate,
a throughput-drop event is recorded (``watchdog_throughput_drop_total`` +
a bounded event list in :meth:`snapshot`), catching the slow-leak failure
mode (one host degrading, store backpressure) that never quite stalls.

The clock is injectable so every threshold is unit-testable without
sleeping; the optional background thread (:meth:`start`) only matters for
unpolled runs — ``/healthz`` calls :meth:`check` live, so a scraped
process needs no thread at all.
"""

from __future__ import annotations

import collections
import threading
import time

from firebird_tpu.obs import metrics as obs_metrics


class Watchdog:
    """Deadline + rolling-throughput monitor over driver batch beats.

    Parameters
    ----------
    stall_sec:
        No beat for this long => stalled.  Must be > 0.
    grace_factor:
        Until the FIRST beat the effective deadline is ``stall_sec *
        grace_factor``: bring-up (first fetch + first XLA compile, which
        only a completed drain can ack) legitimately exceeds the
        steady-state cadence, and a liveness supervisor restarting on a
        false bring-up stall would loop restart -> recompile -> restart
        forever.  A hung bring-up still stalls — just on the longer
        deadline.
    window:
        Number of recent beats kept for the throughput baseline.
    drop_frac:
        Recent rate below ``drop_frac * baseline`` records a drop event.
    clock:
        Monotonic time source (injectable for tests).
    """

    def __init__(self, stall_sec: float, *, grace_factor: float = 3.0,
                 window: int = 32, drop_frac: float = 0.5,
                 clock=time.monotonic):
        if stall_sec <= 0:
            raise ValueError(f"stall_sec must be > 0, got {stall_sec}")
        self.stall_sec = float(stall_sec)
        self.grace_factor = max(float(grace_factor), 1.0)
        self.drop_frac = float(drop_frac)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_beat = clock()  # guarded-by: _lock
        self._stalled = False  # guarded-by: _lock
        self._beats: collections.deque = \
            collections.deque(maxlen=window)  # guarded-by: _lock
        self._beat_count = 0  # guarded-by: _lock
        self._in_drop = False  # guarded-by: _lock
        self._drop_events: collections.deque = \
            collections.deque(maxlen=16)  # guarded-by: _lock
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- signal ingest -----------------------------------------------------

    def beat(self, units: int = 1) -> None:
        """Record a unit of forward progress (a drained batch)."""
        now = self._clock()
        with self._lock:
            self._last_beat = now
            self._beats.append((now, max(int(units), 0)))
            self._beat_count += 1
            if self._stalled:
                self._stalled = False
                obs_metrics.counter("watchdog_recovered_total").inc()
                from firebird_tpu.obs import logger
                logger("change-detection").warning(
                    "watchdog: run recovered after stall")
            self._check_throughput_locked(now)

    def _check_throughput_locked(self, now: float) -> None:
        # Baseline over the whole rolling window vs. the most recent
        # quarter of it; both need enough beats to be rates, not noise.
        beats = list(self._beats)
        if len(beats) < 8:
            return
        span = now - beats[0][0]
        if span <= 0:
            return
        baseline = sum(n for _, n in beats) / span
        recent = beats[-max(len(beats) // 4, 2):]
        rspan = now - recent[0][0]
        if rspan <= 0:
            return
        recent_rate = sum(n for _, n in recent) / rspan
        if recent_rate < self.drop_frac * baseline:
            if not self._in_drop:
                self._in_drop = True
                obs_metrics.counter("watchdog_throughput_drop_total").inc()
                # Wall-clock timestamp + the threshold that was crossed:
                # the event is read post-hoc from /progress's degraded
                # block and the flight-recorder bundle, where a bare
                # monotonic offset is meaningless.  UTC with designator
                # — the written_at/generated_at artifact convention.
                self._drop_events.append({
                    "at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()),
                    "at_sec": now, "recent_per_sec": recent_rate,
                    "baseline_per_sec": baseline,
                    "threshold_per_sec": self.drop_frac * baseline})
        else:
            self._in_drop = False

    # -- state reads -------------------------------------------------------

    def check(self, now: float | None = None) -> bool:
        """Evaluate the deadline; returns the (possibly new) stalled state.

        Called live by the ops server's ``/healthz`` handler and by the
        optional background thread — the stall counter increments exactly
        once per stall episode regardless of how often either polls."""
        now = self._clock() if now is None else now
        declared = None
        with self._lock:
            deadline = self.stall_sec if self._beat_count \
                else self.stall_sec * self.grace_factor
            if not self._stalled and now - self._last_beat > deadline:
                self._stalled = True
                declared = now - self._last_beat
                obs_metrics.counter("watchdog_stall_total").inc()
                from firebird_tpu.obs import logger
                logger("change-detection").error(
                    "watchdog: no batch completed in %.1fs (deadline %.1fs%s)"
                    " — run stalled", declared, deadline,
                    "" if self._beat_count else ", bring-up grace")
            stalled = self._stalled
        if declared is not None:
            # Flight-recorder trigger OUTSIDE the lock: the postmortem
            # bundle reads this watchdog's own snapshot(), which takes
            # the lock again.  Dumps the rings while every wedged
            # thread's recent events are still in them (no-op disarmed).
            from firebird_tpu.obs import flightrec
            flightrec.on_stall(declared, deadline)
        return stalled

    @property
    def stalled(self) -> bool:
        return self.check()

    def snapshot(self) -> dict:
        """JSON-ready state for /progress and the report run block."""
        now = self._clock()
        with self._lock:
            return {
                "stalled": self._stalled,
                "stall_sec": self.stall_sec,
                "last_beat_age_sec": now - self._last_beat,
                "beats": self._beat_count,
                "in_throughput_drop": self._in_drop,
                "throughput_drops": list(self._drop_events),
            }

    # -- background polling ------------------------------------------------

    def start(self, interval: float | None = None) -> "Watchdog":
        """Poll :meth:`check` on a daemon thread (for unscraped runs)."""
        if self._thread is not None:
            return self
        interval = interval or max(min(self.stall_sec / 4.0, 5.0), 0.05)
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                self.check()

        self._thread = threading.Thread(
            target=loop, name="firebird-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
