"""Telemetry collector: per-process spools -> one fleet trace + attribution.

The spool (obs/spool.py) leaves each fleet process's telemetry on disk;
this module is the read side — ``firebird trace collect`` merges every
segment under the spool directory into:

- **One Perfetto trace.**  Process- and thread-aware Chrome-trace JSON
  (``{"traceEvents": [...]}``, validated by obs.report.validate_trace):
  each OS process renders as its own Perfetto process track (named
  ``<role> <pid>``), each of its threads as a thread track, and every
  span event carries its ``trace`` id in args — so one scene's causal
  chain (watcher -> queue -> worker -> alert append -> delivery) reads
  as one filterable id across the whole fleet, including segments a
  SIGKILLed worker left behind.
- **Per-alert critical-path breakdowns.**  For every trace id that
  reached a durable alert append, the scene's measured
  ``acquisition_to_alert_seconds`` decomposes into consecutive stages
  (watch lag, queue wait, fetch, step, append, other; delivery rides on
  top once a webhook carries it out) — computed from the cross-process
  marks the fleet stamps at each hop, summing to the measured total by
  construction (``other`` is the explicit residual, never silently
  absorbed).
- **A fleet metric view.**  The latest metric snapshot per process,
  merged under the obs_report fleet policy (counters sum, histogram
  buckets add and percentiles re-derive, gauges per
  merge_gauge_values) — what ``firebird top`` renders live.

Spool lines that a crash tore mid-write are skipped, not fatal: a
telemetry reader must never refuse the exact artifact a crash produced.
"""

from __future__ import annotations

import glob
import json
import os

from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import spool as spool_mod

COLLECT_SCHEMA = "firebird-telemetry-collect/1"

# The critical-path stage catalog (docs/OBSERVABILITY.md "Critical-path
# attribution"): consecutive wall-clock stages of one scene's
# publish -> durable-alert-append window, plus delivery past it.
CRITICAL_PATH_STAGES = ("watch_lag", "queue_wait", "fetch", "step",
                       "append", "other")


def read_events(directory: str) -> list[dict]:
    """Parse every spool segment under ``directory`` into a flat event
    list; each event gains ``role``/``pid`` (and ``run_id``) from its
    segment header.  Torn lines (a crash mid-write) are skipped."""
    events: list[dict] = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              spool_mod.SPOOL_GLOB))):
        role = pid = run_id = None
        try:
            with open(path) as f:
                for line in f:
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue            # torn tail line
                    if not isinstance(doc, dict):
                        continue
                    if doc.get("kind") == "header":
                        role = doc.get("role")
                        pid = doc.get("pid")
                        run_id = doc.get("run_id")
                        continue
                    doc["role"], doc["pid"] = role, pid
                    if run_id is not None:
                        doc.setdefault("run_id", run_id)
                    events.append(doc)
        except OSError:
            continue
    return events


def processes(events: list[dict]) -> list[dict]:
    """The distinct (role, pid) processes behind an event list."""
    seen: dict[tuple, dict] = {}
    for ev in events:
        key = (ev.get("role"), ev.get("pid"))
        if key[1] is None:
            continue
        p = seen.setdefault(key, {"role": key[0], "pid": key[1],
                                  "run_id": ev.get("run_id"), "events": 0})
        p["events"] += 1
    return [seen[k] for k in sorted(seen, key=str)]


def build_chrome_trace(events: list[dict]) -> dict:
    """Merge spool span/mark events into process/thread-aware
    Chrome-trace JSON (absolute wall-clock microseconds re-based to the
    earliest event, so cross-process ordering is faithful)."""
    spans = [e for e in events if e.get("kind") == "span"
             and e.get("pid") is not None]
    marks = [e for e in events if e.get("kind") == "mark"
             and e.get("pid") is not None]
    times = [e["t0"] for e in spans] + [e["t"] for e in marks]
    epoch = min(times) if times else 0.0
    out: list[dict] = []
    named_pids: set = set()
    tids: dict[tuple, int] = {}

    def tid_of(ev) -> int:
        pid = ev["pid"]
        if pid not in named_pids:
            named_pids.add(pid)
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "tid": 0,
                        "args": {"name": f"{ev.get('role')} {pid}"}})
        key = (pid, ev.get("tid"))
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = sum(1 for k in tids if k[0] == pid)
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid,
                        "args": {"name": ev.get("thread")
                                 or f"tid {ev.get('tid')}"}})
        return tid

    for ev in sorted(spans + marks,
                     key=lambda e: e.get("t0", e.get("t", 0.0))):
        args = {}
        if ev.get("trace"):
            args["trace"] = ev["trace"]
        args.update({k: (v if isinstance(v, (int, float, bool))
                         else str(v))
                     for k, v in (ev.get("attrs") or {}).items()})
        if ev["kind"] == "span":
            rec = {"name": ev["name"], "ph": "X", "pid": ev["pid"],
                   "tid": tid_of(ev), "ts": (ev["t0"] - epoch) * 1e6,
                   "dur": ev["dur"] * 1e6}
        else:
            rec = {"name": ev["name"], "ph": "i", "s": "p",
                   "pid": ev["pid"], "tid": tid_of(ev),
                   "ts": (ev["t"] - epoch) * 1e6}
        if args:
            rec["args"] = args
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"producer": "firebird_tpu.obs.collect",
                          "epoch_unix": epoch}}


def _first_mark(marks: list[dict], name: str) -> dict | None:
    cands = [m for m in marks if m["name"] == name]
    return min(cands, key=lambda m: m["t"]) if cands else None


def critical_paths(events: list[dict]) -> list[dict]:
    """Per-trace critical-path breakdowns for every trace id that
    reached a durable alert append.

    Stages are consecutive wall-clock intervals, so they sum to the
    appended-minus-published total EXACTLY (``other`` is the residual of
    the claim->append window not covered by fetch/step/append spans);
    ``measured_acq_to_alert`` is the very value the emitting process
    observed into ``acquisition_to_alert_seconds`` at the append,
    carried on the mark — the breakdown and the histogram cannot drift
    apart by more than the mark-to-observe clock skew."""
    by_trace: dict[str, dict] = {}
    for ev in events:
        tr = ev.get("trace")
        if not tr or ev.get("kind") not in ("span", "mark"):
            continue
        g = by_trace.setdefault(tr, {"spans": [], "marks": []})
        g["spans" if ev["kind"] == "span" else "marks"].append(ev)
    out = []
    for tr in sorted(by_trace):
        marks = by_trace[tr]["marks"]
        appended = _first_mark(marks, "alert_appended")
        if appended is None:
            continue
        attrs = appended.get("attrs") or {}
        enq = _first_mark(marks, "scene_enqueued")
        claimed = _first_mark(marks, "job_claimed")
        delivered = _first_mark(marks, "alert_delivered")
        published = attrs.get("published")
        if published is None and enq is not None:
            published = (enq.get("attrs") or {}).get("published")
        t_app = appended["t"]
        t_enq = enq["t"] if enq is not None else None
        t_clm = claimed["t"] if claimed is not None else None

        def span_sum(name: str) -> float:
            return sum(s["dur"] for s in by_trace[tr]["spans"]
                       if s["name"] == name and s["t0"] <= t_app)

        doc: dict = {"trace": tr, "alerts": attrs.get("alerts"),
                     "appended_at": t_app}
        stages: dict[str, float] = {}
        if published is not None and t_enq is not None \
                and t_clm is not None:
            stages["watch_lag"] = t_enq - published
            stages["queue_wait"] = t_clm - t_enq
            covered = 0.0
            for name, key in (("fetch", "fetch"), ("step", "step"),
                              ("alert", "append")):
                stages[key] = span_sum(name)
                covered += stages[key]
            stages["other"] = (t_app - t_clm) - covered
            doc["stages"] = {k: round(v, 6) for k, v in stages.items()}
            doc["total"] = round(t_app - published, 6)
            doc["published"] = published
        measured = attrs.get("acq_to_alert")
        if measured is not None:
            doc["measured_acq_to_alert"] = measured
        if delivered is not None and delivered["t"] >= t_app:
            doc["delivery"] = round(delivered["t"] - t_app, 6)
        doc["processes"] = sorted(
            {f"{e.get('role')}:{e.get('pid')}"
             for g in (by_trace[tr]["spans"], by_trace[tr]["marks"])
             for e in g if e.get("pid") is not None})
        out.append(doc)
    return out


def snap_events(directory: str) -> list[dict]:
    """Just the metric snapshots under ``directory`` (role/pid
    attributed, torn lines skipped) — the series store's ingest feed
    (obs/series.py): history wants every stamped snapshot, not only
    the newest per process like :func:`latest_snapshots`."""
    return [ev for ev in read_events(directory)
            if ev.get("kind") == "snap" and ev.get("pid") is not None]


def latest_snapshots(events: list[dict]) -> dict:
    """The newest metric snapshot per process:
    ``{"<role>:<pid>": {"t": ..., "metrics": {...}}}``."""
    out: dict[str, dict] = {}
    for ev in events:
        if ev.get("kind") != "snap" or ev.get("pid") is None:
            continue
        key = f"{ev.get('role')}:{ev.get('pid')}"
        if key not in out or ev["t"] > out[key]["t"]:
            out[key] = {"t": ev["t"], "metrics": ev.get("metrics") or {}}
    return out


def merge_snapshots(snaps: dict) -> dict:
    """Fold per-process snapshots into one fleet view under the
    obs_report merge policy: counters sum, histogram buckets add (and
    percentiles re-derive), gauges combine per their declared policy."""
    shards = [s["metrics"] for s in snaps.values()]
    counters: dict[str, float] = {}
    gauges: dict[str, list] = {}
    hists: dict[str, list] = {}
    for m in shards:
        for n, v in (m.get("counters") or {}).items():
            counters[n] = counters.get(n, 0) + v
        for n, v in (m.get("gauges") or {}).items():
            gauges.setdefault(n, []).append(v)
        for n, h in (m.get("histograms") or {}).items():
            hists.setdefault(n, []).append(h)
    return {
        "counters": dict(sorted(counters.items())),
        "gauges": {n: obs_metrics.merge_gauge_values(n, vs)
                   for n, vs in sorted(gauges.items())},
        "histograms": {n: obs_metrics.merge_histogram_snapshots(hs)
                       for n, hs in sorted(hists.items())},
    }


def collect(directory: str) -> dict:
    """The full collected artifact for a spool directory."""
    events = read_events(directory)
    snaps = latest_snapshots(events)
    return {
        "schema": COLLECT_SCHEMA,
        "spool_dir": directory,
        "processes": processes(events),
        "trace": build_chrome_trace(events),
        "critical_paths": critical_paths(events),
        "metrics": merge_snapshots(snaps),
        "snapshots": snaps,
    }


def write(doc: dict, path: str) -> str:
    """Write a collected artifact (atomic tmp+rename)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    os.replace(tmp, path)
    return path
