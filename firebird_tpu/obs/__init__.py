"""Observability: logging, span tracing, metrics, and per-run reports.

The reference logs exclusively through JVM log4j over the py4j bridge
(ccdc/__init__.py:60-76 "the jvm is what is actually doing all the logging"),
with per-subsystem categories configured in resources/log4j.properties:48-53
(`ids`, `change-detection`, `random-forest-training`,
`random-forest-classification`, `timeseries`, `pyccd`), and publishes no
metrics at all (SURVEY.md §5).

Here there is no JVM: plain Python logging with the same category names and
an ISO8601 stderr format mirroring log4j.properties:20-24, plus the
telemetry layer the reference lacks:

- :mod:`firebird_tpu.obs.tracing` — a low-overhead span tracer
  (``span("fetch", chip=cid)``) exporting Chrome-trace/Perfetto JSON, so a
  tile run's fetch/pack/dispatch/drain overlap is visually inspectable
  alongside the ``profile_dir`` XLA trace.
- :mod:`firebird_tpu.obs.metrics` — counters, gauges, and fixed-bucket
  latency histograms (p50/p95/p99) with Prometheus text exposition and a
  JSON snapshot.
- :mod:`firebird_tpu.obs.report` — the per-run ``obs_report.json`` artifact
  (metrics snapshot + span summary) the driver and tools emit, with
  per-host shards + a merged fleet report under multi-host SPMD.
- :mod:`firebird_tpu.obs.server` — the embedded HTTP ops endpoint
  (``/healthz /readyz /metrics /progress /report``), off by default.
- :mod:`firebird_tpu.obs.watchdog` — stall detection over driver batch
  beats; flips ``/healthz`` to 503 and counts ``watchdog_stall_total``.
- :mod:`firebird_tpu.obs.jsonlog` — run-correlated structured JSON log
  lines (``FIREBIRD_LOG_FORMAT=json``) carrying run_id/host/process_id.

Env vars: FIREBIRD_LOG_LEVEL / FIREBIRD_LOG_LEVELS (logging),
FIREBIRD_LOG_FORMAT (json opts into structured lines), FIREBIRD_TRACE
(span tracer output), FIREBIRD_METRICS (0 disables metric recording),
FIREBIRD_OBS_REPORT (report path override; 0 disables), FIREBIRD_OPS_PORT
(ops endpoint; unset = no port bound), FIREBIRD_STALL_SEC (watchdog
deadline; unset = off).  See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import logging
import sys
import threading

from firebird_tpu.obs import jsonlog
from firebird_tpu.obs.metrics import (Counters, Gauge, Histogram,
                                      MetricsRegistry, counter, gauge,
                                      get_registry, histogram,
                                      metrics_enabled, timer)
from firebird_tpu.obs.report import (build_report, validate_driver_artifacts,
                                     validate_report, validate_trace,
                                     write_report)
from firebird_tpu.obs.tracing import Tracer, span

# Per-subsystem categories, mirroring resources/log4j.properties:48-53
# (plus the streaming driver's own category, no reference analogue).
CATEGORIES = (
    "ids",
    "change-detection",
    "random-forest-training",
    "random-forest-classification",
    "timeseries",
    "pyccd",
)

_configured = False  # guarded-by: _lock
_lock = threading.Lock()


def configure(level: int | None = None) -> None:
    """Install the ISO8601 stderr handler once (idempotent).

    Levels mirror the reference's per-subsystem log4j categories
    (log4j.properties:48-53): FIREBIRD_LOG_LEVEL sets the root, and
    FIREBIRD_LOG_LEVELS="pyccd=DEBUG,timeseries=WARNING" overrides
    individual categories.
    """
    import os

    global _configured
    with _lock:
        if _configured:
            return
        root = logging.getLogger("firebird")
        if not root.handlers:      # never stack duplicate handlers
            root.addHandler(logging.StreamHandler(sys.stderr))
        # (Re)apply the format choice on every configure pass so flipping
        # FIREBIRD_LOG_FORMAT between runs (tests reset _configured) takes
        # effect on the existing handler rather than requiring a fresh
        # process.  json: one object per line with run_id/host/process_id
        # (obs/jsonlog.py); default: the log4j-parity ISO8601 line.
        if jsonlog.wants_json():
            fmt: logging.Formatter = jsonlog.JsonFormatter()
        else:
            fmt = logging.Formatter(
                fmt="%(asctime)s %(levelname)s %(name)s: %(message)s",
                datefmt="%Y-%m-%dT%H:%M:%S")
        for handler in root.handlers:
            handler.setFormatter(fmt)
        if level is None:
            level = _parse_level(os.environ.get("FIREBIRD_LOG_LEVEL", "INFO"),
                                 logging.INFO)
        root.setLevel(level)
        root.propagate = False
        for spec in os.environ.get("FIREBIRD_LOG_LEVELS", "").split(","):
            if "=" in spec:
                name, _, lv = spec.partition("=")
                logging.getLogger(f"firebird.{name.strip()}").setLevel(
                    _parse_level(lv, logging.INFO))
        _configured = True


def _level_names() -> dict[str, int]:
    """Level-name map; logging.getLevelNamesMapping is 3.11+, so older
    interpreters fall back to the stdlib's stable name set."""
    get_map = getattr(logging, "getLevelNamesMapping", None)
    if get_map is not None:
        return dict(get_map())
    return {"CRITICAL": logging.CRITICAL, "FATAL": logging.FATAL,
            "ERROR": logging.ERROR, "WARN": logging.WARNING,
            "WARNING": logging.WARNING, "INFO": logging.INFO,
            "DEBUG": logging.DEBUG, "NOTSET": logging.NOTSET}


def _parse_level(name: str, default: int) -> int:
    """Level name -> int; log4j's TRACE maps to DEBUG; unknown names fall
    back to the default with a stderr warning instead of silently lying
    about (or crashing on) the requested level."""
    n = name.strip().upper()
    levels = _level_names()
    levels["TRACE"] = logging.DEBUG
    if n in levels:
        return levels[n]
    print(f"firebird: unknown log level {name!r}, using "
          f"{logging.getLevelName(default)}", file=sys.stderr)
    return default


def logger(name: str) -> logging.Logger:
    """Get a per-subsystem logger (replaces ccdc.logger(ctx, name))."""
    configure()
    return logging.getLogger(f"firebird.{name}")


__all__ = [
    "CATEGORIES", "configure", "logger", "jsonlog",
    "Counters", "Gauge", "Histogram", "MetricsRegistry", "timer",
    "counter", "gauge", "histogram", "get_registry", "metrics_enabled",
    "Tracer", "span",
    "build_report", "write_report", "validate_report", "validate_trace",
    "validate_driver_artifacts",
]
