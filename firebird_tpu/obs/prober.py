"""Black-box canary prober: the SLO plane's outside view.

Every number the obs stack had before this module is white-box
self-report — the process being judged emits the histogram that judges
it, so a wedged serve replica or a dead watcher simply stops reporting
and the SLOs go quiet instead of red.  The prober closes that gap: a
standalone process (``firebird probe``) that continuously exercises the
REAL surfaces from outside and emits ``probe_*`` latency/success
metrics into its own telemetry spool (role ``prober``), where the
series store (obs/series.py) and the error budgets (obs/slo.py) read
them like any other host's — outage detection no longer depends on the
sick process reporting itself.

Surfaces (each armed only when its target is configured):

- **serve** — GET ``/v1/pixel`` and ``/v1/pyramid/<name>/z/x/y`` with
  ETag revalidation (If-None-Match from the previous answer; a 304
  counts as ``probe_etag_304``).  Success is "the service answered
  under 500"; transport errors, timeouts, and 5xx are failures —
  exactly what an outside client experiences during a brownout.
- **alert** — a synthetic scene dropped into the FileSource landing
  zone, bbox'd to a dedicated probe chip, must come back as an alert
  on the ``/v1/alerts/stream`` SSE feed: the full watcher -> fleet
  queue -> worker -> alert log -> SSE path, timed from the manifest
  append.  CCD confirms a break only after ``SCENES_TO_CONFIRM``
  consecutive exceeding acquisitions, so the prober runs a conveyor of
  staggered probe chips — one scene per chip per cycle — and one chip
  confirms (one end-to-end sample) per cycle once the pipeline fills.
  Probe chips come from a reserved block of the watched tile's chip
  list (``chip_offset``/``chips``) and are single-use: a confirmed
  break cannot re-break without a full re-establishment series, so the
  conveyor stops attempting when the reserve is spent (reported in
  :meth:`status`, counted neither attempt nor failure).
- **webhook** — the prober hosts a local sink, registers it via POST
  ``/v1/alerts/webhooks``, and times the same probe alert's arrival
  through the serve process's background deliverer.

No-data honesty: an unresolved probe is neither attempt nor failure
until it resolves (SSE event seen, or the per-probe timeout passes) —
the budget math's no-data-is-zero-burn rule starts here.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import spool as obs_spool
from firebird_tpu.obs import tracing

PROBE_ROLE = "prober"

# CCD's peek window: consecutive exceeding acquisitions before a break
# confirms — the conveyor depth (one scene per chip per cycle).
SCENES_TO_CONFIRM = 6

BOOT_START = "1995-01-01"
BOOT_END = "1999-01-01"
CADENCE_DAYS = 16
PROBE_STEP = 900.0            # spectral step: well past any CCD band RMSE


def _http_get(url: str, timeout: float, headers: dict | None = None):
    """(status, headers, body, seconds); transport trouble raises."""
    req = urllib.request.Request(url, headers=headers or {})
    t0 = time.time()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read(), time.time() - t0
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers), body, time.time() - t0


class _WebhookSink:
    """A local sink recording each probe chip's first webhook receipt
    time — the far end of the append -> deliver round trip."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.received: dict = {}      # (cx, cy) -> wall-clock receipt
        self._lock = threading.Lock()
        sink = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length") or 0))
                now = time.time()
                try:
                    recs = json.loads(body).get("alerts", ())
                except ValueError:
                    recs = ()
                with sink._lock:
                    for r in recs:
                        key = (int(r.get("cx", 0)), int(r.get("cy", 0)))
                        sink.received.setdefault(key, now)
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        self.port = self._srv.server_address[1]

    def first_receipt(self, cid, after: float) -> float | None:
        with self._lock:
            t = self.received.get(tuple(cid))
        return t if t is not None and t >= after else None

    def close(self) -> None:
        self._srv.shutdown()


class _SSEWatcher(threading.Thread):
    """A persistent ``/v1/alerts/stream`` session recording each probe
    chip's first SSE sighting; reconnects from its cursor when the
    server closes the window or dies (the SSE contract)."""

    def __init__(self, serve_url: str, timeout: float):
        super().__init__(name="firebird-probe-sse", daemon=True)
        self.serve_url = serve_url.rstrip("/")
        self.timeout = timeout
        self.seen: dict = {}          # (cx, cy) -> wall-clock receipt
        self.cursor: int | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def first_seen(self, cid, after: float) -> float | None:
        with self._lock:
            t = self.seen.get(tuple(cid))
        return t if t is not None and t >= after else None

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            url = f"{self.serve_url}/v1/alerts/stream"
            if self.cursor is not None:
                url += f"?since={self.cursor}"
            try:
                req = urllib.request.Request(url)
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    self._consume(r)
            except OSError:
                pass
            self._stop.wait(0.5)

    def _consume(self, resp) -> None:
        event: dict = {}
        for raw in resp:
            if self._stop.is_set():
                return
            line = raw.decode("utf-8", "replace").rstrip("\n").rstrip("\r")
            if not line:                      # dispatch on blank line
                data = event.pop("data", None)
                if data is not None and event.get("event") == "alert":
                    try:
                        rec = json.loads(data)
                    except ValueError:
                        rec = None
                    if rec:
                        now = time.time()
                        with self._lock:
                            self.seen.setdefault(
                                (int(rec.get("cx", 0)),
                                 int(rec.get("cy", 0))), now)
                if "id" in event:
                    try:
                        self.cursor = int(event["id"])
                    except ValueError:
                        pass
                event = {}
                continue
            field, _, value = line.partition(":")
            if field in ("event", "data", "id"):
                event[field] = value.lstrip(" ")


class _AlertConveyor:
    """The staggered probe-chip pipeline: each cycle every in-flight
    chip gains one scene (archive extended, then the scene appended to
    the manifest bbox'd to the chip alone — production chips never see
    probe scenes), and the chip whose scene was its
    ``SCENES_TO_CONFIRM``-th exceeding one becomes this cycle's
    end-to-end alert attempt."""

    def __init__(self, landing: str, x: float, y: float, *,
                 chip_offset: int, chips: int):
        import numpy as np

        from firebird_tpu import grid
        from firebird_tpu.ccd import synthetic
        from firebird_tpu.utils import dates as dt
        from firebird_tpu.utils.fn import take

        self._np = np
        self._synthetic = synthetic
        self._dt = dt
        self.landing = landing
        tile = grid.tile(x=x, y=y)
        cids = [tuple(int(v) for v in c)
                for c in take(chip_offset + chips, grid.chips(tile))]
        self.reserve = cids[chip_offset:]
        self.span = (grid.CONUS.chip.sx, grid.CONUS.chip.sy)
        self.boot_t = synthetic.acquisition_dates(
            BOOT_START, BOOT_END, CADENCE_DAYS)
        self.scene_t = [int(self.boot_t[-1]) + CADENCE_DAYS * (k + 1)
                        for k in range(SCENES_TO_CONFIRM)]
        self._next = 0
        self.in_flight: list = []     # [{"cid", "stage"}]

    def exhausted(self) -> bool:
        return self._next >= len(self.reserve) and not self.in_flight

    def _series(self, cid, upto_ord: int):
        """The chip's clean harmonic archive up to ``upto_ord``, every
        post-boot scene carrying the spectral step (deterministic per
        chip — rebuilt each land, never cached)."""
        np, synthetic = self._np, self._synthetic
        full_t = np.concatenate(
            [self.boot_t, np.asarray(self.scene_t, self.boot_t.dtype)])
        rng = np.random.default_rng(hash(cid) & 0xFFFF)
        base = synthetic.harmonic_series(full_t, rng)
        base = base + np.where(full_t >= self.scene_t[0],
                               PROBE_STEP, 0.0)[None, :]
        m = full_t <= upto_ord
        return full_t[m], np.clip(base[:, m], -32768, 32767).astype(
            np.int16)

    def _land(self, cid, upto_ord: int) -> None:
        import numpy as np

        from firebird_tpu.ingest.packer import CHIP_SIDE, ChipData
        from firebird_tpu.ingest.sources import FileSource

        t, series = self._series(cid, upto_ord)
        spectra = np.ascontiguousarray(np.broadcast_to(
            series[:, :, None, None],
            (series.shape[0], series.shape[1], CHIP_SIDE, CHIP_SIDE)))
        qas = np.full((t.shape[0], CHIP_SIDE, CHIP_SIDE),
                      self._synthetic.QA_CLEAR, np.uint16)
        FileSource(self.landing).save_chip(ChipData(
            cx=cid[0], cy=cid[1], dates=t, spectra=spectra, qas=qas))

    def _bbox(self, cid):
        """A box strictly inside the chip's 3 km cell, so the watcher
        maps the probe scene to this chip and no other."""
        sx, sy = self.span
        cx, cy = cid
        return (cx + 0.25 * sx, cy - 0.75 * sy,
                cx + 0.75 * sx, cy - 0.25 * sy)

    def tick(self) -> list:
        """Advance every in-flight chip one scene; returns the
        confirming appends as ``[{"cid", "scene_id", "t_appended"}]``."""
        from firebird_tpu.ingest.sources import FileSource

        if self._next < len(self.reserve) \
                and len(self.in_flight) < SCENES_TO_CONFIRM:
            self.in_flight.append(
                {"cid": self.reserve[self._next], "stage": 0})
            self._next += 1
        fs = FileSource(self.landing)
        confirmed = []
        for chip in list(self.in_flight):
            stage = chip["stage"]          # scenes appended so far
            cid = chip["cid"]
            date_ord = self.scene_t[stage]
            self._land(cid, date_ord)
            iso = self._dt.to_iso(date_ord)
            sid = f"PROBE_{cid[0]}_{cid[1]}_{stage}"
            fs.append_scene(sid, date=iso, bbox=self._bbox(cid))
            chip["stage"] = stage + 1
            if chip["stage"] >= SCENES_TO_CONFIRM:
                self.in_flight.remove(chip)
                confirmed.append({"cid": cid, "scene_id": sid,
                                  "t_appended": time.time()})
        return confirmed


class CanaryProber:
    """The standing canary: one :meth:`cycle` per ``interval``, every
    surface probed from outside, ``probe_*`` metrics into this
    process's own spool."""

    def __init__(self, cfg, *, serve_url: str | None = None,
                 landing: str | None = None, x: float | None = None,
                 y: float | None = None, chip_offset: int = 8,
                 chips: int = 24, pixel_date: str = "2010-01-01",
                 pyramid_product: str = "ccd",
                 interval: float | None = None,
                 timeout: float | None = None):
        if cfg.probe_sec <= 0 and interval is None:
            raise ValueError(
                "FIREBIRD_PROBE_SEC=0 — the prober refuses to arm "
                "(the zero-cost path)")
        if serve_url is None and landing is None:
            raise ValueError(
                "prober needs at least one surface: a serve URL "
                "and/or a FileSource landing zone")
        if landing is not None and (x is None or y is None):
            raise ValueError(
                "the alert probe needs the watched tile's -x/-y")
        self.cfg = cfg
        self.serve_url = serve_url.rstrip("/") if serve_url else None
        self.interval = float(interval if interval is not None
                              else cfg.probe_sec)
        self.timeout = float(timeout if timeout is not None
                             else cfg.probe_timeout)
        self.pixel = (x, y, pixel_date)
        self.pyramid_product = pyramid_product
        self._etags: dict = {}
        self.conveyor = _AlertConveyor(
            landing, x, y, chip_offset=chip_offset, chips=chips) \
            if landing is not None else None
        self.sse: _SSEWatcher | None = None
        self.sink: _WebhookSink | None = None
        self.pending: list = []       # unresolved alert/webhook probes
        self.cycles = 0
        self._webhook_registered = False

    # -- bookkeeping -------------------------------------------------------

    @staticmethod
    def _attempt(surface: str, ok: bool) -> None:
        obs_metrics.counter(
            "probe_attempts",
            help="black-box probes resolved (all surfaces)").inc()
        obs_metrics.counter(
            f"probe_attempts_{surface}",
            help="black-box probes resolved, by surface").inc()
        if not ok:
            obs_metrics.counter(
                "probe_failures",
                help="black-box probes failed (timeout, transport "
                     "error, or 5xx)").inc()
            obs_metrics.counter(
                f"probe_failures_{surface}",
                help="black-box probe failures, by surface").inc()

    # -- serve surface -----------------------------------------------------

    def _probe_url(self, url: str) -> None:
        headers = {}
        etag = self._etags.get(url)
        if etag:
            headers["If-None-Match"] = etag
        try:
            status, hdrs, _, dt_s = _http_get(url, self.timeout, headers)
        except OSError:
            self._attempt("serve", False)
            return
        if status == 304:
            obs_metrics.counter(
                "probe_etag_304",
                help="probe conditional GETs answered 304 (ETag "
                     "revalidation worked end to end)").inc()
        elif status == 200 and hdrs.get("ETag"):
            self._etags[url] = hdrs["ETag"]
        ok = status < 500
        if ok:
            obs_metrics.histogram(
                "probe_serve_seconds",
                help="black-box serve GET seconds (the outside view "
                     "of /v1 latency)").observe(dt_s)
        self._attempt("serve", ok)

    def probe_serve(self) -> None:
        x, y, date = self.pixel
        if x is not None:
            self._probe_url(f"{self.serve_url}/v1/pixel?x={x}&y={y}"
                            f"&date={date}")
        self._probe_url(f"{self.serve_url}/v1/pyramid/"
                        f"{self.pyramid_product}/0/0/0?date={date}")

    # -- alert + webhook surfaces ------------------------------------------

    def _resolve_pending(self) -> None:
        now = time.time()
        for p in list(self.pending):
            t_seen = None
            if p["kind"] == "alert" and self.sse is not None:
                t_seen = self.sse.first_seen(p["cid"], p["t_appended"])
            elif p["kind"] == "webhook" and self.sink is not None:
                t_seen = self.sink.first_receipt(p["cid"],
                                                 p["t_appended"])
            if t_seen is not None:
                obs_metrics.histogram(
                    f"probe_{p['kind']}_seconds",
                    help="black-box scene drop -> alert visibility "
                         "seconds, by egress surface").observe(
                    t_seen - p["t_appended"])
                self._attempt(p["kind"], True)
                self.pending.remove(p)
            elif now - p["t_appended"] > p["deadline"]:
                self._attempt(p["kind"], False)
                self.pending.remove(p)

    def probe_alerts(self) -> None:
        for c in self.conveyor.tick():
            # The end-to-end deadline is the full pipeline's, not one
            # request's: scene -> watcher poll -> bootstrap + stream
            # jobs -> alert append -> SSE/webhook egress.
            deadline = max(self.timeout,
                           4 * self.interval + self.timeout)
            self.pending.append({"kind": "alert", "cid": c["cid"],
                                 "t_appended": c["t_appended"],
                                 "deadline": deadline})
            if self.sink is not None:
                self.pending.append({"kind": "webhook", "cid": c["cid"],
                                     "t_appended": c["t_appended"],
                                     "deadline": deadline})

    # -- lifecycle ---------------------------------------------------------

    def _register_webhook(self) -> None:
        """POST the sink to ``/v1/alerts/webhooks`` with ``since`` at
        the log's current latest — a canary wants new alerts, not a
        backlog replay.  Retried from :meth:`cycle` until it lands, so
        a serve restart between arm and first probe self-heals."""
        try:
            _, _, body, _ = _http_get(
                f"{self.serve_url}/v1/alerts?limit=1", self.timeout)
            latest = int(json.loads(body).get("latest", 0))
            req = urllib.request.Request(
                f"{self.serve_url}/v1/alerts/webhooks"
                f"?url=http://127.0.0.1:{self.sink.port}/probe"
                f"&since={latest}", data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                self._webhook_registered = r.status == 200
        except (OSError, ValueError):
            pass

    def arm(self) -> "CanaryProber":
        obs_spool.arm(self.cfg, PROBE_ROLE)
        if self.serve_url is not None:
            self.sse = _SSEWatcher(self.serve_url, self.timeout)
            self.sse.start()
            if self.conveyor is not None:
                self.sink = _WebhookSink()
                self._register_webhook()
        return self

    def cycle(self) -> None:
        self.cycles += 1
        with tracing.span("probe_cycle", cycle=self.cycles):
            if self.sink is not None and not self._webhook_registered:
                self._register_webhook()
            if self.serve_url is not None:
                self.probe_serve()
            if self.conveyor is not None and not self.conveyor.exhausted():
                self.probe_alerts()
            self._resolve_pending()
        sp = obs_spool.active()
        if sp is not None:
            sp.snapshot()

    def status(self) -> dict:
        return {"cycles": self.cycles, "interval_sec": self.interval,
                "timeout_sec": self.timeout,
                "serve_url": self.serve_url,
                "pending": len(self.pending),
                "alert_reserve_exhausted":
                    (self.conveyor.exhausted()
                     if self.conveyor is not None else None)}

    def run(self, stop: threading.Event | None = None,
            cycles: int | None = None) -> None:
        stop = stop or threading.Event()
        while not stop.is_set():
            t0 = time.time()
            self.cycle()
            if cycles is not None and self.cycles >= cycles:
                return
            stop.wait(max(self.interval - (time.time() - t0), 0.05))

    def close(self) -> None:
        if self.sse is not None:
            self.sse.stop()
        if self.sink is not None:
            self.sink.close()
        obs_spool.disarm()
