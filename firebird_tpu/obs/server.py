"""Embedded HTTP ops endpoint: the live ops surface for in-flight runs.

Everything PR 1's telemetry produced was post-hoc — obs_report.json and
the Chrome trace land at run *end*, useless for a multi-hour tile run you
need to watch (or for a supervisor that must decide whether to restart a
wedged SPMD process; there is no Spark UI here to fall back on).  This
module embeds a stdlib ``http.server`` on a daemon thread — off by
default, enabled with ``FIREBIRD_OPS_PORT`` / ``--ops-port`` — serving:

``/healthz``
    Liveness.  200 ``ok`` while the run progresses; 200 ``degraded``
    when it is alive but routing around failures (chips in quarantine,
    ingest breaker not closed — docs/ROBUSTNESS.md); 503 once the stall
    watchdog (obs/watchdog.py) sees no batch complete within its
    deadline.  The handler evaluates the deadline live, so no background
    thread is needed when something scrapes.
``/readyz``
    Readiness: the device mesh is up AND the first batch has been
    dispatched — i.e. compile + bring-up are behind us and the run is in
    its steady state.  503 before that.
``/metrics``
    The process metrics registry in Prometheus text exposition 0.0.4
    (``MetricsRegistry.prometheus()``) — point a scraper at it.
``/progress``
    JSON: run_id, chips done/total, batches dispatched/drained, current
    stage, the run counters with ``*_per_sec`` rates, and the watchdog
    state.
``/report``
    The live ``build_report`` dict — the same document obs_report.json
    will contain, available at any moment mid-run.

The drivers register a :class:`RunStatus` (run identity, totals, the
shared ``Counters``, the watchdog) in a process-global slot; the
module-level hooks (:func:`set_stage`, :func:`batch_dispatched`,
:func:`batch_done`) are no-ops when no run is registered, so
instrumentation call sites cost one global read when the surface is off —
the same discipline as obs/tracing.py.
"""

from __future__ import annotations

import threading

from firebird_tpu.obs import httpd


class RunStatus:
    """Shared mutable view of one driver run, read by the HTTP handlers.

    ``counters`` is the driver's live ``obs.Counters`` (chips/pixels/
    segments accumulate as batches drain); ``watchdog`` is optional;
    ``run`` is the report run block (kind, tile, run_id, ...).
    """

    def __init__(self, run_id: str, kind: str, *, chips_total: int = 0,
                 counters=None, watchdog=None, run: dict | None = None,
                 mesh_up: bool = True, pipeline_depth: int = 2,
                 quarantine=None, breaker=None, profiler=None,
                 slo_spec: str | None = None, fleet=None, alerts=None,
                 streamops=None):
        self.run_id = run_id
        self.kind = kind
        self.chips_total = int(chips_total)
        self.counters = counters
        self.watchdog = watchdog
        # Degradation sources: the dead-letter quarantine
        # (driver.quarantine.Quarantine) and the ingest circuit breaker
        # (retry.CircuitBreaker) — both optional, both only *read* here.
        self.quarantine = quarantine
        self.breaker = breaker
        # Deep-dive hooks: the run's device profiler (POST /profile,
        # obs/profiling.py) and its SLO spec (/slo, obs/slo.py).
        self.profiler = profiler
        self.slo_spec = slo_spec
        # Fleet view provider (fleet workers pass FleetWorker.fleet_block):
        # a zero-arg callable returning the queue/worker snapshot dict
        # rendered as /progress's "fleet" block; None for non-fleet runs.
        self.fleet = fleet
        # Alerts view provider (the stream driver passes a zero-arg
        # callable over its AlertLog.status): /progress's "alerts"
        # block; None for runs without an alert log.
        self.alerts = alerts
        # Streamops view provider (the stream driver passes its
        # checkpoint store's status; `firebird watch` passes the
        # watcher's): /progress's "streamops" block; None elsewhere.
        self.streamops = streamops
        self.run = dict(run or {})
        self.pipeline_depth = max(int(pipeline_depth), 1)
        self._lock = threading.Lock()
        self._stage = "init"  # guarded-by: _lock
        self._mesh_up = bool(mesh_up)  # guarded-by: _lock
        self._first_batch = False  # guarded-by: _lock
        self._batches_dispatched = 0  # guarded-by: _lock
        self._batches_done = 0  # guarded-by: _lock

    # -- driver-side updates ----------------------------------------------

    def set_stage(self, name: str) -> None:
        with self._lock:
            self._stage = name
        from firebird_tpu.obs import flightrec
        flightrec.mark("stage", stage=name)

    def mark_mesh_up(self) -> None:
        with self._lock:
            self._mesh_up = True

    def batch_dispatched(self) -> None:
        """First dispatch flips readiness: compile/bring-up are done."""
        with self._lock:
            self._first_batch = True
            self._batches_dispatched += 1
            n = self._batches_dispatched
            self._record_inflight()
        from firebird_tpu.obs import flightrec
        flightrec.mark("batch_dispatched", n=n)
        # FIREBIRD_PROFILE's auto window starts HERE: the first dispatch
        # means steady-state kernels, not bring-up compile.
        if self.profiler is not None:
            self.profiler.maybe_start_auto()

    def batch_done(self, units: int = 1) -> None:
        """A batch finished draining — forward progress; beats the
        watchdog."""
        with self._lock:
            self._batches_done += 1
            n = self._batches_done
            self._record_inflight()
        from firebird_tpu.obs import flightrec
        flightrec.mark("batch_done", n=n, units=units)
        if self.watchdog is not None:
            self.watchdog.beat(units)

    def _record_inflight(self) -> None:  # guarded-by: _lock
        # Called under self._lock: compute-and-set must be atomic or a
        # dispatch/done race could strand the gauge at a stale value.
        from firebird_tpu.obs import metrics as obs_metrics

        n = self._batches_dispatched - self._batches_done
        obs_metrics.gauge(
            "pipeline_inflight",
            help="batches dispatched but not yet drained").set(max(n, 0))

    # -- endpoint reads ----------------------------------------------------

    def healthy(self) -> bool:
        return self.watchdog is None or not self.watchdog.check()

    def degraded(self) -> bool:
        """Alive but bleeding: chips in quarantine, or the ingest breaker
        not closed.  ``/healthz`` stays 200 (a supervisor must NOT
        restart a run that is making progress around failures) but the
        body says 'degraded' and ``/progress`` carries the detail."""
        if self.quarantine is not None and len(self.quarantine) > 0:
            return True
        if self.breaker is not None and self.breaker.state != 0:
            return True
        return False

    def degraded_block(self) -> dict:
        """The /progress 'degraded' sub-document (docs/ROBUSTNESS.md)."""
        from firebird_tpu.obs import metrics as obs_metrics

        # Recent rolling-window throughput-drop events (timestamp, the
        # window rate, the threshold it crossed): the slow-leak signal
        # was only COUNTED before — the events themselves belong in the
        # degraded view an operator actually reads.
        drops: list = []
        if self.watchdog is not None:
            drops = self.watchdog.snapshot().get("throughput_drops", [])
        return {
            "active": self.degraded(),
            "chips_quarantined": (len(self.quarantine)
                                  if self.quarantine is not None else 0),
            "breaker": (self.breaker.snapshot()
                        if self.breaker is not None else None),
            "faults_injected": obs_metrics.counter("faults_injected").value,
            "retries": obs_metrics.counter("fetch_retries").value
            + obs_metrics.counter("store_write_retries").value,
            "throughput_drops": drops,
        }

    @staticmethod
    def _kernel_block() -> dict:
        """Event-loop lane occupancy for /progress (kernel.record_occupancy
        feeds the counters as batches drain): active vs wasted lane-rounds
        and the compaction count — a wasted share near zero means the
        compacted loop pays only for working pixels under the skip-guard
        accounting (measured on Pallas-guarded kernels, modeled on the
        lax fallbacks — ChipSegments.occupancy)."""
        from firebird_tpu.obs import metrics as obs_metrics

        active = obs_metrics.counter("kernel_active_lane_rounds").value
        wasted = obs_metrics.counter("kernel_wasted_lane_rounds").value
        return {
            "active_lane_rounds": active,
            "wasted_lane_rounds": wasted,
            "wasted_share": round(wasted / max(active + wasted, 1), 4),
            "compactions": obs_metrics.counter(
                "kernel_compactions").value,
        }

    def ready(self) -> bool:
        with self._lock:
            return self._mesh_up and self._first_batch

    def progress(self) -> dict:
        with self._lock:
            stage = self._stage
            dispatched, done = self._batches_dispatched, self._batches_done
            mesh_up, first = self._mesh_up, self._first_batch
        counters = self.counters.snapshot() if self.counters is not None \
            else {}
        inflight = max(dispatched - done, 0)
        return {
            "run_id": self.run_id,
            "kind": self.kind,
            "stage": stage,
            "ready": mesh_up and first,
            "healthy": self.healthy(),
            "chips_done": int(counters.get("chips", 0)),
            "chips_total": self.chips_total,
            "batches_dispatched": dispatched,
            "batches_done": done,
            # Occupancy ~1 while dispatching: the device stays fed and the
            # drain bound (pipeline_depth) is the limiter; ~0 means the
            # host (fetch/pack/stage) is starving the device.
            "pipeline": {
                "depth": self.pipeline_depth,
                "in_flight": inflight,
                "occupancy": round(inflight / self.pipeline_depth, 3),
                "kernel": self._kernel_block(),
            },
            "counters": counters,
            "degraded": self.degraded_block(),
            "fleet": self._fleet_block(),
            "alerts": self._alerts_block(),
            "streamops": self._streamops_block(),
            "watchdog": (self.watchdog.snapshot()
                         if self.watchdog is not None else None),
        }

    def _alerts_block(self) -> dict | None:
        """The /progress 'alerts' sub-document: alert-log depth, latest
        cursor, per-subscriber delivery lag, plus this run's emission
        tallies (docs/ALERTS.md).  None for runs without an alert log; a
        snapshot failure degrades this block, never /progress itself."""
        if self.alerts is None:
            return None
        try:
            return self.alerts()
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _streamops_block(self) -> dict | None:
        """The /progress 'streamops' sub-document: the packed
        checkpoint store's activity (or the watcher's cursor view, for
        ``firebird watch``; docs/STREAMING.md).  None for runs without
        streamops; a snapshot failure degrades this block only."""
        if self.streamops is None:
            return None
        try:
            return self.streamops()
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _fleet_block(self) -> dict | None:
        """The /progress 'fleet' sub-document: queue depths by type and
        state, active leases with age/holder, dead-letter classes, and
        this worker's tallies (docs/ROBUSTNESS.md "Fleet scheduling").
        None for non-fleet runs; a snapshot failure must not take the
        whole progress endpoint down with it."""
        if self.fleet is None:
            return None
        try:
            return self.fleet()
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}


# Mutation under _status_lock; the per-batch hook reads (set_stage,
# current, ...) grab the one reference lock-free on purpose.
_status: RunStatus | None = None  # guarded-by: _status_lock
_status_lock = threading.Lock()


def set_status(status: RunStatus) -> RunStatus:
    global _status
    with _status_lock:
        _status = status
    return status


def clear_status() -> None:
    global _status
    with _status_lock:
        _status = None


def current() -> RunStatus | None:
    return _status


# Module-level hooks for instrumentation sites (driver/core.py,
# driver/stream.py): one global read + None check when no run registered.

def set_stage(name: str) -> None:
    st = _status
    if st is not None:
        st.set_stage(name)


def batch_dispatched() -> None:
    st = _status
    if st is not None:
        st.batch_dispatched()


def batch_done(units: int = 1) -> None:
    st = _status
    if st is not None:
        st.batch_done(units)


def mark_mesh_up() -> None:
    st = _status
    if st is not None:
        st.mark_mesh_up()


# One process-wide SeriesStore for the threaded handlers: per-request
# instances share this pid, so two concurrent /slo or /metrics/history
# requests would append to (or resume over) the same segment files from
# two uncoordinated writers — SeriesStore is thread-safe only within
# one instance.  Re-keyed when the ambient config changes (knob flips,
# tests); _budget_lock additionally serializes evaluate+record so two
# requests cannot race the durable event log's read-then-append.
_series_lock = threading.Lock()
_series_cache: dict = {"key": None, "store": None}
_budget_lock = threading.Lock()


def _shared_store(cfg):
    from firebird_tpu.obs import series as series_mod

    key = (series_mod.series_dir(cfg), getattr(cfg, "series", 0),
           getattr(cfg, "series_segments", 0), cfg.telemetry)
    with _series_lock:
        if _series_cache["key"] != key:
            if _series_cache["store"] is not None:
                _series_cache["store"].close()
            _series_cache["store"] = series_mod.open_store(cfg)
            _series_cache["key"] = key
        return _series_cache["store"]


def _budget_block() -> dict:
    """The /slo budgets block: ingest fresh spool snapshots into the
    series rings, then evaluate + durably record the error budgets for
    the ambient config.  Raises when the store cannot open — the /slo
    route degrades that to an error string."""
    from firebird_tpu.config import Config
    from firebird_tpu.obs import slo as slomod

    cfg = Config.from_env()
    store = _shared_store(cfg)
    if store is None:
        return {"disabled": True,
                "reason": "no series store (FIREBIRD_SERIES=0 / "
                          "FIREBIRD_TELEMETRY=0 / memory backend)"}
    with _budget_lock:
        store.ingest_spools()
        return slomod.evaluate_and_record(
            store.dir, cfg.slo_budget or None,
            fast_sec=cfg.slo_fast_sec, slow_sec=cfg.slo_slow_sec,
            burn_threshold=cfg.slo_burn)


class _OpsHandler(httpd.JsonHandler):
    server_version = "firebird-ops/1"

    def _route(self, path: str, query: dict) -> None:
        from firebird_tpu.obs import metrics as obs_metrics

        st = self.server.status if self.server.status is not None \
            else current()
        if path == "/healthz":
            if st is not None and not st.healthy():
                self._send(503, b"stalled\n", "text/plain")
            elif st is not None and st.degraded():
                # Degraded is a 200: the run is alive and routing around
                # failures (quarantined chips, open breaker) — restarting
                # it would lose the progress it is still making.
                self._send(200, b"degraded\n", "text/plain")
            else:
                self._send(200, b"ok\n", "text/plain")
        elif path == "/readyz":
            if st is not None and st.ready():
                self._send(200, b"ready\n", "text/plain")
            else:
                self._send(503, b"not ready\n", "text/plain")
        elif path == "/metrics":
            self._send(200, obs_metrics.get_registry().prometheus().encode(),
                       "text/plain; version=0.0.4")
        elif path == "/progress":
            if st is None:
                self._send_json(503, {"error": "no run registered"})
            else:
                self._send_json(200, st.progress())
        elif path == "/report":
            from firebird_tpu.obs import report as obs_report
            from firebird_tpu.obs import tracing
            self._send_json(200, obs_report.build_report(
                tracer=tracing.active(),
                run=st.run if st is not None else {},
                run_counters=(st.counters.snapshot()
                              if st is not None and st.counters is not None
                              else None)))
        elif path == "/slo":
            from firebird_tpu.obs import slo as slomod
            doc = slomod.evaluate_snapshot(
                obs_metrics.get_registry().snapshot(),
                watchdog=(st.watchdog.snapshot()
                          if st is not None and st.watchdog is not None
                          else None),
                spec=st.slo_spec if st is not None else None)
            # Durable error budgets ride along whenever a series store
            # exists for this config; a broken store degrades to an
            # error string, never a dead endpoint (the status-section
            # rule).  ?budgets=0 skips the disk walk.
            if (query.get("budgets") or ["1"])[0] not in ("0", "false"):
                try:
                    doc["budgets"] = _budget_block()
                except Exception as e:
                    doc["budgets"] = {"error": f"{type(e).__name__}: {e}"}
            self._send_json(200, doc)
        elif path == "/metrics/history":
            self._history(query)
        elif path == "/profile":
            # GET reports the windows captured so far (POST starts one).
            from firebird_tpu.obs import profiling
            prof = st.profiler if st is not None else None
            if prof is None:
                prof_active = profiling.active()
                if prof_active is None:
                    self._send_json(503, {"error": "no profiler for this "
                                                   "run (memory backend?)"})
                    return
                prof = prof_active
            self._send_json(200, prof.summary())
        else:
            self._send_json(404, {"error": f"unknown path {path!r}",
                                  "paths": ["/healthz", "/readyz", "/metrics",
                                            "/metrics/history", "/progress",
                                            "/report", "/slo", "/profile"]})

    def _history(self, query: dict) -> None:
        """``/metrics/history?res=&window=&metric=``: windowed points
        from the durable series rings (obs/series.py) — spools are
        re-ingested first, so the answer includes snapshots from
        processes that died since the last read."""
        import time as _time

        from firebird_tpu.config import Config
        from firebird_tpu.obs import series as series_mod

        try:
            res = int((query.get("res") or ["10"])[0])
            window = float((query.get("window") or ["600"])[0])
        except ValueError:
            self._send_json(400, {"error": "res/window must be numbers"})
            return
        metric = (query.get("metric") or [None])[0]
        store = _shared_store(Config.from_env())
        if store is None:
            self._send_json(503, {
                "error": "metric history disabled (FIREBIRD_SERIES=0 / "
                         "FIREBIRD_TELEMETRY=0) or homeless (memory "
                         "backend, no FIREBIRD_SERIES_DIR)"})
            return
        if res not in store.resolutions:
            self._send_json(400, {
                "error": f"unknown resolution {res}s",
                "resolutions": list(store.resolutions)})
            return
        store.ingest_spools()
        now = _time.time()
        pts = store.points(res, now - window, now)
        if metric:
            pts = [dict(p, m={k: {metric: (p.get("m") or {})[k][metric]}
                              if metric in ((p.get("m") or {}).get(k) or {})
                              else {}
                              for k in ("counters", "gauges",
                                        "histograms")})
                   for p in pts]
        self._send_json(200, {
            "schema": "firebird-metric-history/1", "res_sec": res,
            "window_sec": window, "t1": now, "metric": metric,
            "sources": series_mod.sources(pts), "points": pts})

    def _route_post(self, path: str, query: dict) -> None:
        from firebird_tpu.obs import profiling

        st = self.server.status if self.server.status is not None \
            else current()
        if path != "/profile":
            super()._route_post(path, query)
            return
        prof = st.profiler if st is not None else None
        if prof is None:
            prof = profiling.active()
        if prof is None:
            self._send_json(503, {"error": "no profiler for this run "
                                           "(memory backend?)"})
            return
        import math

        try:
            seconds = float((query.get("seconds") or ["3"])[0])
        except ValueError:
            self._send_json(400, {"error": "seconds must be a number"})
            return
        if not math.isfinite(seconds):
            # nan slips through min/max clamping (Event.wait(nan) raises
            # after a real trace started) and inf isn't a window.
            self._send_json(400, {"error": "seconds must be finite"})
            return
        try:
            info = prof.window(seconds)
        except profiling.ProfilerBusy as e:
            self._send_json(409, {"error": str(e)})
            return
        self._send_json(202, dict(info, started=True))


class OpsServer(httpd.Httpd):
    """The ops endpoint server (shared lifecycle: obs/httpd.py)."""

    thread_name = "firebird-ops"

    def __init__(self, addr, status: RunStatus | None = None):
        super().__init__(addr, _OpsHandler)
        self.status = status


def start_ops_server(port: int, status: RunStatus | None = None,
                     host: str | None = None) -> OpsServer:
    """Bind and start the ops endpoint.

    ``port`` 0 binds an OS-assigned ephemeral port (tests, obs-smoke);
    callers gating on config must only call this when the operator set
    ``FIREBIRD_OPS_PORT``/``--ops-port`` — the surface is off by default
    and no port is ever bound otherwise (driver/core.py guards on
    ``cfg.ops_port > 0``).  Bind host comes from ``Config.ops_host`` /
    FIREBIRD_OPS_HOST (default all interfaces — the endpoint exists to
    be scraped); cfg-carrying callers pass it explicitly.
    """
    if host is None:
        from firebird_tpu.config import env_knob

        host = env_knob("FIREBIRD_OPS_HOST")
    srv = OpsServer((host, int(port)), status=status).start()
    from firebird_tpu.obs import logger
    logger("change-detection").info(
        "ops endpoint up on %s:%d (/healthz /readyz /metrics /progress "
        "/report /slo; POST /profile)", host, srv.port)
    return srv
