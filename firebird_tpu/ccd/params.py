"""CCDC algorithm parameters.

One place for every constant of the change-detection spec.  Values are
pinned to the published CCDC algorithm (Zhu & Woodcock 2014, "Continuous
change detection and classification of land cover using all available
Landsat data", RSE 144) with the lcmap-pyccd 2018.03.12 parameterization the
reference pins (setup.py:32) where known.  The reference repo itself never
contains these numbers — they lived inside the external pyccd package — so
this module is the authoritative spec for both the NumPy oracle and the TPU
kernel.

Everything is expressed so both implementations can share it: plain floats /
ints / tuples, no callables.
"""

from __future__ import annotations

import numpy as np
from scipy import stats

# ---------------------------------------------------------------------------
# Bands.  Input order follows the reference timeseries contract
# (ccdc/timeseries.py:33-45): blues, greens, reds, nirs, swir1s, swir2s,
# thermals — indexes 0..6.
# ---------------------------------------------------------------------------
NUM_BANDS = 7
BAND_NAMES = ("blue", "green", "red", "nir", "swir1", "swir2", "thermal")
# Plural forms are the data-plane keyword names (ccdc/timeseries.py:33-45)
# and index the spectra axis everywhere.
BAND_NAMES_PLURAL = ("blues", "greens", "reds", "nirs", "swir1s", "swir2s",
                     "thermals")

# Bands used for change scoring (green, red, nir, swir1, swir2).
DETECTION_BANDS = (1, 2, 3, 4, 5)

# Bands used by the Tmask outlier screen (green, swir1).
TMASK_BANDS = (1, 4)

# Valid data ranges; observations outside are treated as unusable.
# Optical bands are scaled reflectance [0, 10000]; thermal is Kelvin*10.
OPTICAL_MIN, OPTICAL_MAX = 0, 10000
THERMAL_MIN, THERMAL_MAX = -9320, 7070
FILL_VALUE = -9999

# ---------------------------------------------------------------------------
# QA.  ARD pixel_qa is bit-packed (see the reference example series values
# 1 / 66 / 322, ccdc/timeseries.py:104-115: 1 = fill bit, 66/322 contain the
# clear bit).
# ---------------------------------------------------------------------------
QA_FILL_BIT = 0
QA_CLEAR_BIT = 1
QA_WATER_BIT = 2
QA_SHADOW_BIT = 3
QA_SNOW_BIT = 4
QA_CLOUD_BIT = 5

# Procedure selection thresholds (Zhu 2014 §3.2; pyccd procedures).
CLEAR_PCT_THRESHOLD = 0.25   # below: not enough clear obs for standard proc
SNOW_PCT_THRESHOLD = 0.75    # above (of snow/(snow+clear)): permanent snow

# ---------------------------------------------------------------------------
# Model structure.
# ---------------------------------------------------------------------------
# Harmonic design: [1, t, cos wt, sin wt, cos 2wt, sin 2wt, cos 3wt, sin 3wt]
# with w = 2*pi / 365.25 and t in ordinal days.
OMEGA = 2.0 * np.pi / 365.25
MAX_COEFS = 8
MIN_COEFS = 4
MID_COEFS = 6

# Coefficient count by observation density: >= 24 obs -> 8 coefs,
# >= 18 -> 6, else 4 (pyccd num-obs factor 3).
NUM_OBS_FACTOR = 3  # num_coefs*3 observations required per tier

# Minimum observations and time span to initialize a model (Zhu 2014 §3.1).
MEOW_SIZE = 12            # minimum observations in an initialization window
INIT_DAYS = 365.25        # minimum time span of the initialization window

# Stability: initial model is unstable if |slope * span| or the first/last
# absolute residual exceeds STABILITY_FACTOR * adjusted-RMSE (Zhu 2014 §3.1).
STABILITY_FACTOR = 3.0

# Number of consecutive exceeding observations that confirm a change.
PEEK_SIZE = 6

# Change score threshold: chi2 inverse CDF at 0.99 with one degree of
# freedom per detection band.
CHISQUARE_PROB = 0.99
CHANGE_THRESHOLD = float(stats.chi2.ppf(CHISQUARE_PROB, len(DETECTION_BANDS)))

# Single-observation outlier threshold (obs removed, not a change):
# the far chi2 tail, as pyccd's T_MAX_CG.
OUTLIER_PROB = 1 - 1e-6
OUTLIER_THRESHOLD = float(stats.chi2.ppf(OUTLIER_PROB, len(DETECTION_BANDS)))

# Refit schedule: refit the running model when the segment has grown to
# REFIT_FACTOR x the observation count at the previous fit (Zhu 2014 §3.3.1).
REFIT_FACTOR = 1.33

# ---------------------------------------------------------------------------
# Fitting.
# ---------------------------------------------------------------------------
# Lasso regularization (sklearn-style objective 1/(2n)||y-Xb||^2 + alpha|b|_1,
# intercept unpenalized).  Solved by cyclic coordinate descent with a fixed
# iteration count so the TPU kernel jits to a static loop.
LASSO_ALPHA = 1.0
LASSO_ITERS = 50

# Mixed-precision coef/rmse drift budget (FIREBIRD_MIXED_PRECISION):
# max scale-anchored ulp distance |mixed - f32| / (eps32 * scale)
# enforced by tools/precision_smoke.py and tests/test_precision.py,
# where ``scale`` anchors at the magnitude the error actually
# propagates from — max(|f32 value|, 1) for rmse, and the coefficient
# VECTOR's max(|coef|, 1) per (pixel, band, segment) for coefs (a
# lasso-thresholded near-zero coefficient absorbs absolute error
# proportional to its siblings' scale, so elementwise ulps there are
# meaningless).  The bf16 split-dot gram carries ~2^-17 relative error
# into the normal equations versus f32's ~2^-24; measured drift on the
# adversarial-fuzz chip is ~340 coef / ~670 rmse scaled ulps, while a
# naive bf16 weight cast (the bug this budget exists to catch) lands
# ~2^15.  Decisions (break day/QA/segment count/curve rank) must be
# IDENTICAL — the budget applies only to the continuous payload.
MIXED_ULP_BUDGET = 1 << 12

# Tmask robust screen: IRLS (Huber weights) harmonic fit without trend on
# TMASK_BANDS; an observation is an outlier if |residual| exceeds
# TMASK_CONST * max(variogram, rmse) in any Tmask band.
TMASK_COEFS = 5           # [1, cos wt, sin wt, cos 2wt, sin 2wt]
TMASK_CONST = 4.89

# Minimum date gap for a successive-difference pair to enter the ADJUSTED
# variogram (lcmap-pyccd's adjusted_variogram rule, reconstructed — the
# pinned package at reference setup.py:32 is unreachable offline; see
# docs/DIVERGENCE.md #1).  Near-coincident multi-sensor acquisitions
# (combined L7+L8 archives) otherwise crater the madogram denominator.
VARIOGRAM_GAP_DAYS = 30.0
TMASK_IRLS_ITERS = 5
HUBER_K = 1.345


def variogram_adjusted_default() -> bool:
    """Whether the ADJUSTED variogram rule is active (FIREBIRD_VARIOGRAM;
    default 'adjusted').

    The default follows the reconstruction's own conclusion
    (docs/DIVERGENCE.md #1): the reference pins the *ncompare* release of
    lcmap-pyccd (setup.py:32) — the combined-L7+L8 line whose raison
    d'être is exactly the near-coincident-pair correction the adjusted
    rule implements — so the pinned algorithm is taken to run adjusted.
    ``FIREBIRD_VARIOGRAM=plain`` restores the plain madogram; both modes
    hold the full kernel<->oracle parity envelope.  Read at trace time —
    set before the first detect call (one compiled fn per mode).
    """
    from firebird_tpu.config import env_knob

    return env_knob("FIREBIRD_VARIOGRAM") == "adjusted"

def compact_default() -> bool:
    """Whether active-lane compaction runs in the event loop
    (FIREBIRD_COMPACT; default on).

    Compaction periodically permutes the per-pixel loop state so lanes
    whose pixels are still working (phase != DONE) form a dense prefix —
    trailing all-dead lane blocks then cost a per-block predicate in the
    Pallas kernels instead of a Gram build + CD loop, and the long tail
    re-enters a smaller bucketed loop (kernel._detect_batch_impl).
    Results are row-identical either way (the permutation is inverted at
    loop exit).  Read at trace time like FIREBIRD_PALLAS — set before
    the first detect call; explicit ``compact=`` arguments to
    detect_packed/detect_sharded override per call."""
    from firebird_tpu.config import env_knob

    return env_knob("FIREBIRD_COMPACT") not in ("", "0")


def compact_every() -> int:
    """Rounds between compaction checks (FIREBIRD_COMPACT_EVERY,
    default 4, min 1).  A check only permutes when at least 1/16 of a
    chip's lanes died since the last compaction — the gather sweep over
    the carried residents must buy skipped blocks.  Trace-time read."""
    from firebird_tpu.config import env_knob

    return max(int(env_knob("FIREBIRD_COMPACT_EVERY")), 1)


def compact_min_lanes() -> int:
    """Smallest pixel count that builds the bucketed re-entry loop
    (FIREBIRD_COMPACT_MIN_LANES, default 1024).  The cascade is a second
    traced copy of the round body — real lane savings at chip scale
    (P=10000), pure compile cost for the tiny pixel slices the test
    suite dispatches — so small batches keep the single compacted loop.
    Trace-time read; tests crafting small cascades lower it."""
    from firebird_tpu.config import env_knob

    return max(int(env_knob("FIREBIRD_COMPACT_MIN_LANES")), 1)


def compact_floor() -> float:
    """Alive-fraction floor triggering bucketed re-entry
    (FIREBIRD_COMPACT_FLOOR, default 1/8; 0 disables the cascade).
    When every chip's alive count fits the next power-of-two bucket of
    floor*P lanes, the loop exits, survivors (a dense prefix after the
    forced compaction) are sliced into the bucket, and a smaller-shape
    loop finishes them (kernel._detect_batch_impl stage 2).  Trace-time
    read."""
    from firebird_tpu.config import env_knob

    v = float(env_knob("FIREBIRD_COMPACT_FLOOR"))
    return min(max(v, 0.0), 1.0)


# ---------------------------------------------------------------------------
# Curve QA flags (segment provenance), pyccd-style bit values.
# ---------------------------------------------------------------------------
CURVE_QA_INSUF_CLEAR = 1    # fit by the insufficient-clear procedure
CURVE_QA_PERSIST_SNOW = 2   # fit by the permanent-snow procedure
CURVE_QA_INSIDE = 4         # interior segment (bounded by breaks both sides)
CURVE_QA_START = 8          # first segment of the series
CURVE_QA_END = 16           # segment running to the end of the series

# Insufficient-clear procedure: keep non-fill obs whose blue value is below
# median(blue) + INSUF_CLEAR_BLUE_DELTA.
INSUF_CLEAR_BLUE_DELTA = 400.0
