"""CCDC science kernel.

Replaces the external lcmap-pyccd package (the reference's hot path:
``ccd.detect(dates, blues, ..., qas)`` called per pixel inside a Spark
flatMap, ccdc/pyccd.py:151-183).  Two implementations of one spec:

- :mod:`firebird_tpu.ccd.reference` — NumPy float64 oracle.  Readable,
  per-pixel, defines the algorithm.  Used as the golden standard in tests
  and for CPU fallback.
- :mod:`firebird_tpu.ccd.kernel` — the TPU implementation: jit + vmap over
  all 10,000 pixels of a chip, scan-over-time state machine, batched linear
  algebra on the MXU.

Both read their constants from :mod:`firebird_tpu.ccd.params`.

The result contract mirrors pyccd's exactly (ccdc/pyccd.py:106-148 and the
golden fixture test/test_pyccd.py:37-126): a dict with ``change_models``
(list of segments with per-band {magnitude, rmse, coefficients, intercept})
and ``processing_mask`` aligned to the input observation order.
"""

from firebird_tpu.ccd import params
from firebird_tpu.ccd.reference import detect

__all__ = ["params", "detect"]
