"""Synthetic Landsat-like time series for tests and benchmarks.

The reference has no numerical-accuracy fixtures (SURVEY.md §4 "notably
absent"); this generator closes that gap: harmonic + trend + noise series
with controllable QA patterns, step changes and outliers, so segment counts
and break dates have known ground truth.
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.ccd import harmonic, params
from firebird_tpu.utils import dates as dt

QA_CLEAR = 1 << params.QA_CLEAR_BIT
QA_FILL = 1 << params.QA_FILL_BIT
QA_SNOW = 1 << params.QA_SNOW_BIT
QA_CLOUD = 1 << params.QA_CLOUD_BIT

# A plausible mean reflectance per band (blue..thermal, int16 scale).
DEFAULT_MEANS = np.array([400.0, 600.0, 500.0, 2500.0, 1500.0, 800.0, 2900.0])
DEFAULT_AMPS = np.array([50.0, 80.0, 80.0, 400.0, 250.0, 120.0, 500.0])


def means_amps(sensor) -> tuple[np.ndarray, np.ndarray]:
    """Per-band (means, amps) sized to a sensor spec.

    Landsat ARD gets the calibrated defaults; other band counts cycle the
    optical palette (plausible vegetation-reflectance scale), with thermal
    bands pinned to the thermal default so range checks behave."""
    B = sensor.n_bands
    if B == DEFAULT_MEANS.shape[0] and sensor.thermal_bands == (6,):
        return DEFAULT_MEANS.copy(), DEFAULT_AMPS.copy()
    means = np.resize(DEFAULT_MEANS[:6], B).astype(np.float64)
    amps = np.resize(DEFAULT_AMPS[:6], B).astype(np.float64)
    for b in sensor.thermal_bands:
        means[b], amps[b] = DEFAULT_MEANS[6], DEFAULT_AMPS[6]
    return means, amps


def acquisition_dates(start="1995-01-01", end="2015-01-01", cadence_days=16,
                      rng=None, drop_frac=0.0) -> np.ndarray:
    """Ordinal acquisition dates at a fixed cadence, optionally thinned."""
    t0, t1 = dt.to_ordinal(start), dt.to_ordinal(end)
    t = np.arange(t0, t1, cadence_days, dtype=np.int64)
    if rng is not None and drop_frac > 0:
        keep = rng.random(t.shape[0]) >= drop_frac
        t = t[keep]
    return t


def harmonic_series(t: np.ndarray, rng: np.random.Generator, *,
                    means: np.ndarray = DEFAULT_MEANS,
                    amps: np.ndarray = DEFAULT_AMPS,
                    slope_per_year: float = 0.0,
                    noise: float = 30.0) -> np.ndarray:
    """[B, T] spectra: mean + annual harmonic + trend + N(0, noise);
    B follows ``means`` (7-band Landsat defaults)."""
    means = np.asarray(means)
    ph = harmonic.day_phase(t)
    yr = (t - t[0]) / 365.25
    Y = (means[:, None]
         + np.asarray(amps)[:, None] * np.cos(ph)[None, :]
         + slope_per_year * yr[None, :]
         + rng.normal(0.0, noise, size=(means.shape[0], t.shape[0])))
    return Y


def with_step_change(Y: np.ndarray, t: np.ndarray, change_date: str,
                     delta: np.ndarray | float = 800.0) -> np.ndarray:
    """Add a step change to all bands at the given date."""
    c = dt.to_ordinal(change_date)
    out = Y.copy()
    after = t >= c
    delta = np.broadcast_to(np.asarray(delta, dtype=np.float64),
                            (Y.shape[0],))
    out[:, after] += delta[:, None]
    return out


def pixel(t: np.ndarray, Y: np.ndarray, qa: np.ndarray | None = None) -> dict:
    """Pack into the detect() keyword contract (ccdc/pyccd.py:161-168)."""
    if qa is None:
        qa = np.full(t.shape[0], QA_CLEAR, dtype=np.uint16)
    names = params.BAND_NAMES_PLURAL
    d = {n: np.clip(Y[i], -32768, 32767).astype(np.int16)
         for i, n in enumerate(names)}
    d["dates"] = t.astype(np.int64)
    d["qas"] = np.asarray(qa, dtype=np.uint16)
    return d


def chip(rng: np.random.Generator, n_pixels: int = 100, *,
         start="1995-01-01", end="2015-01-01", cadence_days=16,
         change_frac: float = 0.3) -> list[dict]:
    """A bag of pixels, a fraction of which contain one step change."""
    t = acquisition_dates(start, end, cadence_days)
    out = []
    for p in range(n_pixels):
        Y = harmonic_series(t, rng)
        if rng.random() < change_frac:
            mid = dt.to_iso(int(t[t.shape[0] // 2]))
            Y = with_step_change(Y, t, mid, delta=600 + 400 * rng.random())
        out.append(pixel(t, Y))
    return out
