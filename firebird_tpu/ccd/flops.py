"""Closed-form FLOP / HBM-traffic model of the CCDC kernel.

The event-horizon kernel (kernel._detect_core) does the same algebra every
round, so its arithmetic is computable in closed form from the dispatch
shape: P pixels, T observations, W window cap, and the sensor's band
counts, times the measured round count.  bench.py multiplies this model by
the measured pixel rate to report achieved FLOP/s and an MFU estimate
against the device's peak — the roofline accounting VERDICT r1 asked for
(docs/ROOFLINE.md holds the written argument).

Conventions:
- one multiply-add = 2 FLOPs (MXU convention);
- formulas mirror kernel.py line by line (cited per term) so a kernel
  change that shifts the arithmetic is a model bug you can grep for;
- elementwise [P,T] bookkeeping (masks, cumsum/cummin, selects) is
  counted in the *bytes* model, not the FLOP model — on TPU those ops are
  VPU/bandwidth work and never the FLOP bottleneck.

The model is an upper-level estimate of *useful* arithmetic, not a count
of what XLA finally executes (fusion may duplicate cheap ops; masked
lanes still burn MXU cycles — that's the point of counting them: the
dense batched formulation pays for masked work, and MFU against the
dense count is the honest utilization number).
"""

from __future__ import annotations

import dataclasses

from firebird_tpu.ccd import params
from firebird_tpu.ccd.sensor import LANDSAT_ARD

K = params.MAX_COEFS            # 8 design columns
NT = params.TMASK_COEFS         # 5 Tmask columns


def _lasso_fit_flops(P: int, T: int, B: int, with_rmse: bool) -> float:
    """One batched Lasso fit (kernel._fit_lasso_coefs / _fit_lasso).

    Gram:  w @ XX            [P,T]x[T,K^2]        (kernel.py:174)
    corr:  (Y*w) einsum X    [P,B,T]x[T,K] + the Y*w mult (kernel.py:175)
    CD:    LASSO_ITERS x K coordinate updates, each an einsum
           G[:,j,:] . b over [P,B,K] (kernel.py:195-205)
    rmse:  pred einsum [P,B,T]x? + residual reduction (kernel.py:220-223)
    """
    gram = 2.0 * P * T * K * K
    corr = 2.0 * P * B * T * K + P * B * T
    cd = params.LASSO_ITERS * K * (2.0 * P * B * K + 4.0 * P * B)
    f = gram + corr + cd
    if with_rmse:
        f += 2.0 * P * B * T * K + 4.0 * P * B * T
    return f


def _tmask_flops(P: int, W: int, nb: int) -> float:
    """One Tmask IRLS screen over the compacted window (kernel._tmask_bad).

    One-time XtXt outer products [P,W,NT^2], then (1 + TMASK_IRLS_ITERS)
    weighted SPD solves, each: flat Gram dot [P,nb,W]x[P,W,NT^2], corr
    dot, unrolled 5x5 Cholesky (kernel._tmask_bad/_chol_solve_small);
    per-iteration residual einsum + two masked medians over W (bitonic
    network).
    """
    solves = 1 + params.TMASK_IRLS_ITERS
    xtxt = P * W * NT * NT                       # outer products, once
    per_solve = (2.0 * P * nb * W * NT * NT      # flat Gram dot
                 + 2.0 * P * nb * W * NT         # cc (incl. Y2*wt mult)
                 + P * nb * (NT ** 3 / 3 + 2 * NT * NT))   # unrolled chol
    resid = 2.0 * P * nb * W * NT + 2.0 * P * nb * W
    med = 2 * _sort_flops(P * nb, W)             # med + mad networks
    return xtxt + solves * per_solve \
        + (params.TMASK_IRLS_ITERS + 1) * resid \
        + params.TMASK_IRLS_ITERS * med


def _sort_flops(rows: float, n: int) -> float:
    """Bitonic network over a length-n axis: log2^2 stages of compare /
    select (kernel._bitonic_sort_last) — ~3 elementwise ops per element
    per stage."""
    if n <= 1:
        return 0.0
    lg = max(1, (n - 1).bit_length())
    stages = lg * (lg + 1) / 2
    return 3.0 * rows * n * stages


def round_flops(P: int, T: int, W: int, sensor=LANDSAT_ARD,
                mixed: bool = False) -> dict:
    """FLOPs of one event-horizon round over P pixels (kernel.body).

    Terms are grouped by the cond gate that executes them (kernel
    _detect_batch_impl): ``init`` only runs on rounds with an
    initializing pixel, ``close`` on rounds closing a segment, ``refit``
    on rounds fitting a model, ``monitor`` every round.  ``total`` is
    the ungated (every-round) sum — the pre-gating upper bound.

    ``mixed`` (FIREBIRD_MIXED_PRECISION) adds a ``mixed`` sub-dict
    modeling the bf16 split-dot gram (pallas_ops._gram_cd_core): the
    useful arithmetic is UNCHANGED (total stays comparable across
    rungs), but the Gram/corr dots execute 2 and 3 bf16 MXU passes
    instead of f32-"highest"'s 6, with bf16 (half-width) operands —
    bench_detail turns that into the mixed compute ceiling.
    """
    B = sensor.n_bands
    D = len(sensor.detection_bands)
    nb = len(sensor.tmask_bands)
    init_fit = _lasso_fit_flops(P, T, B, with_rmse=False)   # c4 stability
    init_resid = 2.0 * P * B * W * K + 6.0 * P * B * W      # r_w + rmse4
    tmask = _tmask_flops(P, W, nb)
    # One-hot window selections (the scatter-free MXU formulation):
    # Yw7 [P,B,T]x[P,W,T], XW [P,W,T]x[T,K+NT] (kernel._init_block; these
    # replaced serialized per-lane gathers).
    onehot_w = (2.0 * P * B * W * T               # Yw7
                + 2.0 * P * W * T * (K + NT))     # XW
    monitor = (2.0 * P * D * T * K      # pred_d
               + 4.0 * P * D * T)       # score s
    # Segment-close work (kernel._close_block): PEEK-run one-hot
    # selections + break-magnitude medians.
    close = (2.0 * P * params.PEEK_SIZE * T * (K + B)        # X_run + Y_run
             + 2.0 * P * B * params.PEEK_SIZE * K            # pred_run
             + _sort_flops(P * B, params.PEEK_SIZE))         # mags median
    refit = _lasso_fit_flops(P, T, B, with_rmse=True)       # cfull
    init = init_fit + init_resid + tmask + onehot_w
    out = {"init_fit": init_fit, "init_resid": init_resid,
           "tmask": tmask, "onehot": onehot_w, "monitor": monitor,
           "close": close, "refit": refit, "init": init,
           "total": init + monitor + close + refit}
    if mixed:
        # Per firing fit (the INIT stability fit and the shared refit
        # each contain one Gram + one corr): the useful dot FLOPs that
        # move from the f32-"highest" MXU schedule (6 bf16 passes per
        # dot) to the split-dot schedule (Gram 2 — 0/1 weights are
        # bf16-exact; corr 3 — lo·lo dropped).  Everything else (CD
        # loop, RMSE, monitor, Tmask, medians) stays f32: the decision
        # envelope.
        gram_dot = 2.0 * P * T * K * K
        corr_dot = 2.0 * P * B * T * K
        out["mixed"] = {
            "gram_dot_flops": gram_dot, "corr_dot_flops": corr_dot,
            "mxu_passes_f32": 6, "mxu_passes_gram": 2,
            "mxu_passes_corr": 3,
            "gram_operand_bytes_ratio": 0.5,    # bf16 vs f32 operands
            "dot_stage_speedup_model": round(
                6.0 * (gram_dot + corr_dot)
                / (2.0 * gram_dot + 3.0 * corr_dot), 2),
        }
    return out


def setup_flops(P: int, T: int, sensor=LANDSAT_ARD) -> float:
    """One-time work outside the round loop: QA triage, variogram (sorted
    successive diffs, kernel._variogram), the alt-procedure fit, XX outer
    products."""
    B = sensor.n_bands
    triage = 12.0 * P * T
    vario = P * B * T + _sort_flops(P * B, T - 1)
    alt = _lasso_fit_flops(P, T, B, with_rmse=True)
    xx = T * K * K
    return triage + vario + alt + xx


def detect_flops(P: int, T: int, W: int, rounds: float,
                 sensor=LANDSAT_ARD,
                 phase_rounds: tuple | None = None,
                 mixed: bool = False) -> dict:
    """Total kernel FLOPs for one dispatch and the per-pixel figure.

    ``phase_rounds`` = (init_rounds, fit_rounds, close_rounds) — the
    measured cond-gate execution counts (ChipSegments.round_counts).
    None models the ungated loop (every block every round)."""
    r = round_flops(P, T, W, sensor, mixed=mixed)
    ir, fr, cr = phase_rounds if phase_rounds is not None \
        else (rounds, rounds, rounds)
    total = (r["monitor"] * rounds + r["init"] * ir + r["refit"] * fr
             + r["close"] * cr + setup_flops(P, T, sensor))
    return {"per_round": r, "rounds": rounds, "total": total,
            "per_pixel": total / max(P, 1)}


def round_bytes(P: int, T: int, W: int, S: int, dtype_bytes: int,
                sensor=LANDSAT_ARD,
                rounds: float = 1.0,
                phase_rounds: tuple | None = None,
                pallas: frozenset | set | tuple = (),
                wire_bytes: int = 2, mixed: bool = False) -> float:
    """Estimated HBM traffic (read+write) over the event loop.

    ``mixed`` (FIREBIRD_MIXED_PRECISION): the HBM model is mixed-
    INVARIANT on every route the knob actually reaches — the Pallas fit
    kernels stream the wire int16 spectra either way, and the bf16 gram
    operands live at the VMEM→MXU boundary, not in HBM (their halved
    bytes are modeled in round_flops' ``mixed`` block and fold into
    bench_detail's mixed compute ceiling).  The parameter is accepted so
    call sites can pass the picked config through uniformly; it changes
    no HBM term by design, and this docstring is the written argument.

    Per-phase apportionment mirrors the kernel's cond gates
    (_detect_batch_impl): the score-group spectra read, the [P,T]
    temporaries, and the carried state move every round; the one-hot
    window tensors + stability-fit spectra read only on INIT rounds; the
    refit spectra read on fit rounds; the PEEK-run tensors + result-
    buffer rewrite on close rounds.  ``phase_rounds`` = (init, fit,
    close) counts; None models every block every round.

    ``pallas`` names the enabled Pallas components (the bench's picked
    FIREBIRD_PALLAS config): a component's term is then modeled from its
    kernel's actual block streams (in/out BlockSpecs — known exactly,
    unlike the XLA estimate) instead of the XLA path's materializations:

    - 'score': the monitor round streams the [D,T,P] *wire-dtype*
      spectra once and 4 [T,P] i32 planes (alive/included in, inc/rem_q
      out); the [P,D,T] prediction einsum, the f32 score plane and the
      rank planes never exist in HBM (pallas_ops._monitor_scored_block).
    - 'init': the INIT round streams the [B,T,P] wire spectra + ~3
      [T,P] i32 planes (alive in/out, w_stab out); the [P,W,T] one-hot
      tensors and the stability fit's float Y re-read never exist
      (pallas_ops._init_window_block).
    - 'fit': the refit streams the [B,T,P] wire spectra + the [T,P]
      window plane; Gram/corr/CD/RMSE stay in VMEM
      (pallas_ops._fit_block).
    - 'fused': the FIREBIRD_FUSED_FIT gram→CD→close kernel
      (pallas_ops._fused_fit_close_block) — the close + shared-fit pair
      runs on ONE wire-spectra residency per fit round, and the close's
      buffer rewrite crosses the kernel boundary once instead of
      round-tripping the [P,S*k] planes plus the PEEK-run one-hot
      tensors.  Close-only rounds (the shared tail close) still pay one
      buffer boundary; the rare break round adds kernel._close_mags'
      spectra read, modeled under the close term.
    """
    B = sensor.n_bands
    D = len(sensor.detection_bands)
    ir, fr, cr = phase_rounds if phase_rounds is not None \
        else (rounds, rounds, rounds)
    pallas = frozenset(pallas)
    if "mega" in pallas:
        # Whole-loop kernel (pallas_ops._detect_mega_block): the event
        # loop's HBM traffic is ROUND-INDEPENDENT — one [B,T,P] wire
        # read, the start-state planes in (alive + phase/cursor vectors),
        # and the result-buffer/final-alive boundary out.  Matches the
        # mega pallas_call's in/out BlockSpecs term by term.
        return (P * B * T * wire_bytes          # wire spectra, once
                + 2 * 4.0 * P * T               # alive0 in + alive out
                + 8.0 * P                        # i32 state vectors
                + 2.0 * P * S * (6 + 2 * B + B * K) * dtype_bytes)
    # carried loop state: alive/included bool planes + coefs, read+written
    carry = 2 * (2.0 * P * T + P * B * K * dtype_bytes)
    if "score" in pallas:
        # wire spectra once + 4 i32 planes through the kernel boundary
        every = P * D * T * wire_bytes + 16.0 * P * T + carry
    else:
        # score-group read [P,D,T] + ~10 [P,T] temporaries (bufs counted
        # on close rounds — unchanged cond pass-through aliases in place)
        every = (1.0 * P * D * T * dtype_bytes
                 + 10.0 * P * T * dtype_bytes + 6.0 * P * T + carry)
    if "init" in pallas:
        init = P * B * T * wire_bytes + 12.0 * P * T
    else:
        # oh_w bool written+read + float view read by the two selection
        # matmuls + window members/XtXt + the c4 fit's Y read
        init = (3.0 * P * W * T
                + 3.0 * P * W * T * dtype_bytes
                + 2.0 * P * W * (NT + B + NT * NT) * dtype_bytes
                + P * B * T * dtype_bytes)
    if "fused" in pallas:
        # The fused kernel reads the wire spectra once per firing round
        # and serves BOTH the fit and the close row write from it; the
        # buffer planes stream through the kernel boundary (in + out)
        # instead of the XLA path's oh_run tensors + cond round-trips.
        # The pre-fusion model charged the spectra twice (fit + close
        # oh_run) — the satellite bugfix this branch exists for: an
        # unfused byte model here overstated the fused path's traffic
        # and understated its intensity/MFU.
        fit = P * B * T * wire_bytes + 5.0 * P * T
        close = 2.0 * P * S * (6 + 2 * B + B * K) * dtype_bytes + 8.0 * P
    else:
        if "fit" in pallas:
            fit = P * B * T * wire_bytes + 5.0 * P * T
        else:
            fit = P * B * T * dtype_bytes         # cfull Gram corr Y read
        close = (2.0 * P * params.PEEK_SIZE * T * dtype_bytes    # oh_run
                 + 2.0 * P * S * (6 + 2 * B + B * K) * dtype_bytes)  # bufs
    return every * rounds + init * ir + fit * fr + close * cr


# ---------------------------------------------------------------------------
# Occupancy-weighted lane-round model (active-lane compaction, ISSUE 6).
# The dense batched loop pays every padded lane every round; compaction
# (kernel._detect_batch_impl) makes the paid set track the ACTIVE set:
# dense-prefix permutation clusters dead lanes into whole trailing
# blocks the Pallas kernels skip, and the bucketed re-entry shrinks the
# lane width itself for the long tail.  The kernel captures per-round
# (active, paid) lane counts per chip (ChipSegments.occupancy); this
# model turns the capture into the padded-vs-effective accounting the
# bench artifact and the obs counters report.
# ---------------------------------------------------------------------------

# Bench artifacts embed occupancy_detail's per_round list verbatim; cap
# it so a deep-round dispatch (rounds scale with 2T+8) cannot bloat the
# single JSON line past what log-tail parsers handle (BENCH_r05 lesson).
PER_ROUND_CAP = 128


def occupancy_detail(occupancy, rounds, lanes: int) -> dict:
    """Padded vs effective lane-rounds from the kernel's per-round
    occupancy capture.

    Args:
        occupancy: [C, R_max, 2] int (active_lanes, paid_lanes) per chip
            per executed round (ChipSegments.occupancy, host array).
        rounds: [C] executed round counts (ChipSegments.rounds).
        lanes: padded lanes per chip (P).

    Returns a dict with ``padded_lane_rounds`` (lanes x rounds — what the
    uncompacted loop pays), ``effective_lane_rounds`` (paid lanes summed:
    blocks containing a working pixel, at the bucket width after
    re-entry), ``active_lane_rounds`` (lanes with a working pixel — the
    lower bound any compaction scheme can reach), ``wasted_lane_rounds``
    (effective - active), ``occupancy_savings`` (padded / effective), a
    ``per_round`` list of {round, active, paid} summed over chips
    (bounded at PER_ROUND_CAP rows so a deep-round artifact cannot
    regrow the oversized-JSON-line failure the bench satellites fixed;
    ``per_round_dropped`` counts rows past the cap — totals always
    cover every round), and ``_fractions`` (active/lanes per
    chip-round, consumed by kernel.record_occupancy's histogram).

    Vectorized: this runs on the driver's drain thread per batch, and a
    deep time series executes ~2T+8 rounds per chip — a python loop over
    chip-rounds there competes with egress."""
    import numpy as np

    occ = np.asarray(occupancy)
    rds = np.asarray(rounds).reshape(-1)
    C, R_max = occ.shape[0], occ.shape[1]
    r_c = rds[np.minimum(np.arange(C), rds.size - 1)].astype(np.int64)
    mask = np.arange(R_max)[None, :] < np.minimum(r_c, R_max)[:, None]
    act = np.where(mask, occ[..., 0], 0)
    paid = np.where(mask, occ[..., 1], 0)
    padded = int(lanes) * int(mask.sum())
    active = int(act.sum())
    effective = int(paid.sum())
    # Per-round sums over chips; executed rounds form a dense prefix per
    # chip, so the used rounds are 0..max(r_c)-1.
    n_rows = int(mask.any(0).sum())
    a_r, p_r = act.sum(0), paid.sum(0)
    fractions = occ[..., 0][mask] / max(lanes, 1)   # row-major: (c, r)
    return {
        "padded_lane_rounds": padded,
        "effective_lane_rounds": effective,
        "active_lane_rounds": active,
        "wasted_lane_rounds": effective - active,
        "occupancy_savings": round(padded / max(effective, 1), 3),
        "mean_active_fraction": round(
            float(fractions.mean()) if fractions.size else 0.0, 4),
        "per_round": [{"round": r, "active": int(a_r[r]),
                       "paid": int(p_r[r])}
                      for r in range(min(n_rows, PER_ROUND_CAP))],
        **({"per_round_dropped": n_rows - PER_ROUND_CAP}
           if n_rows > PER_ROUND_CAP else {}),
        "_fractions": fractions,
    }


def expected_compaction_speedup(mean_active_fraction: float,
                                lane_block: int = 512,
                                lanes: int = 10000) -> float:
    """The occupancy model's closed-form ceiling for the event loop's
    per-round cost under compaction: with mean active fraction a, the
    compacted loop pays ~ceil(a*P/B)*B of P lanes per round, so the
    loop-cost speedup approaches P / (ceil(a*P/B)*B) — e.g. a=0.5 -> ~2x,
    a=0.125 with bucketed re-entry -> ~8x.  Deviation from the measured
    wall ratio quantifies the non-lane-proportional terms (chip-shared
    design work, cond-gate overhead, the compaction sweeps themselves);
    docs/ROOFLINE.md "Occupancy" holds the written argument."""
    a = min(max(mean_active_fraction, 0.0), 1.0)
    paid = -lane_block * (-max(a * lanes, 1.0) // lane_block)
    return lanes / max(paid, 1.0)


def rebalance_detail(rounds_by_shard, wall_seconds: float,
                     lanes_migrated: int = 0) -> dict:
    """Straggler-idle model for the cross-device rebalancing ring
    (parallel.mesh; docs/ROOFLINE.md "Fused fit").

    In SPMD each device runs its own event loop and the dispatch ends at
    the SLOWEST device, so per-device round counts bound the idle:
    a device executing r_d rounds of a max-R dispatch idles
    ~(R - r_d)/R of the wall.  ``rounds_by_shard`` is the per-device
    executed round count (one value per shard — under sharding every
    chip of a shard reports its loop's count, so callers pass one per
    device); the model reports the idle seconds a perfect balancer
    could reclaim and the balance ratio (mean/max rounds, 1.0 = no
    straggler).  ``lanes_migrated`` (the kernel counter) rides along so
    the bench artifact pairs the model with what the ring actually
    moved."""
    import numpy as np

    r = np.asarray(rounds_by_shard, np.float64).reshape(-1)
    r = r[r > 0] if (r > 0).any() else r
    if r.size == 0:
        return {"straggler_idle_seconds_saved_model": 0.0,
                "balance_ratio": 1.0, "lanes_migrated": int(lanes_migrated)}
    mx = float(r.max())
    ratio = float(r.mean()) / max(mx, 1.0)
    idle = (1.0 - ratio) * float(wall_seconds)
    return {"straggler_idle_seconds_saved_model": round(idle, 4),
            "balance_ratio": round(ratio, 4),
            "rounds_by_shard": [int(x) for x in r[:64]],
            "lanes_migrated": int(lanes_migrated)}


# ---------------------------------------------------------------------------
# Device peaks (per chip).  Sources: published Google Cloud TPU system
# specs; matched by substring of jax Device.device_kind.  f32 matmul on
# TPU runs through the MXU at a fraction of bf16 throughput; the kernel
# computes in f32, so MFU is reported against BOTH numbers.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Peak:
    name: str
    bf16_flops: float          # peak dense matmul FLOP/s, bf16
    f32_flops: float           # effective f32 matmul peak (~bf16/4)
    hbm_bytes: float           # HBM bandwidth, bytes/s


PEAKS = (
    Peak("v6", 918e12, 229e12, 1640e9),        # Trillium
    Peak("v5p", 459e12, 115e12, 2765e9),
    Peak("v5 lite", 197e12, 49e12, 819e9),     # v5e (device_kind "TPU v5 lite")
    Peak("v5e", 197e12, 49e12, 819e9),
    Peak("v4", 275e12, 69e12, 1228e9),
    Peak("v3", 123e12, 31e12, 900e9),
    Peak("v2", 46e12, 12e12, 700e9),
)


def peak_for(device_kind: str) -> Peak | None:
    dk = device_kind.lower()
    for p in PEAKS:
        if p.name in dk:
            return p
    return None


def bench_detail(pixels_per_sec: float, P: int, T: int, W: int, S: int,
                 rounds: float, device_kind: str, dtype_bytes: int = 4,
                 sensor=LANDSAT_ARD, phase_rounds: tuple | None = None,
                 pallas: frozenset | set | tuple = (),
                 wire_bytes: int = 2, mixed: bool = False) -> dict:
    """The roofline block bench.py embeds in its detail output.

    ``phase_rounds`` = measured (init, fit, close) cond-gate counts
    (ChipSegments.round_counts) — makes the model reflect what the
    phase-gated loop actually executed instead of the ungated bound.
    ``pallas`` = the enabled component set (see round_bytes) so the byte
    model reflects the picked config's actual streams.  ``mixed`` = the
    picked config runs the bf16 split-dot gram: the model's MFU numbers
    stay against the SAME useful-arithmetic count (comparable across
    rungs), and a ``mixed`` block reports the dot-stage pass/operand
    model plus the raised compute ceiling — with
    ``mfu_pct_vs_bf16_peak`` the headline utilization figure for the
    picked config, since the dots then run on the bf16 MXU path."""
    fl = detect_flops(P, T, W, rounds, sensor, phase_rounds=phase_rounds,
                      mixed=mixed)
    by = round_bytes(P, T, W, S, dtype_bytes, sensor, rounds=rounds,
                     phase_rounds=phase_rounds, pallas=pallas,
                     wire_bytes=wire_bytes, mixed=mixed) / max(P, 1)
    achieved = pixels_per_sec * fl["per_pixel"]
    hbm_rate = pixels_per_sec * by
    out = {
        "model_flops_per_pixel": round(fl["per_pixel"], 1),
        "model_bytes_per_pixel": round(by, 1),
        "arithmetic_intensity": round(fl["per_pixel"] / max(by, 1.0), 2),
        "achieved_tflops": round(achieved / 1e12, 4),
        "achieved_hbm_gbps": round(hbm_rate / 1e9, 2),
        "rounds": round(float(rounds), 1),
        "device_kind": device_kind,
    }
    if phase_rounds is not None:
        out["phase_rounds"] = {"init": round(float(phase_rounds[0]), 1),
                               "fit": round(float(phase_rounds[1]), 1),
                               "close": round(float(phase_rounds[2]), 1)}
    if pallas:
        out["pallas_modeled"] = sorted(pallas)
    pk = peak_for(device_kind)
    if pk is not None:
        out["mfu_pct_vs_f32_peak"] = round(100 * achieved / pk.f32_flops, 2)
        out["mfu_pct_vs_bf16_peak"] = round(100 * achieved / pk.bf16_flops, 2)
        out["hbm_util_pct"] = round(100 * hbm_rate / pk.hbm_bytes, 2)
        # roofline-implied ceilings for this dispatch shape
        out["compute_bound_pixels_per_sec"] = round(
            pk.f32_flops / fl["per_pixel"], 1)
        out["hbm_bound_pixels_per_sec"] = round(pk.hbm_bytes / max(by, 1.0), 1)
    if mixed:
        r = fl["per_round"]
        md = dict(r["mixed"])
        if pk is not None and phase_rounds is not None:
            # Mixed compute ceiling: the Gram/corr dots fire on init +
            # fit rounds and run pass-counted at the bf16 peak; the rest
            # of the useful arithmetic stays at the f32 peak.  Per
            # pixel, over the dispatch:
            ir, frr, _ = phase_rounds
            dots = (md["gram_dot_flops"] + md["corr_dot_flops"]) \
                * (ir + frr) / max(P, 1)
            rest = max(fl["per_pixel"] - dots, 0.0)
            t_mixed = (md["mxu_passes_gram"] * md["gram_dot_flops"]
                       + md["mxu_passes_corr"] * md["corr_dot_flops"]) \
                * (ir + frr) / max(P, 1) / pk.bf16_flops \
                + rest / pk.f32_flops
            md["mixed_compute_bound_pixels_per_sec"] = round(
                1.0 / max(t_mixed, 1e-30), 1)
        out["mixed"] = md
    return out
