"""Pallas TPU kernels for the CCD hot ops.

:func:`monitor_chain` — the MONITOR event logic (kernel._monitor_chain):
a pipeline of ~15 cumulative/reduce ops over the [P, T] score plane whose
intermediates otherwise stream through HBM between fusions (the round-2
profile shows the loop body paying a ~0.3 ms-per-op floor at these
shapes).  One block computes cursor ranks, break-run lengths (reverse
cummin as a log-step shift scan), refit-ladder crossings (cumsum
likewise), and the tail/break/refit event selection entirely in VMEM,
with the pixel axis on lanes and T on sublanes.

:func:`lasso_cd` — the Lasso coordinate-descent loop, the detector's
serial core: every event-loop round runs LASSO_ITERS x MAX_COEFS
sequential coordinate updates over [P, B, 8] Gram systems
(kernel._fit_lasso_coefs; the round count is small, so the CD loop
dominates the non-matmul step count).
Under plain XLA each of those ~400 steps materializes its [P, B]
intermediates between fused ops; this kernel keeps the whole state
(G, c, diag, mask, b) resident in VMEM for all iterations, streaming each
pixel block exactly once.

Layout: the pixel axis goes LAST ([K, K, P], [B, K, P], ...) so it rides
the 128-wide vector lanes and the tiny K=8 axis sits on sublanes — the
natural VPU shape for the per-coordinate updates, which are elementwise
over P.

:func:`tmask_bad` — the Tmask IRLS screen (kernel._tmask_bad): six
sequential weighted SPD solves plus ten masked medians per round, each a
separate fusion paying the per-op floor; the kernel runs the whole IRLS
in VMEM, with a shift-exchange bitonic network for the medians.

Enablement: `firebird_tpu.ccd.kernel` routes a component through its
Pallas kernel when FIREBIRD_PALLAS names it — "1" enables all three,
"lasso,monitor"-style lists pick a subset (kernel.use_pallas; bench.py
auto-tunes the winning set on hardware; CPU tests run the same kernels
under ``interpret=True``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from firebird_tpu.ccd import params

BLOCK_P = 512   # pixels per grid step (4 x 128 lanes, f32)


def _env_block_p() -> int | None:
    """FIREBIRD_MEGA_BLOCK_P: static lane-block width override for the
    multi-phase kernels (detect_mega / fused_round), consumed when the
    caller passes ``block_p=None``.  This is how tools/fuse_repro.py's
    bisected smallest-compiling block shape becomes the DEFAULT instead
    of an advisory artifact: bench.py seeds the knob from
    fuse_repro.json before racing the mega rungs.  Read at trace time
    (set before the first dispatch, like FIREBIRD_PALLAS); values are
    rounded down to the 128-lane vector width, <=0/garbage means no
    override."""
    from firebird_tpu.config import env_knob

    v = env_knob("FIREBIRD_MEGA_BLOCK_P")
    try:
        n = int(v) if v else 0
    except (TypeError, ValueError):
        n = 0
    return (n // 128) * 128 if n >= 128 else None


def _split_bf16(x):
    """hi/lo bf16 split of an f32 plane: ``hi`` is x rounded to bf16,
    ``lo`` the bf16-rounded residual — together a ~16-bit-significand
    representation whose MXU dots accumulate in f32 (the mixed-precision
    gram's operand form; see _gram_cd_core)."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(x.dtype)).astype(jnp.bfloat16)
    return hi, lo


# ---------------------------------------------------------------------------
# Per-block skip guards (active-lane compaction).  Every kernel here
# grids over pixel-lane blocks; with the event loop's dense-prefix
# compaction on (FIREBIRD_COMPACT, kernel._detect_batch_impl), dead
# lanes cluster into whole trailing blocks — so each wrapper accepts an
# optional ``active`` [P] lane mask, reduces it to a per-block count,
# and the block body runs under ``pl.when(count > 0)``: an all-dead
# block costs a predicate plus a zero-fill of its outputs (exactly the
# values the dead lanes would compute — all-zero windows / in_mon=False
# produce zeros through the real math, so the guard is bit-identical).
# ``active=None`` (the default, and every pre-compaction call site)
# traces the unguarded program unchanged.
# ---------------------------------------------------------------------------

def _block_counts(active, BP: int, Pp: int):
    """[1, Pp//BP] i32 per-block active-lane counts (prefix sums over the
    compacted alive mask, differenced per block — computed as one padded
    reshape-reduce)."""
    a = jnp.pad(jnp.asarray(active).astype(jnp.int32),
                (0, Pp - active.shape[0]))
    return jnp.sum(a.reshape(Pp // BP, BP), -1)[None]


_CNT_SPEC = pl.BlockSpec((1, 1), lambda i: (0, i))


def _when_active(cnt_ref, compute, zero):
    """Run ``compute`` when the block has any active lane, else ``zero``
    (the cheap output fill).  ``cnt_ref is None`` means unguarded."""
    if cnt_ref is None:
        compute()
        return

    @pl.when(cnt_ref[0, 0] > 0)
    def _():
        compute()

    @pl.when(cnt_ref[0, 0] == 0)
    def _():
        zero()


def _zero_refs(*refs):
    for r in refs:
        r[...] = jnp.zeros(r.shape, r.dtype)


def _cd_block(G_ref, c_ref, diag_ref, mask_ref, *refs, iters, alpha,
              n_coefs, guarded=False):
    """One pixel block: full CD loop in VMEM.

    G [K,K,Pb], c [B,K,Pb], diag [K,Pb], mask [K,Pb] (0/1) -> b [B,K,Pb].
    """
    cnt_ref, out_ref = (refs if guarded else (None,) + refs)
    G = G_ref[...]
    c = c_ref[...]
    diag = diag_ref[...]
    mask = mask_ref[...]

    def one_iter(_, b):
        for j in range(n_coefs):
            # rho_j = c_j - sum_k G[j,k] b_k + diag_j b_j   (all [B,Pb])
            rho = (c[:, j] - jnp.sum(G[j][None, :, :] * b, axis=1)
                   + diag[j][None, :] * b[:, j])
            if j == 0:                       # intercept: unpenalized
                bj = rho / diag[0][None, :]
            else:
                bj = (jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - alpha, 0.0)
                      / diag[j][None, :])
            bj = jnp.where(mask[j][None, :] > 0, bj, 0.0)
            # one-hot select, not b.at[:, j].set: scatter has no Mosaic
            # lowering, and j is static so a select is exact.  The iota
            # must be >=2D (Mosaic has no 1D iota) and traced (pallas_call
            # rejects captured array constants).
            sel = lax.broadcasted_iota(jnp.int32, (1, n_coefs, 1), 1) == j
            b = jnp.where(sel, bj[:, None, :], b)
        return b

    def compute():
        out_ref[...] = lax.fori_loop(0, iters, one_iter, jnp.zeros_like(c))

    # A dead block's lanes all carry zero-weight systems (c == 0), whose
    # CD output is exactly zero — the fill matches the computed values.
    _when_active(cnt_ref, compute, lambda: _zero_refs(out_ref))


@functools.partial(jax.jit, static_argnames=("iters", "interpret"))
def lasso_cd(G, c, diag, coefmask, *, iters=params.LASSO_ITERS,
             active=None, interpret=False):
    """Pallas port of kernel's CD loop (bit-compatible update order).

    Args:
        G: [P, K, K] normalized Gram matrices.
        c: [P, B, K] normalized X^T y per band.
        diag: [P, K] Gram diagonals (pre-floored).
        coefmask: [P, K] allowed coefficients (bool or 0/1).
        active: optional [P] bool skip guard — lanes outside it must
            carry zero-weight systems (see module note).
    Returns:
        b [P, B, K], identical (up to float assoc.) to the lax fori_loop
        version in kernel._fit_lasso_coefs.
    """
    P, B, K = c.shape
    dt = c.dtype
    Pp = -BLOCK_P * (-P // BLOCK_P)
    pad = Pp - P

    # Pixel axis last; pad to the block multiple (diag pads to 1 so the
    # padded lanes divide harmlessly; mask pads to 0 so they output 0).
    Gt = jnp.pad(G.transpose(1, 2, 0), ((0, 0), (0, 0), (0, pad)))
    ct = jnp.pad(c.transpose(1, 2, 0), ((0, 0), (0, 0), (0, pad)))
    dg = jnp.pad(diag.T, ((0, 0), (0, pad)), constant_values=1.0)
    mk = jnp.pad(coefmask.T.astype(dt), ((0, 0), (0, pad)))

    args = [Gt, ct, dg, mk]
    in_specs = [
        pl.BlockSpec((K, K, BLOCK_P), lambda i: (0, 0, i)),
        pl.BlockSpec((B, K, BLOCK_P), lambda i: (0, 0, i)),
        pl.BlockSpec((K, BLOCK_P), lambda i: (0, i)),
        pl.BlockSpec((K, BLOCK_P), lambda i: (0, i)),
    ]
    if active is not None:
        args.append(_block_counts(active, BLOCK_P, Pp))
        in_specs.append(_CNT_SPEC)
    kern = functools.partial(_cd_block, iters=iters,
                             alpha=float(params.LASSO_ALPHA), n_coefs=K,
                             guarded=active is not None)
    bt = pl.pallas_call(
        kern,
        grid=(Pp // BLOCK_P,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B, K, BLOCK_P), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((B, K, Pp), dt),
        interpret=interpret,
    )(*args)
    return bt[:, :, :P].transpose(2, 0, 1)


# ---------------------------------------------------------------------------
# Fused Lasso fit kernel (Gram + corr + CD + RMSE)
# ---------------------------------------------------------------------------

def fit_block_p(T: int, B: int, y_bytes: int) -> int:
    """Lane-block width for the fit kernel: the [B, T, BP] spectra block
    plus ~4 live [T, BP] f32 planes dominate the footprint."""
    budget = 10 * 2 ** 20
    per_lane = max(T, 1) * (B * y_bytes + 4 * 4)
    return max(128, min(512, (budget // per_lane) // 128 * 128))


def _gram_cd_core(XT, XXT, y_of, wb, mask, *, B, K, iters, alpha,
                  mixed=False):
    """Gram + corr + CD loop on VMEM-resident planes — the exact
    kernel._fit_lasso_coefs math (same normalization, update order,
    unpenalized intercept), shared by the fused fit kernel and the
    INIT-window kernel.

    XT [K,T], XXT [K*K,T] (chip-shared), ``y_of(b)`` -> [T,BP] f32 band
    plane, wb [T,BP] 0/1 weights.  ``mask`` is always a [K,BP] runtime
    array of allowed-coefficient 0/1 flags — per-pixel counts at the fit
    call sites, and the INIT stability fit's fixed 4-coef model as an
    iota-built comparison (cm4 in _init_logic).  Even a fixed model
    must arrive that way, never as a constant-folded array literal:
    Mosaic's ApplyVectorLayoutPass dies on the folded sublane-slice
    pattern ("Check failed: limits[i] <= dim(i) (4 vs. 1)", real-v5e
    remote compiler, bisected r5).  Returns (beta [B,K,BP], n [1,BP]).

    ``mixed`` (FIREBIRD_MIXED_PRECISION) swaps the Gram/corr dots — the
    only MXU work here, which default_matmul_precision("highest") runs
    as SIX bf16 passes each on TPU — for hi/lo bf16 split dots with f32
    accumulators (preferred_element_type) and an int32 window count:

      * ``wb`` is exactly 0/1, so its bf16 image is EXACT and the Gram
        needs only the XXT split: 2 passes instead of 6.
      * ``y*wb`` is int16-valued (the wire spectra are int16; PR 11), so
        its hi/lo split is EXACT (hi captures the top 8 significand
        bits, the residual is an integer < 2^8 — bf16-representable);
        dropping only the lo·lo cross term leaves 3 passes with a
        ~2^-17 relative error vs "highest"'s ~2^-24 — inside the pinned
        ulp budget (params.MIXED_ULP_BUDGET) and empirically
        decision-identical (tools/precision_smoke.py, tests/test_fuse).
      * the count n is an exact int32 sum of 0/1 weights.

    Everything downstream of the dots — diag floors, the CD loop, and
    every consumer (RMSE predictions, monitor scores, chi2 thresholds,
    the close-median) — stays f32: the decision envelope.
    """
    f32 = wb.dtype
    if mixed:
        ni = jnp.sum(wb.astype(jnp.int32), 0, keepdims=True)  # exact count
        n = jnp.maximum(ni, 1).astype(f32)                    # [1, BP]
        wh = wb.astype(jnp.bfloat16)                          # exact 0/1
        xxh, xxl = _split_bf16(XXT)
        G = (jnp.dot(xxh, wh, preferred_element_type=f32)
             + jnp.dot(xxl, wh, preferred_element_type=f32)) / n
    else:
        n = jnp.maximum(jnp.sum(wb, 0, keepdims=True), 1.0)   # [1, BP]
        G = jnp.dot(XXT, wb, preferred_element_type=f32) / n  # [K*K, BP]
    diag = jnp.maximum(
        jnp.concatenate([G[j * K + j][None] for j in range(K)], 0), 1e-12)

    if mixed:
        th, tl = _split_bf16(XT)
        cs = []
        for bb in range(B):
            yh, yl = _split_bf16(y_of(bb) * wb)               # exact split
            cs.append((jnp.dot(th, yh, preferred_element_type=f32)
                       + jnp.dot(th, yl, preferred_element_type=f32)
                       + jnp.dot(tl, yh, preferred_element_type=f32)) / n)
    else:
        cs = [jnp.dot(XT, y_of(bb) * wb, preferred_element_type=f32) / n
              for bb in range(B)]                             # B x [K, BP]

    # Mosaic legality (real-v5e remote compiler, r5): any 3D [B,K,BP] op
    # whose lowering touches the tiled sublane (K) axis — vector.extract
    # c[:, j], one-hot selects over K, and axis-1 reductions — dies in
    # ApplyVectorLayoutPass ("Check failed: limits[i] <= dim(i)").  This
    # core is shared by EVERY fit call site — the INIT-window kernel and
    # the mega block inline it, and the fit component's _fit_block wraps
    # it — so the 2D-column-plane discipline below is the contract for
    # all of them, not an inlining workaround.  The CD state lives as a
    # python list of K 2D [B,BP] column planes: the Gauss-Seidel update
    # reads rows via strided slices, the column write is a free
    # trace-time list rebind, and the iteration loop is python-unrolled
    # (no scf.for region for the pass to walk).
    c_cols = [jnp.concatenate([cs[bb][j:j + 1] for bb in range(B)], 0)
              for j in range(K)]                              # K x [B, BP]
    G_rows = [[G[j * K + k:j * K + k + 1] for k in range(K)]
              for j in range(K)]                              # [1, BP] each
    b_cols = [jnp.zeros_like(c_cols[0]) for _ in range(K)]
    for _ in range(iters):
        for j in range(K):
            acc = G_rows[j][0] * b_cols[0]
            for k in range(1, K):
                acc = acc + G_rows[j][k] * b_cols[k]
            rho = c_cols[j] - acc + diag[j:j + 1] * b_cols[j]
            if j == 0:
                bj = rho / diag[0:1]
            else:
                bj = (jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - alpha, 0.0)
                      / diag[j:j + 1])
            b_cols[j] = jnp.where(mask[j:j + 1] > 0, bj, 0.0)
    beta = jnp.concatenate(
        [jnp.concatenate([b_cols[j][bb:bb + 1] for j in range(K)],
                         0)[None] for bb in range(B)], 0)     # [B, K, BP]
    return beta, n


def _fit_block(x_ref, xt_ref, xxt_ref, y_ref, w_ref, mask_ref, *refs,
               B, K, iters, alpha, with_rmse, mixed=False, guarded=False):
    """One pixel block: Gram/corr builds, the full CD loop, and the
    weighted-window RMSE, all in VMEM.

    x [T,K], xt [K,T], xxt [K*K,T] (chip-shared designs), y [B,T,BP]
    (wire dtype — int16 widens in-register, exactly), w [T,BP] 0/1,
    mask [K,BP] -> b [B,K,BP], rmse [B,BP].

    Mirrors kernel._fit_lasso exactly: Gram and corr divided by the
    window count before the CD loop, same update order, intercept
    unpenalized, rmse over the same weighted window.
    """
    cnt_ref, b_ref, r_ref = (refs if guarded else (None,) + refs)

    def compute():
        X = x_ref[...]
        wb = w_ref[...]                                       # [T, BP]
        f32 = wb.dtype
        y_of = lambda bb: y_ref[bb].astype(f32)
        beta, n = _gram_cd_core(xt_ref[...], xxt_ref[...], y_of, wb,
                                mask_ref[...], B=B, K=K, iters=iters,
                                alpha=alpha, mixed=mixed)
        b_ref[...] = beta

        if with_rmse:
            rs = []
            for bb in range(B):
                pred = jnp.dot(X, beta[bb], preferred_element_type=f32)
                r = y_of(bb) - pred
                rs.append(jnp.sqrt(jnp.maximum(
                    jnp.sum(r * r * wb, 0, keepdims=True) / n, 0.0)))
            r_ref[...] = jnp.concatenate(rs, 0)               # [B, BP]
        else:
            r_ref[...] = jnp.zeros(r_ref.shape, r_ref.dtype)

    # A dead block's lanes carry all-zero windows: Gram/corr are zero,
    # the CD output is zero, and the zero-window RMSE is zero — the fill
    # is the exact computed value, not an approximation.
    _when_active(cnt_ref, compute, lambda: _zero_refs(b_ref, r_ref))


@functools.partial(jax.jit, static_argnames=("with_rmse", "mixed",
                                             "interpret"))
def lasso_fit(Yt, w, X, coefmask, *, with_rmse=True, mixed=False,
              active=None, interpret=False):
    """Fused Pallas twin of kernel._fit_lasso / _fit_lasso_coefs.

    Under plain XLA the fit path materializes the [P,B,T] ``Y*w`` product
    around each corr dot and re-reads the widened float spectra; this
    kernel streams the *wire-dtype* resident spectra once per block and
    keeps every intermediate (Gram, corr, CD state, predictions) in VMEM.

    Args:
        Yt: [B, T, P] resident spectra — wire int16 (widened in-register,
            exact) or float32.
        w: [P, T] 0/1 fit-window weights (float).
        X: [T, K] design (chip-shared).
        coefmask: [P, K] allowed coefficients.
        mixed: FIREBIRD_MIXED_PRECISION — bf16 split-dot Gram/corr with
            f32 accumulation + int32 counts (see _gram_cd_core); the CD
            loop and RMSE stay f32.
        active: optional [P] bool skip guard — inactive lanes must carry
            all-zero windows (see module note).
    Returns:
        (coefs [P, B, K], rmse [P, B]) — rmse is zeros when
        ``with_rmse=False``.
    """
    B, T, P = Yt.shape
    K = X.shape[-1]
    f32 = w.dtype
    BP = fit_block_p(T, B, Yt.dtype.itemsize)
    Pp = -BP * (-P // BP)
    pad = Pp - P

    XT = X.T                                                  # [K, T]
    XXT = (X[:, :, None] * X[:, None, :]).reshape(T, K * K).T  # [K*K, T]
    yp = jnp.pad(Yt, ((0, 0), (0, 0), (0, pad)))
    wp = jnp.pad(w.T, ((0, 0), (0, pad)))
    mk = jnp.pad(coefmask.T.astype(f32), ((0, 0), (0, pad)))

    args = [X.astype(f32), XT.astype(f32), XXT.astype(f32), yp, wp, mk]
    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    in_specs = [
        full((T, K)), full((K, T)), full((K * K, T)),
        pl.BlockSpec((B, T, BP), lambda i: (0, 0, i)),
        pl.BlockSpec((T, BP), lambda i: (0, i)),
        pl.BlockSpec((K, BP), lambda i: (0, i)),
    ]
    if active is not None:
        args.append(_block_counts(active, BP, Pp))
        in_specs.append(_CNT_SPEC)
    kern = functools.partial(_fit_block, B=B, K=K,
                             iters=int(params.LASSO_ITERS),
                             alpha=float(params.LASSO_ALPHA),
                             with_rmse=bool(with_rmse), mixed=bool(mixed),
                             guarded=active is not None)
    beta, rmse = pl.pallas_call(
        kern,
        grid=(Pp // BP,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((B, K, BP), lambda i: (0, 0, i)),
                   pl.BlockSpec((B, BP), lambda i: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((B, K, Pp), f32),
                   jax.ShapeDtypeStruct((B, Pp), f32)],
        interpret=interpret,
    )(*args)
    return beta[:, :, :P].transpose(2, 0, 1), rmse[:, :P].T


# ---------------------------------------------------------------------------
# MONITOR event-chain kernel
# ---------------------------------------------------------------------------

def mon_block_p(T: int) -> int:
    """Lane-block width for the monitor kernel, derived from T.

    The kernel keeps ~12 [T, BP] planes live (inputs + scan temporaries),
    so its VMEM footprint is linear in T; a fixed 512-lane block that fits
    a bucketed 512-obs archive would blow VMEM on a multi-decade T~1800
    series.  Budget ~10 MB of VMEM for the planes (leaving room for the
    pipeline's double-buffered input blocks) and round down to the 128
    lane width, floored at one lane tile.
    """
    budget = 10 * 2 ** 20
    per_lane = 12 * max(T, 1) * 4
    return max(128, min(512, (budget // per_lane) // 128 * 128))


def _shift_scan_min_rev(x, T, fill):
    """Reverse cummin along axis 0 (sublanes) as a log-step shift-min."""
    k = 1
    while k < T:
        pad = jnp.full((k,) + x.shape[1:], fill, x.dtype)
        x = jnp.minimum(x, jnp.concatenate([x[k:], pad], axis=0))
        k *= 2
    return x


def _shift_scan_add(x, T):
    """Inclusive cumsum along axis 0 (sublanes) as a log-step shift-add."""
    k = 1
    while k < T:
        pad = jnp.zeros((k,) + x.shape[1:], x.dtype)
        x = x + jnp.concatenate([pad, x[:T - k]], axis=0)
        k *= 2
    return x


def _pad_helpers(pad):
    """(plane, vec) input builders shared by the monitor wrappers:
    transpose to [T, P] / [1, P] layout and pad the lane axis."""
    plane = lambda x, cv=0: jnp.pad(
        jnp.asarray(x).T, ((0, 0), (0, pad)), constant_values=cv)
    vec = lambda x, cv=0: jnp.pad(
        jnp.asarray(x)[None, :], ((0, 0), (0, pad)), constant_values=cv)
    return plane, vec


def _mon_outs_to_dict(outs, P):
    """Unpack the 10 monitor-kernel outputs (kernel._monitor_chain
    contract) — shared by both wrappers so the two FIREBIRD_PALLAS paths
    cannot diverge on the output assembly."""
    m, istail, isbrk, isrefit, evrank, posev, nexc, nrf, incq, remq = outs
    cut = lambda x: x[0, :P]
    cutb = lambda x: x[0, :P] > 0
    return dict(m=cut(m), is_tail=cutb(istail), is_brk=cutb(isbrk),
                is_refit=cutb(isrefit), ev_rank=cut(evrank),
                pos_ev=cut(posev), n_exceed=cut(nexc), n_rf=cut(nrf),
                inc_q=(incq[:, :P] > 0).T, rem_q=(remq[:, :P] > 0).T)


def _monitor_logic(s, alive, included, rank, cur_k, nlast, in_mon, *,
                   change_thr, outlier_thr, peek, refit_factor, T):
    """The MONITOR event logic on VMEM-resident planes, shared by the
    plain and score-fused blocks.

    Planes are [T, Pb] (T on sublanes, pixels on lanes); per-pixel vectors
    are [1, Pb].  Mirrors the jnp reference op for op — argmax becomes a
    first-index min-reduce with the same no-hit default (0), and the
    rank/count lookups become one-hot reduces (no gather in Mosaic).
    Returns the 10 output planes/vectors in kernel._monitor_chain order.
    """
    INF = jnp.int32(T + 1)
    ti = lax.broadcasted_iota(jnp.int32, s.shape, 0)          # [T,Pb]
    one = jnp.int32(1)
    m = jnp.sum(jnp.where(alive, one, 0), 0, keepdims=True)   # [1,Pb]
    kq = jnp.sum(jnp.where(alive & (ti < cur_k), one, 0), 0, keepdims=True)

    ex = alive & (s > change_thr)
    reset_r = jnp.where(alive & ~ex, rank, INF)
    nrr = _shift_scan_min_rev(reset_r, T, T + 1)
    runlen = jnp.minimum(nrr, m) - rank
    elig = alive & (rank >= kq)
    brk = elig & ex & (runlen >= peek)
    has_brk = jnp.any(brk, 0, keepdims=True)
    b_abs = jnp.where(has_brk,
                      jnp.min(jnp.where(brk, ti, INF), 0, keepdims=True), 0)

    o = s > outlier_thr
    absq = elig & ~o
    n0 = jnp.sum(jnp.where(included, one, 0), 0, keepdims=True)
    n_inc = n0 + _shift_scan_add(jnp.where(absq, one, 0), T)
    refit_hit = absq & (n_inc.astype(s.dtype)
                        >= refit_factor * nlast.astype(s.dtype))
    has_refit = jnp.any(refit_hit, 0, keepdims=True)
    f_abs = jnp.where(
        has_refit,
        jnp.min(jnp.where(refit_hit, ti, INF), 0, keepdims=True), 0)

    q_tail = jnp.maximum(m - (peek - 1), kq)

    def at_idx(plane, idx):
        return jnp.sum(jnp.where(ti == idx, plane, 0), 0, keepdims=True)

    b_ev = jnp.where(has_brk, at_idx(rank, b_abs), INF)
    f_ev = jnp.where(has_refit, at_idx(rank, f_abs), INF)
    is_tail = in_mon & (q_tail <= jnp.minimum(b_ev, f_ev))
    is_brk = in_mon & ~is_tail & has_brk & (b_ev <= f_ev)
    is_refit = in_mon & ~is_tail & ~is_brk & has_refit

    ev_rank = jnp.where(is_tail, q_tail, jnp.where(is_brk, b_ev, f_ev))
    normal_hi = jnp.where(is_refit, ev_rank + 1, ev_rank)
    normalq = elig & (rank < normal_hi)
    inc_q = normalq & ~o
    rem_q = normalq & o
    tailq = elig & (rank >= q_tail) & is_tail
    tail_ex = tailq & (s > change_thr)
    inc_q = inc_q | (tailq & ~tail_ex)
    rem_q = rem_q | tail_ex
    n_exceed = jnp.sum(jnp.where(tail_ex, one, 0), 0, keepdims=True)
    pos_ev = jnp.where(is_brk, b_abs, f_abs)
    n_rf = at_idx(n_inc, pos_ev)

    as_i = lambda b: jnp.where(b, one, 0)
    return (m, as_i(is_tail), as_i(is_brk), as_i(is_refit), ev_rank,
            pos_ev, n_exceed, n_rf, as_i(inc_q), as_i(rem_q))


def _monitor_block(s_ref, alive_ref, inc_ref, rank_ref, curk_ref, nlast_ref,
                   inmon_ref, *refs, change_thr, outlier_thr, peek,
                   refit_factor, T, guarded=False):
    """One pixel block of kernel._monitor_chain, everything in VMEM."""
    cnt_ref, out_refs = ((refs[0], refs[1:]) if guarded
                         else (None, refs))

    def compute():
        outs = _monitor_logic(
            s_ref[...], alive_ref[...] > 0, inc_ref[...] > 0, rank_ref[...],
            curk_ref[...], nlast_ref[...], inmon_ref[...] > 0,
            change_thr=change_thr, outlier_thr=outlier_thr, peek=peek,
            refit_factor=refit_factor, T=T)
        for ref, val in zip(out_refs, outs):
            # x64 mode promotes index arithmetic to int64; ref stores
            # don't auto-cast in interpret mode, so land at the ref's
            # dtype.
            ref[...] = val.astype(ref.dtype)

    # An all-inactive block (no in_mon lane) has every consumer of its
    # outputs masked on in_mon downstream (kernel._mon_block): zeros are
    # inert, same as _mon_zeros' skip branch.
    _when_active(cnt_ref, compute, lambda: _zero_refs(*out_refs))


def _mon_scored_logic(yd_of, coefs_d, dden, X, alive, included, cur_k,
                      nlast, in_mon, *, change_thr, outlier_thr, peek,
                      refit_factor, T, nb):
    """Score + shared event logic on VMEM-resident planes — used by the
    scored monitor block and the whole-loop mega kernel.

    ``yd_of(b)`` -> [T,BP] detection-band plane (wire dtype), coefs_d
    [nb,K,BP], dden [nb,BP], X [T,K], alive/included [T,BP] bool, cur_k/
    nlast [1,BP] i32, in_mon [1,BP] bool.  Returns the 10 outputs of
    kernel._monitor_chain order (i32 planes/vectors).
    """
    f32 = X.dtype
    s = None
    for b in range(nb):
        pred = jnp.dot(X, coefs_d[b], preferred_element_type=f32)
        r = (yd_of(b).astype(f32) - pred) / dden[b][None, :]
        s = r * r if s is None else s + r * r                 # [T, BP]

    rank = _shift_scan_add(jnp.where(alive, jnp.int32(1), 0), T) - 1
    return _monitor_logic(
        s, alive, included, rank, cur_k, nlast, in_mon,
        change_thr=change_thr, outlier_thr=outlier_thr, peek=peek,
        refit_factor=refit_factor, T=T)


def _monitor_scored_block(yd_ref, coef_ref, dden_ref, x_ref, alive_ref,
                          inc_ref, curk_ref, nlast_ref, inmon_ref,
                          *refs, change_thr, outlier_thr, peek,
                          refit_factor, T, nb, guarded=False):
    """Score-fused monitor block: compute the chi2 score plane s — the
    detection-band predictions against the current model — *inside* VMEM
    from wire-dtype spectra, then run the shared event logic.

    Replaces the XLA path's [P,nb,T] prediction einsum + [P,T] score and
    rank materializations (the dominant HBM terms of a steady-state
    monitor round now that the INIT block is cond-gated): spectra stream
    once as int16, predictions are one [T,K]x[K,BP] MXU dot per band,
    and rank is a log-step shift-add over the alive plane.
    """
    cnt_ref, out_refs = ((refs[0], refs[1:]) if guarded
                         else (None, refs))

    def compute():
        outs = _mon_scored_logic(
            lambda b: yd_ref[b], coef_ref[...], dden_ref[...], x_ref[...],
            alive_ref[...] > 0, inc_ref[...] > 0, curk_ref[...],
            nlast_ref[...], inmon_ref[...] > 0, change_thr=change_thr,
            outlier_thr=outlier_thr, peek=peek, refit_factor=refit_factor,
            T=T, nb=nb)
        for ref, val in zip(out_refs, outs):
            ref[...] = val.astype(ref.dtype)   # see _monitor_block

    _when_active(cnt_ref, compute, lambda: _zero_refs(*out_refs))


@functools.partial(jax.jit, static_argnames=("change_thr", "outlier_thr",
                                             "interpret"))
def monitor_chain(s, alive, included, rank, cur_k, n_last_fit, in_mon, *,
                  change_thr, outlier_thr, active=None, interpret=False):
    """Pallas port of kernel._monitor_chain (same output contract).

    Values are identical for every lane the caller uses: argmax' no-hit
    default (0), the INF sentinels, and the normal/tail partition all
    mirror the jnp reference exactly; the only arithmetic is integer.
    ``active`` (normally in_mon) is the per-block skip guard.
    """
    P, T = s.shape
    BP = mon_block_p(T)
    Pp = -BP * (-P // BP)
    pad = Pp - P
    plane, vec = _pad_helpers(pad)

    i32 = jnp.int32
    args = [plane(s), plane(alive.astype(i32)), plane(included.astype(i32)),
            plane(rank.astype(i32)), vec(cur_k.astype(i32)),
            vec(n_last_fit.astype(i32), 1), vec(in_mon.astype(i32))]
    pspec = pl.BlockSpec((T, BP), lambda i: (0, i))
    vspec = pl.BlockSpec((1, BP), lambda i: (0, i))
    in_specs = [pspec, pspec, pspec, pspec, vspec, vspec, vspec]
    if active is not None:
        args.append(_block_counts(active, BP, Pp))
        in_specs.append(_CNT_SPEC)
    kern = functools.partial(_monitor_block, change_thr=float(change_thr),
                             outlier_thr=float(outlier_thr),
                             peek=int(params.PEEK_SIZE),
                             refit_factor=float(params.REFIT_FACTOR), T=T,
                             guarded=active is not None)
    vshape = jax.ShapeDtypeStruct((1, Pp), i32)
    pshape = jax.ShapeDtypeStruct((T, Pp), i32)
    outs = pl.pallas_call(
        kern,
        grid=(Pp // BP,),
        in_specs=in_specs,
        out_specs=[vspec] * 8 + [pspec] * 2,
        out_shape=[vshape] * 8 + [pshape] * 2,
        interpret=interpret,
    )(*args)
    return _mon_outs_to_dict(outs, P)


def scored_block_p(T: int, nb: int, y_bytes: int) -> int:
    """Lane-block width for the score-fused monitor kernel: the monitor
    planes (~12 [T, BP] f32) plus the [nb, T, BP] wire-dtype spectra
    block and the live score/pred temporaries."""
    budget = 10 * 2 ** 20
    per_lane = max(T, 1) * (14 * 4 + nb * y_bytes)
    return max(128, min(512, (budget // per_lane) // 128 * 128))


@functools.partial(jax.jit, static_argnames=("change_thr", "outlier_thr",
                                             "interpret"))
def monitor_chain_scored(Yd, coefs_d, dden, X, alive, included, cur_k,
                         n_last_fit, in_mon, *, change_thr, outlier_thr,
                         active=None, interpret=False):
    """Score-fused Pallas twin of kernel._mon_block's score + chain.

    Args:
        Yd: [nb, T, P] detection-band resident spectra (wire int16 or
            float32; widened in-register, exact).
        coefs_d: [P, nb, K] current model coefficients (detection bands).
        dden: [P, nb] score denominators (max(rmse, vario), detection).
        X: [T, K] design (chip-shared).
        alive, included: [P, T] bool planes.
        cur_k, n_last_fit: [P] int; in_mon: [P] bool.
        active: optional [P] bool per-block skip guard (normally in_mon).
    Returns:
        The kernel._monitor_chain output dict (same contract); rank is
        derived in-kernel from the alive plane.
    """
    nb, T, P = Yd.shape
    K = X.shape[-1]
    f32 = X.dtype
    BP = scored_block_p(T, nb, Yd.dtype.itemsize)
    Pp = -BP * (-P // BP)
    pad = Pp - P
    i32 = jnp.int32

    plane, vec = _pad_helpers(pad)
    yp = jnp.pad(Yd, ((0, 0), (0, 0), (0, pad)))
    cf = jnp.pad(coefs_d.transpose(1, 2, 0), ((0, 0), (0, 0), (0, pad)))
    dd = jnp.pad(dden.T, ((0, 0), (0, pad)), constant_values=1.0)

    kern = functools.partial(
        _monitor_scored_block, change_thr=float(change_thr),
        outlier_thr=float(outlier_thr), peek=int(params.PEEK_SIZE),
        refit_factor=float(params.REFIT_FACTOR), T=T, nb=nb,
        guarded=active is not None)
    pspec = pl.BlockSpec((T, BP), lambda i: (0, i))
    vspec = pl.BlockSpec((1, BP), lambda i: (0, i))
    args = [yp, cf.astype(f32), dd.astype(f32), X,
            plane(alive.astype(i32)), plane(included.astype(i32)),
            vec(cur_k.astype(i32)), vec(n_last_fit.astype(i32), 1),
            vec(in_mon.astype(i32))]
    in_specs = [
        pl.BlockSpec((nb, T, BP), lambda i: (0, 0, i)),
        pl.BlockSpec((nb, K, BP), lambda i: (0, 0, i)),
        pl.BlockSpec((nb, BP), lambda i: (0, i)),
        pl.BlockSpec((T, K), lambda i: (0, 0)),
        pspec, pspec, vspec, vspec, vspec,
    ]
    if active is not None:
        args.append(_block_counts(active, BP, Pp))
        in_specs.append(_CNT_SPEC)
    outs = pl.pallas_call(
        kern,
        grid=(Pp // BP,),
        in_specs=in_specs,
        out_specs=[vspec] * 8 + [pspec] * 2,
        out_shape=[jax.ShapeDtypeStruct((1, Pp), i32)] * 8
        + [jax.ShapeDtypeStruct((T, Pp), i32)] * 2,
        interpret=interpret,
    )(*args)
    return _mon_outs_to_dict(outs, P)


# ---------------------------------------------------------------------------
# Fused INIT-window kernel
# ---------------------------------------------------------------------------

def init_block_p(T: int, W: int, B: int, y_bytes: int) -> int:
    """Lane-block width for the INIT kernel: the [B,T,BP] wire spectra,
    ~8 live [T,BP] planes, and ~50 [W,BP] window/IRLS planes."""
    budget = 10 * 2 ** 20
    per_lane = max(T, 1) * (B * y_bytes + 8 * 4) + max(W, 1) * 50 * 4
    return max(128, min(512, (budget // per_lane) // 128 * 128))


def _first_ge(mask, ti, T):
    """(exists [1,BP], index [1,BP]) of the first True row in mask [T,BP]
    — argmax semantics (index 0 when none)."""
    INF = jnp.int32(T + 1)
    ex = jnp.any(mask, 0, keepdims=True)
    idx = jnp.min(jnp.where(mask, ti, INF), 0, keepdims=True)
    return ex, jnp.where(ex, idx, 0)


def _init_logic(alive, cur_i, in_init, t_col, X, Xtr, XTK, XXT, y_of,
                vario, *, T, W, B, K, NT, n_pow, det, tmb, cd_iters,
                alpha, tm_iters, huber_k, tmask_const, meow, init_days,
                stab_factor, mixed=False):
    """The INIT-phase round work on VMEM-resident planes — shared by the
    standalone init_window kernel and the whole-loop mega kernel.

    Replaces the XLA path's [P,W,T] one-hot window tensors (the peak
    memory of a dispatch and the dominant bytes of an INIT round) with
    per-slot one-hot reduces over T — exact: each window slot selects
    exactly one observation, so the selection sums have a single nonzero
    term.  The stability c4 fit reuses the fused fit kernel's Gram/CD
    math over the full T axis (bit-aligned with the 'fit' component);
    the Tmask IRLS reuses the tmask kernel's core over the compacted
    window.

    alive [T,BP] bool, cur_i [1,BP] i32, in_init [1,BP] bool,
    t_col [T,1] f32, X [T,K], Xtr [T,NT], XTK [K,T], XXT [K*K,T],
    ``y_of(b)`` -> [T,BP] wire-dtype band plane, vario [B,BP].
    Returns a dict of value planes (bools stay bool).

    Program-size note (r2 advice): the per-slot unrolls scale this body
    at ~124 jaxpr eqns per window slot over a ~7.2k W-independent base
    (measured: 10.2k eqns at W=24, 21.2k at W=112).  A fori_loop with
    dynamic_update_slice rows would flatten the W term if Mosaic compile
    time proves excessive at production W — deferred until a real-TPU
    compile-time measurement exists, since the rewrite carries parity
    risk and the persistent compile cache amortizes whatever the cost
    is across sessions.
    """
    i32 = jnp.int32
    f32 = t_col.dtype
    ti = lax.broadcasted_iota(i32, alive.shape, 0)

    def at_t(plane, idx):
        # plane [T, *] one-hot-selected at row idx [1, BP] -> [1, BP]
        return jnp.sum(jnp.where(ti == idx, plane, 0), 0, keepdims=True)

    # ---- window search (kernel._init_block: has_i/i/j/w_init) ----
    has_i, i = _first_ge(alive & (ti >= cur_i), ti, T)
    t_i = at_t(jnp.broadcast_to(t_col, alive.shape), i)       # [1, BP]
    one = i32(1)
    Acum = _shift_scan_add(jnp.where(alive, one, 0), T)       # [T, BP]
    A_before = at_t(Acum, i) - at_t(jnp.where(alive, one, 0), i)
    cnt = Acum - A_before
    okj = alive & (ti >= i) & (cnt >= meow) \
        & (jnp.broadcast_to(t_col, alive.shape) - t_i >= init_days)
    has_w_raw, j = _first_ge(okj, ti, T)
    has_w = has_i & has_w_raw
    w_init = alive & (ti >= i) & (ti <= j) & (has_w & in_init)
    n_win = jnp.sum(jnp.where(w_init, one, 0), 0, keepdims=True)
    rank = Acum - 1
    rel_w = rank - A_before                                   # [T, BP]

    # ---- window member selection (exact one-hot sums) ----
    Xcat = jnp.concatenate([X, Xtr], axis=1)                  # [T, K+NT]
    Yf = [y_of(b) for b in range(B)]                          # B x [T, BP]
    Yw = [[] for _ in range(B)]
    Xw = [[] for _ in range(K + NT)]
    for w in range(W):
        mf = jnp.where(alive & (rel_w == w), 1.0, 0.0).astype(f32)
        for b in range(B):
            Yw[b].append(jnp.sum(Yf[b] * mf, 0, keepdims=True))
        for c in range(K + NT):
            Xw[c].append(jnp.sum(Xcat[:, c:c + 1] * mf, 0, keepdims=True))
    Yw = [jnp.concatenate(v, 0) for v in Yw]                  # B x [W, BP]
    Xw = [jnp.concatenate(v, 0) for v in Xw]                  # K+NT x [W, BP]

    wi = lax.broadcasted_iota(i32, (W,) + alive.shape[1:], 0)
    valid_w = (wi < n_win)                                    # [W, BP]

    # ---- Tmask IRLS over the compacted window ----
    bad_w = _tmask_core([Xw[K + c] for c in range(NT)],
                        [Yw[b] for b in tmb],
                        jnp.where(valid_w, 1.0, 0.0).astype(f32),
                        jnp.concatenate([vario[b][None] for b in tmb], 0),
                        nt=NT, nb=len(tmb), n_pow=n_pow, iters=tm_iters,
                        huber_k=huber_k, tmask_const=tmask_const)
    tm_removed = jnp.any(bad_w, 0, keepdims=True)             # [1, BP]
    bad_abs = jnp.zeros_like(alive)
    for w in range(W):
        bad_abs = bad_abs | (alive & (rel_w == w) & bad_w[w:w + 1])

    # ---- stability: c4 fit (fit-kernel math over T) + window resid ----
    w_stab = w_init & ~tm_removed                             # [T, BP]
    cm4 = jnp.where(
        lax.broadcasted_iota(i32, (K,) + alive.shape[1:], 0) < 4,
        1.0, 0.0).astype(f32)
    c4, _ = _gram_cd_core(XTK, XXT, lambda b: Yf[b],
                          jnp.where(w_stab, 1.0, 0.0).astype(f32), cm4,
                          B=B, K=K, iters=cd_iters, alpha=alpha,
                          mixed=mixed)
    stab_w = valid_w & ~bad_w
    stab_f = jnp.where(stab_w, 1.0, 0.0).astype(f32)
    n4 = jnp.maximum(jnp.sum(stab_f, 0, keepdims=True), 1.0)
    t_j = at_t(jnp.broadcast_to(t_col, alive.shape), j)
    span = t_j - t_i                                          # [1, BP]
    last_i = jnp.maximum(n_win - 1, 0)                        # [1, BP]
    # Coefficient rows via strided slices (c4[b, c:c+1]), never the
    # multi-index extract c4[b, c]: a vector.extract whose second index
    # lands in the tiled sublane dim of a 3D vector crashes Mosaic's
    # vector layout pass (Check failed: limits[i] <= dim(i), real v5e
    # remote compiler, r5).  Same rule applies in _close_logic.
    stable = None
    for b in range(B):
        pred = None
        for c in range(K):
            term = c4[b, c:c + 1] * Xw[c]
            pred = term if pred is None else pred + term      # [W, BP]
        r_w = Yw[b] - pred
        r4 = jnp.sqrt(jnp.maximum(
            jnp.sum(r_w * r_w * stab_f, 0, keepdims=True) / n4, 0.0))
        denom = stab_factor * jnp.maximum(r4, vario[b][None, :])
        r_first = r_w[0:1]
        r_last = jnp.sum(jnp.where(wi == last_i, r_w, 0.0), 0,
                         keepdims=True)
        slope_day = c4[b, 1:2] / 365.25
        ok_b = ((jnp.abs(slope_day * span) <= denom)
                & (jnp.abs(r_first) <= denom)
                & (jnp.abs(r_last) <= denom))                 # [1, BP]
        if b in det:
            stable = ok_b if stable is None else stable & ok_b

    # ---- flags + cursor advance ----
    init_nowin = in_init & ~has_w
    init_tm = in_init & has_w & tm_removed
    init_ok = in_init & has_w & ~tm_removed & stable
    init_bad = in_init & has_w & ~tm_removed & ~stable
    ex_tm, i_next = _first_ge((alive & ~bad_abs) & (ti >= i), ti, T)
    i_next = jnp.where(ex_tm, i_next, T)
    has_adv, i_adv = _first_ge(alive & (ti >= i + 1), ti, T)

    return dict(init_nowin=init_nowin, init_tm=init_tm, init_ok=init_ok,
                init_bad=init_bad, has_adv=has_adv, i_next_tm=i_next,
                i_adv=i_adv, j=j,
                n_ok=jnp.sum(jnp.where(w_stab, one, 0), 0, keepdims=True),
                w_stab=w_stab, alive_init=alive & ~bad_abs)


def _init_window_block(alive_ref, curi_ref, inin_ref, t_ref, x_ref, xtr_ref,
                       xtk_ref, xxt_ref, y_ref, vario_ref, *refs,
                       guarded=False, **statics):
    """One pixel block of kernel._init_block: ref boundary around
    _init_logic (the standalone 'init' component's pallas_call body)."""
    cnt_ref, (nowin_ref, tm_ref, ok_ref, bad_flag_ref, hasadv_ref,
              inext_ref, iadv_ref, j_ref, nok_ref, wstab_ref,
              alive_out_ref) = ((refs[0], refs[1:]) if guarded
                                else (None, refs))

    def compute():
        t_col = t_ref[...]
        f32 = t_col.dtype
        out = _init_logic(
            alive_ref[...] > 0, curi_ref[...], inin_ref[...] > 0, t_col,
            x_ref[...], xtr_ref[...], xtk_ref[...], xxt_ref[...],
            lambda b: y_ref[b].astype(f32), vario_ref[...], **statics)
        one = jnp.int32(1)
        as_i = lambda b: jnp.where(b, one, 0)
        nowin_ref[...] = as_i(out["init_nowin"])
        tm_ref[...] = as_i(out["init_tm"])
        ok_ref[...] = as_i(out["init_ok"])
        bad_flag_ref[...] = as_i(out["init_bad"])
        hasadv_ref[...] = as_i(out["has_adv"])
        # index arithmetic promotes to int64 under x64: land at ref dtype
        inext_ref[...] = out["i_next_tm"].astype(inext_ref.dtype)
        iadv_ref[...] = out["i_adv"].astype(iadv_ref.dtype)
        j_ref[...] = out["j"].astype(j_ref.dtype)
        nok_ref[...] = out["n_ok"].astype(nok_ref.dtype)
        wstab_ref[...] = as_i(out["w_stab"])
        alive_out_ref[...] = as_i(out["alive_init"])

    def skip():
        # The no-initializing-lane block mirrors kernel._init_zeros:
        # every flag/index output is inert zeros (consumers mask on
        # in_init-derived flags), and alive passes through unchanged —
        # the Tmask screen only removes observations for INIT lanes.
        _zero_refs(nowin_ref, tm_ref, ok_ref, bad_flag_ref, hasadv_ref,
                   inext_ref, iadv_ref, j_ref, nok_ref, wstab_ref)
        alive_out_ref[...] = alive_ref[...].astype(alive_out_ref.dtype)

    _when_active(cnt_ref, compute, skip)


@functools.partial(jax.jit, static_argnames=("W", "sensor", "mixed",
                                             "interpret"))
def init_window(alive, cur_i, in_init, t, X, Xt, Yt, vario, *, W, sensor,
                mixed=False, active=None, interpret=False):
    """Fused Pallas twin of kernel._init_block (same output contract).

    Args:
        alive: [P, T] bool; cur_i: [P] int; in_init: [P] bool.
        t: [T] float ordinal days; X: [T, K]; Xt: [T, NT] designs.
        Yt: [B, T, P] resident spectra (wire int16 or float32).
        vario: [P, B] variogram.
        active: optional [P] bool per-block skip guard (normally
            in_init; skipped blocks pass alive through and zero the
            rest, kernel._init_zeros' contract).
    Returns:
        kernel._init_block's output dict.
    """
    B, T, P = Yt.shape
    K = X.shape[-1]
    NT = Xt.shape[-1]
    f32 = X.dtype
    det = tuple(sensor.detection_bands)
    tmb = tuple(sensor.tmask_bands)
    BP = init_block_p(T, W, B, Yt.dtype.itemsize)
    Pp = -BP * (-P // BP)
    pad = Pp - P
    n_pow = 1 << max(1, (W - 1).bit_length())
    i32 = jnp.int32

    plane, vec = _pad_helpers(pad)
    yp = jnp.pad(Yt, ((0, 0), (0, 0), (0, pad)))
    vp = jnp.pad(vario.T, ((0, 0), (0, pad)), constant_values=1.0)
    XT = X.T                                                  # [K, T]
    XXT = (X[:, :, None] * X[:, None, :]).reshape(T, K * K).T  # [K*K, T]

    kern = functools.partial(
        _init_window_block, T=T, W=W, B=B, K=K, NT=NT, n_pow=n_pow,
        det=det, tmb=tmb, cd_iters=int(params.LASSO_ITERS),
        alpha=float(params.LASSO_ALPHA),
        tm_iters=int(params.TMASK_IRLS_ITERS),
        huber_k=float(params.HUBER_K),
        tmask_const=float(params.TMASK_CONST),
        meow=int(params.MEOW_SIZE), init_days=float(params.INIT_DAYS),
        stab_factor=float(params.STABILITY_FACTOR), mixed=bool(mixed),
        guarded=active is not None)
    pspec = pl.BlockSpec((T, BP), lambda i: (0, i))
    vspec = pl.BlockSpec((1, BP), lambda i: (0, i))
    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    vshape = jax.ShapeDtypeStruct((1, Pp), i32)
    pshape = jax.ShapeDtypeStruct((T, Pp), i32)
    args = [plane(alive.astype(i32)), vec(cur_i.astype(i32)),
            vec(in_init.astype(i32)), t.astype(f32)[:, None], X, Xt,
            XT.astype(f32), XXT.astype(f32), yp, vp]
    in_specs = [
        pspec, vspec, vspec,
        full((T, 1)), full((T, K)), full((T, NT)),
        full((K, T)), full((K * K, T)),
        pl.BlockSpec((B, T, BP), lambda i: (0, 0, i)),
        pl.BlockSpec((B, BP), lambda i: (0, i)),
    ]
    if active is not None:
        args.append(_block_counts(active, BP, Pp))
        in_specs.append(_CNT_SPEC)
    outs = pl.pallas_call(
        kern,
        grid=(Pp // BP,),
        in_specs=in_specs,
        out_specs=[vspec] * 9 + [pspec] * 2,
        out_shape=[vshape] * 9 + [pshape] * 2,
        interpret=interpret,
    )(*args)
    (nowin, tm, ok, badf, hasadv, inext, iadv, jj, nok, wstab,
     alive_out) = outs
    cut = lambda x: x[0, :P]
    cutb = lambda x: x[0, :P] > 0
    return dict(init_nowin=cutb(nowin), init_tm=cutb(tm), init_ok=cutb(ok),
                init_bad=cutb(badf), has_adv=cutb(hasadv),
                i_next_tm=cut(inext), i_adv=cut(iadv), j=cut(jj),
                n_ok=cut(nok), w_stab=(wstab[:, :P] > 0).T,
                alive_init=(alive_out[:, :P] > 0).T)


# ---------------------------------------------------------------------------
# Tmask IRLS kernel
# ---------------------------------------------------------------------------

def tmask_block_p(W: int) -> int:
    """Lane-block width for the Tmask kernel (footprint linear in the
    padded window length; ~30 [W, BP] planes live through the IRLS)."""
    budget = 10 * 2 ** 20
    per_lane = 30 * max(W, 1) * 4
    return max(128, min(512, (budget // per_lane) // 128 * 128))


def _bitonic_sublane(x, n, fill):
    """Ascending bitonic sort along axis 0 (length n, a power of two) via
    index-arithmetic shift exchanges — no gather/scatter, Mosaic-friendly.
    Produces the same sorted values as any sort (stability irrelevant for
    order statistics)."""
    i = lax.broadcasted_iota(jnp.int32, x.shape, 0)
    k = 2
    while k <= n:
        j = k // 2
        while j >= 1:
            fillp = jnp.full((j,) + x.shape[1:], fill, x.dtype)
            up = jnp.concatenate([x[j:], fillp], axis=0)      # x[i + j]
            dn = jnp.concatenate([fillp, x[:-j]], axis=0)     # x[i - j]
            low = (i & j) == 0
            partner = jnp.where(low, up, dn)
            asc = (i & k) == 0
            keep_small = low == asc
            mn = jnp.minimum(x, partner)
            mx = jnp.maximum(x, partner)
            x = jnp.where(keep_small, mn, mx)
            j //= 2
        k *= 2
    return x


def _median_sublane(r, mask, n_pow):
    """kernel._masked_median along axis 0: sort masked values (+inf
    padding), average the two middle order statistics.  The plane is
    padded up to the power-of-two network size — the bitonic exchange
    indices are only correct on a full n_pow-row array."""
    W = r.shape[0]
    x = jnp.where(mask, r, jnp.inf)
    if n_pow != W:
        x = jnp.concatenate(
            [x, jnp.full((n_pow - W,) + x.shape[1:], jnp.inf, x.dtype)], 0)
    s = _bitonic_sublane(x, n_pow, jnp.inf)
    i = lax.broadcasted_iota(jnp.int32, s.shape, 0)
    n = jnp.sum(jnp.where(mask, 1, 0), 0, keepdims=True)
    lo_i = jnp.maximum((n - 1) // 2, 0)
    hi_i = jnp.maximum(n // 2, 0)
    lo = jnp.sum(jnp.where(i == lo_i, s, 0.0), 0, keepdims=True)
    hi = jnp.sum(jnp.where(i == hi_i, s, 0.0), 0, keepdims=True)
    return jnp.where(n > 0, 0.5 * (lo + hi), 0.0)             # [1, BP]


def _tmask_core(X, Y, wm, vario, *, nt, nb, n_pow, iters, huber_k,
                tmask_const):
    """The Tmask IRLS screen on VMEM-resident window planes — shared by
    the standalone tmask kernel and the INIT-window kernel.

    X: list of nt [W, BP] design columns; Y: list of nb [W, BP] band
    planes; wm [W, BP] 0/1; vario [nb, BP].  Returns bad [W, BP] bool.
    Mirrors the jnp reference's arithmetic order exactly: XtXt outer
    products precomputed once, Gram/corr as weight-times-product reduces
    over W, the unrolled 5x5 Cholesky with its NaN-on-non-PD contract,
    MAD/Huber iterations with the same masked-median semantics.
    """
    xx = {}
    for ii in range(nt):
        for jj in range(ii + 1):
            xx[(ii, jj)] = X[ii] * X[jj]

    def chol_solve(G, c):
        # G: dict (i,j)->[1,BP] lower half; c: list of nt [1,BP]
        ok = None
        L = [[None] * nt for _ in range(nt)]
        for ii in range(nt):
            for jj in range(ii + 1):
                sacc = G[(ii, jj)]
                for q in range(jj):
                    sacc = sacc - L[ii][q] * L[jj][q]
                if ii == jj:
                    pos = sacc > 0
                    ok = pos if ok is None else ok & pos
                    L[ii][jj] = jnp.sqrt(jnp.maximum(sacc, 1e-30))
                else:
                    L[ii][jj] = sacc / L[jj][jj]
        yv = [None] * nt
        for ii in range(nt):
            sacc = c[ii]
            for q in range(ii):
                sacc = sacc - L[ii][q] * yv[q]
            yv[ii] = sacc / L[ii][ii]
        xv = [None] * nt
        for ii in reversed(range(nt)):
            sacc = yv[ii]
            for q in range(ii + 1, nt):
                sacc = sacc - L[q][ii] * xv[q]
            xv[ii] = sacc / L[ii][ii]
        nan = jnp.float32(jnp.nan)
        return [jnp.where(ok, v, nan) for v in xv]

    def solve(wt):
        # wt: list of nb [W, BP] weight planes -> beta[b] = list of nt [1,BP]
        betas = []
        for b in range(nb):
            G = {}
            for ii in range(nt):
                for jj in range(ii + 1):
                    G[(ii, jj)] = jnp.sum(wt[b] * xx[(ii, jj)], 0,
                                          keepdims=True) \
                        + (1e-9 if ii == jj else 0.0)
            c = [jnp.sum((Y[b] * wt[b]) * X[ii], 0, keepdims=True)
                 for ii in range(nt)]
            betas.append(chol_solve(G, c))
        return betas

    def pred(betas, b):
        acc = betas[b][0] * X[0]
        for c in range(1, nt):
            acc = acc + betas[b][c] * X[c]
        return acc                                            # [W, BP]

    w0 = [wm for _ in range(nb)]
    betas = solve(w0)
    mask = wm > 0
    for _ in range(iters):
        wts = []
        for b in range(nb):
            r = Y[b] - pred(betas, b)
            med = _median_sublane(r, mask, n_pow)
            mad = _median_sublane(jnp.abs(r - med), mask, n_pow)
            sigma = jnp.maximum(mad / 0.6745, 1e-6)
            a = jnp.abs(r) / (huber_k * sigma)
            huber = jnp.where(a <= 1.0, 1.0, 1.0 / jnp.maximum(a, 1e-12))
            wts.append(wm * huber)
        betas = solve(wts)

    bad = None
    for b in range(nb):
        r = jnp.abs(Y[b] - pred(betas, b))
        bb = (r > tmask_const * vario[b:b + 1]) & mask
        bad = bb if bad is None else bad | bb
    return bad


def _tmask_block(xt_ref, y2_ref, w_ref, vario_ref, *refs, nt, nb,
                 n_pow, iters, huber_k, tmask_const, guarded=False):
    """One pixel block of kernel._tmask_bad, all six IRLS solves in VMEM
    (xt [nt,W,BP], y2 [nb,W,BP], w [W,BP] 0/1, vario [nb,BP] -> bad
    [W,BP] int32 0/1)."""
    cnt_ref, bad_ref = (refs if guarded else (None,) + refs)

    def compute():
        bad = _tmask_core([xt_ref[c] for c in range(nt)],
                          [y2_ref[b] for b in range(nb)],
                          w_ref[...], vario_ref[...], nt=nt, nb=nb,
                          n_pow=n_pow, iters=iters, huber_k=huber_k,
                          tmask_const=tmask_const)
        bad_ref[...] = jnp.where(bad, jnp.int32(1), 0)

    # A dead block carries all-zero window masks: bad = (...) & mask is
    # False everywhere, so the zero fill is the exact computed value.
    _when_active(cnt_ref, compute, lambda: _zero_refs(bad_ref))


@functools.partial(jax.jit, static_argnames=("interpret",))
def tmask_bad(Xtw, Y2, w, vario2, *, active=None, interpret=False):
    """Pallas port of kernel._tmask_bad (same contract: [P,W] bool).

    Replaces the six sequential Gram/corr reduces, Cholesky chains, and
    ten masked medians per round — each a separate [P,*]-sized fusion
    paying the profiled per-op floor — with one VMEM-resident pass per
    pixel block.  ``active`` (normally the caller's in_init set) is the
    per-block skip guard.
    """
    P, W, nt = Xtw.shape
    nb = Y2.shape[1]
    BP = tmask_block_p(W)
    Pp = -BP * (-P // BP)
    pad = Pp - P
    n_pow = 1 << max(1, (W - 1).bit_length())

    xt = jnp.pad(Xtw.transpose(2, 1, 0), ((0, 0), (0, 0), (0, pad)))
    y2 = jnp.pad(Y2.transpose(1, 2, 0), ((0, 0), (0, 0), (0, pad)))
    wp = jnp.pad(w.T, ((0, 0), (0, pad)))
    vp = jnp.pad(vario2.T, ((0, 0), (0, pad)), constant_values=1.0)

    kern = functools.partial(
        _tmask_block, nt=nt, nb=nb, n_pow=n_pow,
        iters=int(params.TMASK_IRLS_ITERS),
        huber_k=float(params.HUBER_K),
        tmask_const=float(params.TMASK_CONST),
        guarded=active is not None)
    args = [xt, y2, wp, vp]
    in_specs = [
        pl.BlockSpec((nt, W, BP), lambda i: (0, 0, i)),
        pl.BlockSpec((nb, W, BP), lambda i: (0, 0, i)),
        pl.BlockSpec((W, BP), lambda i: (0, i)),
        pl.BlockSpec((nb, BP), lambda i: (0, i)),
    ]
    if active is not None:
        args.append(_block_counts(active, BP, Pp))
        in_specs.append(_CNT_SPEC)
    out = pl.pallas_call(
        kern,
        grid=(Pp // BP,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((W, BP), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((W, Pp), jnp.int32),
        interpret=interpret,
    )(*args)
    return (out[:, :P] > 0).T


# ---------------------------------------------------------------------------
# Fused fit+close round kernel (FIREBIRD_FUSED_FIT): the gram→CD→close
# boundary of one event-loop round in a single pallas_call.
# ---------------------------------------------------------------------------

def fused_block_p(T: int, B: int, S: int, y_bytes: int) -> int:
    """Lane-block width for the fused fit+close kernel: the [B,T,BP]
    wire spectra, ~8 live [T,BP] f32 planes (fit window, alive/included,
    prediction temporaries), the [S,*,BP] result buffers twice (in+out
    live across the block), and the PEEK-run selection planes."""
    budget = 10 * 2 ** 20
    per_lane = (max(T, 1) * (B * y_bytes + 8 * 4)
                + 2 * max(S, 1) * (6 + 2 * B + B * params.MAX_COEFS) * 4
                + params.PEEK_SIZE * (params.MAX_COEFS + B + 4) * 4)
    return max(128, min(512, (budget // per_lane) // 128 * 128))


def _fused_fit_close_block(x_ref, xtk_ref, xxt_ref, t_ref, y_ref,
                           wfit_ref, dofit_ref, nfull_ref,
                           incm_ref, coefs_ref, rmse_ref, magsin_ref,
                           tail_ref, brk_ref, pos_ref, nexc_ref,
                           first_ref, nseg_ref, meta0_ref, rmses0_ref,
                           mags0_ref, coefs0_ref, *refs, T, B, K, S, peek,
                           qa_start, qa_inside, qa_end,
                           cd_iters, alpha, num_obs_factor, mid_coefs,
                           mixed=False, guarded=False):
    """One pixel block's fit round ACROSS the gram→CD→close boundary:
    the segment-close row write against the closing model and the shared
    Lasso refit (_gram_cd_core + RMSE) run back to back on one VMEM
    residency of the wire spectra — the XLA loop streams the [B,T,P]
    spectra for the fit's Gram/corr/RMSE and round-trips the [P,S*k]
    result buffers plus the [P,*] intermediates between its two
    cond-gated fusions.  Every close value here is an exact select, an
    integer in f32, or a carried input: the break magnitudes (the one
    genuinely float close term) arrive PRE-COMPUTED in ``magsin_ref`` —
    kernel._close_mags runs the identical program on fused and unfused
    paths under a rare any(is_brk) cond — and the fit half is the same
    _gram_cd_core the per-component fit kernel wraps.  That is what
    makes the fused-on/off stores byte-identical (tests/test_fuse.py
    golden) instead of decision-exact-with-envelope like the mega route.
    """
    cnt_ref, (meta_ref, rmses_ref, mags_ref, coefsb_ref, nsego_ref,
              co_ref, ro_ref) = ((refs[0], refs[1:]) if guarded
                                 else (None, refs))

    def compute():
        X = x_ref[...]
        t_col = t_ref[...]
        f32 = X.dtype
        y_of = lambda b: y_ref[b].astype(f32)
        i32 = jnp.int32
        one = i32(1)
        coefs = coefs_ref[...]
        rmse = rmse_ref[...]

        # ---- close row write (kernel._close_block, minus the
        #      pre-computed magnitudes; the OLD model closes) ----
        incm = incm_ref[...] > 0                              # [T, BP]
        is_tail = tail_ref[...] > 0
        is_brk = brk_ref[...] > 0
        first_seg = first_ref[...] > 0
        nseg0 = nseg_ref[...]
        close = is_tail | is_brk                              # [1, BP]
        ti = lax.broadcasted_iota(i32, incm.shape, 0)
        t_plane = jnp.broadcast_to(t_col, incm.shape)

        def at_t(plane, idx):
            return jnp.sum(jnp.where(ti == idx, plane, 0), 0,
                           keepdims=True)

        any_inc = jnp.any(incm, 0, keepdims=True)
        INF = i32(T + 1)
        first_inc = jnp.where(
            any_inc,
            jnp.min(jnp.where(incm, ti, INF), 0, keepdims=True), 0)
        last_inc = jnp.where(
            any_inc,
            jnp.max(jnp.where(incm, ti, -1), 0, keepdims=True), T - 1)
        start_day = at_t(t_plane, first_inc)
        end_day = at_t(t_plane, last_inc)
        break_day = jnp.where(is_brk, at_t(t_plane, pos_ref[...]),
                              end_day)
        chprob = jnp.where(is_brk, 1.0,
                           nexc_ref[...].astype(f32) / float(peek))
        qa_tail = qa_end + jnp.where(first_seg, qa_start, 0)
        qa_brk = jnp.where(first_seg, qa_start, qa_inside)
        qa = jnp.where(is_brk, qa_brk, qa_tail).astype(f32)
        n_obs = jnp.sum(jnp.where(incm, one, 0), 0,
                        keepdims=True).astype(f32)
        meta_new = jnp.concatenate(
            [start_day, end_day, break_day, chprob, qa, n_obs], 0)
        mag_new = jnp.where(is_brk, magsin_ref[...], 0.0)     # [B, BP]
        coef_new = jnp.concatenate([coefs[b] for b in range(B)], 0)

        # One-hot append at nseg (kernel._write_seg): rows past capacity
        # are never selected, but nseg still counts — the overflow
        # contract detect_packed's capacity_retry relies on.
        si = lax.broadcasted_iota(i32, (S, 1) + incm.shape[1:], 0)
        sel = (si == nseg0[None]) & close[None]               # [S,1,BP]
        meta_b = jnp.where(sel, meta_new[None], meta0_ref[...])
        rmses_b = jnp.where(sel, rmse[None], rmses0_ref[...])
        mags_b = jnp.where(sel, mag_new[None], mags0_ref[...])
        coefs_b = jnp.where(sel, coef_new[None], coefs0_ref[...])
        nseg = nseg0 + jnp.where(close, one, 0)

        # ---- shared Lasso fit (init-ok + refit; mega's run_fit math) ----
        wf = wfit_ref[...]                                    # [T, BP]
        n_full = nfull_ref[...]                               # [1, BP]
        nc = jnp.where(
            n_full >= K * num_obs_factor, K,
            jnp.where(n_full >= mid_coefs * num_obs_factor,
                      mid_coefs, 4))
        cm = jnp.where(
            lax.broadcasted_iota(i32, (K,) + n_full.shape[1:], 0) < nc,
            1.0, 0.0).astype(f32)
        beta, n = _gram_cd_core(xtk_ref[...], xxt_ref[...], y_of, wf, cm,
                                B=B, K=K, iters=cd_iters, alpha=alpha,
                                mixed=mixed)
        rs = []
        for b in range(B):
            pred = jnp.dot(X, beta[b], preferred_element_type=f32)
            r = y_of(b) - pred
            rs.append(jnp.sqrt(jnp.maximum(
                jnp.sum(r * r * wf, 0, keepdims=True) / n, 0.0)))
        rmse_new = jnp.concatenate(rs, 0)                     # [B, BP]

        do_fit = dofit_ref[...] > 0                           # [1, BP]
        meta_ref[...] = meta_b
        rmses_ref[...] = rmses_b
        mags_ref[...] = mags_b
        coefsb_ref[...] = coefs_b
        nsego_ref[...] = nseg.astype(nsego_ref.dtype)
        co_ref[...] = jnp.where(do_fit[None], beta, coefs)
        ro_ref[...] = jnp.where(do_fit, rmse_new, rmse)

    def skip():
        # A block with no closing and no fitting lane is a pure
        # pass-through: the close write-mask selects nothing and the
        # do_fit merge keeps the old model — so copying the inputs IS
        # the computed value, exactly (the skip-guard contract).
        meta_ref[...] = meta0_ref[...]
        rmses_ref[...] = rmses0_ref[...]
        mags_ref[...] = mags0_ref[...]
        coefsb_ref[...] = coefs0_ref[...]
        nsego_ref[...] = nseg_ref[...].astype(nsego_ref.dtype)
        co_ref[...] = coefs_ref[...]
        ro_ref[...] = rmse_ref[...]

    _when_active(cnt_ref, compute, skip)


@functools.partial(jax.jit, static_argnames=("S", "mixed", "block_p",
                                             "interpret"))
def fused_fit_close(Yt, X, t, w_fit, do_fit, n_full, included_mon,
                    coefs, rmse, mags, is_tail, is_brk, pos_ev,
                    n_exceed, first_seg, nseg, bufs, *, S, mixed=False,
                    active=None, block_p=None, interpret=False):
    """Fused Pallas twin of one round's close + shared-fit pair
    (kernel._close_block + the refit's fit), reading the wire-dtype
    resident spectra ONCE per pixel block.

    Args:
        Yt: [B, T, P] resident spectra (wire int16 or float32).
        X: [T, K] design (chip-shared); t: [T] float ordinal days.
        w_fit: [P, T] 0/1 fit window (init w_stab or included&refit).
        do_fit: [P] bool; n_full: [P] int (the fit's obs count).
        included_mon: [P, T] bool round plane.
        coefs: [P, B, K]; rmse: [P, B] — the CURRENT model (closes the
            segment; replaced where do_fit).
        mags: [P, B] break magnitudes, pre-computed by
            kernel._close_mags under an any(is_brk) cond (identical
            program fused and unfused — the byte-identity anchor).
        pos_ev, n_exceed: [P] int; is_tail/is_brk/first_seg: [P] bool
            (the monitor chain's event outputs).
        nseg: [P] int32; bufs: the four FLAT result buffers
            (meta [P,S*6], rmse [P,S*B], mag [P,S*B], coef [P,S*B*K]).
        active: optional [P] bool per-block skip guard — normally
            do_fit | is_tail | is_brk; skipped blocks pass everything
            through unchanged (exact, see the block's skip note).
        block_p: static lane-width override (tools/fuse_repro.py's
            block-shape reduction); None sizes from the VMEM budget.
    Returns:
        (bufs', nseg', coefs', rmse') in the caller's layouts.
    """
    B, T, P = Yt.shape
    K = X.shape[-1]
    f32 = X.dtype
    i32 = jnp.int32
    peek = int(params.PEEK_SIZE)
    BP = block_p or fused_block_p(T, B, S, Yt.dtype.itemsize)
    Pp = -BP * (-P // BP)
    pad = Pp - P
    plane, vec = _pad_helpers(pad)

    meta0, rmse0, mag0, coef0 = bufs
    XT = X.T                                                  # [K, T]
    XXT = (X[:, :, None] * X[:, None, :]).reshape(T, K * K).T  # [K*K, T]
    padb = lambda a: jnp.pad(a, ((0, 0), (0, 0), (0, pad)))
    padr = lambda a, cv=0.0: jnp.pad(a.T, ((0, 0), (0, pad)),
                                     constant_values=cv)
    args = [X, XT.astype(f32), XXT.astype(f32), t.astype(f32)[:, None],
            padb(Yt), plane(w_fit.astype(f32)), vec(do_fit.astype(i32)),
            vec(n_full.astype(i32)),
            plane(included_mon.astype(i32)),
            padb(coefs.transpose(1, 2, 0)),
            padr(rmse, 1.0), padr(mags),
            vec(is_tail.astype(i32)), vec(is_brk.astype(i32)),
            vec(pos_ev.astype(i32)), vec(n_exceed.astype(i32)),
            vec(first_seg.astype(i32)), vec(nseg.astype(i32)),
            padb(meta0.reshape(P, S, 6).transpose(1, 2, 0)),
            padb(rmse0.reshape(P, S, B).transpose(1, 2, 0)),
            padb(mag0.reshape(P, S, B).transpose(1, 2, 0)),
            padb(coef0.reshape(P, S, B * K).transpose(1, 2, 0))]

    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    pspec = pl.BlockSpec((T, BP), lambda i: (0, i))
    vspec = pl.BlockSpec((1, BP), lambda i: (0, i))
    bspec = pl.BlockSpec((B, BP), lambda i: (0, i))
    b3 = lambda lead: pl.BlockSpec((lead[0], lead[1], BP),
                                   lambda i: (0, 0, i))
    in_specs = [full((T, K)), full((K, T)), full((K * K, T)), full((T, 1)),
                b3((B, T)), pspec, vspec, vspec, pspec,
                b3((B, K)), bspec, bspec,
                vspec, vspec, vspec, vspec, vspec, vspec,
                b3((S, 6)), b3((S, B)), b3((S, B)), b3((S, B * K))]
    if active is not None:
        args.append(_block_counts(active, BP, Pp))
        in_specs.append(_CNT_SPEC)

    kern = functools.partial(
        _fused_fit_close_block, T=T, B=B, K=K, S=S, peek=peek,
        qa_start=int(params.CURVE_QA_START),
        qa_inside=int(params.CURVE_QA_INSIDE),
        qa_end=int(params.CURVE_QA_END),
        cd_iters=int(params.LASSO_ITERS), alpha=float(params.LASSO_ALPHA),
        num_obs_factor=int(params.NUM_OBS_FACTOR),
        mid_coefs=int(params.MID_COEFS), mixed=bool(mixed),
        guarded=active is not None)
    outs = pl.pallas_call(
        kern,
        grid=(Pp // BP,),
        in_specs=in_specs,
        out_specs=[b3((S, 6)), b3((S, B)), b3((S, B)), b3((S, B * K)),
                   vspec, b3((B, K)), pl.BlockSpec((B, BP),
                                                   lambda i: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((S, 6, Pp), f32),
                   jax.ShapeDtypeStruct((S, B, Pp), f32),
                   jax.ShapeDtypeStruct((S, B, Pp), f32),
                   jax.ShapeDtypeStruct((S, B * K, Pp), f32),
                   jax.ShapeDtypeStruct((1, Pp), i32),
                   jax.ShapeDtypeStruct((B, K, Pp), f32),
                   jax.ShapeDtypeStruct((B, Pp), f32)],
        interpret=interpret,
    )(*args)
    meta_n, rmses_n, mags_n, coefsb_n, nseg_n, co, ro = outs
    unflat = lambda a, k: a[..., :P].transpose(2, 0, 1).reshape(P, S * k)
    bufs_n = (unflat(meta_n, 6), unflat(rmses_n, B), unflat(mags_n, B),
              unflat(coefsb_n, B * K))
    return (bufs_n, nseg_n[0, :P], co[..., :P].transpose(2, 0, 1),
            ro[:, :P].T)


# ---------------------------------------------------------------------------
# Monitor-fused round kernel (FIREBIRD_FUSED_FIT=mon): monitor → close →
# fit — the ENTIRE post-INIT round — in one pallas_call / one VMEM
# residency of the wire spectra.
# ---------------------------------------------------------------------------

def fused_round_block_p(T: int, B: int, S: int, y_bytes: int) -> int:
    """Lane-block width for the monitor-fused round kernel: the fused
    fit+close footprint (fused_block_p) plus the monitor chain's ~12
    live [T,BP] scan planes (score, rank, run-length / refit-ladder
    shift scans) — hence the 20-plane T term."""
    budget = 10 * 2 ** 20
    per_lane = (max(T, 1) * (B * y_bytes + 20 * 4)
                + 2 * max(S, 1) * (6 + 2 * B + B * params.MAX_COEFS) * 4
                + params.PEEK_SIZE * (params.MAX_COEFS + B + 4) * 4)
    return max(128, min(512, (budget // per_lane) // 128 * 128))


def _fused_round_block(x_ref, xtk_ref, xxt_ref, t_ref, y_ref,
                       alive_ref, inc_ref, curk_ref, nlast_ref, inmon_ref,
                       coefs_ref, rmse_ref, vario_ref,
                       initok_ref, wstab_ref, nok_ref,
                       first_ref, nseg_ref,
                       meta0_ref, rmses0_ref, mags0_ref, coefs0_ref,
                       *refs, T, B, K, S, det, peek, n_pow_peek,
                       change_thr, outlier_thr, refit_factor,
                       qa_start, qa_inside, qa_end, cd_iters, alpha,
                       num_obs_factor, mid_coefs, mixed, guarded=False):
    """One pixel block's ENTIRE post-INIT round — monitor scoring/event
    chain, segment close (including the break-magnitude median, in-VMEM
    like the mega kernel), and the shared Lasso refit — on one VMEM
    residency of the wire spectra.  Composes the same shared cores as
    the per-component kernels (_mon_scored_logic, _close_logic,
    _gram_cd_core) with the mega block's cond gates, so a round with no
    monitoring / closing / fitting lane skips that phase's work
    entirely.  The contract is the mega route's decision-exact-with-
    envelope, NOT fused_fit_close's byte identity: the break magnitudes
    are computed here from the in-VMEM PEEK run rather than arriving
    from kernel._close_mags (seg_mag sits inside the pinned ulp
    envelope; every decision field is an exact select/integer).
    """
    cnt_ref, (meta_ref, rmseso_ref, magso_ref, coefsbo_ref, nsego_ref,
              co_ref, ro_ref, tail_ref, brk_ref, refit_ref, pos_ref,
              dofit_ref, nfull_ref, incmon_ref, alivemon_ref) = (
                  (refs[0], refs[1:]) if guarded else (None, refs))

    def compute():
        X = x_ref[...]
        t_col = t_ref[...]
        f32 = X.dtype
        i32 = jnp.int32
        one = i32(1)
        as_i = lambda v: jnp.where(v, one, 0)
        det_l = list(det)
        nb = len(det_l)
        y_of = lambda b: y_ref[b].astype(f32)
        alive = alive_ref[...] > 0
        included = inc_ref[...] > 0
        in_mon = inmon_ref[...] > 0
        coefs = coefs_ref[...]
        rmse = rmse_ref[...]
        vario = vario_ref[...]
        first_seg = first_ref[...] > 0
        nseg0 = nseg_ref[...]
        BP = rmse.shape[-1]

        # ---- MONITOR (skipped when no lane of the block monitors) ----
        any_mon = jnp.any(in_mon)
        dden = jnp.concatenate(
            [jnp.maximum(rmse[b], vario[b])[None] for b in det_l], 0)
        coefs_d = jnp.concatenate([coefs[b][None] for b in det_l], 0)

        def run_mon():
            outs = _mon_scored_logic(
                lambda b: y_ref[det_l[b]], coefs_d, dden, X, alive,
                included, curk_ref[...], nlast_ref[...], in_mon,
                change_thr=change_thr, outlier_thr=outlier_thr,
                peek=peek, refit_factor=refit_factor, T=T, nb=nb)
            # .astype(i32): x64 promotes integer sums to i64, which
            # would mismatch the skip branch's i32 zeros.
            return tuple(v.astype(i32) for v in outs)

        def zero_mon():
            zv = jnp.zeros((1, BP), i32)
            zp = jnp.zeros((T, BP), i32)
            return (zv, zv, zv, zv, zv, zv, zv, zv, zp, zp)

        (m, is_tail_i, is_brk_i, is_refit_i, ev_rank, pos_ev, n_exceed,
         n_rf, inc_q_i, rem_q_i) = lax.cond(any_mon, run_mon, zero_mon)
        is_tail = is_tail_i > 0
        is_brk = is_brk_i > 0
        is_refit = is_refit_i > 0
        included_mon = included | ((inc_q_i > 0) & in_mon)
        alive_mon = alive & ~((rem_q_i > 0) & in_mon)

        # ---- CLOSE (in-VMEM magnitudes; the mega route's math) ----
        close = is_tail | is_brk
        any_close = jnp.any(close)

        def run_close():
            return _close_logic(
                y_of, X, t_col, coefs, rmse, alive, included_mon, m,
                is_tail, is_brk, ev_rank, pos_ev, n_exceed, first_seg,
                nseg0, meta0_ref[...], rmses0_ref[...], mags0_ref[...],
                coefs0_ref[...], T=T, B=B, K=K, S=S, peek=peek,
                n_pow_peek=n_pow_peek, qa_start=qa_start,
                qa_inside=qa_inside, qa_end=qa_end)

        def keep_close():
            return (meta0_ref[...], rmses0_ref[...], mags0_ref[...],
                    coefs0_ref[...], nseg0)

        meta_n, rmses_n, mags_n, coefs_bn, nseg_n = lax.cond(
            any_close, run_close, keep_close)

        # ---- shared Lasso fit (init-ok + refit; mega's run_fit) ----
        init_ok = initok_ref[...] > 0
        do_fit = init_ok | is_refit
        any_fit = jnp.any(do_fit)
        n_full = jnp.where(init_ok, nok_ref[...], n_rf)        # [1,BP]

        def run_fit():
            # f32-valued selects, not bool ones: an i1-result select_n
            # lowers to an i8->i1 trunci Mosaic rejects (r5).
            wf = jnp.where(init_ok,
                           jnp.where(wstab_ref[...] > 0, 1.0, 0.0),
                           jnp.where(included_mon & is_refit, 1.0, 0.0)
                           ).astype(f32)
            nc = jnp.where(
                n_full >= K * num_obs_factor, K,
                jnp.where(n_full >= mid_coefs * num_obs_factor,
                          mid_coefs, 4))
            cm = jnp.where(
                lax.broadcasted_iota(i32, (K, BP), 0) < nc,
                1.0, 0.0).astype(f32)
            beta, n = _gram_cd_core(xtk_ref[...], xxt_ref[...], y_of, wf,
                                    cm, B=B, K=K, iters=cd_iters,
                                    alpha=alpha, mixed=mixed)
            rs = []
            for b in range(B):
                pred = jnp.dot(X, beta[b], preferred_element_type=f32)
                r = y_of(b) - pred
                rs.append(jnp.sqrt(jnp.maximum(
                    jnp.sum(r * r * wf, 0, keepdims=True) / n, 0.0)))
            return beta, jnp.concatenate(rs, 0)

        def keep_fit():
            return coefs, rmse

        cfull, rfull = lax.cond(any_fit, run_fit, keep_fit)

        meta_ref[...] = meta_n
        rmseso_ref[...] = rmses_n
        magso_ref[...] = mags_n
        coefsbo_ref[...] = coefs_bn
        nsego_ref[...] = nseg_n.astype(nsego_ref.dtype)
        co_ref[...] = jnp.where(do_fit[None], cfull, coefs)
        ro_ref[...] = jnp.where(do_fit, rfull, rmse)
        tail_ref[...] = as_i(is_tail)
        brk_ref[...] = as_i(is_brk)
        refit_ref[...] = as_i(is_refit)
        pos_ref[...] = pos_ev.astype(pos_ref.dtype)
        dofit_ref[...] = as_i(do_fit)
        nfull_ref[...] = n_full.astype(nfull_ref.dtype)
        incmon_ref[...] = as_i(included_mon)
        alivemon_ref[...] = as_i(alive_mon)

    def skip():
        # A block with no monitoring and no initializing lane is a pure
        # pass-through — exactly kernel._mon_zeros + keep-old-model:
        # every event flag is False (zero), included/alive pass through
        # unchanged, the close mask selects nothing, and the do_fit
        # merge keeps the old coefs/rmse.  Copying the inputs IS the
        # computed value (the skip-guard contract).
        meta_ref[...] = meta0_ref[...]
        rmseso_ref[...] = rmses0_ref[...]
        magso_ref[...] = mags0_ref[...]
        coefsbo_ref[...] = coefs0_ref[...]
        nsego_ref[...] = nseg_ref[...].astype(nsego_ref.dtype)
        co_ref[...] = coefs_ref[...]
        ro_ref[...] = rmse_ref[...]
        _zero_refs(tail_ref, brk_ref, refit_ref, pos_ref, dofit_ref,
                   nfull_ref)
        incmon_ref[...] = inc_ref[...].astype(incmon_ref.dtype)
        alivemon_ref[...] = alive_ref[...].astype(alivemon_ref.dtype)

    _when_active(cnt_ref, compute, skip)


@functools.partial(jax.jit, static_argnames=(
    "S", "sensor", "change_thr", "outlier_thr", "mixed", "block_p",
    "interpret"))
def fused_round(Yt, X, t, alive, included, cur_k, n_last_fit, in_mon,
                coefs, rmse, vario, init_ok, w_stab, n_ok, first_seg,
                nseg, bufs, *, S, sensor, change_thr, outlier_thr,
                mixed=False, active=None, block_p=None, interpret=False):
    """The whole post-INIT round — monitor chain, segment close, shared
    Lasso refit — as ONE pallas_call (FIREBIRD_FUSED_FIT=mon): one VMEM
    residency of the wire spectra per round instead of the three
    separate score/close/fit streams of the per-component kernels, with
    the INIT block still cond-gated outside (its outputs arrive as
    ``init_ok``/``w_stab``/``n_ok``).

    Args:
        Yt: [B, T, P] resident spectra (wire int16 or float32).
        X: [T, K] design (chip-shared); t: [T] float ordinal days.
        alive, included: [P, T] bool state planes.
        cur_k, n_last_fit: [P] int; in_mon: [P] bool.
        coefs: [P, B, K]; rmse: [P, B] — the CURRENT model; vario [P, B].
        init_ok: [P] bool; w_stab: [P, T] 0/1; n_ok: [P] int — the INIT
            block's fit handoff (zeros when no lane initialized).
        first_seg: [P] bool; nseg: [P] int32; bufs: the four FLAT result
            buffers (meta [P,S*6], rmse [P,S*B], mag [P,S*B],
            coef [P,S*B*K]).
        active: optional [P] bool per-block skip guard — normally
            in_mon | init_ok; skipped blocks pass state through and
            zero the event flags (kernel._mon_zeros' contract, exact).
        block_p: static lane-width override (fuse_repro's ladder /
            FIREBIRD_MEGA_BLOCK_P); None sizes from the VMEM budget.
    Returns:
        (bufs', nseg' [P], coefs' [P,B,K], rmse' [P,B], ev) where ev is
        a dict of the event outputs the outer next-state needs:
        is_tail/is_brk/is_refit/do_fit [P] bool, pos_ev/n_full [P] i32,
        included_mon/alive_mon [P,T] bool.
    """
    B, T, P = Yt.shape
    K = X.shape[-1]
    f32 = X.dtype
    i32 = jnp.int32
    det = tuple(sensor.detection_bands)
    peek = int(params.PEEK_SIZE)
    BP = (block_p or _env_block_p()
          or fused_round_block_p(T, B, S, Yt.dtype.itemsize))
    Pp = -BP * (-P // BP)
    pad = Pp - P
    plane, vec = _pad_helpers(pad)

    meta0, rmse0, mag0, coef0 = bufs
    XT = X.T                                                  # [K, T]
    XXT = (X[:, :, None] * X[:, None, :]).reshape(T, K * K).T  # [K*K, T]
    padb = lambda a: jnp.pad(a, ((0, 0), (0, 0), (0, pad)))
    padr = lambda a, cv=0.0: jnp.pad(a.T, ((0, 0), (0, pad)),
                                     constant_values=cv)
    args = [X, XT.astype(f32), XXT.astype(f32), t.astype(f32)[:, None],
            padb(Yt),
            plane(alive.astype(i32)), plane(included.astype(i32)),
            vec(cur_k.astype(i32)), vec(n_last_fit.astype(i32), 1),
            vec(in_mon.astype(i32)),
            padb(coefs.transpose(1, 2, 0)), padr(rmse, 1.0),
            padr(vario, 1.0),
            vec(init_ok.astype(i32)), plane(w_stab.astype(i32)),
            vec(n_ok.astype(i32)),
            vec(first_seg.astype(i32)), vec(nseg.astype(i32)),
            padb(meta0.reshape(P, S, 6).transpose(1, 2, 0)),
            padb(rmse0.reshape(P, S, B).transpose(1, 2, 0)),
            padb(mag0.reshape(P, S, B).transpose(1, 2, 0)),
            padb(coef0.reshape(P, S, B * K).transpose(1, 2, 0))]

    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    pspec = pl.BlockSpec((T, BP), lambda i: (0, i))
    vspec = pl.BlockSpec((1, BP), lambda i: (0, i))
    bspec = pl.BlockSpec((B, BP), lambda i: (0, i))
    b3 = lambda lead: pl.BlockSpec((lead[0], lead[1], BP),
                                   lambda i: (0, 0, i))
    in_specs = [full((T, K)), full((K, T)), full((K * K, T)), full((T, 1)),
                b3((B, T)),
                pspec, pspec, vspec, vspec, vspec,
                b3((B, K)), bspec, bspec,
                vspec, pspec, vspec,
                vspec, vspec,
                b3((S, 6)), b3((S, B)), b3((S, B)), b3((S, B * K))]
    if active is not None:
        args.append(_block_counts(active, BP, Pp))
        in_specs.append(_CNT_SPEC)

    kern = functools.partial(
        _fused_round_block, T=T, B=B, K=K, S=S, det=det, peek=peek,
        n_pow_peek=1 << max(1, (peek - 1).bit_length()),
        change_thr=float(change_thr), outlier_thr=float(outlier_thr),
        refit_factor=float(params.REFIT_FACTOR),
        qa_start=int(params.CURVE_QA_START),
        qa_inside=int(params.CURVE_QA_INSIDE),
        qa_end=int(params.CURVE_QA_END),
        cd_iters=int(params.LASSO_ITERS), alpha=float(params.LASSO_ALPHA),
        num_obs_factor=int(params.NUM_OBS_FACTOR),
        mid_coefs=int(params.MID_COEFS), mixed=bool(mixed),
        guarded=active is not None)
    outs = pl.pallas_call(
        kern,
        grid=(Pp // BP,),
        in_specs=in_specs,
        out_specs=[b3((S, 6)), b3((S, B)), b3((S, B)), b3((S, B * K)),
                   vspec, b3((B, K)), bspec,
                   vspec, vspec, vspec, vspec, vspec, vspec,
                   pspec, pspec],
        out_shape=[jax.ShapeDtypeStruct((S, 6, Pp), f32),
                   jax.ShapeDtypeStruct((S, B, Pp), f32),
                   jax.ShapeDtypeStruct((S, B, Pp), f32),
                   jax.ShapeDtypeStruct((S, B * K, Pp), f32),
                   jax.ShapeDtypeStruct((1, Pp), i32),
                   jax.ShapeDtypeStruct((B, K, Pp), f32),
                   jax.ShapeDtypeStruct((B, Pp), f32)]
        + [jax.ShapeDtypeStruct((1, Pp), i32)] * 6
        + [jax.ShapeDtypeStruct((T, Pp), i32)] * 2,
        interpret=interpret,
    )(*args)
    (meta_n, rmses_n, mags_n, coefsb_n, nseg_n, co, ro,
     tail, brk, refit, pos, dofit, nfull, incmon, alivemon) = outs
    unflat = lambda a, k: a[..., :P].transpose(2, 0, 1).reshape(P, S * k)
    bufs_n = (unflat(meta_n, 6), unflat(rmses_n, B), unflat(mags_n, B),
              unflat(coefsb_n, B * K))
    cut = lambda x: x[0, :P]
    cutb = lambda x: x[0, :P] > 0
    ev = dict(is_tail=cutb(tail), is_brk=cutb(brk), is_refit=cutb(refit),
              pos_ev=cut(pos), do_fit=cutb(dofit), n_full=cut(nfull),
              included_mon=(incmon[:, :P] > 0).T,
              alive_mon=(alivemon[:, :P] > 0).T)
    return (bufs_n, nseg_n[0, :P], co[..., :P].transpose(2, 0, 1),
            ro[:, :P].T, ev)


# ---------------------------------------------------------------------------
# Ring remote-copy kernel (cross-device straggler rebalancing).  One ring
# hop of the rebalancing exchange (parallel.mesh): ship a shard-local
# array to the logical neighbor over ICI via an async remote DMA —
# SNIPPETS.md [1]/[2]'s shard_map + make_async_remote_copy template.
# TPU-compiled only: the CPU/simulated-mesh path uses lax.ppermute
# (mesh._ring_shift), which is semantically identical (a fixed
# source→dest permutation along the ring axis).
# ---------------------------------------------------------------------------

def _ring_copy_kernel(dst_ref, x_ref, out_ref, send_sem, recv_sem):
    from jax.experimental.pallas import tpu as pltpu  # TPU-only lowering

    copy = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=out_ref, send_sem=send_sem,
        recv_sem=recv_sem, device_id=(dst_ref[0],),
        device_id_type=pltpu.DeviceIdType.LOGICAL)
    copy.start()
    copy.wait()


def ring_remote_copy(x, dst_index):
    """Ship ``x`` (shard-local, any shape) to logical device
    ``dst_index`` on the ring; returns the buffer received from whichever
    neighbor targeted THIS device (every device along the axis calls
    with its own neighbor, so the exchange is a pure ring rotation).

    The payload stays in HBM (``TPUMemorySpace.ANY``) — the rebalancing
    slabs are MB-scale state trees, not VMEM blocks — and the DMA
    completes before return (start+wait; the overlap the rebalancer
    needs is across payload FIELDS, which jax schedules as independent
    kernels).  ``dst_index`` is a traced scalar (axis_index ± 1), fed
    through scalar prefetch.
    """
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )
    return pl.pallas_call(
        _ring_copy_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid_spec=grid_spec,
    )(jnp.asarray(dst_index, jnp.int32).reshape(1), x)


# ---------------------------------------------------------------------------
# Whole-loop mega kernel: the entire event-horizon loop in one pallas_call
# ---------------------------------------------------------------------------

def _mega_per_lane_bytes(T: int, W: int, B: int, S: int,
                         y_bytes: int) -> int:
    """Estimated VMEM bytes per lane for the mega block: the [B,T,BP]
    wire spectra and their widened f32 twins, ~24 live [T,BP] planes
    (state + monitor/init temporaries), the [W,BP] window/IRLS planes,
    and the [S,*,BP] result buffers."""
    return (max(T, 1) * (B * y_bytes + B * 4 + 24 * 4)
            + max(W, 1) * 60 * 4
            + max(S, 1) * (6 + 2 * B + B * 8) * 4 + 2048)


def mega_block_p(T: int, W: int, B: int, S: int, y_bytes: int) -> int:
    """Lane-block width for the mega kernel (see _mega_per_lane_bytes)."""
    budget = 10 * 2 ** 20
    per_lane = _mega_per_lane_bytes(T, W, B, S, y_bytes)
    return max(128, min(512, (budget // per_lane) // 128 * 128))


def mega_fits(T: int, W: int, B: int, S: int, y_bytes: int) -> bool:
    """Whether the mega block fits VMEM at the minimum 128-lane width.

    The lane floor is the TPU vector width — a narrower block cannot
    exist, so when 128 lanes of PEAK working set exceed ~14 MB of the
    ~16 MB VMEM (leaving room for the pipeline's double-buffered input
    blocks), the mega route must be refused and the dispatch fall back
    to the XLA loop (kernel._detect_batch_impl does this).

    The peak model is TIGHTER than _mega_per_lane_bytes' width-sizing
    budget (which deliberately over-provisions so wider blocks never
    thrash): at any instant the block holds the wire spectra, at most
    one widened f32 band set (the per-phase logics widen inside their
    branches), ~12 live [T,BP] f32 planes (state + the deepest branch's
    temporaries), the [W,BP] IRLS planes, and the result buffers.  The
    full-archive bucketed shapes (T<=768) fit; multi-decade unbucketed
    T~1800 archives are refused.  An estimate wrong in the tight
    direction surfaces as a Mosaic OOM at compile, which the bench
    autotune's safe_rate catches — the guard exists so PRODUCTION
    dispatches never hit that path."""
    peak_per_lane = (max(T, 1) * (B * y_bytes + B * 4 + 12 * 4)
                     + max(W, 1) * 60 * 4
                     + max(S, 1) * (6 + 2 * B + B * 8) * 4 + 2048)
    return 128 * peak_per_lane <= 14 * 2 ** 20


def _close_logic(y_of, X, t_col, coefs, rmse, alive, included_mon,
                 m, is_tail, is_brk, ev_rank, pos_ev, n_exceed, first_seg,
                 nseg, meta_b, rmses_b, mags_b, coefs_b, *,
                 T, B, K, S, peek, n_pow_peek,
                 qa_start, qa_inside, qa_end):
    """Segment-close work on VMEM-resident planes (kernel._close_block +
    _write_seg): break magnitudes over the PEEK run (one-hot member
    selection + bitonic median), the 6-column meta row, and the one-hot
    append into the [S,*,BP] result buffers at each closing pixel's nseg.

    coefs [B,K,BP], rmse [B,BP], alive/included_mon [T,BP] bool,
    m/ev_rank/pos_ev/n_exceed [1,BP] i32, is_tail/is_brk/first_seg
    [1,BP] bool, nseg [1,BP] i32, buffers meta_b [S,6,BP],
    rmses_b/mags_b [S,B,BP], coefs_b [S,B*K,BP].
    Returns the updated (meta_b, rmses_b, mags_b, coefs_b, nseg).
    """
    i32 = jnp.int32
    f32 = X.dtype
    one = i32(1)
    close = is_tail | is_brk                                   # [1,BP]
    ti = lax.broadcasted_iota(i32, alive.shape, 0)             # [T,BP]
    rank = _shift_scan_add(jnp.where(alive, one, 0), T) - 1
    rel_ev = rank - ev_rank                                    # [T,BP]
    t_plane = jnp.broadcast_to(t_col, alive.shape)

    def at_t(plane, idx):
        return jnp.sum(jnp.where(ti == idx, plane, 0), 0, keepdims=True)

    # PEEK-run member selection: one-hot over T per run slot (each slot
    # holds at most one observation — the same scatter-free construction
    # as the INIT window; kernel._close_block's oh_run einsums).
    Yf = [y_of(b) for b in range(B)]
    xsel = [[None] * K for _ in range(peek)]
    ysel = [[None] * B for _ in range(peek)]
    for k in range(peek):
        mf = jnp.where(alive & (rel_ev == k), 1.0, 0.0).astype(f32)
        for c in range(K):
            xsel[k][c] = jnp.sum(X[:, c:c + 1] * mf, 0, keepdims=True)
        for b in range(B):
            ysel[k][b] = jnp.sum(Yf[b] * mf, 0, keepdims=True)

    ki = lax.broadcasted_iota(i32, (peek,) + alive.shape[1:], 0)
    run_ok = (ev_rank + ki) < m                                # [peek,BP]
    mags = []
    for b in range(B):
        rows = []
        for k in range(peek):
            pred_k = None
            for c in range(K):
                term = coefs[b, c:c + 1] * xsel[k][c]
                pred_k = term if pred_k is None else pred_k + term
            rows.append(ysel[k][b] - pred_k)
        resid = jnp.concatenate(rows, 0)                       # [peek,BP]
        mags.append(_median_sublane(resid, run_ok, n_pow_peek))
    mags = jnp.concatenate(mags, 0)                            # [B,BP]

    # Segment meta (kernel._close_block meta_new) — argmax semantics for
    # the none-included edge (first->0, last->T-1) mirror the jnp path.
    any_inc = jnp.any(included_mon, 0, keepdims=True)
    INF = i32(T + 1)
    first_inc = jnp.where(
        any_inc,
        jnp.min(jnp.where(included_mon, ti, INF), 0, keepdims=True), 0)
    last_inc = jnp.where(
        any_inc,
        jnp.max(jnp.where(included_mon, ti, -1), 0, keepdims=True), T - 1)
    start_day = at_t(t_plane, first_inc)
    end_day = at_t(t_plane, last_inc)
    break_day = jnp.where(is_brk, at_t(t_plane, pos_ev), end_day)
    chprob = jnp.where(is_brk, 1.0, n_exceed.astype(f32) / float(peek))
    qa_tail = qa_end + jnp.where(first_seg, qa_start, 0)
    qa_brk = jnp.where(first_seg, qa_start, qa_inside)
    qa = jnp.where(is_brk, qa_brk, qa_tail).astype(f32)
    n_obs = jnp.sum(jnp.where(included_mon, one, 0), 0,
                    keepdims=True).astype(f32)
    meta_new = jnp.concatenate(
        [start_day, end_day, break_day, chprob, qa, n_obs], 0)  # [6,BP]
    mag_new = jnp.where(is_brk, mags, 0.0)                      # [B,BP]
    coef_new = jnp.concatenate([coefs[b] for b in range(B)], 0)  # [B*K,BP]

    # One-hot append at nseg (kernel._write_seg): rows past capacity are
    # never selected (iota < S), but nseg still counts — the overflow
    # contract detect_packed's capacity_retry relies on.
    si = lax.broadcasted_iota(i32, (S, 1) + alive.shape[1:], 0)
    sel = (si == nseg[None]) & close[None]                      # [S,1,BP]
    meta_b = jnp.where(sel, meta_new[None], meta_b)
    rmses_b = jnp.where(sel, rmse[None], rmses_b)
    mags_b = jnp.where(sel, mag_new[None], mags_b)
    coefs_b = jnp.where(sel, coef_new[None], coefs_b)
    nseg = nseg + jnp.where(close, one, 0)
    return meta_b, rmses_b, mags_b, coefs_b, nseg


def _detect_mega_block(phase0_ref, curi0_ref, nseg0_ref, alive0_ref,
                       t_ref, x_ref, xtr_ref, xtk_ref, xxt_ref, y_ref,
                       vario_ref, meta0_ref, rmses0_ref, mags0_ref,
                       coefs0_ref,
                       meta_ref, rmses_ref, mags_ref, coefs_ref, nseg_ref,
                       alive_ref, rounds_ref, counts_ref, *,
                       T, W, B, K, NT, S, n_pow_w, det, tmb,
                       change_thr, outlier_thr, max_rounds,
                       cd_iters, alpha, tm_iters, huber_k, tmask_const,
                       meow, init_days, stab_factor, peek, refit_factor,
                       num_obs_factor, mid_coefs,
                       qa_start, qa_inside, qa_end,
                       ph_init, ph_mon, ph_done, mixed=False):
    """One pixel block's ENTIRE event-horizon loop in VMEM.

    The [B,T,BP] wire spectra are read from HBM exactly once per pixel;
    every round's INIT window search, Tmask screen, stability fit,
    monitor scoring/event chain, Lasso refit, and segment write runs on
    VMEM residents inside a single lax.while_loop, with the same
    block-level cond gates as the XLA loop (kernel._detect_batch_impl) —
    a block whose pixels are all monitoring skips the INIT work
    outright, and each block exits as soon as its own pixels are DONE
    (no batch-wide lockstep).  Composes the shared per-phase logic
    (_init_logic, _mon_scored_logic, _gram_cd_core, _close_logic), so
    the arithmetic is bit-aligned with the per-component kernels.
    """
    i32 = jnp.int32
    X = x_ref[0]                                               # [T,K]
    Xtr = xtr_ref[0]                                           # [T,NT]
    XTK = xtk_ref[0]                                           # [K,T]
    XXT = xxt_ref[0]                                           # [K*K,T]
    t_col = t_ref[0]                                           # [T,1]
    f32 = X.dtype
    vario = vario_ref[0]                                       # [B,BP]
    BP = vario.shape[-1]
    det_l = list(det)
    nb = len(det_l)
    y_of = lambda b: y_ref[0, b].astype(f32)
    one = i32(1)
    as_i = lambda v: jnp.where(v, one, 0)

    carry0 = (phase0_ref[0], curi0_ref[0], jnp.zeros((1, BP), i32),
              jnp.ones((1, BP), i32),              # n_last_fit
              jnp.ones((1, BP), i32),              # first_seg
              nseg0_ref[0],
              alive0_ref[0],                       # [T,BP] i32
              jnp.zeros((T, BP), i32),             # included
              jnp.zeros((B, K, BP), f32),          # coefs
              jnp.ones((B, BP), f32),              # rmse
              meta0_ref[0], rmses0_ref[0], mags0_ref[0], coefs0_ref[0],
              # round/gate counters as [1,1] planes, not 0-d scalars —
              # scalar while-carries are unproven under Mosaic; tiny
              # vectors lower like every other carry here.
              jnp.zeros((1, 1), i32),              # rounds
              jnp.zeros((1, 1), i32), jnp.zeros((1, 1), i32),
              jnp.zeros((1, 1), i32))

    def cond(c):
        return (c[14][0, 0] < max_rounds) & jnp.any(c[0] != ph_done)

    def body(c):
        (phase, cur_i, cur_k, nlast, first_i, nseg, alive_i, inc_i,
         coefs, rmse, meta_b, rmses_b, mags_b, coefs_b, rounds,
         cnt_i, cnt_f, cnt_c) = c
        alive = alive_i > 0
        included = inc_i > 0
        first_seg = first_i > 0
        in_init = phase == ph_init                             # [1,BP]
        in_mon = phase == ph_mon

        # ---- INIT block (skipped when no pixel of the block inits) ----
        any_init = jnp.any(in_init)

        def run_init():
            o = _init_logic(alive, cur_i, in_init, t_col, X, Xtr, XTK,
                            XXT, y_of, vario, T=T, W=W, B=B, K=K, NT=NT,
                            n_pow=n_pow_w, det=det, tmb=tmb,
                            cd_iters=cd_iters, alpha=alpha,
                            tm_iters=tm_iters, huber_k=huber_k,
                            tmask_const=tmask_const, meow=meow,
                            init_days=init_days, stab_factor=stab_factor,
                            mixed=mixed)
            # .astype(i32): x64 mode promotes integer sums to i64, which
            # would mismatch the skip branch's i32 zeros.
            return (as_i(o["init_nowin"]), as_i(o["init_tm"]),
                    as_i(o["init_ok"]), as_i(o["init_bad"]),
                    as_i(o["has_adv"]), o["i_next_tm"].astype(i32),
                    o["i_adv"].astype(i32), o["j"].astype(i32),
                    o["n_ok"].astype(i32), as_i(o["w_stab"]),
                    as_i(o["alive_init"]))

        def zero_init():
            zv = jnp.zeros((1, BP), i32)
            return (zv, zv, zv, zv, zv, zv, zv, zv, zv,
                    jnp.zeros((T, BP), i32), alive_i)

        (i_nowin, i_tm, i_ok, i_bad, i_hasadv, i_next, i_adv, i_j,
         i_nok, i_wstab, i_alive) = lax.cond(any_init, run_init, zero_init)
        init_ok = i_ok > 0

        # ---- MONITOR block ----
        any_mon = jnp.any(in_mon)
        dden = jnp.concatenate(
            [jnp.maximum(rmse[b], vario[b])[None] for b in det_l], 0)
        coefs_d = jnp.concatenate([coefs[b][None] for b in det_l], 0)

        def run_mon():
            outs = _mon_scored_logic(
                lambda b: y_ref[0, det_l[b]], coefs_d, dden, X, alive,
                included, cur_k, nlast, in_mon, change_thr=change_thr,
                outlier_thr=outlier_thr, peek=peek,
                refit_factor=refit_factor, T=T, nb=nb)
            return tuple(v.astype(i32) for v in outs)

        def zero_mon():
            zv = jnp.zeros((1, BP), i32)
            zp = jnp.zeros((T, BP), i32)
            return (zv, zv, zv, zv, zv, zv, zv, zv, zp, zp)

        (m, is_tail_i, is_brk_i, is_refit_i, ev_rank, pos_ev, n_exceed,
         n_rf, inc_q_i, rem_q_i) = lax.cond(any_mon, run_mon, zero_mon)
        is_tail = is_tail_i > 0
        is_brk = is_brk_i > 0
        is_refit = is_refit_i > 0
        inc_abs = (inc_q_i > 0) & in_mon
        rem_abs = (rem_q_i > 0) & in_mon
        included_mon = included | inc_abs
        alive_mon = alive & ~rem_abs

        # ---- CLOSE block ----
        close = is_tail | is_brk
        any_close = jnp.any(close)

        def run_close():
            return _close_logic(
                y_of, X, t_col, coefs, rmse, alive, included_mon, m,
                is_tail, is_brk, ev_rank, pos_ev, n_exceed, first_seg,
                nseg, meta_b, rmses_b, mags_b, coefs_b, T=T, B=B, K=K,
                S=S, peek=peek,
                n_pow_peek=1 << max(1, (peek - 1).bit_length()),
                qa_start=qa_start, qa_inside=qa_inside, qa_end=qa_end)

        def keep_close():
            return meta_b, rmses_b, mags_b, coefs_b, nseg

        meta_n, rmses_n, mags_n, coefs_bn, nseg_n = lax.cond(
            any_close, run_close, keep_close)

        # ---- shared Lasso fit (init-ok + refit) ----
        do_fit = init_ok | is_refit
        any_fit = jnp.any(do_fit)
        n_full = jnp.where(init_ok, i_nok, n_rf)               # [1,BP]

        def run_fit():
            # One f32 select, not a bool-valued one: select_n on i1
            # operands lowers to an i8->i1 arith.trunci that Mosaic
            # rejects ("Unsupported target bitwidth for truncation",
            # seen on the real v5e remote compiler, r5).
            wf = jnp.where(init_ok,
                           jnp.where(i_wstab > 0, 1.0, 0.0),
                           jnp.where(included_mon & is_refit, 1.0, 0.0)
                           ).astype(f32)
            nc = jnp.where(
                n_full >= K * num_obs_factor, K,
                jnp.where(n_full >= mid_coefs * num_obs_factor,
                          mid_coefs, 4))
            cm = jnp.where(
                lax.broadcasted_iota(i32, (K, BP), 0) < nc,
                1.0, 0.0).astype(f32)
            beta, n = _gram_cd_core(XTK, XXT, y_of, wf, cm, B=B, K=K,
                                    iters=cd_iters, alpha=alpha,
                                    mixed=mixed)
            rs = []
            for b in range(B):
                pred = jnp.dot(X, beta[b], preferred_element_type=f32)
                r = y_of(b) - pred
                rs.append(jnp.sqrt(jnp.maximum(
                    jnp.sum(r * r * wf, 0, keepdims=True) / n, 0.0)))
            return beta, jnp.concatenate(rs, 0)

        def keep_fit():
            return coefs, rmse

        cfull, rfull = lax.cond(any_fit, run_fit, keep_fit)

        # ---- next state (kernel._detect_batch_impl body) ----
        phase_n = jnp.where(
            (i_nowin > 0) | ((i_bad > 0) & ~(i_hasadv > 0)), ph_done,
            jnp.where(init_ok, ph_mon,
                      jnp.where(is_tail, ph_done,
                                jnp.where(is_brk, ph_init, phase))))
        cur_i_n = jnp.where(
            i_tm > 0, i_next,
            jnp.where((i_bad > 0) & (i_hasadv > 0), i_adv,
                      jnp.where(is_brk, pos_ev, cur_i)))
        cur_k_n = jnp.where(init_ok, i_j + 1,
                            jnp.where(is_refit, pos_ev + 1, cur_k))
        # Logical forms, not bool-valued selects: an i1-result select_n
        # lowers to an i8->i1 trunci Mosaic rejects (same mechanism as
        # run_fit's wf above).
        alive_n = ((in_init & (i_alive > 0))
                   | (~in_init & in_mon & alive_mon)
                   | (~in_init & ~in_mon & alive))
        included_n = ((init_ok & (i_wstab > 0))
                      | (~init_ok & ~is_brk
                         & ((in_mon & included_mon)
                            | (~in_mon & included))))
        coefs_n = jnp.where(do_fit[None], cfull, coefs)
        rmse_n = jnp.where(do_fit, rfull, rmse)
        nlast_n = jnp.where(do_fit, n_full, nlast)
        first_n = first_seg & ~is_brk

        return (phase_n, cur_i_n, cur_k_n, nlast_n, as_i(first_n),
                nseg_n, as_i(alive_n), as_i(included_n), coefs_n, rmse_n,
                meta_n, rmses_n, mags_n, coefs_bn, rounds + 1,
                cnt_i + jnp.where(any_init, 1, 0).astype(i32),
                cnt_f + jnp.where(any_fit, 1, 0).astype(i32),
                cnt_c + jnp.where(any_close, 1, 0).astype(i32))

    fin = lax.while_loop(cond, body, carry0)
    (_, _, _, _, _, nseg, alive_f, _, _, _, meta_b, rmses_b, mags_b,
     coefs_b, rounds, cnt_i, cnt_f, cnt_c) = fin
    meta_ref[0] = meta_b
    rmses_ref[0] = rmses_b
    mags_ref[0] = mags_b
    coefs_ref[0] = coefs_b
    nseg_ref[0] = nseg
    alive_ref[0] = alive_f
    rounds_ref[0] = jnp.broadcast_to(rounds, (1, BP))
    counts_ref[0] = jnp.concatenate(
        [jnp.broadcast_to(cnt_i, (1, BP)), jnp.broadcast_to(cnt_f, (1, BP)),
         jnp.broadcast_to(cnt_c, (1, BP))], 0)


@functools.partial(jax.jit, static_argnames=(
    "W", "S", "sensor", "phases", "change_thr", "outlier_thr",
    "mixed", "block_p", "interpret"))
def detect_mega(Yt, phase0, cur_i0, alive0, nseg0, bufs0, t, X, Xt, vario,
                *, W, S, sensor, phases, change_thr, outlier_thr,
                mixed=False, block_p=None, interpret=False):
    """The whole event-horizon loop as ONE pallas_call (the 'mega'
    component): grid over (chip, pixel-block), each block running its own
    while_loop with the wire spectra VMEM-resident — HBM traffic for the
    entire loop is one [B,T,P] wire read + the state/buffer boundary,
    ~B*T*wire_bytes per pixel instead of per-round re-streams.

    Args (C chips, batched leading axis):
        Yt: [C,B,T,P] resident spectra (wire int16 or float32).
        phase0, cur_i0, nseg0: [C,P] i32 start state (kernel._prologue).
        alive0: [C,P,T] bool.
        bufs0: (meta [C,P,S*6], rmse [C,P,S*B], mag [C,P,S*B],
                coef [C,P,S*B*K]) flat result buffers (may hold the
                prologue's alt-procedure rows).
        t: [C,T]; X: [C,T,K]; Xt: [C,T,NT]; vario: [C,P,B].
        phases: (PHASE_INIT, PHASE_MONITOR, PHASE_DONE) static ints.
    Returns:
        dict(meta [C,P,S,6], rmse [C,P,S,B], mag [C,P,S,B],
             coef [C,P,S,B,K], nseg [C,P], rounds [C], counts [C,3]).
    """
    C, B, T, P = Yt.shape
    K = X.shape[-1]
    NT = Xt.shape[-1]
    f32 = X.dtype
    i32 = jnp.int32
    det = tuple(sensor.detection_bands)
    tmb = tuple(sensor.tmask_bands)
    ph_init, ph_mon, ph_done = phases
    # ``block_p`` (static) overrides the budget-derived width — the
    # SIGABRT repro's block-shape reduction (tools/fuse_repro.py); the
    # FIREBIRD_MEGA_BLOCK_P knob (bench-seeded from fuse_repro.json's
    # smallest compiling shape) sits between the two.
    BP = block_p or _env_block_p() or mega_block_p(T, W, B, S,
                                                   Yt.dtype.itemsize)
    Pp = -BP * (-P // BP)
    pad = Pp - P
    nblk = Pp // BP

    def padP(a, cv=0):
        return jnp.pad(a, ((0, 0),) * (a.ndim - 1) + ((0, pad),),
                       constant_values=cv)

    meta0, rmse0, mag0, coef0 = bufs0
    args = (
        padP(phase0[:, None, :].astype(i32), ph_done),         # [C,1,Pp]
        padP(cur_i0[:, None, :].astype(i32)),
        padP(nseg0[:, None, :].astype(i32)),
        padP(alive0.transpose(0, 2, 1).astype(i32)),           # [C,T,Pp]
        t.astype(f32)[:, :, None],                             # [C,T,1]
        X, Xt,
        X.transpose(0, 2, 1),                                  # [C,K,T]
        (X[..., :, None] * X[..., None, :])
        .reshape(C, T, K * K).transpose(0, 2, 1),              # [C,K*K,T]
        padP(Yt),                                              # [C,B,T,Pp]
        padP(vario.transpose(0, 2, 1).astype(f32), 1.0),       # [C,B,Pp]
        padP(meta0.reshape(C, P, S, 6).transpose(0, 2, 3, 1)),  # [C,S,6,Pp]
        padP(rmse0.reshape(C, P, S, B).transpose(0, 2, 3, 1)),
        padP(mag0.reshape(C, P, S, B).transpose(0, 2, 3, 1)),
        padP(coef0.reshape(C, P, S, B * K).transpose(0, 2, 3, 1)),
    )

    def bmap(shape):
        # per-(chip, block) input: trailing axis is the pixel axis
        nlead = len(shape) - 1
        return pl.BlockSpec(
            (1,) + shape,
            lambda c, i, _n=nlead: (c,) + (0,) * _n + (i,))

    def cmap(shape):
        # chip-shared input (designs): no pixel axis
        return pl.BlockSpec(
            (1,) + shape,
            lambda c, i, _n=len(shape): (c,) + (0,) * _n)

    kern = functools.partial(
        _detect_mega_block, T=T, W=W, B=B, K=K, NT=NT, S=S,
        n_pow_w=1 << max(1, (W - 1).bit_length()), det=det, tmb=tmb,
        change_thr=float(change_thr), outlier_thr=float(outlier_thr),
        max_rounds=2 * T + 8,
        cd_iters=int(params.LASSO_ITERS), alpha=float(params.LASSO_ALPHA),
        tm_iters=int(params.TMASK_IRLS_ITERS),
        huber_k=float(params.HUBER_K),
        tmask_const=float(params.TMASK_CONST),
        meow=int(params.MEOW_SIZE), init_days=float(params.INIT_DAYS),
        stab_factor=float(params.STABILITY_FACTOR),
        peek=int(params.PEEK_SIZE),
        refit_factor=float(params.REFIT_FACTOR),
        num_obs_factor=int(params.NUM_OBS_FACTOR),
        mid_coefs=int(params.MID_COEFS),
        qa_start=int(params.CURVE_QA_START),
        qa_inside=int(params.CURVE_QA_INSIDE),
        qa_end=int(params.CURVE_QA_END),
        ph_init=int(ph_init), ph_mon=int(ph_mon), ph_done=int(ph_done),
        mixed=bool(mixed))

    outs = pl.pallas_call(
        kern,
        grid=(C, nblk),
        in_specs=[
            bmap((1, BP)), bmap((1, BP)), bmap((1, BP)), bmap((T, BP)),
            cmap((T, 1)), cmap((T, K)), cmap((T, NT)), cmap((K, T)),
            cmap((K * K, T)),
            bmap((B, T, BP)), bmap((B, BP)),
            bmap((S, 6, BP)), bmap((S, B, BP)), bmap((S, B, BP)),
            bmap((S, B * K, BP)),
        ],
        out_specs=[
            bmap((S, 6, BP)), bmap((S, B, BP)), bmap((S, B, BP)),
            bmap((S, B * K, BP)), bmap((1, BP)), bmap((T, BP)),
            bmap((1, BP)), bmap((3, BP)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, S, 6, Pp), f32),
            jax.ShapeDtypeStruct((C, S, B, Pp), f32),
            jax.ShapeDtypeStruct((C, S, B, Pp), f32),
            jax.ShapeDtypeStruct((C, S, B * K, Pp), f32),
            jax.ShapeDtypeStruct((C, 1, Pp), i32),
            jax.ShapeDtypeStruct((C, T, Pp), i32),
            jax.ShapeDtypeStruct((C, 1, Pp), i32),
            jax.ShapeDtypeStruct((C, 3, Pp), i32),
        ],
        interpret=interpret,
    )(*args)
    meta, rmses, mags, coefsb, nseg, alive_f, rounds, counts = outs
    return dict(
        meta=meta[..., :P].transpose(0, 3, 1, 2),
        rmse=rmses[..., :P].transpose(0, 3, 1, 2),
        mag=mags[..., :P].transpose(0, 3, 1, 2),
        coef=coefsb[..., :P].transpose(0, 3, 1, 2)
        .reshape(C, P, S, B, K),
        nseg=nseg[:, 0, :P],
        alive=(alive_f[..., :P] > 0).transpose(0, 2, 1),
        rounds=jnp.max(rounds[:, 0, :], axis=-1),
        counts=jnp.max(counts, axis=-1),
    )
