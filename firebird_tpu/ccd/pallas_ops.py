"""Pallas TPU kernels for the CCD hot ops.

The Lasso coordinate-descent loop is the detector's serial core: every
event-loop round runs LASSO_ITERS x MAX_COEFS sequential coordinate
updates over [P, B, 8] Gram systems (kernel._fit_lasso_coefs; the round
count is small, so the CD loop dominates the non-matmul step count).
Under plain XLA each of those ~400 steps materializes its [P, B]
intermediates between fused ops; this kernel keeps the whole state
(G, c, diag, mask, b) resident in VMEM for all iterations, streaming each
pixel block exactly once.

Layout: the pixel axis goes LAST ([K, K, P], [B, K, P], ...) so it rides
the 128-wide vector lanes and the tiny K=8 axis sits on sublanes — the
natural VPU shape for the per-coordinate updates, which are elementwise
over P.

Enablement: `firebird_tpu.ccd.kernel` calls :func:`lasso_cd` when
FIREBIRD_PALLAS=1 (off by default until benchmarked on hardware; CPU
tests run the same kernel under ``interpret=True``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from firebird_tpu.ccd import params

BLOCK_P = 512   # pixels per grid step (4 x 128 lanes, f32)


def _cd_block(G_ref, c_ref, diag_ref, mask_ref, out_ref, *, iters, alpha,
              n_coefs):
    """One pixel block: full CD loop in VMEM.

    G [K,K,Pb], c [B,K,Pb], diag [K,Pb], mask [K,Pb] (0/1) -> b [B,K,Pb].
    """
    G = G_ref[...]
    c = c_ref[...]
    diag = diag_ref[...]
    mask = mask_ref[...]

    def one_iter(_, b):
        for j in range(n_coefs):
            # rho_j = c_j - sum_k G[j,k] b_k + diag_j b_j   (all [B,Pb])
            rho = (c[:, j] - jnp.sum(G[j][None, :, :] * b, axis=1)
                   + diag[j][None, :] * b[:, j])
            if j == 0:                       # intercept: unpenalized
                bj = rho / diag[0][None, :]
            else:
                bj = (jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - alpha, 0.0)
                      / diag[j][None, :])
            bj = jnp.where(mask[j][None, :] > 0, bj, 0.0)
            # one-hot select, not b.at[:, j].set: scatter has no Mosaic
            # lowering, and j is static so a select is exact.  The iota
            # must be >=2D (Mosaic has no 1D iota) and traced (pallas_call
            # rejects captured array constants).
            sel = lax.broadcasted_iota(jnp.int32, (1, n_coefs, 1), 1) == j
            b = jnp.where(sel, bj[:, None, :], b)
        return b

    out_ref[...] = lax.fori_loop(0, iters, one_iter, jnp.zeros_like(c))


@functools.partial(jax.jit, static_argnames=("iters", "interpret"))
def lasso_cd(G, c, diag, coefmask, *, iters=params.LASSO_ITERS,
             interpret=False):
    """Pallas port of kernel's CD loop (bit-compatible update order).

    Args:
        G: [P, K, K] normalized Gram matrices.
        c: [P, B, K] normalized X^T y per band.
        diag: [P, K] Gram diagonals (pre-floored).
        coefmask: [P, K] allowed coefficients (bool or 0/1).
    Returns:
        b [P, B, K], identical (up to float assoc.) to the lax fori_loop
        version in kernel._fit_lasso_coefs.
    """
    P, B, K = c.shape
    dt = c.dtype
    Pp = -BLOCK_P * (-P // BLOCK_P)
    pad = Pp - P

    # Pixel axis last; pad to the block multiple (diag pads to 1 so the
    # padded lanes divide harmlessly; mask pads to 0 so they output 0).
    Gt = jnp.pad(G.transpose(1, 2, 0), ((0, 0), (0, 0), (0, pad)))
    ct = jnp.pad(c.transpose(1, 2, 0), ((0, 0), (0, 0), (0, pad)))
    dg = jnp.pad(diag.T, ((0, 0), (0, pad)), constant_values=1.0)
    mk = jnp.pad(coefmask.T.astype(dt), ((0, 0), (0, pad)))

    kern = functools.partial(_cd_block, iters=iters,
                             alpha=float(params.LASSO_ALPHA), n_coefs=K)
    bt = pl.pallas_call(
        kern,
        grid=(Pp // BLOCK_P,),
        in_specs=[
            pl.BlockSpec((K, K, BLOCK_P), lambda i: (0, 0, i)),
            pl.BlockSpec((B, K, BLOCK_P), lambda i: (0, 0, i)),
            pl.BlockSpec((K, BLOCK_P), lambda i: (0, i)),
            pl.BlockSpec((K, BLOCK_P), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((B, K, BLOCK_P), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((B, K, Pp), dt),
        interpret=interpret,
    )(Gt, ct, dg, mk)
    return bt[:, :, :P].transpose(2, 0, 1)
