"""The CCDC TPU kernel: whole chips per dispatch, jit + vmap, no per-pixel
Python.

This replaces the reference's hot loop — ``ccd.detect`` called per pixel
inside a Spark flatMap (ccdc/pyccd.py:171-183; "pure CPU, seconds per pixel
series", SURVEY.md §3.1) — with a fixed-shape JAX program that runs all
10,000 pixels of a chip in lockstep and implements the same spec as
:mod:`firebird_tpu.ccd.reference`.

Design: **event-horizon fast-forward.**  CCDC is a per-pixel sequential
state machine, but between model refits its decisions depend only on the
*current* model.  So instead of scanning observation-by-observation, each
round advances every pixel to its next *model event*:

- INIT pixels derive their initialization window, run the Tmask IRLS screen,
  and test stability — one batched fit.
- MONITOR pixels score *all* remaining observations against their current
  model in one shot ([P, T] ops against the chip-shared design matrix) and
  locate the first event in closed form: a confirmed break (six consecutive
  exceeding observations, found via shifted-AND on the compacted alive
  sequence), a refit point (absorbed-count crossing the 1.33x ladder), or
  the series tail.  Everything before the event is absorbed/removed per the
  spec's rules without iteration.

Every round's heavy math is a handful of [P,T]x[T,8] matmuls (MXU) plus
fixed-iteration coordinate descent on [P,7,8] Gram systems; the number of
rounds equals the deepest pixel's event count (typically a few dozen), not
the series length.  The dates grid — and therefore the design matrix — is
shared chip-wide, which is what makes the batching work; the wire path
builds the designs ON DEVICE from the int32 day ordinals (device_designs;
an exact-integer phase reduction keeps the phase argument bit-identical
to the host float64 spec in harmonic.design_matrix), so nothing float
crosses the h2d wire at all.

Batching over chips is a vmap; sharding over devices is a NamedSharding on
the chip axis (firebird_tpu.parallel).
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from firebird_tpu.ccd import harmonic, params
from firebird_tpu.ccd.sensor import LANDSAT_ARD, chi2_thresholds

MAX_SEGMENTS = 10

PHASE_INIT, PHASE_MONITOR, PHASE_DONE = 0, 1, 2
PROC_STANDARD, PROC_SNOW, PROC_INSUF, PROC_NODATA = 0, 1, 2, 3


def use_pallas(component: str = "lasso") -> bool:
    """Whether `component` runs as its Pallas VMEM-resident kernel.

    FIREBIRD_PALLAS is "0"/"" (none), "1" (all), or a comma list of
    component names ("lasso,monitor,tmask,fit,score,init") — bench.py
    tunes the components independently on hardware, so a kernel that
    loses on a given toolchain can't drag down the ones that win.
    "fit" (the fused Gram+corr+CD+RMSE kernel) supersedes "lasso" (CD
    loop only) at the fit call sites; "score" (the score-fused monitor
    kernel) supersedes "monitor"; "init" (the fused INIT-window kernel)
    supersedes "tmask" inside the init block; "mega" (the whole-loop
    kernel) supersedes ALL of them and must be named explicitly — "1"
    means every per-component kernel, not the mega route, so existing
    "all-on" configs keep their meaning.  Read at trace time: set it
    before the first detect call — already-compiled programs keep their
    path."""
    from firebird_tpu.config import env_knob

    v = env_knob("FIREBIRD_PALLAS")
    if v in ("", "0"):
        return False
    if v == "1":
        return component != "mega"
    return component in {c.strip() for c in v.split(",")}


def _wire_resident_only() -> bool:
    """True when every event-loop consumer of the widened float spectra
    is routed to a Pallas kernel reading the wire-dtype residents (the
    init, score, and fit components together) — the prologue then keeps
    the float view out of ``res`` so XLA frees it after the pre-loop
    work.  _detect_batch_impl combines this with the f32-on-TPU gate
    (the float64-on-TPU fallback keeps the float view resident) and
    independently with the mega route (which reads only the wire residents by
    construction, but only when mega_fits accepts the shape — a refused
    mega must fall back to a loop that still has its float view)."""
    return (use_pallas("init") and use_pallas("score")
            and use_pallas("fit"))


def use_fused_fit() -> bool:
    """Whether the event loop's segment-close + shared-Lasso-fit pair
    runs as the fused Pallas gram→CD→close kernel
    (pallas_ops.fused_fit_close): one VMEM residency of the wire spectra
    serves both phases instead of two HBM streams plus the [P,*]
    intermediates between the cond-gated fusions.  FIREBIRD_FUSED_FIT,
    default off; read at trace time like use_pallas (f32-on-TPU only
    when compiled; interpret elsewhere); the mega route supersedes it."""
    from firebird_tpu.config import env_knob

    return env_knob("FIREBIRD_FUSED_FIT") not in ("", "0")


def fused_mode():
    """FIREBIRD_FUSED_FIT's three-way resolution: 0 (off), 1 (the fused
    fit+close kernel, byte-identical to the unfused chain), or "mon"
    (value "mon" or "2" — the monitor-fused round kernel
    pallas_ops.fused_round, one VMEM residency for the whole post-INIT
    round; decision-exact with the seg_mag f32 envelope, like the mega
    route).  Read at trace time like use_pallas."""
    from firebird_tpu.config import env_knob

    v = env_knob("FIREBIRD_FUSED_FIT")
    if v in ("", "0"):
        return 0
    if v in ("2", "mon"):
        return "mon"
    return 1


def use_mixed_precision() -> bool:
    """Whether the fit kernels accumulate the Gram/corr dots in bf16
    split form (f32 accumulators, int32 counts) instead of the 6-pass
    f32-"highest" emulation — pallas_ops._gram_cd_core's ``mixed``
    path.  Decision fields stay identical to the f32 path (the split
    exploits the int16-valued spectra and 0/1 weights; coef/rmse drift
    is bounded by params.MIXED_ULP_BUDGET — tools/precision_smoke.py
    enforces both).  FIREBIRD_MIXED_PRECISION, default off; read at
    trace time like use_pallas; applies only to f32 stores (the f64
    bit-parity path keeps full precision) and only to the Pallas fit
    routes — the XLA reference path stays f32, it IS the oracle the
    identity tests compare against."""
    from firebird_tpu.config import env_knob

    return env_knob("FIREBIRD_MIXED_PRECISION") not in ("", "0")


# ---------------------------------------------------------------------------
# Results container
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ChipSegments:
    """Fixed-capacity per-pixel segment results (device or host arrays).

    Leading axes may be [P] (one chip) or [C, P] (a batch).
    seg_meta fields: sday, eday, bday, chprob, curqa, nobs.
    seg_coef holds *internal* coefficients [.., 7 bands, 8]; convert with
    harmonic.to_pyccd_convention(anchor=first series date).
    """

    n_segments: jnp.ndarray      # [.., P] int32
    seg_meta: jnp.ndarray        # [.., P, S, 6] float32
    seg_rmse: jnp.ndarray        # [.., P, S, 7]
    seg_mag: jnp.ndarray         # [.., P, S, 7]
    seg_coef: jnp.ndarray        # [.., P, S, 7, 8]
    mask: jnp.ndarray            # [.., P, T] bool — processing mask
    procedure: jnp.ndarray       # [.., P] int32
    rounds: jnp.ndarray | None = None  # [..] int32 event-loop rounds (diag)
    vario: jnp.ndarray | None = None   # [.., P, 7] variogram (streaming seed)
    round_counts: jnp.ndarray | None = None
    # ^ [.., 3] int32: rounds in which the cond-gated INIT / shared-fit /
    #   segment-close blocks actually executed (diagnostic; feeds the
    #   measurement-driven roofline model in ccd.flops / bench.py).
    occupancy: jnp.ndarray | None = None
    # ^ [.., R_max, 2] int32 per-chip, per-executed-round (active_lanes,
    #   paid_lanes): active = lanes with phase != DONE entering the round,
    #   paid = lanes in COMPACT_LANE_BLOCK-wide blocks containing any
    #   active lane (the skip-guard accounting unit; full width when
    #   compaction is off).  The capture is the same on every backend so
    #   CPU runs predict TPU behavior — which means ``paid`` is measured
    #   compute only where the Pallas per-block guards execute; the lax
    #   fallback paths carry the guards for control-flow parity but
    #   compute every lane (under vmap the slab cond is a select), so
    #   there ``paid`` models what the guards would skip and only the
    #   stage-2 bucket narrows real work.  Rows past ``rounds`` are
    #   zero.  Feeds flops.occupancy_detail and record_occupancy.
    compactions: jnp.ndarray | None = None
    # ^ [..] int32: dense-prefix compactions the batch's loop performed,
    #   recorded at each loop's first chip row and zero elsewhere — sum
    #   over the chip axis for the batch total (correct under sharding,
    #   where each shard runs its own loop; see _detect_batch_impl).
    lanes_migrated: jnp.ndarray | None = None
    # ^ [..] int32 per chip: straggler lanes this chip DONATED to the
    #   right-neighbor device through the rebalancing ring at the
    #   bucketed-tail boundary (parallel.mesh.rebalance_tail_out).  The
    #   donated lanes' results are computed on the neighbor and merged
    #   back positionally, so stores stay row-identical; the chip-sum
    #   feeds the kernel_lanes_migrated counter (record_occupancy).
    #   None on every non-rebalancing dispatch.


jax.tree_util.register_pytree_node(
    ChipSegments,
    lambda s: ((s.n_segments, s.seg_meta, s.seg_rmse, s.seg_mag, s.seg_coef,
                s.mask, s.procedure, s.rounds, s.vario, s.round_counts,
                s.occupancy, s.compactions, s.lanes_migrated),
               None),
    lambda _, c: ChipSegments(*c),
)


# ---------------------------------------------------------------------------
# Small batched primitives
# ---------------------------------------------------------------------------

def _bitonic_sort_last(x):
    """Ascending sort along the (static) last axis as a bitonic network:
    log^2(W) stages of reshape + min/max + select, no generic Sort HLO.
    XLA's Sort is the slowest primitive in this kernel on both CPU and
    TPU for the many-rows/short-axis shapes the medians use; the network
    is pure elementwise VPU work and produces bit-identical values for
    non-NaN data.  A NaN input poisons its whole row (min/max propagate),
    unlike Sort's NaNs-last — acceptable here because the only upstream
    NaN source is a degenerate Tmask Gram, whose terminal behavior
    (comparisons read False, nothing flagged) is NaN-absorbing either
    way.  Non-power-of-two axes pad with +inf (dropped before
    returning)."""
    W = x.shape[-1]
    if W <= 1:
        return x
    n = 1 << (W - 1).bit_length()
    if n != W:
        pad = jnp.full(x.shape[:-1] + (n - W,), jnp.inf, x.dtype)
        x = jnp.concatenate([x, pad], axis=-1)
    k = 2
    while k <= n:
        j = k // 2
        while j >= 1:
            shp = x.shape
            x2 = x.reshape(shp[:-1] + (n // (2 * j), 2, j))
            a, b = x2[..., 0, :], x2[..., 1, :]
            lo, hi = jnp.minimum(a, b), jnp.maximum(a, b)
            # ascending iff bit k of the element's absolute index is 0;
            # that bit is constant across the (pair, lane) axes.
            asc = ((jnp.arange(n // (2 * j)) * (2 * j)) & k) == 0
            asc = asc[(None,) * (lo.ndim - 2) + (slice(None), None)]
            x = jnp.stack([jnp.where(asc, lo, hi),
                           jnp.where(asc, hi, lo)], axis=-2).reshape(shp)
            j //= 2
        k *= 2
    return x[..., :W]


def _onehot_take(x, i):
    """x[..., i] along the last axis via a masked one-hot reduce.

    take_along_axis per-lane gathers along the minor axis lower to
    serialized loops on TPU (each profiled at ~0.5 ms/round in the event
    loop); the reduce is one fused elementwise pass.
    """
    k = jnp.arange(x.shape[-1])
    return jnp.sum(jnp.where(k == i[..., None], x, 0), -1)


def _masked_median(x, m):
    """Median of x where m, along the last axis (numpy even-count average)."""
    s = _bitonic_sort_last(jnp.where(m, x, jnp.inf))
    n = jnp.sum(m, axis=-1)
    lo = _onehot_take(s, jnp.maximum((n - 1) // 2, 0))
    hi = _onehot_take(s, jnp.maximum(n // 2, 0))
    med = 0.5 * (lo + hi)
    return jnp.where(n > 0, med, 0.0)


def _fit_lasso_coefs(X, Y, w, coefmask, XX=None, active=None):
    """Batched Lasso coefficients via cyclic coordinate descent on Grams.

    Mirrors harmonic.lasso_cd_gram exactly (same update, same iteration
    count, intercept unpenalized); column restriction (4/6/8 coefs) is the
    coefmask — zeroed coordinates never update, which is equivalent to
    fitting with fewer design columns.

    Args:
        X: [T, 8] design (chip-shared).
        Y: [P, 7, T] observations.
        w: [P, T] 0/1 weights (the fit window).
        coefmask: [P, 8] allowed coefficients.
        XX: optional [T, 64] flattened per-row outer products X[t] X[t]^T,
            precomputed once per chip.  The 0/1 weights make the two Gram
            formulations bit-identical per term, and [P,T]x[T,64] is one
            MXU matmul instead of a [P,T,8] broadcast temporary.
        active: optional [P] bool skip guard (compaction mode): pixels
            outside it are guaranteed all-zero ``w`` rows, whose CD
            output is exactly zero — so the Pallas kernel skips whole
            dead lane blocks (a per-block ``pl.when``) and the lax path
            cond-gates the CD slab on any(active).  ``None`` preserves
            the unguarded program.

    Returns:
        coefs [P,7,8].
    """
    K = params.MAX_COEFS
    n = jnp.maximum(jnp.sum(w, -1), 1.0)                       # [P]
    if XX is None:
        XX = (X[:, :, None] * X[:, None, :]).reshape(-1, K * K)
    G = (w @ XX).reshape(-1, K, K) / n[:, None, None]          # [P,8,8]
    c = jnp.einsum("pbt,tc->pbc", Y * w[:, None, :], X) / n[:, None, None]
    diag = jnp.maximum(jnp.diagonal(G, axis1=-2, axis2=-1), 1e-12)  # [P,8]

    if use_pallas("lasso"):
        on_tpu = jax.default_backend() == "tpu"
        # Mosaic cannot lower float64; compiled Pallas is f32-on-TPU only.
        # Off-TPU the same kernel runs interpreted (tests), any dtype.
        if not on_tpu or c.dtype == jnp.float32:
            from firebird_tpu.ccd import pallas_ops

            return pallas_ops.lasso_cd(G, c, diag, coefmask,
                                       active=active,
                                       interpret=not on_tpu)
    if active is None:
        return _lasso_cd_lax(G, c, diag, coefmask)
    # The lax slab guard: an all-dead slab (here the slab is the whole
    # call — the batch-level cond gates already bound it) skips the CD
    # loop for the exact zeros it would compute.  Under vmap the cond
    # degenerates to a select; the value is identical either way, so
    # tier-1 CPU runs exercise the same control flow the Pallas
    # per-block guards take on TPU.
    return lax.cond(jnp.any(active),
                    lambda: _lasso_cd_lax(G, c, diag, coefmask),
                    lambda: jnp.zeros_like(c))


def _lasso_cd_lax(G, c, diag, coefmask):
    """The CD loop as a lax fori_loop (the default / reference path; the
    Pallas VMEM-resident version is pallas_ops.lasso_cd)."""
    alpha = params.LASSO_ALPHA

    def one_iter(_, b):
        for j in range(params.MAX_COEFS):
            rho = (c[..., j] - jnp.sum(G[:, j, None, :] * b, -1)
                   + diag[:, j][:, None] * b[..., j])
            if j == 0:
                bj = rho / diag[:, j][:, None]
            else:
                bj = jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - alpha, 0.0) \
                    / diag[:, j][:, None]
            bj = jnp.where(coefmask[:, j][:, None], bj, 0.0)
            b = b.at[..., j].set(bj)
        return b

    b0 = jnp.zeros_like(c)
    return lax.fori_loop(0, params.LASSO_ITERS, one_iter, b0)


def _fit_lasso(X, Y, w, coefmask, XX=None, active=None):
    """_fit_lasso_coefs plus the weighted-window RMSE.

    Returns:
        (coefs [P,7,8], rmse [P,7]).
    """
    b = _fit_lasso_coefs(X, Y, w, coefmask, XX=XX, active=active)
    n = jnp.maximum(jnp.sum(w, -1), 1.0)
    pred = jnp.einsum("pbc,tc->pbt", b, X)
    r = Y - pred
    rmse = jnp.sqrt(jnp.maximum(
        jnp.sum(r * r * w[:, None, :], -1) / n[:, None], 0.0))
    return b, rmse


def _coefmask_for(n):
    """[P,8] allowed-coefficient mask from per-pixel obs counts (4/6/8)."""
    nc = jnp.where(n >= params.MAX_COEFS * params.NUM_OBS_FACTOR, 8,
                   jnp.where(n >= params.MID_COEFS * params.NUM_OBS_FACTOR, 6, 4))
    return jnp.arange(params.MAX_COEFS)[None, :] < nc[:, None]


def _chol_solve_small(G, c):
    """Solve G x = c for SPD G [.., n*n] (row-major flat), c [.., n] with
    n tiny and static: fully unrolled Cholesky + two substitutions as
    elementwise ops over the batch lanes — no LAPACK-style
    Cholesky/TriangularSolve HLOs, which are latency-bound at small n.
    G is FLAT on purpose: a [.., 5, 5] trailing shape takes a TPU tiled
    layout padded 8x128 (20x the logical bytes), and the per-IRLS-round
    relayout copies showed up at ~2 ms each in the profile.

    Numerically non-PD lanes (a pivot <= 0) return NaN, matching
    jnp.linalg.cholesky — callers' downstream comparisons then read
    False, which is the degenerate-Gram contract _tmask_bad relies on
    (flag nothing rather than fabricate huge betas)."""
    n = c.shape[-1]
    ok = None
    L = [[None] * n for _ in range(n)]
    for i in range(n):
        for j in range(i + 1):
            s = G[..., i * n + j]
            for q in range(j):
                s = s - L[i][q] * L[j][q]
            if i == j:
                pos = s > 0
                ok = pos if ok is None else ok & pos
                L[i][j] = jnp.sqrt(jnp.maximum(s, 1e-30))
            else:
                L[i][j] = s / L[j][j]
    y = [None] * n
    for i in range(n):
        s = c[..., i]
        for q in range(i):
            s = s - L[i][q] * y[q]
        y[i] = s / L[i][i]
    x = [None] * n
    for i in reversed(range(n)):
        s = y[i]
        for q in range(i + 1, n):
            s = s - L[q][i] * x[q]
        x[i] = s / L[i][i]
    out = jnp.stack(x, axis=-1)
    return jnp.where(ok[..., None], out, jnp.nan)


def _tmask_bad(Xtw, Y2, w, vario2):
    """Batched Tmask: IRLS Huber harmonic fit on the Tmask bands.

    Mirrors harmonic.irls_huber + reference.tmask_outliers: fixed
    TMASK_IRLS_ITERS iterations, MAD sigma, Huber weights, outlier if the
    final absolute residual exceeds TMASK_CONST * variogram in any band.

    Operates on the *compacted window* axis W (the gathered init-window
    members, bounded by the host-computed window cap) — the per-iteration
    median/MAD selections and Gram builds run over W instead of the full
    series, which is what makes the per-round Tmask cheap.

    Args:
        Xtw: [P, W, 5] no-trend design rows gathered at the window members.
        Y2: [P, 2, W] Tmask-band observations at the window members.
        w: [P, W] 0/1 validity of each gathered slot.
        vario2: [P, 2].

    Returns:
        bad [P, W] bool (within the window).
    """
    k = params.HUBER_K
    nt = Xtw.shape[-1]
    # Per-member design outer products, shared by every IRLS Gram build:
    # each solve is then one [P,2,W]x[P,W,nt^2] dot producing a FLAT Gram
    # instead of a 4-operand einsum whose [.., nt, nt] output takes a
    # padded tiled layout (6 Gram einsums + relayout copies were ~27 ms
    # of the profiled dispatch).
    XtXt = (Xtw[..., :, None] * Xtw[..., None, :]
            ).reshape(*Xtw.shape[:-1], nt * nt)                # [P,W,25]
    eye = (1e-9 * jnp.eye(nt, dtype=Xtw.dtype)).reshape(nt * nt)

    def solve(wt):
        # wt [P,2,W] weights -> beta [P,2,nt].  SPD solve via an unrolled
        # Cholesky over the batch lanes (_chol_solve_small): nt is a tiny
        # static 5, and XLA's batched Cholesky/TriangularSolve run a
        # LAPACK-shaped blocked algorithm that is latency-bound at this
        # size on both CPU and TPU.  Gram/corr are broadcast-multiply-
        # reduce fusions, NOT batched dots: a [2,W]x[W,25] matmul per
        # pixel makes XLA grid over the 10k-pixel batch axis (profiled
        # ~3.4 ms per solve vs ~0.1 ms of actual bytes).
        G = jnp.sum(wt[:, :, :, None] * XtXt[:, None, :, :], axis=2)
        cc = jnp.sum((Y2 * wt)[:, :, :, None] * Xtw[:, None, :, :], axis=2)
        return _chol_solve_small(G + eye, cc)

    def pred(beta):
        return jnp.sum(beta[:, :, None, :] * Xtw[:, None, :, :], axis=-1)

    w2 = jnp.broadcast_to(w[:, None, :], Y2.shape).astype(Y2.dtype)
    beta = solve(w2)
    for _ in range(params.TMASK_IRLS_ITERS):
        r = Y2 - pred(beta)
        med = _masked_median(r, w2 > 0)
        mad = _masked_median(jnp.abs(r - med[..., None]), w2 > 0)
        sigma = jnp.maximum(mad / 0.6745, 1e-6)
        a = jnp.abs(r) / (k * sigma[..., None])
        huber = jnp.where(a <= 1.0, 1.0, 1.0 / jnp.maximum(a, 1e-12))
        beta = solve(w2 * huber)
    r = jnp.abs(Y2 - pred(beta))
    bad = (r > params.TMASK_CONST * vario2[..., None]) & (w2 > 0)
    return jnp.any(bad, axis=1)


# ---------------------------------------------------------------------------
# Preprocessing (QA triage, dedup, variogram)
# ---------------------------------------------------------------------------

def _qa_bit(qa, bit):
    return (qa >> bit) & 1 == 1


def _dedup_first(cand, same_prev):
    """Keep the first candidate per equal-date group.

    cand [P,T]; same_prev [T] marks t[k]==t[k-1] (chip-shared).  Scan over T
    carrying 'a candidate was already kept in this group'.
    """
    def step(carry, xs):
        cand_t, same_t = xs
        seen = jnp.where(same_t, carry, False)
        keep = cand_t & ~seen
        return seen | cand_t, keep

    _, keep = lax.scan(step, jnp.zeros(cand.shape[0], bool),
                       (cand.T, same_prev))
    return keep.T


def _variogram(Y, usable, t=None, adjusted=False):
    """[P,B] median |successive difference| over usable obs, floor 1e-6.

    Successive usable values pair up via an associative last-valid scan
    along T (log T combine steps of elementwise selects) instead of
    compacting with a [P,B,T] gather: per-lane gathers along the time
    axis lower to serialized fusion loops on TPU — profiled at 0.77 s per
    chip, 37% of the whole dispatch.  The difference set is identical to
    the compacted successive-diff formulation (each usable obs with a
    usable predecessor contributes exactly one pair), so the median is
    bit-identical.

    ``adjusted=True`` (with ``t`` [T]) applies the reconstructed
    lcmap-pyccd adjusted_variogram rule (reference.variogram,
    docs/DIVERGENCE.md #1): keep only pairs more than VARIOGRAM_GAP_DAYS
    apart; per pixel, if no pair clears the gap, fall back to the plain
    set.  The selection is date-driven and shared across bands, as in
    pyccd.

    Bands are independent, so the scan + bitonic median run per band
    under lax.map — the sort's working set is [P,T] instead of [P,B,T],
    cutting the prologue's peak memory ~B-fold at identical per-element
    math (one-time cost; wall impact negligible).
    """
    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av), af | bf

    pair_sel = None
    if adjusted:
        tb = jnp.broadcast_to(t[None, :], usable.shape)
        tv, tf = lax.associative_scan(op, (jnp.where(usable, tb, 0.0),
                                           usable), axis=-1)
        prev_t = jnp.concatenate([jnp.zeros_like(tv[..., :1]),
                                  tv[..., :-1]], -1)
        prev_tf = jnp.concatenate([jnp.zeros_like(tf[..., :1]),
                                   tf[..., :-1]], -1)
        gap_ok = (tb - prev_t) > params.VARIOGRAM_GAP_DAYS
        base_ok = usable & prev_tf
        sel = base_ok & gap_ok
        # pyccd's fallback: no qualifying pair -> plain successive diffs
        pair_sel = jnp.where(jnp.any(sel, -1, keepdims=True), sel, base_ok)

    def one_band(yb):                                          # [P,T]
        v, f = lax.associative_scan(op, (jnp.where(usable, yb, 0.0),
                                         usable), axis=-1)
        prev_v = jnp.concatenate([jnp.zeros_like(v[..., :1]),
                                  v[..., :-1]], -1)
        prev_f = jnp.concatenate([jnp.zeros_like(f[..., :1]),
                                  f[..., :-1]], -1)
        pair_ok = usable & prev_f               # usable with a predecessor
        if pair_sel is not None:
            pair_ok = pair_sel
        d = jnp.abs(yb - prev_v)
        return _masked_median(d, pair_ok)                      # [P]

    v = lax.map(one_band, Y.transpose(1, 0, 2)).T              # [P,B]
    m = jnp.sum(usable, -1)                                     # [P]
    return jnp.where((m >= 2)[:, None], jnp.maximum(v, 1e-6), 1.0)


# ---------------------------------------------------------------------------
# The detector
# ---------------------------------------------------------------------------

def _first_at_or_after(mask, i):
    """First True position >= i in mask [P,T]; (exists [P], idx [P])."""
    T = mask.shape[-1]
    ar = jnp.arange(T)[None, :]
    m = mask & (ar >= i[:, None])
    return jnp.any(m, -1), jnp.argmax(m, -1)


def _monitor_chain(s, alive, included, rank, cur_k, n_last_fit, in_mon, *,
                   change_thr: float, outlier_thr: float):
    """The MONITOR fast-forward event logic: score-derived break/refit/
    tail location in rank space (see the body walkthrough in
    _mon_block).  Pure function of the round state so the Pallas
    twin (pallas_ops.monitor_chain, FIREBIRD_PALLAS=1) can replace it —
    the chain is a pipeline of cumulative/reduce ops over T whose
    intermediates otherwise stream through HBM between fusions.

    Returns a dict: m, is_tail, is_brk, is_refit, ev_rank, pos_ev,
    n_exceed, n_rf, inc_q, rem_q.
    """
    P, T = s.shape
    ar = jnp.arange(T)[None, :]
    INF = T + 1
    m = jnp.sum(alive, -1)                                    # [P]
    kq = jnp.sum(alive & (ar < cur_k[:, None]), -1)           # cursor rank

    ex = alive & (s > change_thr)
    # Consecutive-exceeding run length starting at each alive obs:
    # (rank of next alive non-exceeding obs, else m) - own rank.
    reset_r = jnp.where(alive & ~ex, rank, INF)
    nrr = lax.cummin(reset_r, axis=1, reverse=True)
    runlen = jnp.minimum(nrr, m[:, None]) - rank
    elig = alive & (rank >= kq[:, None])
    brk = elig & ex & (runlen >= params.PEEK_SIZE)
    has_brk = jnp.any(brk, -1)
    b_abs = jnp.argmax(brk, -1)

    o = s > outlier_thr
    absq = elig & ~o
    n0 = jnp.sum(included, -1)
    n_inc = n0[:, None] + jnp.cumsum(absq, -1)
    refit_hit = absq & (n_inc >= params.REFIT_FACTOR
                        * n_last_fit[:, None])
    has_refit = jnp.any(refit_hit, -1)
    f_abs = jnp.argmax(refit_hit, -1)

    q_tail = jnp.maximum(m - (params.PEEK_SIZE - 1), kq)      # a rank

    def rank_at(idx):
        return jnp.take_along_axis(rank, idx[:, None], -1)[:, 0]

    b_ev = jnp.where(has_brk, rank_at(b_abs), INF)
    f_ev = jnp.where(has_refit, rank_at(f_abs), INF)
    is_tail = in_mon & (q_tail <= jnp.minimum(b_ev, f_ev))
    is_brk = in_mon & ~is_tail & has_brk & (b_ev <= f_ev)
    is_refit = in_mon & ~is_tail & ~is_brk & has_refit

    ev_rank = jnp.where(is_tail, q_tail, jnp.where(is_brk, b_ev, f_ev))

    # Normal-rules region ends before the event (inclusive for refit).
    normal_hi = jnp.where(is_refit, ev_rank + 1, ev_rank)     # exclusive
    normalq = elig & (rank < normal_hi[:, None])
    inc_q = normalq & ~o
    rem_q = normalq & o
    # Tail region: score <= threshold absorbed, else removed+counted.
    tailq = elig & (rank >= q_tail[:, None]) & is_tail[:, None]
    tail_ex = tailq & (s > change_thr)
    inc_q = inc_q | (tailq & ~tail_ex)
    rem_q = rem_q | tail_ex
    n_exceed = jnp.sum(tail_ex, -1)

    pos_ev = jnp.where(is_brk, b_abs, f_abs)
    n_rf = jnp.take_along_axis(n_inc, pos_ev[:, None], -1)[:, 0]
    return dict(m=m, is_tail=is_tail, is_brk=is_brk, is_refit=is_refit,
                ev_rank=ev_rank, pos_ev=pos_ev, n_exceed=n_exceed,
                n_rf=n_rf, inc_q=inc_q, rem_q=rem_q)


def _detect_core(X, Xt, t, valid, Y, qa, *, wcap: int | None = None,
                 sensor=LANDSAT_ARD, max_segments: int = MAX_SEGMENTS,
                 dtype=None):
    """One chip (X [T,8], Xt [T,5], t [T], valid [T], Y [B,P,T], qa [P,T]
    int32) — a batch of one through :func:`_detect_batch_core`."""
    out = _detect_batch_core(X[None], Xt[None], t[None], valid[None],
                             Y[None], qa[None], wcap=wcap, sensor=sensor,
                             max_segments=max_segments, dtype=dtype)
    return jax.tree_util.tree_map(lambda a: a[0], out)


def _fit_chip(res, w, coefmask, with_rmse=True, *, fit_pallas, on_tpu,
              mixed=False, active=None):
    """One chip's batched Lasso fit, routed to the winning implementation
    (the fused Pallas Gram+corr+CD+RMSE kernel reads the wire-dtype
    resident spectra; the lax path reads the widened float view).
    ``mixed`` (FIREBIRD_MIXED_PRECISION) selects the bf16 split-dot
    Gram on the Pallas route only — the XLA path stays f32 (it is the
    oracle the decision-identity tests compare against).  ``active`` is
    the compaction-mode skip guard: pixels outside it carry all-zero
    windows, so dead lane blocks are skipped for the zeros they would
    compute (see _fit_lasso_coefs)."""
    if fit_pallas:
        from firebird_tpu.ccd import pallas_ops

        b, r = pallas_ops.lasso_fit(res["Yt"], w, res["X"], coefmask,
                                    with_rmse=with_rmse, mixed=mixed,
                                    active=active, interpret=not on_tpu)
        return (b, r) if with_rmse else b
    if with_rmse:
        return _fit_lasso(res["X"], res["Y"], w, coefmask, XX=res["XX"],
                          active=active)
    return _fit_lasso_coefs(res["X"], res["Y"], w, coefmask, XX=res["XX"],
                            active=active)


def _write_seg(bufs, nseg, wmask, meta, rmse_s, mag_s, coef_s, *, S):
    """Append one segment row (where wmask) into the flat result buffers.

    Buffers are FLAT [P, S*k]: trailing [S, 7, 8] shapes take TPU tiled
    layouts padded to (8, 128) — 16x the logical bytes — and the per-round
    buffer select was the loop's single hottest op (24 ms/dispatch
    profiled).  Reshaped once on exit."""
    meta_b, rmse_b, mag_b, coef_b = bufs
    P = nseg.shape[0]
    oh = (nseg[:, None] == jnp.arange(S)[None, :]) & wmask[:, None]  # [P,S]

    def upd(buf, val):                     # buf [P,S*k], val [P,k]
        kk = val.shape[-1]
        m = jnp.broadcast_to(oh[:, :, None], (P, S, kk)).reshape(P, S * kk)
        v = jnp.broadcast_to(val[:, None, :], (P, S, kk)).reshape(P, S * kk)
        return jnp.where(m, v, buf)

    bufs = (upd(meta_b, meta), upd(rmse_b, rmse_s), upd(mag_b, mag_s),
            upd(coef_b, coef_s.reshape(P, -1)))
    return bufs, nseg + wmask.astype(jnp.int32)


def _prologue(X, Xt, t, valid, Y, qa, *, sensor, S, fdtype, fit,
              wire_only=False, guards=False):
    """One chip's pre-loop work: QA triage, usable sets, the one-shot
    snow/insufficient-clear fit, variogram, and the standard-procedure
    start state.  Returns (res, state): ``res`` holds the loop-invariant
    residents (spectra views, designs, variogram, procedure routing),
    ``state`` the event-loop carry."""
    # Resident wire-dtype spectra [B,T,P] for the Pallas consumers (int16
    # reads halve the round loop's dominant HBM term; widening in-register
    # is exact), alongside the widened [P,B,T] float view the XLA paths
    # read.  When the init+score+fit Pallas components are all enabled,
    # the float view leaves ``res`` — the loop then never references it,
    # XLA frees it after the prologue, and its [P,B,T] residency (~4.7 GB
    # at the 8-chip bench shape) comes off the loop's working set.
    Yt_res = Y.transpose(0, 2, 1)                              # [B,T,P]
    Y = Y.astype(fdtype).transpose(1, 0, 2)                    # -> [P,B,T]
    P, B, T = Y.shape
    # Per-row design outer products, shared by every Lasso Gram build.
    XX = (X[:, :, None] * X[:, None, :]).reshape(T, -1)        # [T,64]
    # Detection-band wire-dtype slice for the score-fused monitor kernel
    # (DCE'd from the program when FIREBIRD_PALLAS doesn't enable it).
    Yd = Yt_res[np.asarray(sensor.detection_bands)]            # [nb,T,P]
    res = dict(X=X, Xt=Xt, t=t, Yt=Yt_res, Yd=Yd, XX=XX)
    if not wire_only:
        res["Y"] = Y

    # ---------------- QA triage (reference.detect) ----------------
    fill = _qa_bit(qa, params.QA_FILL_BIT) | ~valid[None, :]
    clear = (_qa_bit(qa, params.QA_CLEAR_BIT) | _qa_bit(qa, params.QA_WATER_BIT)) & ~fill
    snow = _qa_bit(qa, params.QA_SNOW_BIT) & ~fill

    n_nonfill = jnp.sum(~fill, -1)
    n_clear = jnp.sum(clear, -1)
    n_snow = jnp.sum(snow, -1)
    clear_pct = n_clear / jnp.maximum(n_nonfill, 1)
    snow_pct = n_snow / jnp.maximum(n_clear + n_snow, 1)

    opt = list(sensor.optical_bands)
    rng_ok = jnp.all((Y[:, opt] > params.OPTICAL_MIN)
                     & (Y[:, opt] < params.OPTICAL_MAX), axis=1)
    if sensor.thermal_bands:
        th = list(sensor.thermal_bands)
        rng_ok &= jnp.all((Y[:, th] > params.THERMAL_MIN)
                          & (Y[:, th] < params.THERMAL_MAX), axis=1)

    procedure = jnp.where(
        n_nonfill == 0, PROC_NODATA,
        jnp.where(clear_pct >= params.CLEAR_PCT_THRESHOLD, PROC_STANDARD,
                  jnp.where(snow_pct > params.SNOW_PCT_THRESHOLD,
                            PROC_SNOW, PROC_INSUF)))

    same_prev = jnp.concatenate([jnp.array([False]), t[1:] == t[:-1]])

    usable_std = _dedup_first(clear & rng_ok, same_prev)
    usable_snow = _dedup_first((clear | snow) & rng_ok, same_prev)
    cand_ins = ~fill & rng_ok
    Yblue = Y[:, sensor.blue_band]
    blue_med = _masked_median(Yblue, cand_ins)
    cand_ins = cand_ins & (Yblue < blue_med[:, None] + params.INSUF_CLEAR_BLUE_DELTA)
    usable_ins = _dedup_first(cand_ins, same_prev)

    # ---------------- result buffers (flat; see _write_seg) ----------------
    nseg0 = jnp.zeros(P, jnp.int32)
    meta0 = jnp.zeros((P, S * 6), fdtype)
    rmse0 = jnp.zeros((P, S * B), fdtype)
    mag0 = jnp.zeros((P, S * B), fdtype)
    coef0 = jnp.zeros((P, S * B * params.MAX_COEFS), fdtype)

    # ---------------- snow / insufficient-clear: one fit ----------------
    alt_usable = jnp.where((procedure == PROC_SNOW)[:, None], usable_snow,
                           usable_ins)
    is_alt = (procedure == PROC_SNOW) | (procedure == PROC_INSUF)
    alt_n = jnp.sum(alt_usable, -1)
    alt_fit = is_alt & (alt_n >= params.MEOW_SIZE)
    w_alt = (alt_usable & alt_fit[:, None]).astype(fdtype)
    alt_coefs, alt_rmse = fit(res, w_alt, _coefmask_for(alt_n), True,
                              active=alt_fit if guards else None)
    first_i = jnp.argmax(alt_usable, -1)
    last_i = T - 1 - jnp.argmax(alt_usable[:, ::-1], -1)
    alt_meta = jnp.stack([
        jnp.take(t, first_i), jnp.take(t, last_i), jnp.take(t, last_i),
        jnp.zeros(P, fdtype),
        jnp.where(procedure == PROC_SNOW,
                  float(params.CURVE_QA_PERSIST_SNOW),
                  float(params.CURVE_QA_INSUF_CLEAR)).astype(fdtype),
        alt_n.astype(fdtype)], axis=1)
    bufs = (meta0, rmse0, mag0, coef0)
    bufs, nseg = _write_seg(bufs, nseg0, alt_fit, alt_meta, alt_rmse,
                            jnp.zeros((P, B), fdtype), alt_coefs, S=S)
    alt_mask = alt_usable & alt_fit[:, None]

    # ---------------- standard procedure state ----------------
    is_std = procedure == PROC_STANDARD
    alive0 = usable_std & is_std[:, None]
    # Mode read at trace time, like use_pallas — set FIREBIRD_VARIOGRAM
    # before the first detect call (one compiled fn per mode).
    vario = _variogram(Y, alive0, t=t,
                       adjusted=params.variogram_adjusted_default())
    ex0, i0 = _first_at_or_after(alive0, jnp.zeros(P, jnp.int32))
    phase0 = jnp.where(is_std & ex0, PHASE_INIT, PHASE_DONE).astype(jnp.int32)

    res.update(vario=vario, is_std=is_std, is_alt=is_alt,
               alt_mask=alt_mask, procedure=procedure)
    state = dict(
        phase=phase0,
        cur_i=i0.astype(jnp.int32),
        cur_k=jnp.zeros(P, jnp.int32),
        alive=alive0,
        included=jnp.zeros((P, T), bool),
        coefs=jnp.zeros((P, B, params.MAX_COEFS), fdtype),
        rmse=jnp.ones((P, B), fdtype),
        n_last_fit=jnp.ones(P, jnp.int32),
        first_seg=jnp.ones(P, bool),
        nseg=nseg, bufs=bufs,
    )
    return res, state


def _init_block(res, st, *, sensor, W, fdtype, fit, f32_ok, mixed=False,
                guards=False):
    """One chip's INIT-phase round work: initialization-window search, the
    Tmask IRLS screen, and the stability test.  Runs under a scalar
    lax.cond — on rounds where no pixel is initializing (most of them:
    after round 1 the only INIT pixels are post-break restarts) the whole
    block, including its one-hot window tensors (the loop's dominant HBM
    term), is skipped outright.  Every output is consumed downstream only
    under in_init-derived masks, so the skip branch's zeros are inert.
    ``guards`` (compaction mode) threads the in_init lane set into the
    Pallas kernels as a per-block skip guard — dense-prefix compaction
    clusters DONE lanes into whole trailing blocks, which then cost a
    predicate instead of the window search + IRLS."""
    _DET = list(sensor.detection_bands)
    _TMB = list(sensor.tmask_bands)
    X, Xt, t = res["X"], res["Xt"], res["t"]
    alive = st["alive"]
    in_init = st["phase"] == PHASE_INIT
    act = in_init if guards else None

    if use_pallas("init") and f32_ok:
        # f32_ok: the shared Mosaic gate from _detect_batch_impl
        # (f32-on-TPU only — Mosaic cannot lower float64).
        on_tpu = jax.default_backend() == "tpu"
        from firebird_tpu.ccd import pallas_ops

        return pallas_ops.init_window(
            alive, st["cur_i"], in_init, t, X, Xt, res["Yt"],
            res["vario"], W=W, sensor=sensor, mixed=mixed, active=act,
            interpret=not on_tpu)

    Y = res["Y"]
    P, B, T = Y.shape
    ar = jnp.arange(T)[None, :]
    has_i, i = _first_at_or_after(alive, st["cur_i"])
    t_i = jnp.take(t, i)
    Acum = jnp.cumsum(alive, -1)
    rank = Acum - 1                                        # [P,T]
    A_before = jnp.take_along_axis(Acum, i[:, None], -1)[:, 0] \
        - jnp.take_along_axis(alive, i[:, None], -1)[:, 0]
    cnt = Acum - A_before[:, None]
    okj = alive & (ar >= i[:, None]) & (cnt >= params.MEOW_SIZE) \
        & (t[None, :] - t_i[:, None] >= params.INIT_DAYS)
    has_w = has_i & jnp.any(okj, -1)
    j = jnp.argmax(okj, -1)
    w_init = alive & (ar >= i[:, None]) & (ar <= j[:, None]) \
        & (has_w & in_init)[:, None]

    # Tmask screen over the compacted window: the window members are
    # exactly the alive obs with ranks [rank(i), rank(i)+n_win), so a
    # rank-indexed selection bounds all IRLS median/Gram work by
    # W << T.  Member positions come from a one-hot reduce over T
    # (ranks are unique among alive obs) rather than a rank scatter +
    # gather — scatters lower to sort + serialized-loop fusions on
    # TPU (~32 ms/round profiled, the loop body's hottest ops).
    n_win = jnp.sum(w_init, -1)                            # [P] <= W
    r_i = A_before                                         # rank of i
    rel_w = rank - r_i[:, None]                            # [P,T]
    # (the == against arange(W) already implies 0 <= rel_w < W)
    oh_w = alive[:, None, :] \
        & (rel_w[:, None, :] == jnp.arange(W)[None, :, None])  # [P,W,T]
    valid_w = (jnp.arange(W)[None, :] < n_win[:, None])
    # Window members selected by one-hot MXU matmuls — exact (each
    # output is 1.0 x one element; HIGHEST precision keeps f32 inputs
    # unrounded) and an order of magnitude cheaper than per-lane
    # take_along_axis gathers, which serialize on TPU (profiled at
    # ~7 ms/round combined).  Empty slots read 0 and are masked by
    # valid_w downstream, as the gathered garbage was before.
    ohf = oh_w.astype(fdtype)                              # [P,W,T]
    Yw7 = jnp.einsum("pbt,pwt->pbw", Y, ohf,
                     precision=lax.Precision.HIGHEST)      # [P,7,W]
    XW = jnp.einsum("pwt,tc->pwc", ohf,
                    jnp.concatenate([X, Xt], axis=1),
                    precision=lax.Precision.HIGHEST)       # [P,W,13]
    Xw8, Xt_w = XW[..., :8], XW[..., 8:]
    Y2w = Yw7[:, _TMB, :]
    tmask_fn = _tmask_bad
    if use_pallas("tmask") and f32_ok:
        on_tpu = jax.default_backend() == "tpu"
        from firebird_tpu.ccd import pallas_ops

        tmask_fn = functools.partial(pallas_ops.tmask_bad, active=act,
                                     interpret=not on_tpu)
    bad_w = tmask_fn(Xt_w, Y2w, valid_w.astype(fdtype),
                     res["vario"][:, _TMB])
    bad = jnp.any(oh_w & bad_w[:, :, None], axis=1)        # [P,T]
    tm_removed = jnp.any(bad_w, -1)

    # Stability fit: 4 coefs over the (pre-screen-clean) window.  RMSE
    # and the endpoint residuals only involve window members (member 0
    # is i, member n_win-1 is j), so residuals are evaluated on the
    # compacted window instead of the full series.
    w_stab = w_init & ~tm_removed[:, None]
    cm4 = jnp.arange(params.MAX_COEFS)[None, :] < 4
    cm4 = jnp.broadcast_to(cm4, (P, params.MAX_COEFS))
    c4 = fit(res, w_stab.astype(fdtype), cm4, False, active=act)
    r_w = Yw7 - jnp.sum(c4[:, :, None, :] * Xw8[:, None, :, :], -1)
    stab_w = valid_w & ~bad_w
    n4 = jnp.maximum(jnp.sum(stab_w, -1), 1.0)
    r4 = jnp.sqrt(jnp.maximum(
        jnp.sum(r_w * r_w * stab_w[:, None, :], -1) / n4[:, None], 0.0))
    r_first = r_w[:, :, 0]                        # [P,7]
    r_last = _onehot_take(r_w, jnp.maximum(n_win - 1, 0)[:, None])
    span = jnp.take(t, j) - t_i
    denom = params.STABILITY_FACTOR * jnp.maximum(r4, res["vario"])  # [P,7]
    slope_day = c4[..., 1] / 365.25
    band_ok = ((jnp.abs(slope_day * span[:, None]) <= denom)
               & (jnp.abs(r_first) <= denom)
               & (jnp.abs(r_last) <= denom))                  # [P,7]
    stable = jnp.all(band_ok[:, _DET], axis=1)

    init_nowin = in_init & ~has_w
    init_tm = in_init & has_w & tm_removed
    init_ok = in_init & has_w & ~tm_removed & stable
    init_bad = in_init & has_w & ~tm_removed & ~stable

    # Cursor advance for INIT failures; a missing successor parks the
    # cursor at T (out of range -> no-window -> DONE next round).
    ex_tm, i_next_tm = _first_at_or_after(alive & ~bad, i)
    i_next_tm = jnp.where(ex_tm, i_next_tm, T)
    has_adv, i_adv = _first_at_or_after(alive, i + 1)

    return dict(init_nowin=init_nowin, init_tm=init_tm, init_ok=init_ok,
                init_bad=init_bad, has_adv=has_adv,
                i_next_tm=i_next_tm.astype(jnp.int32),
                i_adv=i_adv.astype(jnp.int32), j=j.astype(jnp.int32),
                w_stab=w_stab, n_ok=jnp.sum(w_stab, -1).astype(jnp.int32),
                alive_init=alive & ~bad)


def _init_zeros(st):
    """The skip branch of the INIT cond: inert outputs (every consumer
    masks on in_init-derived flags, all False when no pixel initializes)."""
    C, P, T = st["included"].shape
    zb = jnp.zeros((C, P), bool)
    zi = jnp.zeros((C, P), jnp.int32)
    zp = jnp.zeros((C, P, T), bool)
    return dict(init_nowin=zb, init_tm=zb, init_ok=zb, init_bad=zb,
                has_adv=zb, i_next_tm=zi, i_adv=zi, j=zi, w_stab=zp,
                n_ok=zi, alive_init=st["alive"])


def _mon_block(res, st, *, sensor, change_thr, outlier_thr, f32_ok,
               guards=False):
    """One chip's MONITOR-phase round work: score all remaining
    observations against the current model and locate the first event
    (break / refit / tail) in rank space.  Runs under a scalar lax.cond
    (skipped on round 1, when every standard pixel is still
    initializing).  ``guards`` threads the in_mon lane set into the
    Pallas kernels as a per-block skip guard (see _init_block)."""
    _DET = list(sensor.detection_bands)
    X = res["X"]
    alive, included = st["alive"], st["included"]
    in_mon = st["phase"] == PHASE_MONITOR
    act = in_mon if guards else None

    # All event logic runs in rank space on the absolute time axis:
    # rank[p, t] = index of observation t in pixel p's compacted alive
    # sequence.  Ranks are monotone in t among alive obs, so rank
    # comparisons reproduce the compacted-sequence semantics without the
    # argsort/compaction/scatter round-trip ([P,T] bitonic sorts are the
    # expensive op on TPU, not the matmuls).
    dden = jnp.maximum(st["rmse"], res["vario"])[:, _DET]      # [P,5]
    on_tpu = jax.default_backend() == "tpu"
    # f32_ok (Mosaic cannot lower float64; compiled Pallas is f32-on-TPU
    # only) is computed ONCE from fdtype in _detect_batch_impl and shared
    # with the wire-resident gate, so the monitor can never fall down the
    # XLA path while res["Y"] was dropped by wire-only mode.
    if use_pallas("score") and f32_ok:
        # Score-fused kernel: predictions, score, and rank derived in
        # VMEM from the wire-dtype detection-band spectra — skips the
        # [P,nb,T] prediction einsum and the s/rank plane round-trips.
        from firebird_tpu.ccd import pallas_ops

        mon = pallas_ops.monitor_chain_scored(
            res["Yd"], st["coefs"][:, _DET, :], dden, res["X"], alive,
            included, st["cur_k"], st["n_last_fit"], in_mon,
            change_thr=change_thr, outlier_thr=outlier_thr,
            active=act, interpret=not on_tpu)
    else:
        # HIGHEST is already the context default (_detect_batch_core);
        # pinned explicitly so the score matches the Pallas twin's full-f32
        # dot even if the context ever moves.
        Y = res["Y"]
        pred_d = jnp.einsum("pbc,tc->pbt", st["coefs"][:, _DET, :], X,
                            precision=lax.Precision.HIGHEST)
        s = jnp.sum(((Y[:, _DET, :] - pred_d) / dden[:, :, None]) ** 2,
                    axis=1)
        rank = jnp.cumsum(alive, -1) - 1                       # [P,T]
        chain = _monitor_chain
        if use_pallas("monitor") and f32_ok:
            from firebird_tpu.ccd import pallas_ops

            chain = functools.partial(pallas_ops.monitor_chain,
                                      active=act, interpret=not on_tpu)
        mon = chain(s, alive, included, rank, st["cur_k"],
                    st["n_last_fit"], in_mon,
                    change_thr=change_thr, outlier_thr=outlier_thr)

    inc_abs = mon["inc_q"] & in_mon[:, None]
    rem_abs = mon["rem_q"] & in_mon[:, None]
    i32 = lambda a: a.astype(jnp.int32)   # x64 mode promotes the chain's ints
    return dict(m=i32(mon["m"]), is_tail=mon["is_tail"],
                is_brk=mon["is_brk"], is_refit=mon["is_refit"],
                ev_rank=i32(mon["ev_rank"]), pos_ev=i32(mon["pos_ev"]),
                n_exceed=i32(mon["n_exceed"]), n_rf=i32(mon["n_rf"]),
                included_mon=included | inc_abs,
                alive_mon=alive & ~rem_abs)


def _mon_zeros(st):
    """The skip branch of the MONITOR cond: no events, state passes
    through (every consumer masks on in_mon-derived flags)."""
    C, P, _ = st["included"].shape
    zb = jnp.zeros((C, P), bool)
    zi = jnp.zeros((C, P), jnp.int32)
    return dict(m=zi, is_tail=zb, is_brk=zb, is_refit=zb, ev_rank=zi,
                pos_ev=zi, n_exceed=zi, n_rf=zi,
                included_mon=st["included"], alive_mon=st["alive"])


def _close_mags(res, st, mon, *, fdtype):
    """Break magnitudes: median full-band residual over the PEEK run at
    the break — the spectra-reading half of the close, split out so the
    fused-fit route (FIREBIRD_FUSED_FIT) can run EXACTLY this code under
    its own any(is_brk) cond: break rounds are rare, and sharing the
    very same program keeps the fused-on/off stores byte-identical
    (tests/test_fuse.py golden) where a re-derived in-kernel median
    would differ by backend-fusion ulps."""
    X = res["X"]
    alive = st["alive"]
    P, B, _K = st["coefs"].shape
    T = X.shape[0]
    ev_rank, m = mon["ev_rank"], mon["m"]
    rank = jnp.cumsum(alive, -1) - 1

    # Magnitudes: median full-band residual over the PEEK run at the
    # break.  The run has at most PEEK_SIZE members — locate their
    # absolute positions by a one-hot reduce over T (same scatter-free
    # construction as the init window) and take a tiny median instead of
    # masked medians over the whole [P,T] axis.
    relk = ev_rank[:, None] + jnp.arange(params.PEEK_SIZE)[None, :]
    run_ok = relk < m[:, None]                                # [P,PEEK]
    rel_ev = rank - ev_rank[:, None]                          # [P,T]
    oh_run = (alive[:, None, :] & (
        rel_ev[:, None, :]
        == jnp.arange(params.PEEK_SIZE)[None, :, None])
    ).astype(fdtype)                                          # [P,K,T]
    X_run = jnp.einsum("pkt,tc->pkc", oh_run, X,
                       precision=lax.Precision.HIGHEST)       # [P,K,8]
    pred_run = jnp.sum(st["coefs"][:, :, None, :]
                       * X_run[:, None, :, :], -1)            # [P,B,K]
    if "Y" in res:
        Y_run = jnp.einsum("pbt,pkt->pbk", res["Y"], oh_run,
                           precision=lax.Precision.HIGHEST)
    else:
        # Wire-resident mode: the run members come from the int16 view.
        # Each (p,b,k) output selects exactly one observation (one-hot
        # over t), so this contraction is bit-exact vs the float view.
        Y_run = jnp.einsum("btp,pkt->pbk", res["Yt"].astype(fdtype),
                           oh_run, precision=lax.Precision.HIGHEST)
    resid_run = Y_run - pred_run                              # [P,7,PEEK]
    return _masked_median(
        resid_run, jnp.broadcast_to(run_ok[:, None, :], resid_run.shape))


def _close_block(res, st, mon, *, S, fdtype):
    """One chip's segment-close work: break magnitudes and the segment
    row write.  Runs under a scalar lax.cond on any(close) — segment
    closes land on a handful of rounds (the shared tail round plus break
    rounds), so most rounds skip both the PEEK-run one-hot einsums and
    the full result-buffer rewrite."""
    t = res["t"]
    # Shapes from the always-present carries, not res["Yt"]: compaction
    # mode carries only the residents the traced paths actually read, so
    # the wire view may be absent here when the float view serves.
    P, B, _K = st["coefs"].shape
    T = res["X"].shape[0]
    is_tail, is_brk = mon["is_tail"], mon["is_brk"]
    pos_ev = mon["pos_ev"]
    included_mon = mon["included_mon"]
    mags = _close_mags(res, st, mon, fdtype=fdtype)

    last_inc = T - 1 - jnp.argmax(included_mon[:, ::-1], -1)
    first_inc = jnp.argmax(included_mon, -1)
    end_day = jnp.take(t, last_inc)
    start_day = jnp.take(t, first_inc)

    close = is_tail | is_brk
    qa_tail = params.CURVE_QA_END \
        + jnp.where(st["first_seg"], params.CURVE_QA_START, 0)
    qa_brk = jnp.where(st["first_seg"], params.CURVE_QA_START,
                       params.CURVE_QA_INSIDE)
    meta_new = jnp.stack([
        start_day, end_day,
        jnp.where(is_brk, jnp.take(t, pos_ev), end_day),
        jnp.where(is_brk, 1.0,
                  mon["n_exceed"] / params.PEEK_SIZE).astype(fdtype),
        jnp.where(is_brk, qa_brk, qa_tail).astype(fdtype),
        jnp.sum(included_mon, -1).astype(fdtype)], axis=1)
    mag_new = jnp.where(is_brk[:, None], mags, 0.0)
    return _write_seg(st["bufs"], st["nseg"], close, meta_new,
                      st["rmse"], mag_new, st["coefs"], S=S)


# ---------------------------------------------------------------------------
# Active-lane compaction (docs/ROOFLINE.md "Occupancy"): the event loop's
# cost tracks the ACTIVE pixel set, not the padded batch.
# ---------------------------------------------------------------------------

# The skip-guard accounting unit: a trailing lane block containing no
# active lane costs a per-block predicate in the Pallas kernels instead
# of its Gram/CD/monitor work.  Matches pallas_ops.BLOCK_P's scale (the
# per-kernel widths are 128-512; 512 is the accounting width the
# occupancy capture and flops.occupancy_detail use).
COMPACT_LANE_BLOCK = 512

# State-dict keys permuted along their leading pixel axis by a compaction
# (the [C,P,...] loop carries).
_COMPACT_PIXEL_KEYS = ("phase", "cur_i", "cur_k", "alive", "included",
                       "coefs", "rmse", "n_last_fit", "first_seg", "nseg")
# Carried residents whose pixel axis is NOT leading (wire layout [B,T,P]).
_COMPACT_RESP_AXIS = {"Yt": 2, "Yd": 2}


def _dense_prefix_perm(alive):
    """Stable dense-prefix permutation from an alive mask [P]: returns
    gather indices g (i32 [P]) with out[i] = in[g[i]], alive lanes first,
    original order preserved within each class (cumsum-derived targets,
    inverted by one scatter of iota)."""
    P = alive.shape[0]
    a32 = alive.astype(jnp.int32)
    na = jnp.sum(a32)
    tgt = jnp.where(alive, jnp.cumsum(a32) - 1,
                    na + jnp.cumsum(1 - a32) - 1).astype(jnp.int32)
    return jnp.zeros(P, jnp.int32).at[tgt].set(
        jnp.arange(P, dtype=jnp.int32))


def _take_pixels(a, g, axis=0):
    """Lane gather along ``axis``.  Minor-axis residents ([B,T,P] wire
    layouts) route through a leading-axis move so XLA lowers a major-axis
    gather + copies instead of a serialized per-lane minor-axis gather
    (the same TPU pathology the one-hot selections avoid)."""
    if axis == 0:
        return jnp.take(a, g, axis=0)
    return jnp.moveaxis(jnp.take(jnp.moveaxis(a, axis, 0), g, axis=0),
                        0, axis)


def _compact_state(st):
    """One compaction sweep: permute every per-pixel loop carry — state,
    result buffers, carried residents, and the running permutation — so
    lanes with phase != PHASE_DONE form a dense prefix per chip.  The
    math is permutation-invariant per lane (everything in the round body
    is elementwise over P or a per-lane reduce over T), so results are
    bit-identical; ``perm`` carries current-position -> original-pixel
    for the exit unpermute."""
    def one(stc):
        g = _dense_prefix_perm(stc["phase"] != PHASE_DONE)
        out = {k: _take_pixels(stc[k], g) for k in _COMPACT_PIXEL_KEYS}
        out["bufs"] = tuple(_take_pixels(b, g) for b in stc["bufs"])
        out["resp"] = {k: _take_pixels(v, g, _COMPACT_RESP_AXIS.get(k, 0))
                       for k, v in stc["resp"].items()}
        out["perm"] = _take_pixels(stc["perm"], g)
        return dict(stc, **out)

    return jax.vmap(one)(st)


def _unpermute(a, perm):
    """Invert a carried permutation at loop exit: out[perm[p]] = a[p],
    per chip (one scatter per output field, at most twice per dispatch)."""
    return jax.vmap(lambda ac, pc: jnp.zeros_like(ac).at[pc].set(ac))(
        a, perm)


def _paid_lanes(phase, block_widths):
    """Per-chip lanes the round pays for under the per-block skip
    guards: COMPACT_LANE_BLOCK-wide blocks containing any active lane,
    weighted by their real width ([C] i32).  ``block_widths`` is the
    trace-time numpy width vector (last block may be ragged).  This is
    the guard-accounting MODEL, identical on every backend: measured
    compute where the Pallas guards run, predicted skips on the lax
    fallback (whose slab cond computes every lane under vmap — see the
    ChipSegments.occupancy note)."""
    C, P = phase.shape
    nb = block_widths.shape[0]
    pad = nb * COMPACT_LANE_BLOCK - P
    act = jnp.pad(phase != PHASE_DONE, ((0, 0), (0, pad)))
    blk = jnp.any(act.reshape(C, nb, COMPACT_LANE_BLOCK), -1)
    return jnp.sum(blk * jnp.asarray(block_widths, jnp.int32)[None, :],
                   -1).astype(jnp.int32)


def _block_widths(P: int) -> np.ndarray:
    nb = -(-P // COMPACT_LANE_BLOCK)
    w = np.full(nb, COMPACT_LANE_BLOCK, np.int32)
    w[-1] = P - (nb - 1) * COMPACT_LANE_BLOCK
    return w


def _detect_batch_core(Xs, Xts, ts, valids, Ys, qas, *,
                       wcap: int | None = None, sensor=LANDSAT_ARD,
                       max_segments: int = MAX_SEGMENTS, dtype=None,
                       compact: bool | None = None,
                       fused=None, mixed: bool | None = None,
                       rebalance=None):
    """A chip batch: Xs [C,T,8], Xts [C,T,5], ts [C,T], valids [C,T],
    Ys [C,B,P,T] (wire int16 or float), qas [C,P,T] int32 → ChipSegments
    with [C, ...] leading axes.

    The event loop runs ONE while_loop over the whole batch (not a
    vmapped per-chip loop): each round's phase blocks are vmapped over
    chips *inside* scalar lax.cond gates, so a round where no pixel of
    any chip is initializing skips the INIT block's one-hot window
    tensors outright, a round with no close skips the buffer rewrite,
    and a round with no refit skips the Lasso fit.  Under a vmapped
    while_loop those conds would degenerate to selects (both branches
    execute every round for every chip); hoisting the loop above the
    vmap is what makes them real branches.

    Traced under HIGHEST matmul precision: on TPU the default f32 dot
    runs reduced-precision passes, which would silently degrade every
    Gram/prediction below the f32 the oracle-parity envelope was
    measured at (CPU tests run full f32 and would never catch it).

    ``wcap`` (static) bounds the member count of any initialization
    window; window_cap() derives a rigorous bound from the batch's date
    grids (None falls back to the always-correct T).  ``sensor``
    (static) supplies the band layout.  ``max_segments`` (static) is the
    result-buffer capacity; n_segments counts every closed segment even
    past capacity, so a caller can detect overflow (n_segments >
    max_segments) and re-dispatch with a larger buffer — detect_packed
    does this automatically.

    ``compact`` (static) enables active-lane compaction (None defers to
    FIREBIRD_COMPACT at trace time): the loop periodically permutes the
    per-pixel state so working lanes form a dense prefix, threads
    per-block skip guards into the Pallas kernels, and re-enters a
    power-of-two bucket once the alive fraction falls below
    FIREBIRD_COMPACT_FLOOR — row-identical results, cost tracking the
    active set instead of the padded batch.

    ``fused`` (static) routes each round's segment-close + shared-fit
    pair through the fused gram→CD→close Pallas kernel (None defers to
    FIREBIRD_FUSED_FIT at trace time, like ``compact``); results are
    byte-identical against the unfused Pallas-fit configuration
    (tests/test_fuse.py golden).  The value "mon" (or env "mon"/"2")
    instead fuses the WHOLE post-INIT round — monitor chain + close +
    fit — into one pallas_call (pallas_ops.fused_round); that route is
    decision-exact with seg_mag inside the f32 envelope, like mega.

    ``mixed`` (static) accumulates the fit kernels' Gram/corr dots in
    bf16 split form with f32 accumulators and int32 counts (None defers
    to FIREBIRD_MIXED_PRECISION at trace time) — decision fields stay
    identical to f32, coef/rmse inside params.MIXED_ULP_BUDGET; f32
    stores and Pallas fit routes only (see use_mixed_precision).

    ``rebalance`` (static; a parallel.mesh.RebalanceSpec, sharded
    dispatches only) arms the cross-device straggler rebalancing ring at
    the bucketed-tail boundary — lanes migrate to the right-neighbor
    device when the alive-count imbalance crosses the threshold, results
    migrate back, stores stay row-identical."""
    with jax.default_matmul_precision("highest"):
        return _detect_batch_impl(Xs, Xts, ts, valids, Ys, qas, wcap=wcap,
                                  sensor=sensor, max_segments=max_segments,
                                  dtype=dtype, compact=compact,
                                  fused=fused, mixed=mixed,
                                  rebalance=rebalance)


def _detect_batch_impl(Xs, Xts, ts, valids, Ys, qas, *, wcap, sensor,
                       max_segments, dtype, compact=None, fused=None,
                       mixed=None, rebalance=None):
    C, B, P, T = Ys.shape
    S = max_segments
    W = T if wcap is None else min(wcap, T)
    fdtype = jnp.dtype(dtype) if dtype is not None else Ys.dtype
    _DET = list(sensor.detection_bands)
    change_thr, outlier_thr = chi2_thresholds(len(_DET))
    on_tpu = jax.default_backend() == "tpu"
    f32_ok = not on_tpu or fdtype == jnp.float32
    # The mega decision is made ONCE, up front, because it shapes the
    # prologue: mega implies wire-resident mode (drops the float view)
    # and the Pallas fit kernel for the one-shot alt fits — but a mega
    # REFUSED by the VMEM guard must leave both decisions to the
    # per-component flags, or the XLA fallback loop would read a float
    # view the prologue never kept.
    mega = False
    if use_pallas("mega") and f32_ok:
        from firebird_tpu.ccd import pallas_ops

        mega = pallas_ops.mega_fits(T, W, B, S, Ys.dtype.itemsize)
    fit_pallas = (use_pallas("fit") or mega) and f32_ok
    # Mixed-precision gram (FIREBIRD_MIXED_PRECISION / explicit mixed=):
    # bf16 split dots + int32 counts inside the Pallas fit routes, f32
    # everywhere decisions are made.  f32 stores only — the f64
    # bit-parity path keeps full precision — and inert on the XLA fit
    # path, which stays the f32 oracle.
    mixed_on = (use_mixed_precision() if mixed is None else bool(mixed)) \
        and f32_ok and fdtype == jnp.float32
    fit = functools.partial(_fit_chip, fit_pallas=fit_pallas,
                            on_tpu=on_tpu, mixed=mixed_on)
    wire_only = (mega or _wire_resident_only()) and f32_ok
    # Active-lane compaction (trace-time resolution, like use_pallas).
    # The mega route already stops paying for finished pixels its own way
    # (each VMEM block's while_loop exits when ITS pixels are done), so
    # compaction applies to the XLA/per-component loop only.
    compact_on = (params.compact_default() if compact is None
                  else bool(compact)) and not mega
    # Fused gram→CD→close round kernel (FIREBIRD_FUSED_FIT / explicit
    # fused=): each round's segment-close + shared-Lasso-fit pair runs
    # as ONE pallas_call on a single VMEM residency of the wire spectra.
    # Mode "mon" widens the fusion to the whole post-INIT round —
    # monitor chain + close + fit in one kernel (pallas_ops.fused_round).
    # The mega route supersedes both (the whole loop is already one
    # kernel); the f64-on-TPU bit-parity path keeps the XLA pair.
    fused_req = fused_mode() if fused is None else fused
    if fused_req in ("mon", 2):
        fused_req = "mon"
    elif fused_req:
        fused_req = 1
    else:
        fused_req = 0
    fused_on = bool(fused_req) and f32_ok and not mega
    fused_mon = fused_on and fused_req == "mon"

    # Trace-time route counters (host code; a jit trace runs once per
    # compiled shape, so these count PROGRAMS built on each route —
    # tools/precision_smoke.py's "counters moving" check).
    from firebird_tpu.obs import metrics as obs_metrics
    if mixed_on:
        obs_metrics.counter(
            "kernel_mixed_traces",
            help="programs traced with the bf16/int32 mixed-precision "
                 "gram (FIREBIRD_MIXED_PRECISION)").inc()
    if fused_mon:
        obs_metrics.counter(
            "kernel_fused_round_traces",
            help="programs traced with the whole-round monitor-fused "
                 "kernel (FIREBIRD_FUSED_FIT=mon)").inc()

    res, state = jax.vmap(functools.partial(
        _prologue, sensor=sensor, S=S, fdtype=fdtype, fit=fit,
        wire_only=wire_only, guards=compact_on))(Xs, Xts, ts, valids, Ys,
                                                 qas)

    if mega:
        # Whole-loop mega kernel: the entire event loop in one
        # pallas_call, wire spectra VMEM-resident, each block exiting as
        # soon as its own pixels finish (pallas_ops._detect_mega_block).
        # mega_fits guarded the 128-lane VMEM floor above: an oversized
        # T falls down the XLA loop below instead of a Mosaic OOM.
        # (pallas_ops is already bound in scope by the guard import.)
        out = pallas_ops.detect_mega(
            res["Yt"], state["phase"], state["cur_i"], state["alive"],
            state["nseg"], state["bufs"], res["t"], res["X"], res["Xt"],
            res["vario"], W=W, S=S, sensor=sensor,
            phases=(PHASE_INIT, PHASE_MONITOR, PHASE_DONE),
            change_thr=float(change_thr), outlier_thr=float(outlier_thr),
            mixed=mixed_on, interpret=not on_tpu)
        final_mask = jnp.where(
            res["is_std"][..., None], out["alive"],
            jnp.where(res["is_alt"][..., None], res["alt_mask"], False))
        return ChipSegments(
            n_segments=out["nseg"], seg_meta=out["meta"],
            seg_rmse=out["rmse"], seg_mag=out["mag"],
            seg_coef=out["coef"], mask=final_mask,
            procedure=res["procedure"], rounds=out["rounds"],
            vario=res["vario"], round_counts=out["counts"])

    initf = jax.vmap(functools.partial(
        _init_block, sensor=sensor, W=W, fdtype=fdtype, fit=fit,
        f32_ok=f32_ok, mixed=mixed_on, guards=compact_on))
    monf = jax.vmap(functools.partial(
        _mon_block, sensor=sensor, change_thr=change_thr,
        outlier_thr=outlier_thr, f32_ok=f32_ok, guards=compact_on))
    closef = jax.vmap(functools.partial(_close_block, S=S, fdtype=fdtype))
    if compact_on:
        fitf = jax.vmap(lambda r, w, n, a: fit(r, w, _coefmask_for(n),
                                               active=a))
    else:
        fitf = jax.vmap(lambda r, w, n: fit(r, w, _coefmask_for(n)))
    if fused_mon:
        from firebird_tpu.ccd import pallas_ops

        def _round_chip(r, st_c, init_c, act=None):
            in_mon_c = st_c["phase"] == PHASE_MONITOR
            return pallas_ops.fused_round(
                r["Yt"], r["X"], r["t"], st_c["alive"], st_c["included"],
                st_c["cur_k"], st_c["n_last_fit"], in_mon_c,
                st_c["coefs"], st_c["rmse"], r["vario"],
                init_c["init_ok"], init_c["w_stab"], init_c["n_ok"],
                st_c["first_seg"], st_c["nseg"], st_c["bufs"], S=S,
                sensor=sensor, change_thr=float(change_thr),
                outlier_thr=float(outlier_thr), mixed=mixed_on,
                active=act, interpret=not on_tpu)

        roundf = jax.vmap(_round_chip) if compact_on \
            else jax.vmap(functools.partial(_round_chip, act=None))
    elif fused_on:
        from firebird_tpu.ccd import pallas_ops

        def _fused_chip(r, w, df, nf, mg, st_c, mn_c, act=None):
            return pallas_ops.fused_fit_close(
                r["Yt"], r["X"], r["t"], w, df, nf,
                mn_c["included_mon"], st_c["coefs"], st_c["rmse"], mg,
                mn_c["is_tail"], mn_c["is_brk"],
                mn_c["pos_ev"], mn_c["n_exceed"],
                st_c["first_seg"], st_c["nseg"], st_c["bufs"], S=S,
                mixed=mixed_on, active=act, interpret=not on_tpu)

        fusedf = jax.vmap(_fused_chip) if compact_on \
            else jax.vmap(functools.partial(_fused_chip, act=None))
        magsf = jax.vmap(functools.partial(_close_mags, fdtype=fdtype))

    max_rounds = 2 * T + 8

    # ---- compaction parameters (trace-time; params.compact_*) ----
    every = params.compact_every()
    floor = params.compact_floor() if compact_on else 0.0
    bucket = 1 << max(int(max(P * floor, 1) - 1).bit_length(), 3) \
        if floor > 0 else P
    # The re-entry loop is a second traced copy of the round body: real
    # lane savings at chip scale, pure compile cost for tiny batches.
    cascade_on = (compact_on and 0 < bucket < P
                  and P >= params.compact_min_lanes())

    # In-loop per-pixel residents: compaction must permute the spectra
    # views the traced block paths actually read alongside the state, so
    # they move into the while_loop carry (originals die after carry
    # init; the compaction sweep permutes the carried copies).  Keys
    # mirror the blocks' trace-time routing exactly — a path that would
    # read an uncarried resident fails loudly at trace (KeyError), never
    # silently reads the unpermuted original.
    score_pallas = use_pallas("score") and f32_ok
    init_pallas = use_pallas("init") and f32_ok
    resp_keys = ["vario"]
    if "Y" in res:
        resp_keys.append("Y")
    if fit_pallas or init_pallas or fused_on or "Y" not in res:
        resp_keys.append("Yt")
    if score_pallas:
        resp_keys.append("Yd")
    res_shared = {k: res[k] for k in ("X", "Xt", "t", "XX")}

    if compact_on:
        state = dict(state,
                     resp={k: res[k] for k in resp_keys},
                     perm=jnp.tile(jnp.arange(P, dtype=jnp.int32)[None],
                                   (C, 1)),
                     # Baseline for the "enough lanes died" trigger: full
                     # width, so never-alive lanes (snow/insufficient/
                     # no-data pixels, DONE from round 0) count toward
                     # the first periodic compaction.
                     base_alive=jnp.full((C,), P, jnp.int32))

    def _loop_res(st, shared=None):
        if not compact_on:
            return res
        return dict(res_shared if shared is None else shared,
                    **st["resp"])

    def cond(carry):
        st, rounds, _, _, _, tail = carry
        return ((rounds < max_rounds)
                & jnp.any(st["phase"] != PHASE_DONE) & ~tail)

    def _make_body(allow_cascade_exit, shared=None, allow_compact=True,
                   occ_fold=None):
        # ``shared``: chip-shared designs override for the rebalanced
        # tail (own + guest chips concatenated).  ``allow_compact=False``
        # pins lane positions through the loop — the rebalancing ring's
        # un-migration merge is positional, so the rebalanced tail must
        # not permute.  ``occ_fold=C`` folds guest chip rows C..2C into
        # their host rows for the occupancy capture, so migrated lanes
        # stay accounted on the device that computes them.
        def body(carry):
            st, rounds, counts, occ, ncomp, tail = carry
            res_l = _loop_res(st, shared)
            phase = st["phase"]
            in_init = phase == PHASE_INIT
            in_mon = phase == PHASE_MONITOR

            # Occupancy capture: lanes entering the round still working,
            # and lanes the guarded kernels pay for (whole blocks with
            # any active lane; the full width when compaction is off).
            Pc = phase.shape[1]
            active_c = jnp.sum(phase != PHASE_DONE, -1).astype(jnp.int32)
            paid_c = _paid_lanes(phase, _block_widths(Pc)) if compact_on \
                else jnp.full_like(active_c, Pc)
            if occ_fold is not None:
                active_c = active_c[:occ_fold] + active_c[occ_fold:]
                paid_c = paid_c[:occ_fold] + paid_c[occ_fold:]
            occ = lax.dynamic_update_slice(
                occ, jnp.stack([active_c, paid_c], -1)[None],
                (rounds, jnp.zeros((), rounds.dtype),
                 jnp.zeros((), rounds.dtype)))

            any_init = jnp.any(in_init)
            init = lax.cond(any_init,
                            lambda: initf(res_l, st),
                            lambda: _init_zeros(st))

            if fused_mon:
                # Whole-round fusion: monitor chain + segment close +
                # shared refit run as ONE pallas_call per chip
                # (pallas_ops.fused_round), so the separate monf/closef/
                # fitf conds collapse into a single any-work gate.  The
                # INIT block stays cond-gated outside (rare after
                # warmup) and hands its fit window into the kernel; the
                # event flags come back in ``ev`` and feed the same
                # next-state code as the other routes.
                def _run_round():
                    if compact_on:
                        return roundf(res_l, st, init,
                                      in_mon | init["init_ok"])
                    return roundf(res_l, st, init)

                def _skip_round():
                    zb = jnp.zeros_like(in_mon)
                    zi = jnp.zeros_like(st["cur_i"])
                    ev0 = dict(is_tail=zb, is_brk=zb, is_refit=zb,
                               pos_ev=zi, do_fit=zb, n_full=zi,
                               included_mon=st["included"],
                               alive_mon=st["alive"])
                    return (st["bufs"], st["nseg"], st["coefs"],
                            st["rmse"], ev0)

                bufs, nseg, cfull, rfull, ev = lax.cond(
                    jnp.any(in_mon) | jnp.any(init["init_ok"]),
                    _run_round, _skip_round)
                mon = dict(is_tail=ev["is_tail"], is_brk=ev["is_brk"],
                           is_refit=ev["is_refit"], pos_ev=ev["pos_ev"],
                           included_mon=ev["included_mon"],
                           alive_mon=ev["alive_mon"])
                close = mon["is_tail"] | mon["is_brk"]
                any_close = jnp.any(close)
                init_ok, is_refit = init["init_ok"], mon["is_refit"]
                do_fit, n_full = ev["do_fit"], ev["n_full"]
                any_fit = jnp.any(do_fit)
            else:
                mon = lax.cond(jnp.any(in_mon),
                               lambda: monf(res_l, st),
                               lambda: _mon_zeros(st))

                close = mon["is_tail"] | mon["is_brk"]
                any_close = jnp.any(close)
                # Refit / init-ok shared fit (skipped when no pixel
                # needs one).
                init_ok, is_refit = init["init_ok"], mon["is_refit"]
                do_fit = init_ok | is_refit
                any_fit = jnp.any(do_fit)
                n_full = jnp.where(init_ok, init["n_ok"], mon["n_rf"])

            def _w_full():
                # The [C,P,T] fit-window build lives inside the branches
                # so a no-fit round materializes nothing.
                return jnp.where(init_ok[..., None], init["w_stab"],
                                 mon["included_mon"] & is_refit[..., None])

            if fused_mon:
                pass        # bufs/nseg/cfull/rfull merged in-kernel above
            elif fused_on:
                # One fused pallas_call serves the close AND the shared
                # fit on a single VMEM residency of the wire spectra;
                # the do_fit coefs/rmse merge happens in-kernel, so the
                # branch returns the MERGED model directly.  The break
                # magnitudes stay on the shared _close_mags program
                # under their own (rare) any-break cond — the identical
                # code on fused and unfused paths, which is what keeps
                # the golden byte-identical instead of envelope-bound.
                def _run_fused():
                    w = _w_full().astype(fdtype)
                    mg = lax.cond(jnp.any(mon["is_brk"]),
                                  lambda: magsf(res_l, st, mon),
                                  lambda: jnp.zeros_like(st["rmse"]))
                    if compact_on:
                        return fusedf(res_l, w, do_fit, n_full, mg, st,
                                      mon, do_fit | close)
                    return fusedf(res_l, w, do_fit, n_full, mg, st, mon)

                bufs, nseg, cfull, rfull = lax.cond(
                    any_close | any_fit, _run_fused,
                    lambda: (st["bufs"], st["nseg"], st["coefs"],
                             st["rmse"]))
            else:
                bufs, nseg = lax.cond(any_close,
                                      lambda: closef(res_l, st, mon),
                                      lambda: (st["bufs"], st["nseg"]))

                def _run_fit():
                    w = _w_full().astype(fdtype)
                    if compact_on:
                        return fitf(res_l, w, n_full, do_fit)
                    return fitf(res_l, w, n_full)

                cfull, rfull = lax.cond(any_fit, _run_fit,
                                        lambda: (st["coefs"], st["rmse"]))

            # ============== next state (batched elementwise) ============
            is_tail, is_brk = mon["is_tail"], mon["is_brk"]
            phase_n = jnp.where(
                init["init_nowin"] | (init["init_bad"] & ~init["has_adv"]),
                PHASE_DONE,
                jnp.where(init_ok, PHASE_MONITOR,
                          jnp.where(is_tail, PHASE_DONE,
                                    jnp.where(is_brk, PHASE_INIT, phase))))
            cur_i_n = jnp.where(
                init["init_tm"], init["i_next_tm"],
                jnp.where(init["init_bad"] & init["has_adv"],
                          init["i_adv"],
                          jnp.where(is_brk, mon["pos_ev"], st["cur_i"])))
            cur_k_n = jnp.where(init_ok, init["j"] + 1,
                                jnp.where(is_refit, mon["pos_ev"] + 1,
                                          st["cur_k"]))
            alive_n = jnp.where(in_init[..., None], init["alive_init"],
                                jnp.where(in_mon[..., None],
                                          mon["alive_mon"], st["alive"]))
            included_n = jnp.where(
                init_ok[..., None], init["w_stab"],
                jnp.where(is_brk[..., None], False,
                          jnp.where(in_mon[..., None], mon["included_mon"],
                                    st["included"])))
            if fused_on:
                coefs_n, rmse_n = cfull, rfull    # merged in-kernel
            else:
                coefs_n = jnp.where(do_fit[..., None, None], cfull,
                                    st["coefs"])
                rmse_n = jnp.where(do_fit[..., None], rfull, st["rmse"])
            nlast_n = jnp.where(do_fit, n_full.astype(jnp.int32),
                                st["n_last_fit"])
            first_n = st["first_seg"] & ~is_brk

            st_n = dict(st, phase=phase_n.astype(jnp.int32),
                        cur_i=cur_i_n.astype(jnp.int32),
                        cur_k=cur_k_n.astype(jnp.int32),
                        alive=alive_n, included=included_n,
                        coefs=coefs_n, rmse=rmse_n, n_last_fit=nlast_n,
                        first_seg=first_n, nseg=nseg, bufs=bufs)
            counts_n = counts + jnp.stack(
                [any_init, any_fit, any_close]).astype(jnp.int32)

            if compact_on and allow_compact:
                # ---- dense-prefix compaction ----
                n_alive = jnp.sum(st_n["phase"] != PHASE_DONE,
                                  -1).astype(jnp.int32)          # [C]
                dead_since = st_n["base_alive"] - n_alive
                # Slack from the CURRENT lane width: inside the stage-2
                # bucket the "1/16 of lanes died" cadence must mean 1/16
                # of the bucket, or the tail never re-compacts.
                periodic = (((rounds + 1) % every) == 0) \
                    & (jnp.max(dead_since) >= max(Pc // 16, 1))
                if allow_cascade_exit:
                    # Forced compaction on the bucket-entry transition:
                    # survivors must sit in the prefix before the loop
                    # exits and stage 2 slices it.
                    ready = jnp.max(n_alive) <= bucket
                else:
                    ready = jnp.zeros((), bool)
                do_c = periodic | (ready & ~tail)
                st_n = lax.cond(do_c, _compact_state, lambda s: s, st_n)
                st_n = dict(st_n, base_alive=jnp.where(
                    do_c, n_alive, st_n["base_alive"]))
                ncomp = ncomp + do_c.astype(jnp.int32)
                tail = tail | ready
            return (st_n, rounds + 1, counts_n, occ, ncomp, tail)

        return body

    carry0 = (state, jnp.zeros((), jnp.int32), jnp.zeros((3,), jnp.int32),
              jnp.zeros((max_rounds, C, 2), jnp.int32),
              jnp.zeros((), jnp.int32), jnp.zeros((), bool))
    state, rounds, counts, occ, ncomp, tail = lax.while_loop(
        cond, _make_body(cascade_on), carry0)

    lanes_migrated = None
    if cascade_on:
        # ---- stage 2: bucketed re-entry for the long tail ----
        # The exit compaction put every still-working lane in the dense
        # prefix, so the "gather survivors" is a static slice [:, :bucket]
        # of each carried array; the same loop body re-traces at the
        # bucket shape and finishes them; one static slice-assign merges
        # the results back.  All inside the jitted program — no host
        # round-trip, no extra compile shapes for the warm-start cache to
        # predict (a stage-2 that never runs costs zero rounds).
        def _slice_p(a, axis=1):
            idx = [slice(None)] * a.ndim
            idx[axis] = slice(0, bucket)
            return a[tuple(idx)]

        st2 = {k: _slice_p(state[k]) for k in _COMPACT_PIXEL_KEYS}
        st2["bufs"] = tuple(_slice_p(b) for b in state["bufs"])
        st2["resp"] = {
            k: _slice_p(v, 1 + _COMPACT_RESP_AXIS.get(k, 0))
            for k, v in state["resp"].items()}
        st2["perm"] = _slice_p(state["perm"])
        st2["base_alive"] = jnp.sum(st2["phase"] != PHASE_DONE,
                                    -1).astype(jnp.int32)
        if rebalance is not None:
            # ---- cross-device straggler rebalancing ring ----
            # Compaction's per-device alive residue diverges, so without
            # migration every chip waits on the slowest device's tail.
            # At this boundary the survivors sit in a dense prefix per
            # chip: ship the whole stage-2 carry one ring hop rightward
            # (lax.ppermute on simulated meshes, the Pallas
            # async-remote-copy kernel on TPU), activate only the DONATED
            # lanes on the host device, run the tail loop over own+guest
            # chips with lane positions pinned (allow_compact=False —
            # the un-migration merge is positional), then ship the guest
            # results back and merge them into the donor's rows.  Stores
            # stay row-identical by construction; tests/test_fuse.py
            # proves it on the simulated mesh.
            from firebird_tpu.parallel import mesh as _pmesh

            st2cat, shcat, donated, lanes_migrated = \
                _pmesh.rebalance_tail_out(st2, res_shared, rebalance,
                                          bucket)
            carry2 = (st2cat, rounds, counts, occ, ncomp,
                      jnp.zeros((), bool))
            st2cat, rounds, counts, occ, ncomp, _ = lax.while_loop(
                cond, _make_body(False, shared=shcat,
                                 allow_compact=False, occ_fold=C),
                carry2)
            st2 = _pmesh.rebalance_tail_back(st2cat, donated, rebalance,
                                             C)
        else:
            carry2 = (st2, rounds, counts, occ, ncomp,
                      jnp.zeros((), bool))
            st2, rounds, counts, occ, ncomp, _ = lax.while_loop(
                cond, _make_body(False), carry2)
        merge = lambda full, part: full.at[:, :bucket].set(part)
        state = dict(state,
                     nseg=merge(state["nseg"], st2["nseg"]),
                     alive=merge(state["alive"], st2["alive"]),
                     bufs=tuple(merge(f, p) for f, p in
                                zip(state["bufs"], st2["bufs"])),
                     perm=merge(state["perm"], st2["perm"]))

    nseg, bufs, alive = state["nseg"], state["bufs"], state["alive"]
    if compact_on:
        # Land every per-pixel output back in original pixel order (the
        # carried permutation's inverse, one scatter per field).
        perm = state["perm"]
        nseg = _unpermute(nseg, perm)
        alive = _unpermute(alive, perm)
        bufs = tuple(_unpermute(b, perm) for b in bufs)

    meta_b, rmse_b, mag_b, coef_b = bufs
    final_mask = jnp.where(res["is_std"][..., None], alive,
                           jnp.where(res["is_alt"][..., None],
                                     res["alt_mask"], False))
    return ChipSegments(
        n_segments=nseg,
        seg_meta=meta_b.reshape(C, P, S, 6),
        seg_rmse=rmse_b.reshape(C, P, S, B),
        seg_mag=mag_b.reshape(C, P, S, B),
        seg_coef=coef_b.reshape(C, P, S, B, params.MAX_COEFS),
        mask=final_mask, procedure=res["procedure"],
        rounds=jnp.broadcast_to(rounds, (C,)), vario=res["vario"],
        round_counts=jnp.broadcast_to(counts, (C, 3)),
        occupancy=jnp.transpose(occ, (1, 0, 2)),
        # The count lands on the loop's FIRST chip row only (zeros
        # elsewhere): under shard_map each shard runs its own loop over
        # its chip slice, so a per-chip broadcast would make any host
        # aggregation wrong (sum overcounts by chips-per-shard, max
        # drops all but the busiest shard) — one nonzero per loop makes
        # the chip-sum THE batch total (record_occupancy).
        compactions=jnp.where(jnp.arange(C) == 0, ncomp, 0),
        # Zeros (not None) whenever a rebalance spec was armed, even on
        # shapes whose cascade never built — so the sharded program's
        # output structure is one trace and the counter reads 0, not
        # "absent", when the ring had nothing to move.
        lanes_migrated=(lanes_migrated if lanes_migrated is not None
                        else (jnp.zeros((C,), jnp.int32)
                              if rebalance is not None else None)))


# ---------------------------------------------------------------------------
# Host-facing API
# ---------------------------------------------------------------------------

def device_designs(days, n_obs, dtype):
    """The harmonic design matrices, built ON DEVICE from the int32 wire.

    ``days`` [C, T] int32 ordinal days (0-padded past ``n_obs`` [C] int32)
    -> (Xs [C,T,8], Xts [C,T,5], ts [C,T] float, valids [C,T] bool), the
    four host-prepared float planes :func:`prep_batch` used to ship.  The
    design is tiny next to the spectra, but building it here removes the
    last float ingress planes entirely (the wire is all-integer, which
    ``tools/wire_probe.py`` pins) and moves the per-chip host float64
    trig off the staging thread.

    Numerics: the phase uses ``t mod 365.25 == ((4t) mod 1461) / 4`` —
    exact integer arithmetic (4t < 2^23 for any ordinal day), so the
    phase argument is bit-identical to the host float64 ``np.mod`` for
    integer dates in EITHER dtype; ``yr`` subtracts the int anchor before
    widening, so it is exact too.  Only the trig itself is evaluated in
    the compute dtype instead of float64-then-cast, which bounds the
    device-vs-host design difference at trig ulp (~1e-7 relative in f32,
    ~1e-16 in f64) — far inside the measured f32 oracle-parity envelope
    (tests/test_wire.py pins the tolerance; docs/DIVERGENCE.md)."""
    f = jnp.dtype(dtype)
    days = days.astype(jnp.int32)
    C, T = days.shape
    valid = jnp.arange(T)[None, :] < n_obs[:, None]
    quarter = jnp.mod(4 * days, 1461)                          # int, exact
    ph = jnp.asarray(params.OMEGA, f) \
        * (quarter.astype(f) * jnp.asarray(0.25, f))
    anchor = jnp.where(n_obs > 0, days[:, 0], 0)
    yr = (days - anchor[:, None]).astype(f) / jnp.asarray(365.25, f)
    one = jnp.ones_like(yr)
    c1, s1 = jnp.cos(ph), jnp.sin(ph)
    c2, s2 = jnp.cos(2 * ph), jnp.sin(2 * ph)
    c3, s3 = jnp.cos(3 * ph), jnp.sin(3 * ph)
    X = jnp.stack([one, yr, c1, s1, c2, s2, c3, s3], axis=-1)
    Xt = jnp.stack([one, c1, s1, c2, s2], axis=-1)
    # Padding rows contribute nothing (build_designs' zeroing rule).
    X = jnp.where(valid[..., None], X, 0)
    Xt = jnp.where(valid[..., None], Xt, 0)
    return X, Xt, days.astype(f), valid


def _detect_batch_wire(days_i32, n_obs_i32, Y_i16, qa_wire, *, dtype,
                       wcap=None, sensor=LANDSAT_ARD,
                       max_segments=MAX_SEGMENTS, compact=None,
                       fused=None, mixed=None):
    """Batch detect from the all-integer wire: spectra ride int16, QA
    uint8/uint16, and the day ordinals ride int32 — the harmonic design
    matrices, the float date grid, and the validity mask are built on
    device by :func:`device_designs` inside this jitted prologue, so NO
    float plane crosses host->device at all (docs/ROOFLINE.md "Wire
    budget").  The core widens the spectra on device and keeps a
    wire-dtype resident copy so the Pallas fit path reads int16 from HBM.
    ``compact`` (static) is the active-lane-compaction override (None =
    FIREBIRD_COMPACT at trace time)."""
    Xs, Xts, ts, valids = device_designs(days_i32, n_obs_i32, dtype)
    return _detect_batch_core(Xs, Xts, ts, valids, Y_i16,
                              qa_wire.astype(jnp.int32), wcap=wcap,
                              sensor=sensor, max_segments=max_segments,
                              dtype=dtype, compact=compact, fused=fused,
                              mixed=mixed)


_WIRE_STATICS = ("dtype", "wcap", "sensor", "max_segments", "compact",
                 "fused", "mixed")
# Donating twin for the driver's staged steady-state dispatch: the packed
# wire buffers (spectra + QA, the dominant HBM input term) are consumed by
# the dispatch, so a deeper pipeline (Config.pipeline_depth) doesn't pin
# every in-flight batch's inputs alongside its results.  Only safe for
# single-dispatch callers (check_capacity=False) — a capacity retry would
# re-dispatch already-deleted buffers.  (Jitted BEFORE the plain wrapper
# rebinds the name, so both trace the same underlying function and keep
# one HLO module name — persistent cache entries stay shared/valid.)
_detect_batch_wire_donated = jax.jit(_detect_batch_wire,
                                     static_argnames=_WIRE_STATICS,
                                     donate_argnums=(2, 3))
_detect_batch_wire = jax.jit(_detect_batch_wire,
                             static_argnames=_WIRE_STATICS)
# Donated compiles emit jax's "Some donated buffers were not usable"
# advisory once per shape (the wire dtypes rarely alias the float result
# buffers byte-for-byte; the donation is still honored — inputs freed at
# dispatch).  Deliberately NOT suppressed: a process-global filter would
# hide real donation bugs in unrelated jax code, and a per-dispatch
# warnings.catch_warnings races between the warm-compile thread and the
# main dispatch thread (filters are process-global state).


def window_cap(packed) -> int:
    """A rigorous static bound on initialization-window member count.

    A window [i, j] either closes on the observation count (exactly
    MEOW_SIZE members) or on the INIT_DAYS span — in which case all members
    but j lie within INIT_DAYS of t_i, so the count is bounded by the
    densest INIT_DAYS stretch of the (chip-shared) date grid plus one.
    Using all acquisitions (a superset of any alive set) keeps the bound
    valid for every round of the event loop.  Rounded up to a multiple of
    8 so minor date-grid differences reuse the compiled kernel.
    """
    cap = params.MEOW_SIZE
    for c in range(packed.n_chips):
        d = np.asarray(packed.dates[c][: int(packed.n_obs[c])], np.int64)
        if d.size:
            hi = np.searchsorted(d, d + params.INIT_DAYS, side="right")
            cap = max(cap, int((hi - np.arange(d.size)).max()) + 1)
    T = packed.spectra.shape[-1]
    return min(-8 * (-cap // 8), T)


def build_designs(dates: np.ndarray, n_obs: int | None = None,
                  dtype=np.float32) -> tuple[np.ndarray, np.ndarray]:
    """Host-side design matrices for a chip's date grid (float64 phases).

    Padding rows (beyond n_obs) get zeroed so they contribute nothing.
    """
    dates = np.asarray(dates)
    anchor = float(dates[0]) if dates.size else 0.0
    X = harmonic.design_matrix(dates, anchor, params.MAX_COEFS)
    Xt_full = harmonic.design_matrix(dates, anchor, params.TMASK_COEFS + 1)
    Xt = np.concatenate([Xt_full[:, :1], Xt_full[:, 2:]], axis=1)
    if n_obs is not None and n_obs < dates.shape[0]:
        X[n_obs:] = 0.0
        Xt[n_obs:] = 0.0
    return X.astype(dtype), Xt.astype(dtype)


def prep_batch(packed) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side batch prep shared by the single-device and sharded paths:
    stacked design matrices + validity mask for a PackedChips batch."""
    C, _, _, T = packed.spectra.shape
    designs = [build_designs(packed.dates[c], int(packed.n_obs[c]))
               for c in range(C)]
    Xs = np.stack([d[0] for d in designs])
    Xts = np.stack([d[1] for d in designs])
    valid = np.arange(T)[None, :] < packed.n_obs[:, None]
    return Xs, Xts, valid


def ensure_x64(dtype) -> None:
    """Enable jax x64 when a float64 run is requested — without it jnp
    silently downcasts f64 arrays to f32 and a 'bit-parity run' actually
    executes at single precision.  Called by every f64-capable entry
    point (detect_packed, mesh.detect_sharded)."""
    if jnp.dtype(dtype) == jnp.dtype(jnp.float64) \
            and not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)


def working_set_bytes(T: int, W: int | None = None,
                      S: int = MAX_SEGMENTS, sensor=LANDSAT_ARD,
                      dtype_bytes: int = 4) -> int:
    """Estimated peak device bytes one chip needs during a dispatch.

    Drives chips-per-batch auto-sizing (driver.core.auto_chips_per_batch):
    wire arrays (int16 spectra + uint16 QA), the widened float spectra plus
    one [P,B,T]-sized live temporary, ~20 [P,T] loop temporaries (the scale
    the profiled HLO shows), the one-hot window tensors, and the flat
    result buffers (live twice across the while_loop boundary).
    """
    P, B, K = sensor.pixels, sensor.n_bands, params.MAX_COEFS
    W = W or min(T, 48)
    wire = P * B * T * 2 + P * T * 2
    bufs = 2 * P * S * (6 + 2 * B + B * K) * dtype_bytes
    widened = 2 * P * B * T * dtype_bytes
    pt_temps = 20 * P * T * dtype_bytes
    # The [P,W,T] one-hot window tensors exist only on the XLA INIT path;
    # the fused Pallas INIT kernel (FIREBIRD_PALLAS=init) and the
    # whole-loop mega kernel never materialize them, so batches can size
    # past that peak — but mega earns the exemption only on shapes
    # mega_fits ACCEPTS: a refused mega falls back to the XLA init path
    # and its one-hot peak (kernel._detect_batch_impl), which the batch
    # sizing must then have budgeted.  The widened-view and temporary
    # terms stay even for mega: the PROLOGUE (triage/variogram/alt fit)
    # runs identically in every config and its [P,B,T]-scale float peak
    # is the sizing constraint regardless of how lean the loop itself
    # is.  The kernel route is f32-only on TPU (Mosaic), so f64 sizing
    # keeps the term.
    def _mega_applies() -> bool:
        if not use_pallas("mega"):
            return False
        from firebird_tpu.ccd import pallas_ops

        return pallas_ops.mega_fits(T, W, B, S, 2)

    onehot = (0 if (use_pallas("init") or _mega_applies())
              and dtype_bytes == 4
              else P * W * T * (1 + dtype_bytes))
    return int(wire + widened + pt_temps + onehot + bufs)


def result_bytes(T: int, S: int = MAX_SEGMENTS, sensor=LANDSAT_ARD,
                 dtype_bytes: int = 4) -> int:
    """Device bytes one chip's ChipSegments result pins until its drain.

    The pipeline-depth term of batch auto-sizing
    (driver.core.auto_chips_per_batch): each in-flight batch beyond the
    one computing holds its FULL-CAPACITY result buffers on device until
    the drain thread fetches them — the egress diet shrinks what crosses
    the wire, not this residency — so depth must be budgeted against
    HBM explicitly."""
    P, B, K = sensor.pixels, sensor.n_bands, params.MAX_COEFS
    per_px = S * (6 + 2 * B + B * K) * dtype_bytes   # meta+rmse+mag+coef
    per_px += T + (B + 2) * dtype_bytes              # mask + vario + ints
    return int(P * per_px)


def record_first_call(key: tuple, fn):
    """First-call capture per compiled shape (jit compiles synchronously
    inside the first dispatch; warm-cache enqueues are sub-ms, so the
    first-call wall time IS the trace+compile time to within noise).

    Shared by the single-device (detect_packed) and sharded
    (parallel.mesh.detect_sharded) dispatch paths.  Seen-keys live on the
    metrics registry — run-scoped, not process-scoped — so every run's
    obs_report records a kernel_first_call_seconds entry per shape it
    dispatched, even when the jit cache was already warm."""
    from firebird_tpu.obs import metrics, tracing

    reg = metrics.get_registry()
    if not reg.once(("kernel_dispatch",) + tuple(key)):
        return fn()
    t0 = time.perf_counter()
    with tracing.span("first_dispatch", key=str(key)):
        out = fn()
    reg.histogram("kernel_first_call_seconds").observe(
        time.perf_counter() - t0)
    reg.counter("kernel_dispatch_shapes").inc()
    return out


# Histogram buckets for kernel_round_active_fraction (a 0..1 fraction,
# not a latency; sixteenths resolve the tail the compaction targets).
FRACTION_BUCKETS = tuple(i / 16 for i in range(1, 17))


def record_occupancy(seg) -> dict | None:
    """Feed the event loop's occupancy capture into the obs registry.

    ``seg`` is a host-fetched ChipSegments (driver.core.drain_batch calls
    this after its bulk fetch; bench.py after its timed run).  Per
    executed round and chip, ``kernel_round_active_fraction`` observes
    active/padded lanes; the counters accumulate active / wasted
    (paid - active) lane-rounds and compactions — the padded-vs-effective
    accounting flops.occupancy_detail turns into the bench artifact.
    Returns the summary dict, or None when the dispatch carried no
    occupancy capture (mega route, pre-compaction artifacts)."""
    occ = getattr(seg, "occupancy", None)
    if occ is None:
        return None
    from firebird_tpu.ccd import flops
    from firebird_tpu.obs import metrics as obs_metrics

    det = flops.occupancy_detail(
        np.asarray(occ), np.asarray(seg.rounds),
        int(seg.mask.shape[-2]))
    hist = obs_metrics.histogram("kernel_round_active_fraction",
                                 buckets=FRACTION_BUCKETS,
                                 help="active-lane fraction per event-loop "
                                      "round per chip")
    hist.observe_many(det.pop("_fractions"))
    obs_metrics.counter(
        "kernel_active_lane_rounds",
        help="lane-rounds with a working pixel").inc(
        det["active_lane_rounds"])
    obs_metrics.counter(
        "kernel_wasted_lane_rounds",
        help="paid lane-rounds with no working pixel "
             "(effective - active)").inc(det["wasted_lane_rounds"])
    comp = getattr(seg, "compactions", None)
    if comp is not None:
        # Per-loop counts land on each loop's first chip row (zeros
        # elsewhere), so the chip-sum is the batch total across shards.
        obs_metrics.counter(
            "kernel_compactions",
            help="dense-prefix lane compactions").inc(
            int(np.asarray(comp).sum()))
    lm = getattr(seg, "lanes_migrated", None)
    if lm is not None:
        moved = int(np.asarray(lm).sum())
        obs_metrics.counter(
            "kernel_lanes_migrated",
            help="straggler lanes migrated to a neighbor device by the "
                 "rebalancing ring").inc(moved)
        if moved:
            obs_metrics.counter(
                "rebalance_migrations",
                help="dispatches in which the rebalancing ring moved "
                     "lanes").inc()
        det["lanes_migrated"] = moved
    return det


def capacity_bound(packed) -> int:
    """An upper bound on segments any pixel of the batch can close:
    closed segments have disjoint included-observation sets of at least
    MEOW_SIZE members each, so T // MEOW_SIZE bounds the count."""
    T = packed.spectra.shape[-1]
    return max(T // params.MEOW_SIZE, 1)


def capacity_retry(dispatch, read_worst, S: int, bound: int):
    """The one overflow-retry policy, shared by the single-device and
    sharded paths: run ``dispatch(S)``; if any pixel closed more segments
    than S (``read_worst``, a host sync), double S (capped at the
    rigorous ``bound``) and re-dispatch.  S >= bound skips the sync —
    overflow is impossible there."""
    S = max(S, 1)
    while True:
        seg = dispatch(S)
        if S >= bound:
            return seg
        worst = read_worst(seg)
        if worst <= S:
            return seg
        from firebird_tpu.obs import logger

        logger("pyccd").info(
            "segment capacity %d overflowed (deepest pixel closed %d); "
            "re-dispatching at %d", S, worst, min(2 * S, bound))
        S = min(2 * S, bound)


def wire_qa8() -> bool:
    """Whether staging ships the QA plane as uint8 (FIREBIRD_WIRE_QA8,
    default on) — half the uint16 plane, the second-largest h2d term
    after the spectra.  Lossless for the kernel: the QA triage reads bits
    0–5 only (params.QA_*_BIT), all inside the low byte.  Read at
    staging time; the wire dtype is part of the jit key, so both modes
    keep their own compiled program."""
    from firebird_tpu.config import env_knob

    return env_knob("FIREBIRD_WIRE_QA8") not in ("", "0")


def wire_qa_dtype():
    """The staged QA plane's wire dtype under the current knobs."""
    return np.uint8 if wire_qa8() else np.uint16


def wire_args(packed) -> tuple:
    """The host-side ``_detect_batch_wire`` argument tuple (numpy, wire
    dtypes, all integer): day ordinals int32, n_obs int32, spectra int16,
    QA uint8/uint16 (:func:`wire_qa_dtype`).  Shared by stage_packed,
    the sharded stager, bench, and the tools so the wire contract has
    one definition."""
    return (np.asarray(packed.dates, np.int32),
            np.asarray(packed.n_obs, np.int32),
            np.asarray(packed.spectra, np.int16),
            np.asarray(packed.qas).astype(wire_qa_dtype()))


def stage_packed(packed, dtype) -> tuple:
    """Host->device staging of a PackedChips batch: the wire-dtype
    ``_detect_batch_wire`` argument tuple as device arrays, blocking until
    the transfer lands.  Every staged plane is integer (int32 days +
    counts, int16 spectra, uint8/uint16 QA — :func:`wire_args`); the
    float designs/date grid/validity mask are built on device by the
    jitted prologue (:func:`device_designs`).  Split out of
    :func:`detect_packed` so the driver's prefetch thread can ship batch
    i+1's H2D while batch i computes (driver.core.stage_batch); the main
    thread then dispatches with ``staged=``."""
    ensure_x64(dtype)
    args = tuple(jnp.asarray(a) for a in wire_args(packed))
    jax.block_until_ready(args)
    return args


def aot_compile(avatars, *, dtype, wcap, sensor=LANDSAT_ARD,
                max_segments: int = MAX_SEGMENTS, donate: bool = False,
                compact: bool | None = None, fused=None,
                mixed: bool | None = None):
    """AOT lower+compile the wire-dtype batch program for a shape WITHOUT
    running it (``avatars`` are jax.ShapeDtypeStructs in the
    ``_detect_batch_wire`` argument order: days int32 [C,T], n_obs int32
    [C], spectra int16 [C,B,P,T], QA uint8/uint16 [C,P,T] — must match
    :func:`wire_args`' dtypes or the warm entry misses).  With the persistent
    compilation cache on, the serialized executable is what the first
    real dispatch of the same shape deserializes instead of compiling —
    the driver's background warm start (driver.core.warm_start).
    ``compact`` must match what the real dispatch will pass (the drivers
    pass cfg.compact both here and at dispatch) or the warm entry misses
    the jit cache."""
    fn = _detect_batch_wire_donated if donate else _detect_batch_wire
    return fn.lower(*avatars, dtype=jnp.dtype(dtype), wcap=wcap,
                    sensor=sensor, max_segments=max_segments,
                    compact=compact, fused=fused, mixed=mixed).compile()


def detect_packed(packed, dtype=jnp.float32,
                  max_segments: int = MAX_SEGMENTS,
                  check_capacity: bool = True, staged: tuple | None = None,
                  donate: bool = False,
                  compact: bool | None = None,
                  fused=None, mixed: bool | None = None) -> ChipSegments:
    """Run the kernel over a PackedChips batch -> ChipSegments with leading
    chip axis [C, P, ...].  The batch's sensor spec selects the band
    layout the kernel compiles for.

    The segment buffers start at ``max_segments`` capacity; on the rare
    chip where some pixel closes more segments than that (n_segments
    counts true closes, writes past capacity are dropped), the batch is
    re-dispatched with doubled capacity until every segment fits — each
    capacity is a separate compiled program, cached for later batches.
    ``check_capacity=False`` skips the overflow check, keeping the
    dispatch fully asynchronous — the caller must then test
    ``n_segments > capacity`` itself before trusting the buffers (the
    driver does this on its drain thread, driver/core.py::drain_batch).

    ``staged`` takes pre-staged device args from :func:`stage_packed`
    instead of transferring here; ``donate=True`` (honored only with
    ``check_capacity=False`` — a retry would re-dispatch deleted buffers)
    frees the wire input buffers at dispatch.  ``compact`` overrides the
    FIREBIRD_COMPACT default (params.compact_default) per call;
    ``fused`` (False/True/"mon") and ``mixed`` likewise override
    FIREBIRD_FUSED_FIT / FIREBIRD_MIXED_PRECISION.
    """
    ensure_x64(dtype)
    args = staged if staged is not None else stage_packed(packed, dtype)
    kw = dict(dtype=jnp.dtype(dtype), wcap=window_cap(packed),
              sensor=getattr(packed, "sensor", LANDSAT_ARD),
              compact=compact, fused=fused, mixed=mixed)
    fn = _detect_batch_wire_donated if donate and not check_capacity \
        else _detect_batch_wire
    dispatch = lambda S: record_first_call(
        ("single", packed.spectra.shape, str(kw["dtype"]), kw["wcap"],
         kw["sensor"].name, S, compact, fused, mixed),
        lambda: fn(*args, max_segments=S, **kw))
    if not check_capacity:
        return dispatch(max(max_segments, 1))
    return capacity_retry(dispatch,
                          lambda seg: int(np.asarray(seg.n_segments).max()),
                          max_segments, capacity_bound(packed))


# ---------------------------------------------------------------------------
# Int-coded egress: the d2h half of the wire diet (docs/ROOFLINE.md
# "Wire budget").  ChipSegments drains as float32 planes sized for the
# WORST-CASE segment capacity; the store's row values are integers or
# exact functions of the f32 bits, so the drain can cross the wire as
# integer tables sliced to the batch's OBSERVED segment depth — decoded
# bit-exactly on the host (ccd.format.decode_egress), store rows
# byte-identical to the raw-f32 drain (tests/test_wire.py golden).
# ---------------------------------------------------------------------------

def wire_egress_enabled() -> bool:
    """Whether batch drains cross d2h as int-coded tables
    (FIREBIRD_WIRE_EGRESS, default on; f32 results only — the f64
    bit-parity path keeps the raw drain).  Read per drain, not per
    trace: the packing program is a separate jit."""
    from firebird_tpu.config import env_knob

    return env_knob("FIREBIRD_WIRE_EGRESS") not in ("", "0")


def egress_bucket(worst: int, S: int) -> int:
    """The packed egress segment depth: the observed deepest pixel's
    close count rounded up to a power of two (few compiled packing
    shapes), capped at the result buffers' capacity ``S``."""
    w = max(int(worst), 1)
    return min(1 << (w - 1).bit_length(), S)


@functools.partial(jax.jit, static_argnames=("s_eff",))
def pack_egress(seg: ChipSegments, s_eff: int) -> dict:
    """Device-side egress packing of a batched f32 ChipSegments: every
    table integer-dtyped, segment planes sliced to ``s_eff`` slots.

    Codings (all lossless — the golden test requires store rows
    byte-identical to the raw f32 drain):

    - ``meta`` [C,P,s_eff,6] int32: sday/eday/bday/curqa/nobs are exact
      small integers in f32 (ordinal days < 2^24), rint-coded; the
      chprob column is count-coded as ``rint(chprob * PEEK_SIZE)`` —
      chprob is always k/PEEK_SIZE or 1.0, and the host decode re-runs
      the same f32 division the kernel performed, reproducing the f32
      value bit-exactly.
    - ``rmse``/``mag``/``coef``/``vario``: f32 bitcast to int32 (free,
      and it keeps the d2h contract checkable: no float leaves).
    - ``mask``: bitpacked along T (8x).
    - counters/diagnostics (n_segments, procedure, rounds, round_counts,
      occupancy, compactions) are already integer and pass through.

    ``s_eff`` (static; :func:`egress_bucket` of the drain's capacity
    probe) is what buys the big cut: the f32 drain ships S=10 slots per
    pixel while the observed depth is typically 1-2.
    """
    sl = lambda a: a[:, :, :s_eff]
    bc = lambda a: lax.bitcast_convert_type(a, jnp.int32)
    meta = sl(seg.seg_meta)
    meta_i = jnp.rint(meta).astype(jnp.int32)
    meta_i = meta_i.at[..., 3].set(
        jnp.rint(meta[..., 3] * params.PEEK_SIZE).astype(jnp.int32))
    out = dict(n_segments=seg.n_segments, procedure=seg.procedure,
               meta=meta_i, rmse=bc(sl(seg.seg_rmse)),
               mag=bc(sl(seg.seg_mag)), coef=bc(sl(seg.seg_coef)),
               mask=jnp.packbits(seg.mask, axis=-1))
    for f in ("rounds", "round_counts", "occupancy", "compactions",
              "lanes_migrated"):
        v = getattr(seg, f)
        if v is not None:
            out[f] = v
    if seg.vario is not None:
        out["vario"] = bc(seg.vario)
    return out


def chip_slice(seg: ChipSegments, c: int, to_host: bool = False) -> ChipSegments:
    """One chip's view of a batched ChipSegments ([C, ...] -> [...]).

    Single-sources the field set: every field (including future additions)
    is sliced, None-valued optionals pass through.  ``to_host`` fetches the
    slices as numpy arrays.
    """
    out = []
    for f in dataclasses.fields(seg):
        v = getattr(seg, f.name)
        if v is not None:
            v = v[c]
            if to_host:
                v = np.asarray(v)
        out.append(v)
    return ChipSegments(*out)


def segments_to_records(seg: ChipSegments, dates: np.ndarray,
                        pixel: int, sensor=LANDSAT_ARD) -> dict:
    """Convert one pixel's kernel output to the oracle/pyccd result dict
    (change_models + processing_mask), for parity tests and the format
    layer.  ``seg`` must be single-chip ([P, ...]) host-fetched arrays."""
    anchor = float(dates[0]) if len(dates) else 0.0
    # n_segments counts true closes, which can exceed buffer capacity on a
    # raw (non-retried) result; detect_packed re-dispatches so this clip
    # only guards direct _detect_batch_wire callers.
    n = min(int(seg.n_segments[pixel]), seg.seg_meta.shape[-2])
    models = []
    for k in range(n):
        meta = np.asarray(seg.seg_meta[pixel, k], np.float64)
        coefs = np.asarray(seg.seg_coef[pixel, k], np.float64)   # [B,8]
        coefs7, intercept = harmonic.to_pyccd_convention(coefs, anchor)
        rec = {
            "start_day": int(round(meta[0])), "end_day": int(round(meta[1])),
            "break_day": int(round(meta[2])),
            "observation_count": int(round(meta[5])),
            "change_probability": float(meta[3]),
            "curve_qa": int(round(meta[4])),
        }
        for b, name in enumerate(sensor.band_names):
            rec[name] = {
                "magnitude": float(seg.seg_mag[pixel, k, b]),
                "rmse": float(seg.seg_rmse[pixel, k, b]),
                "coefficients": tuple(float(x) for x in coefs7[b]),
                "intercept": float(intercept[b]),
            }
        models.append(rec)
    T = len(dates)
    return {"change_models": models,
            "processing_mask": [int(x) for x in np.asarray(seg.mask[pixel][:T])],
            "procedure": ["standard", "permanent-snow", "insufficient-clear",
                          "no-data"][int(seg.procedure[pixel])]}
