"""Result formatting: kernel output -> the reference's row contracts.

Mirrors ccdc/pyccd.py:99-148 (`default` sentinel + `format` flattening one
pyccd result into 40-column rows with ISO dates, golden-tested by the
reference at test/test_pyccd.py:37-126) — plus a vectorized chip-level path
that goes straight from the kernel's ChipSegments arrays to the three table
frames (chip / pixel / segment), skipping per-pixel Python entirely.
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.ccd import harmonic, params
from firebird_tpu.utils import dates as dt

# Column prefixes in band order (ccdc/pyccd.py:118-145).
BAND_PREFIX = ("bl", "gr", "re", "ni", "s1", "s2", "th")


def default(change_models: list) -> list:
    """Sentinel segment when ccd ran but found no models
    (ccdc/pyccd.py:99-103)."""
    return ([{"start_day": 1, "end_day": 1, "break_day": 1}]
            if not change_models else change_models)


def format_records(cx, cy, px, py, dates, ccdresult) -> list[dict]:
    """Per-pixel result -> list of flat row dicts (ccdc/pyccd.py:106-148).

    ``dates`` are ordinal days; emitted as ISO strings in input order, the
    processing mask alongside.
    """
    def g(cm, *keys, default=None):
        v = cm
        for k in keys:
            if not isinstance(v, dict) or k not in v:
                return default
            v = v[k]
        return v

    mask = ccdresult.get("processing_mask")
    rows = []
    for cm in default(ccdresult.get("change_models") or []):
        row = {
            "cx": int(cx), "cy": int(cy), "px": int(px), "py": int(py),
            "sday": dt.to_iso(cm["start_day"]),
            "eday": dt.to_iso(cm["end_day"]),
            "bday": dt.to_iso(cm.get("break_day", cm["end_day"])),
            "chprob": g(cm, "change_probability"),
            "curqa": g(cm, "curve_qa"),
        }
        for b, name in enumerate(params.BAND_NAMES):
            p = BAND_PREFIX[b]
            row[f"{p}mag"] = g(cm, name, "magnitude")
            row[f"{p}rmse"] = g(cm, name, "rmse")
            row[f"{p}coef"] = g(cm, name, "coefficients")
            row[f"{p}int"] = g(cm, name, "intercept")
        row["dates"] = [dt.to_iso(int(o)) for o in dates]
        row["mask"] = list(mask) if mask is not None else None
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# Int-coded egress decode (the host half of kernel.pack_egress)
# ---------------------------------------------------------------------------

def decode_egress(tables: dict, T: int):
    """Host-fetched int egress tables -> a float32 host ChipSegments,
    bit-exact against the raw f32 drain (the kernel.pack_egress coding
    contract): integer meta columns widen exactly (< 2^24), the
    count-coded chprob column re-runs the kernel's own f32 division,
    the bitcast planes reinterpret in place (zero-copy views), and the
    bitpacked mask unpacks to ``T`` columns.  Segment planes come back
    at the PACKED depth ``s_eff`` — every consumer reads capacity from
    ``seg_meta.shape[-2]``, and the drain's capacity probe guarantees no
    pixel closed more than ``s_eff`` segments, so frames are identical
    to the full-capacity result."""
    from firebird_tpu.ccd import kernel as _kernel

    f32 = lambda a: np.ascontiguousarray(
        np.asarray(a, np.int32)).view(np.float32)
    meta_i = np.asarray(tables["meta"], np.int32)
    meta = meta_i.astype(np.float32)
    meta[..., 3] = meta_i[..., 3].astype(np.float32) \
        / np.float32(params.PEEK_SIZE)
    mask = np.unpackbits(np.asarray(tables["mask"], np.uint8),
                         axis=-1, count=T).astype(bool)
    opt = {f: (np.asarray(tables[f]) if f in tables else None)
           for f in ("rounds", "round_counts", "occupancy", "compactions",
                     "lanes_migrated")}
    vario = f32(tables["vario"]) if "vario" in tables else None
    return _kernel.ChipSegments(
        n_segments=np.asarray(tables["n_segments"]),
        seg_meta=meta, seg_rmse=f32(tables["rmse"]),
        seg_mag=f32(tables["mag"]), seg_coef=f32(tables["coef"]),
        mask=mask, procedure=np.asarray(tables["procedure"]),
        rounds=opt["rounds"], vario=vario,
        round_counts=opt["round_counts"], occupancy=opt["occupancy"],
        compactions=opt["compactions"],
        lanes_migrated=opt["lanes_migrated"])


# ---------------------------------------------------------------------------
# Vectorized chip-level frames
# ---------------------------------------------------------------------------

def _int_or_none(vals: np.ndarray, real: np.ndarray) -> np.ndarray:
    """Object column of ints, None on sentinel rows (NULL in the store)."""
    col = np.empty(vals.shape[0], object)
    col[:] = np.asarray(vals, np.int64).tolist()
    col[~real] = None
    return col


def _iso_col(ordinals: np.ndarray) -> np.ndarray:
    """Vector ordinal->ISO via a small unique-value table."""
    ordinals = np.asarray(ordinals, np.int64)
    uniq, inv = np.unique(ordinals, return_inverse=True)
    table = np.array([dt.to_iso(int(o)) if o > 0 else "0001-01-01"
                      for o in uniq], dtype=object)
    return table[inv]


def _check_landsat_schema(packed, what: str) -> None:
    if packed.sensor.band_names != params.BAND_NAMES:
        raise ValueError(
            f"{what} writes the reference's Landsat segment schema "
            f"(7 bands, ccdc/segment.py:16-56); got sensor "
            f"{packed.sensor.name!r} with {packed.sensor.n_bands} bands — "
            "persist non-Landsat results through a sensor-specific schema")


def chip_frames(packed, chip: int, seg) -> dict[str, dict]:
    """ChipSegments (host arrays, single chip) -> the three table frames.

    Returns {'chip': {...}, 'pixel': {...}, 'segment': {...}} where each
    value is a dict of column -> numpy array, matching the reference table
    schemas (ccdc/chip.py:15-22, pixel.py:14-21, segment.py:16-56).
    Pixels with no segments contribute the sentinel row (sday=eday=bday=
    0001-01-01, ccdc/pyccd.py:99-103) so reruns stay idempotent.
    """
    _check_landsat_schema(packed, "chip_frames")
    cx, cy = (int(v) for v in packed.cids[chip])
    T = int(packed.n_obs[chip])
    dates_ord = packed.dates[chip][:T]
    anchor = float(dates_ord[0]) if T else 0.0
    dates_iso = [dt.to_iso(int(o)) for o in dates_ord]

    P = seg.n_segments.shape[0]
    coords = packed.pixel_coords(chip)                         # [P,2]

    # clip to buffer capacity: detect_packed re-dispatches on overflow, so
    # this only guards frames built from a raw kernel result
    nseg = np.minimum(np.asarray(seg.n_segments, np.int64),
                      seg.seg_meta.shape[-2])
    n_rows = np.maximum(nseg, 1)                               # sentinel rows
    pix_of_row = np.repeat(np.arange(P), n_rows)
    # per-row segment index; sentinel rows get -1
    seg_idx = np.concatenate([
        np.arange(n) if n else np.array([-1])
        for n in nseg]).astype(np.int64)
    real = seg_idx >= 0
    si = np.maximum(seg_idx, 0)

    meta = np.asarray(seg.seg_meta, np.float64)[pix_of_row, si]    # [R,6]
    rmse = np.asarray(seg.seg_rmse, np.float64)[pix_of_row, si]    # [R,7]
    mag = np.asarray(seg.seg_mag, np.float64)[pix_of_row, si]
    coefs = np.asarray(seg.seg_coef, np.float64)[pix_of_row, si]   # [R,7,8]
    coefs7, intercept = harmonic.to_pyccd_convention(coefs, anchor)

    R = meta.shape[0]
    segment = {
        "cx": np.full(R, cx, np.int64), "cy": np.full(R, cy, np.int64),
        "px": coords[pix_of_row, 0], "py": coords[pix_of_row, 1],
        "sday": np.where(real, _iso_col(meta[:, 0]), "0001-01-01"),
        "eday": np.where(real, _iso_col(meta[:, 1]), "0001-01-01"),
        "bday": np.where(real, _iso_col(meta[:, 2]), "0001-01-01"),
        "chprob": np.where(real, meta[:, 3], np.nan),
        "curqa": _int_or_none(meta[:, 4], real),
        "rfrawp": np.full(R, None, object),
    }
    for b in range(params.NUM_BANDS):
        p = BAND_PREFIX[b]
        segment[f"{p}mag"] = np.where(real, mag[:, b], np.nan)
        segment[f"{p}rmse"] = np.where(real, rmse[:, b], np.nan)
        segment[f"{p}int"] = np.where(real, intercept[:, b], np.nan)
        col = np.empty(R, object)
        col[:] = list(coefs7[:, b])         # rows stay numpy; backends pack
        col[~real] = None
        segment[f"{p}coef"] = col

    mask = np.asarray(seg.mask, np.uint8)[:, :T]
    mask_col = np.empty(P, object)
    mask_col[:] = list(mask)                # rows stay numpy; backends pack
    dates_col = np.empty(1, object)
    dates_col[0] = dates_iso
    pixel = {
        "cx": np.full(P, cx, np.int64), "cy": np.full(P, cy, np.int64),
        "px": coords[:, 0], "py": coords[:, 1],
        "mask": mask_col,
    }
    chip_frame = {
        "cx": np.array([cx], np.int64), "cy": np.array([cy], np.int64),
        "dates": dates_col,
    }
    return {"chip": chip_frame, "pixel": pixel, "segment": segment}


def batch_frames(packed, seg,
                 n_real: int | None = None) -> list[tuple[tuple, dict]]:
    """A whole drained batch -> per-chip table frames in ONE numpy pass.

    ``seg`` is a *host-fetched* batched ChipSegments ([C, P, ...] numpy
    arrays, e.g. from one ``jax.device_get`` of the device result); the
    segment table — by far the widest of the three — is built across the
    entire chip axis at once (row expansion, ISO tables, coefficient
    convention) and only *split* per chip at the end, so the egress cost
    is one vectorized pass instead of C python formatting loops.  Padded
    chips beyond ``n_real`` are dropped.

    Returns ``[((cx, cy), {'chip': .., 'pixel': .., 'segment': ..}), ...]``
    for the first ``n_real`` chips, each entry identical to
    ``chip_frames(packed, c, chip_slice(seg, c, to_host=True))`` — the
    regression surface both drivers' drains share (driver/core.py
    ``write_batch_frames``).
    """
    _check_landsat_schema(packed, "batch_frames")
    C = packed.n_chips if n_real is None else int(n_real)
    if C == 0:
        return []
    P = seg.n_segments.shape[1]

    # ---- global row expansion across the chip axis ----
    nseg = np.minimum(np.asarray(seg.n_segments[:C], np.int64),
                      seg.seg_meta.shape[-2])                  # [C,P]
    n_rows = np.maximum(nseg, 1).reshape(-1)                   # sentinels
    R = int(n_rows.sum())
    flat = np.repeat(np.arange(C * P), n_rows)                 # [R] c*P+p
    chip_of_row = flat // P
    pix_of_row = flat % P
    starts = np.cumsum(n_rows) - n_rows
    within = np.arange(R) - np.repeat(starts, n_rows)
    seg_idx = np.where(nseg.reshape(-1)[flat] > 0, within, -1)
    real = seg_idx >= 0
    si = np.maximum(seg_idx, 0)

    meta = np.asarray(seg.seg_meta, np.float64)[chip_of_row, pix_of_row, si]
    rmse = np.asarray(seg.seg_rmse, np.float64)[chip_of_row, pix_of_row, si]
    mag = np.asarray(seg.seg_mag, np.float64)[chip_of_row, pix_of_row, si]
    coefs = np.asarray(seg.seg_coef, np.float64)[chip_of_row, pix_of_row, si]
    # Per-chip design anchors, broadcast per row: the convention change is
    # elementwise, so per-row anchors are bit-identical to the per-chip
    # scalar calls.
    anchors = np.array([float(packed.dates[c][0]) if int(packed.n_obs[c])
                        else 0.0 for c in range(C)])
    coefs7, intercept = harmonic.to_pyccd_convention(
        coefs, anchors[chip_of_row][:, None])

    coords_all = np.stack([packed.pixel_coords(c)
                           for c in range(C)])                 # [C,P,2]
    segment = {
        "cx": packed.cids[chip_of_row, 0].astype(np.int64),
        "cy": packed.cids[chip_of_row, 1].astype(np.int64),
        "px": coords_all[chip_of_row, pix_of_row, 0],
        "py": coords_all[chip_of_row, pix_of_row, 1],
        "sday": np.where(real, _iso_col(meta[:, 0]), "0001-01-01"),
        "eday": np.where(real, _iso_col(meta[:, 1]), "0001-01-01"),
        "bday": np.where(real, _iso_col(meta[:, 2]), "0001-01-01"),
        "chprob": np.where(real, meta[:, 3], np.nan),
        "curqa": _int_or_none(meta[:, 4], real),
        "rfrawp": np.full(R, None, object),
    }
    for b in range(params.NUM_BANDS):
        p = BAND_PREFIX[b]
        segment[f"{p}mag"] = np.where(real, mag[:, b], np.nan)
        segment[f"{p}rmse"] = np.where(real, rmse[:, b], np.nan)
        segment[f"{p}int"] = np.where(real, intercept[:, b], np.nan)
        col = np.empty(R, object)
        col[:] = list(coefs7[:, b])
        col[~real] = None
        segment[f"{p}coef"] = col

    # ---- split per chip (keyed writes preserve the resume invariant) ----
    rows_per_chip = n_rows.reshape(C, P).sum(1)
    bounds = np.concatenate([[0], np.cumsum(rows_per_chip)])
    mask_all = np.asarray(seg.mask, np.uint8)
    out = []
    for c in range(C):
        cx, cy = (int(v) for v in packed.cids[c])
        lo, hi = int(bounds[c]), int(bounds[c + 1])
        seg_c = {k: v[lo:hi] for k, v in segment.items()}
        T = int(packed.n_obs[c])
        mask_col = np.empty(P, object)
        mask_col[:] = list(mask_all[c, :, :T])
        pixel = {
            "cx": np.full(P, cx, np.int64), "cy": np.full(P, cy, np.int64),
            "px": coords_all[c, :, 0], "py": coords_all[c, :, 1],
            "mask": mask_col,
        }
        dates_col = np.empty(1, object)
        dates_col[0] = [dt.to_iso(int(o)) for o in packed.dates[c][:T]]
        chip_frame = {
            "cx": np.array([cx], np.int64), "cy": np.array([cy], np.int64),
            "dates": dates_col,
        }
        out.append(((cx, cy), {"chip": chip_frame, "pixel": pixel,
                               "segment": seg_c}))
    return out
