"""Harmonic regression primitives (NumPy spec).

These define the exact numerics both CCD implementations must follow.  The
JAX kernel re-implements the same operations with lax control flow; parity
tests compare against these float64 versions.

Design matrix convention (the framework spec — chosen for float32/TPU
conditioning, see kernel docs):

    X = [1, yr, cos(wt), sin(wt), cos(2wt), sin(2wt), cos(3wt), sin(3wt)]

where ``yr = (t - anchor) / 365.25`` (years since the fit window's first
observation) and the harmonic phase uses the absolute ordinal day *modulo
365.25* computed in float64 (mathematically identical to absolute t, but
exact in float32).  Output coefficients are converted to the pyccd
convention: slope per ordinal day, intercept at ordinal day 0
(ccdc/pyccd.py:132-145 stores coefficients and intercept separately).
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.ccd import params


def day_phase(t: np.ndarray) -> np.ndarray:
    """Ordinal days -> phase angle in [0, 2*pi), computed in float64."""
    return params.OMEGA * np.mod(np.asarray(t, dtype=np.float64), 365.25)


def design_matrix(t: np.ndarray, anchor: float, ncoef: int = params.MAX_COEFS) -> np.ndarray:
    """Build the [n, ncoef] harmonic design matrix."""
    t = np.asarray(t, dtype=np.float64)
    ph = day_phase(t)
    yr = (t - anchor) / 365.25
    cols = [np.ones_like(yr), yr,
            np.cos(ph), np.sin(ph),
            np.cos(2 * ph), np.sin(2 * ph),
            np.cos(3 * ph), np.sin(3 * ph)]
    return np.stack(cols[:ncoef], axis=1)


def lasso_cd(X: np.ndarray, y: np.ndarray,
             alpha: float = params.LASSO_ALPHA,
             iters: int = params.LASSO_ITERS) -> np.ndarray:
    """Lasso by cyclic coordinate descent with a fixed iteration count.

    Objective: 1/(2n) ||y - X b||^2 + alpha * sum_{j>=1} |b_j|  (intercept,
    column 0, unpenalized).  Operates on the Gram matrix so the TPU kernel
    can run the identical update from incrementally accumulated G = X'X/n
    and c = X'y/n.
    """
    n, p = X.shape
    G = X.T @ X / n
    c = X.T @ y / n
    return lasso_cd_gram(G, c, alpha=alpha, iters=iters)


def lasso_cd_gram(G: np.ndarray, c: np.ndarray,
                  alpha: float = params.LASSO_ALPHA,
                  iters: int = params.LASSO_ITERS) -> np.ndarray:
    """Coordinate descent on precomputed G = X'X/n, c = X'y/n.

    Update for coordinate j:  rho = c_j - sum_{k != j} G_jk b_k
    b_j = soft(rho, alpha_j) / G_jj   with alpha_0 = 0 (intercept).
    """
    p = G.shape[0]
    b = np.zeros(p, dtype=np.float64)
    diag = np.maximum(np.diag(G), 1e-12)
    for _ in range(iters):
        for j in range(p):
            rho = c[j] - G[j] @ b + diag[j] * b[j]
            if j == 0:
                b[j] = rho / diag[j]
            else:
                b[j] = np.sign(rho) * max(abs(rho) - alpha, 0.0) / diag[j]
    return b


def fit_bands(t: np.ndarray, Y: np.ndarray, ncoef: int, anchor: float,
              alpha: float = params.LASSO_ALPHA) -> tuple[np.ndarray, np.ndarray]:
    """Fit all bands at once.

    Args:
        t: [n] ordinal days of the fit window.
        Y: [nbands, n] observations.
        ncoef: number of design columns (4, 6 or 8).
        anchor: design anchor (ordinal day).  The spec anchors ALL fits of a
            pixel at the series' first observation (a global anchor), so the
            TPU kernel can precompute one design matrix per chip and the
            Lasso operates on identical Gram matrices in both
            implementations.

    Returns:
        (coefs [nbands, MAX_COEFS] zero-padded in the internal
        parametrization, rmse [nbands]).
    """
    X = design_matrix(t, anchor, ncoef)
    nb = Y.shape[0]
    coefs = np.zeros((nb, params.MAX_COEFS), dtype=np.float64)
    rmse = np.zeros(nb, dtype=np.float64)
    for b in range(nb):
        beta = lasso_cd(X, Y[b].astype(np.float64), alpha=alpha)
        coefs[b, :ncoef] = beta
        r = Y[b] - X @ beta
        rmse[b] = np.sqrt(np.mean(r * r))
    return coefs, rmse


def predict(t: np.ndarray, coefs: np.ndarray, anchor: float) -> np.ndarray:
    """Evaluate fitted models at times t.

    Args:
        t: [n] ordinal days.
        coefs: [nbands, MAX_COEFS] internal-parametrization coefficients.
        anchor: the fit window anchor the coefficients were fit with.

    Returns:
        [nbands, n] predictions.
    """
    X = design_matrix(t, anchor, params.MAX_COEFS)
    return coefs @ X.T


def to_pyccd_convention(coefs: np.ndarray, anchor: float) -> tuple[np.ndarray, np.ndarray]:
    """Convert internal coefficients to the pyccd output convention.

    Returns (coefficients [nbands, 7], intercept [nbands]) where
    coefficients[:, 0] is slope per ordinal day, columns 1..6 are the
    annual/semiannual/trimodal cos/sin pairs, and intercept is the value of
    the trend line at ordinal day 0 (absolute-t intercept).
    """
    coefs = np.asarray(coefs)
    slope_per_day = coefs[..., 1] / 365.25
    intercept = coefs[..., 0] - slope_per_day * anchor
    out = np.concatenate([slope_per_day[..., None], coefs[..., 2:]], axis=-1)
    return out, intercept


def irls_huber(X: np.ndarray, y: np.ndarray,
               iters: int = params.TMASK_IRLS_ITERS,
               k: float = params.HUBER_K) -> np.ndarray:
    """Robust linear fit via IRLS with Huber weights, fixed iterations.

    Used by the Tmask screen.  Scale is the MAD-based robust sigma,
    re-estimated each iteration.
    """
    n, p = X.shape
    beta = np.linalg.lstsq(X, y, rcond=None)[0]
    for _ in range(iters):
        r = y - X @ beta
        sigma = np.median(np.abs(r - np.median(r))) / 0.6745
        sigma = max(sigma, 1e-6)
        a = np.abs(r) / (k * sigma)
        w = np.where(a <= 1.0, 1.0, 1.0 / np.maximum(a, 1e-12))
        Xw = X * w[:, None]
        beta = np.linalg.lstsq(Xw.T @ X + 1e-9 * np.eye(p), Xw.T @ y, rcond=None)[0]
    return beta
