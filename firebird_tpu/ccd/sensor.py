"""Sensor specs: band layout + chip geometry the kernel is generic over.

The reference is hard-wired to Landsat ARD — 7 bands at 30 m, 100x100-pixel
chips (ccdc/timeseries.py:33-45, test/data/registry_response.json
``data_shape: [100, 100]``).  Here the spectral/spatial contract is a value
(:class:`Sensor`) threaded through the packer and the CCD kernel as a
static argument, so denser sensors compile to their own XLA program with
nothing Landsat-specific baked in.  BASELINE.json config #5 (Sentinel-2
10 m, 12-band stack, 10x pixel density) is the second instance.

The science parameters (params.py) stay shared: CCDC's thresholds are
defined per detection-band-count (chi2 dof = len(detection_bands)), which
the spec derives, not per sensor.
"""

from __future__ import annotations

import dataclasses
import functools


@dataclasses.dataclass(frozen=True)
class Sensor:
    """Immutable (hashable — usable as a jit static arg) sensor spec.

    Band indices index the spectra axis.  ``optical_bands`` are range-
    checked against params.OPTICAL_MIN/MAX, ``thermal_bands`` against
    THERMAL_MIN/MAX (empty for sensors with no thermal band).
    ``blue_band`` drives the insufficient-clear procedure's blue-median
    screen (params.INSUF_CLEAR_BLUE_DELTA).
    """

    name: str
    band_names: tuple[str, ...]
    detection_bands: tuple[int, ...]
    tmask_bands: tuple[int, ...]
    optical_bands: tuple[int, ...]
    thermal_bands: tuple[int, ...]
    blue_band: int
    chip_side: int
    pixel_size_m: int

    @property
    def n_bands(self) -> int:
        return len(self.band_names)

    @property
    def pixels(self) -> int:
        return self.chip_side * self.chip_side

    @property
    def band_names_plural(self) -> tuple[str, ...]:
        return tuple(f"{n}s" for n in self.band_names)


@functools.lru_cache(maxsize=None)
def chi2_thresholds(n_detection_bands: int) -> tuple[float, float]:
    """(change, outlier) score thresholds for a detection-band count —
    the chi2 inverse CDF the spec defines per dof (params.py)."""
    from scipy import stats

    from firebird_tpu.ccd import params

    return (float(stats.chi2.ppf(params.CHISQUARE_PROB, n_detection_bands)),
            float(stats.chi2.ppf(params.OUTLIER_PROB, n_detection_bands)))


# Landsat ARD: the reference's contract (band order ccdc/timeseries.py:33-45).
LANDSAT_ARD = Sensor(
    name="landsat-ard",
    band_names=("blue", "green", "red", "nir", "swir1", "swir2", "thermal"),
    detection_bands=(1, 2, 3, 4, 5),      # green, red, nir, swir1, swir2
    tmask_bands=(1, 4),                   # green, swir1
    optical_bands=(0, 1, 2, 3, 4, 5),
    thermal_bands=(6,),
    blue_band=0,
    chip_side=100,
    pixel_size_m=30,
)

# Sentinel-2 L2A surface reflectance, 12-band stack resampled to 10 m: a
# 3 km chip is 300x300 px — 9x the pixel density of Landsat ARD
# (BASELINE.json config #5).  CCDC detection/Tmask band roles map by
# wavelength: green, red, nir, swir1, swir2; no thermal instrument.
SENTINEL2 = Sensor(
    name="sentinel2",
    band_names=("coastal", "blue", "green", "red", "re1", "re2", "re3",
                "nir", "nir08", "wv", "swir1", "swir2"),
    detection_bands=(2, 3, 7, 10, 11),
    tmask_bands=(2, 10),
    optical_bands=tuple(range(12)),
    thermal_bands=(),
    blue_band=1,
    chip_side=300,
    pixel_size_m=10,
)

# Landsat ARD band semantics on a 10x10 chip: the fleet-scale test
# geometry.  A full-CONUS plan is 726 tiles; at 100 px/chip the elastic
# soak (tools/elastic_soak.py) drains all 726 through real detection in
# smoke time while every queue/fencing/store code path stays the
# production one.  Only the synthetic source honors it
# (FIREBIRD_SYNTH_SENSOR) — real archives are fixed-geometry.
LANDSAT_ARD_TINY = dataclasses.replace(
    LANDSAT_ARD, name="landsat-ard-tiny", chip_side=10)

SENSORS = {s.name: s for s in (LANDSAT_ARD, SENTINEL2, LANDSAT_ARD_TINY)}
