"""Streaming incremental CCDC: append observations, re-test change only.

The batch kernel (ccd/kernel.py) fits the full archive.  Operationally,
LCMAP appends a handful of new Landsat acquisitions per pixel per month;
refitting 35 years for each is wasteful.  This module implements the
lambda-architecture split the reference never had (its only mode is full
reruns of `ccd.detect`, ccdc/pyccd.py:171-183):

- **Hot path (here)**: keep each pixel's *open tail segment* — fitted
  harmonic model, RMSE, variogram, trailing exceed count — as a compact
  :class:`StreamState`, and for every new observation run exactly the batch
  kernel's tail rules: QA triage, score against max(rmse, variogram) over
  the detection bands, absorb / drop-outlier / count-exceeding, confirm a
  break after PEEK_SIZE consecutive exceeding observations.  One jitted
  [P]-wide step, microseconds per chip.
- **Cold path (batch kernel)**: periodic full reruns pick up model refits
  (which need the historical observations) and re-initialize pixels whose
  tail broke.  ``needs_batch`` flags exactly those pixels.

A streamed observation is always at the series end, so the tail rules
apply: an exceeding observation is counted, never absorbed.  The batch
kernel, seeing later clean data, retroactively *absorbs* an isolated
exceeding observation under its normal-region rules — a conservative
divergence (streaming under-counts nobs by the isolated exceeds) that the
next cold-path rerun repairs.

State is initialized from a batch result via :meth:`StreamState.from_chip`
(the kernel exports the variogram for this) and round-trips through the
keyed store as plain arrays.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from firebird_tpu.ccd import harmonic, params
from firebird_tpu.ccd.kernel import ChipSegments
from firebird_tpu.ccd.sensor import LANDSAT_ARD, chi2_thresholds


@dataclasses.dataclass
class StreamState:
    """Per-pixel open-segment state (leading axis [P] or [C, P]).

    A pixel is ``active`` when its last batch segment ran to the series end
    under the standard procedure (CURVE_QA_END set) — only those have a
    model whose change probability can be extended incrementally.
    """

    coefs: jnp.ndarray      # [.., P, B, 8] internal-convention coefficients
    rmse: jnp.ndarray       # [.., P, B]
    vario: jnp.ndarray      # [.., P, B]
    nobs: jnp.ndarray       # [.., P] int32 obs in the open segment
    n_exceed: jnp.ndarray   # [.., P] int32 trailing consecutive exceeding
    end_day: jnp.ndarray    # [.., P] float32 ordinal of last absorbed obs
    exceed_day0: jnp.ndarray  # [.., P] float32 first day of the current
    #   exceed run (0 when none, or unknown for runs begun before seeding —
    #   the batch result stores only the count)
    break_day: jnp.ndarray  # [.., P] float32 ordinal of confirmed break (0 = none)
    active: jnp.ndarray     # [.., P] bool

    @classmethod
    def from_chip(cls, seg: ChipSegments) -> "StreamState":
        """Seed streaming state from one chip's batch result ([P, ...])."""
        if seg.vario is None:
            raise ValueError("batch result lacks vario; rerun the kernel")
        P = seg.n_segments.shape[0]
        # clip to buffer capacity: guards raw check_capacity=False results
        last = jnp.minimum(jnp.maximum(seg.n_segments - 1, 0),
                           seg.seg_meta.shape[-2] - 1)          # [P]
        meta = jnp.take_along_axis(
            seg.seg_meta, last[:, None, None].repeat(6, 2), axis=1)[:, 0]
        curqa = meta[:, 4].astype(jnp.int32)
        active = ((seg.procedure == 0) & (seg.n_segments >= 1)
                  & (curqa & params.CURVE_QA_END > 0))
        gather = lambda a: jnp.take_along_axis(
            a, last.reshape((P,) + (1,) * (a.ndim - 1)), axis=1)[:, 0]
        return cls(
            coefs=gather(seg.seg_coef), rmse=gather(seg.seg_rmse),
            # copy: decouples the stream state from the caller's batch
            # result (step() no longer donates — see the jit note below —
            # but an alias into seg.vario is still a liability if
            # donation ever returns).
            vario=jnp.array(seg.vario, copy=True),
            nobs=meta[:, 5].astype(jnp.int32),
            # chprob on an END segment is n_exceed / PEEK_SIZE.
            n_exceed=jnp.round(meta[:, 3] * params.PEEK_SIZE).astype(jnp.int32),
            end_day=meta[:, 1],
            exceed_day0=jnp.zeros(P, meta.dtype),
            break_day=jnp.zeros(P, meta.dtype),
            active=active)

    @property
    def needs_batch(self) -> jnp.ndarray:
        """Pixels whose tail broke — only a full batch rerun re-initializes
        a fresh segment after the break."""
        return self.break_day > 0


jax.tree_util.register_pytree_node(
    StreamState,
    lambda s: ((s.coefs, s.rmse, s.vario, s.nobs, s.n_exceed, s.end_day,
                s.exceed_day0, s.break_day, s.active), None),
    lambda _, c: StreamState(*c),
)


def design_row(t_new: float, anchor: float, dtype=np.float32) -> np.ndarray:
    """Host-side [8] design row for the new acquisition (float64 phases,
    same convention as the batch designs — kernel.build_designs)."""
    return harmonic.design_matrix(
        np.array([t_new]), anchor, params.MAX_COEFS)[0].astype(dtype)


# NO buffer donation here, deliberately: a donated multi-leaf pytree arg
# round-tripped through the persistent compilation cache loses its
# input-output aliasing on deserialization in this jaxlib — the SECOND
# process to run a cached step computed garbage break days (year 25270)
# and corrupted the heap (glibc "corrupted double-linked list", SIGSEGV/
# SIGABRT), found by tools/alert_soak.py's kill/resume drill.  The copy
# this costs is ~5 MB per [P]-wide step on the host-cheap update path —
# nothing next to a wrong break day published as an alert.
@functools.partial(jax.jit, static_argnames=("sensor",))
def step(state: StreamState, x_row, y_new, qa_new, t_new, *,
         sensor=LANDSAT_ARD) -> StreamState:
    """Advance every pixel's open segment by one acquisition.

    Args:
        state: StreamState [P, ...].
        x_row: [8] design row for t_new (design_row()).
        y_new: [P, B] new spectral values (same band order as the kernel).
        qa_new: [P] int32 bit-packed QA.
        t_new: scalar ordinal day (float).
        sensor: static band layout — detection/range roles and the chi2
            threshold's dof, as in the batch kernel.

    Returns the updated StreamState.  Tail rules mirror the batch kernel's
    monitor fast-forward (kernel.py): clear+in-range obs only; score =
    sum over detection bands of (residual / max(rmse, vario))^2;
    score > CHANGE_THRESHOLD extends the exceed run (PEEK_SIZE consecutive
    confirm a break dated at the run's first exceeding day); anything else
    absorbs and resets the run.
    """
    _DET = list(sensor.detection_bands)
    CHANGE_THRESHOLD, _ = chi2_thresholds(len(_DET))
    fd = state.rmse.dtype
    y = y_new.astype(fd)
    t = jnp.asarray(t_new, fd)
    fill = (qa_new >> params.QA_FILL_BIT) & 1 == 1
    clear = (((qa_new >> params.QA_CLEAR_BIT) & 1 == 1)
             | ((qa_new >> params.QA_WATER_BIT) & 1 == 1)) & ~fill
    opt = list(sensor.optical_bands)
    rng_ok = jnp.all((y[:, opt] > params.OPTICAL_MIN)
                     & (y[:, opt] < params.OPTICAL_MAX), axis=1)
    if sensor.thermal_bands:
        th = list(sensor.thermal_bands)
        rng_ok &= jnp.all((y[:, th] > params.THERMAL_MIN)
                          & (y[:, th] < params.THERMAL_MAX), axis=1)
    usable = clear & rng_ok & state.active & ~state.needs_batch

    pred = jnp.einsum("pbc,c->pb", state.coefs, x_row.astype(fd))
    resid = y - pred
    dden = jnp.maximum(state.rmse, state.vario)[:, _DET]
    s = jnp.sum((resid[:, _DET] / dden) ** 2, axis=1)

    # Batch tail semantics: any score above CHANGE_THRESHOLD (including the
    # far outlier tail) counts toward the exceed run; everything else is
    # absorbed and resets the run.
    exceed = usable & (s > CHANGE_THRESHOLD)
    absorb = usable & ~exceed

    n_exceed = jnp.where(exceed, state.n_exceed + 1,
                         jnp.where(absorb, 0, state.n_exceed))
    run_starts = exceed & (state.n_exceed == 0)
    exceed_day0 = jnp.where(run_starts, t,
                            jnp.where(absorb, jnp.zeros_like(t),
                                      state.exceed_day0))
    broke = usable & (n_exceed >= params.PEEK_SIZE) & ~state.needs_batch
    # Runs already in progress at seed time have no recorded start day
    # (exceed_day0 == 0); the confirmation day is the honest fallback.
    bday = jnp.where(exceed_day0 > 0, exceed_day0, t)
    return StreamState(
        coefs=state.coefs, rmse=state.rmse, vario=state.vario,
        nobs=state.nobs + absorb.astype(jnp.int32),
        n_exceed=n_exceed,
        end_day=jnp.where(absorb, t, state.end_day),
        exceed_day0=exceed_day0,
        break_day=jnp.where(broke, bday, state.break_day),
        active=state.active)
