"""CCDC reference implementation (NumPy float64 oracle).

Defines the algorithm the TPU kernel must match.  Per-pixel, readable,
sequential — the shape of the original science code — while every numeric
step (design matrix, Lasso coordinate descent, IRLS Tmask) is specified so
a fixed-shape JAX translation is possible.

Interface mirrors the external pyccd package the reference drives
(``ccd.detect(**timeseries_data)``, ccdc/pyccd.py:161-168): keyword arrays
``dates, blues, greens, reds, nirs, swir1s, swir2s, thermals, qas`` and a
result dict ``{change_models, processing_mask, algorithm, procedure}`` whose
change-model records carry exactly the fields consumed by the format layer
(ccdc/pyccd.py:106-148, golden-tested by test/test_pyccd.py:37-126).

Algorithm: Zhu & Woodcock 2014 CCDC with the lcmap-pyccd 2018.03.12
parameterization (see params.py):

1. QA triage -> standard / permanent-snow / insufficient-clear procedure.
2. Standard: clear+water obs, de-duplicated, range-filtered; per-band
   variogram; then a sequential pass over time:
   a. *Initialize*: find a window with >= MEOW_SIZE obs spanning >=
      INIT_DAYS; Tmask-screen it (robust IRLS harmonic on green/swir1);
      fit 4-coef Lasso models; stable iff |slope*span|, |first resid| and
      |last resid| all <= STABILITY_FACTOR * max(rmse, variogram) for every
      detection band, else slide the window start forward.
   b. *Extend*: score each next observation against the model
      (sum over detection bands of (resid / max(rmse, vario))^2).  All
      PEEK_SIZE consecutive above CHANGE_THRESHOLD -> change: close the
      segment (break day = first exceeding obs, probability 1, magnitude =
      per-band median residual of the peek window) and re-initialize there.
      A single spike above OUTLIER_THRESHOLD -> drop the obs.  Otherwise
      absorb it, refitting whenever the segment grew REFIT_FACTOR x since
      the last fit (coef count 4/6/8 by obs count).
   c. *Tail*: fewer than PEEK_SIZE obs left -> close the final segment with
      change probability = exceeding/PEEK_SIZE.
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.ccd import harmonic, params
from firebird_tpu.ccd.sensor import LANDSAT_ARD, chi2_thresholds

ALGORITHM = "firebird-ccd:v1"


# ---------------------------------------------------------------------------
# QA predicates
# ---------------------------------------------------------------------------

def _bit(qa: np.ndarray, bit: int) -> np.ndarray:
    return (qa.astype(np.int64) >> bit) & 1 == 1


def qa_fill(qa):
    return _bit(qa, params.QA_FILL_BIT)


def qa_clear(qa):
    return _bit(qa, params.QA_CLEAR_BIT)


def qa_water(qa):
    return _bit(qa, params.QA_WATER_BIT)


def qa_snow(qa):
    return _bit(qa, params.QA_SNOW_BIT)


def in_range(Y: np.ndarray, sensor=LANDSAT_ARD) -> np.ndarray:
    """[B, T] spectra -> [T] all-bands-in-valid-range mask."""
    opt = Y[list(sensor.optical_bands)]
    ok = np.all((opt > params.OPTICAL_MIN) & (opt < params.OPTICAL_MAX),
                axis=0)
    if sensor.thermal_bands:
        th = Y[list(sensor.thermal_bands)]
        ok &= np.all((th > params.THERMAL_MIN) & (th < params.THERMAL_MAX),
                     axis=0)
    return ok


def dedup_first(t: np.ndarray, candidate: np.ndarray) -> np.ndarray:
    """Among candidate obs (sorted by t), keep only the first per date."""
    keep = candidate.copy()
    seen: set[int] = set()
    for k in np.flatnonzero(candidate):
        d = int(t[k])
        if d in seen:
            keep[k] = False
        else:
            seen.add(d)
    return keep


# ---------------------------------------------------------------------------
# Fitting helpers
# ---------------------------------------------------------------------------

def num_coefs(n_obs: int) -> int:
    """4/6/8 coefficients by observation density (pyccd obs factor 3)."""
    if n_obs >= params.MAX_COEFS * params.NUM_OBS_FACTOR:
        return params.MAX_COEFS
    if n_obs >= params.MID_COEFS * params.NUM_OBS_FACTOR:
        return params.MID_COEFS
    return params.MIN_COEFS


def variogram(t: np.ndarray, Y: np.ndarray,
              adjusted: bool = False) -> np.ndarray:
    """Per-band median absolute successive difference, floored at 1e-6.

    ``adjusted=True`` applies the lcmap-pyccd ``adjusted_variogram`` rule
    (reconstructed from the public lcmap-pyccd package the reference pins
    at setup.py:32; the pinned source itself is unreachable offline —
    docs/DIVERGENCE.md #1): restrict the successive-difference set to
    pairs more than VARIOGRAM_GAP_DAYS apart, so dense multi-sensor
    archives with near-coincident acquisitions (the 'ncompare' case: L7+L8
    pairs days apart whose tiny |diffs| crater the madogram and inflate
    false breaks) measure seasonal-scale variation instead.  When no pair
    clears the gap, the plain madogram is used.  The pair-selection is
    date-driven and shared by all bands, as in pyccd.
    """
    if t.shape[0] < 2:
        return np.ones(Y.shape[0], dtype=np.float64)
    d = np.abs(np.diff(Y.astype(np.float64), axis=1))
    if adjusted:
        sel = np.diff(t.astype(np.float64)) > params.VARIOGRAM_GAP_DAYS
        if np.any(sel):
            d = d[:, sel]
    v = np.median(d, axis=1)
    return np.maximum(v, 1e-6)


class _Model:
    """A fitted multi-band harmonic model over a window of observations.

    ``anchor`` is the global series anchor (first observation of the whole
    series), shared by every fit of a pixel — see harmonic.fit_bands.
    """

    def __init__(self, t: np.ndarray, Y: np.ndarray, ncoef: int, anchor: float):
        self.anchor = anchor
        self.ncoef = ncoef
        self.coefs, self.rmse = harmonic.fit_bands(t, Y, ncoef, anchor)

    def resid(self, t: np.ndarray, Y: np.ndarray) -> np.ndarray:
        """[7, n] residuals at times t."""
        return Y.astype(np.float64) - harmonic.predict(t, self.coefs, self.anchor)


def change_score(model: _Model, vario: np.ndarray, t: np.ndarray,
                 Y: np.ndarray, sensor=LANDSAT_ARD) -> np.ndarray:
    """[n] chi-square change scores for obs (t, Y) against the model."""
    r = model.resid(t, Y)
    s = np.zeros(t.shape[0], dtype=np.float64)
    for b in sensor.detection_bands:
        denom = max(model.rmse[b], vario[b])
        s += (r[b] / denom) ** 2
    return s


def tmask_outliers(t: np.ndarray, Y: np.ndarray, vario: np.ndarray,
                   sensor=LANDSAT_ARD) -> np.ndarray:
    """[n] True where an obs fails the robust Tmask screen on the sensor's
    Tmask bands (green/swir1 for Landsat ARD)."""
    # Tmask design has no trend column: build [1, yr, cos, sin, cos2, sin2]
    # then drop the yr column (index 1) -> TMASK_COEFS columns.  With the
    # trend gone the design is anchor-independent.
    X = harmonic.design_matrix(t, 0.0, params.TMASK_COEFS + 1)
    X = np.concatenate([X[:, :1], X[:, 2:]], axis=1)
    bad = np.zeros(t.shape[0], dtype=bool)
    for b in sensor.tmask_bands:
        y = Y[b].astype(np.float64)
        beta = harmonic.irls_huber(X, y)
        r = np.abs(y - X @ beta)
        bad |= r > params.TMASK_CONST * vario[b]
    return bad


# ---------------------------------------------------------------------------
# Segment record assembly
# ---------------------------------------------------------------------------

def _segment_record(model: _Model, *,
                    start_day: int, end_day: int, break_day: int,
                    n_obs: int, change_prob: float, curve_qa: int,
                    magnitudes: np.ndarray, sensor=LANDSAT_ARD) -> dict:
    coefs7, intercept = harmonic.to_pyccd_convention(model.coefs, model.anchor)
    rec = {
        "start_day": int(start_day),
        "end_day": int(end_day),
        "break_day": int(break_day),
        "observation_count": int(n_obs),
        "change_probability": float(change_prob),
        "curve_qa": int(curve_qa),
    }
    for b, name in enumerate(sensor.band_names):
        rec[name] = {
            "magnitude": float(magnitudes[b]),
            "rmse": float(model.rmse[b]),
            "coefficients": tuple(float(x) for x in coefs7[b]),
            "intercept": float(intercept[b]),
        }
    return rec


# ---------------------------------------------------------------------------
# The standard procedure state machine
# ---------------------------------------------------------------------------

def _standard_procedure(t: np.ndarray, Y: np.ndarray, usable: np.ndarray,
                        sensor=LANDSAT_ARD, adjusted_variogram=None):
    """Run CCDC over sorted obs.

    Args:
        t: [T] sorted ordinal days (all obs).
        Y: [B, T] spectra.
        usable: [T] candidate mask (clear, in-range, deduped).
        sensor: band layout (detection/Tmask roles, thresholds per dof).

    Returns:
        (change_models list, processing_mask [T] — usable obs that survived
        Tmask / spike removal).
    """
    CHANGE_THRESHOLD, OUTLIER_THRESHOLD = chi2_thresholds(
        len(sensor.detection_bands))
    if adjusted_variogram is None:
        adjusted_variogram = params.variogram_adjusted_default()
    alive = usable.copy()
    idx_all = np.flatnonzero(usable)
    vario = variogram(t[idx_all], Y[:, idx_all],
                      adjusted=adjusted_variogram)
    # Global design anchor: the series' first observation — shared by all
    # pixels of a chip, so the TPU kernel can precompute one design matrix.
    anchor = float(t[0]) if t.shape[0] else 0.0

    segments: list[dict] = []

    def alive_from(k0: int) -> np.ndarray:
        return np.flatnonzero(alive[k0:]) + k0

    # Cursor i indexes into t (absolute position of the prospective segment
    # start).  Runs until no initialization window fits.
    n_total = t.shape[0]
    i = idx_all[0] if idx_all.size else n_total
    first_segment = True

    while True:
        # ------------------------------------------------------------- init
        w = alive_from(i)
        if w.size < params.MEOW_SIZE:
            break
        # Smallest j with MEOW_SIZE obs and INIT_DAYS span.
        jj = params.MEOW_SIZE - 1
        while jj < w.size and t[w[jj]] - t[w[0]] < params.INIT_DAYS:
            jj += 1
        if jj >= w.size:
            break
        window = w[: jj + 1]

        # Tmask screen (permanent removals).
        bad = tmask_outliers(t[window], Y[:, window], vario, sensor)
        if bad.any():
            alive[window[bad]] = False
            continue  # re-derive the window from the same cursor

        model = _Model(t[window], Y[:, window], params.MIN_COEFS, anchor)
        r = model.resid(t[window], Y[:, window])
        span = float(t[window[-1]] - t[window[0]])
        stable = True
        for b in sensor.detection_bands:
            denom = params.STABILITY_FACTOR * max(model.rmse[b], vario[b])
            slope_per_day = model.coefs[b, 1] / 365.25
            if (abs(slope_per_day * span) > denom
                    or abs(r[b, 0]) > denom
                    or abs(r[b, -1]) > denom):
                stable = False
                break
        if not stable:
            nxt = alive_from(window[0] + 1)
            if nxt.size == 0:
                break
            i = nxt[0]
            continue

        # -------------------------------------------------------- extension
        included = list(window)
        n_last_fit = len(included)
        model = _Model(t[included], Y[:, included], num_coefs(len(included)),
                       anchor)
        cursor = window[-1] + 1
        closed = False

        while not closed:
            peek = alive_from(cursor)[: params.PEEK_SIZE]
            if peek.size < params.PEEK_SIZE:
                # ------------------------------------------------------ tail
                # Absorb below-threshold tail obs into the final segment;
                # exceeding ones feed the residual change probability.
                n_exceed = 0
                if peek.size:
                    scores = change_score(model, vario, t[peek], Y[:, peek],
                                          sensor)
                    n_exceed = int(np.sum(scores > CHANGE_THRESHOLD))
                    for p, s in zip(peek, scores):
                        if s <= CHANGE_THRESHOLD:
                            included.append(p)
                        else:
                            alive[p] = False
                qa = params.CURVE_QA_END | (params.CURVE_QA_START if first_segment else 0)
                segments.append(_segment_record(
                    model,
                    start_day=t[included[0]], end_day=t[included[-1]],
                    break_day=t[included[-1]], n_obs=len(included),
                    change_prob=n_exceed / params.PEEK_SIZE, curve_qa=qa,
                    magnitudes=np.zeros(sensor.n_bands), sensor=sensor))
                return segments, alive

            scores = change_score(model, vario, t[peek], Y[:, peek], sensor)
            if np.all(scores > CHANGE_THRESHOLD):
                # ---------------------------------------------------- break
                resid_peek = model.resid(t[peek], Y[:, peek])
                mags = np.median(resid_peek, axis=1)
                qa = params.CURVE_QA_START if first_segment else params.CURVE_QA_INSIDE
                segments.append(_segment_record(
                    model,
                    start_day=t[included[0]], end_day=t[included[-1]],
                    break_day=t[peek[0]], n_obs=len(included),
                    change_prob=1.0, curve_qa=qa, magnitudes=mags,
                    sensor=sensor))
                first_segment = False
                i = peek[0]
                closed = True
            elif scores[0] > OUTLIER_THRESHOLD:
                alive[peek[0]] = False
                cursor = peek[0] + 1
            else:
                included.append(peek[0])
                if len(included) >= params.REFIT_FACTOR * n_last_fit:
                    model = _Model(t[included], Y[:, included],
                                   num_coefs(len(included)), anchor)
                    n_last_fit = len(included)
                cursor = peek[0] + 1

    return segments, alive


# ---------------------------------------------------------------------------
# Alternate procedures
# ---------------------------------------------------------------------------

def _single_model_procedure(t, Y, usable, curve_qa, sensor=LANDSAT_ARD):
    """Permanent-snow / insufficient-clear: one unbroken model over all
    usable obs (no change monitoring)."""
    idx = np.flatnonzero(usable)
    if idx.size < params.MEOW_SIZE:
        return [], np.zeros_like(usable)
    tw, Yw = t[idx], Y[:, idx]
    anchor = float(t[0])
    model = _Model(tw, Yw, num_coefs(idx.size), anchor)
    rec = _segment_record(
        model,
        start_day=tw[0], end_day=tw[-1], break_day=tw[-1],
        n_obs=idx.size, change_prob=0.0, curve_qa=curve_qa,
        magnitudes=np.zeros(sensor.n_bands), sensor=sensor)
    return [rec], usable.copy()


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def detect(dates, blues, greens, reds, nirs, swir1s, swir2s, thermals, qas,
           adjusted_variogram=None, **ignored) -> dict:
    """Run CCDC on one pixel's time series.

    Same keyword contract as pyccd's ccd.detect (driven at
    ccdc/pyccd.py:161-168).  Input arrays may be in any date order (the
    reference data plane delivers them newest-first); the processing mask in
    the result aligns with the *input* order, as the reference persists it
    next to the input dates (ccdc/pixel.py:14-21).

    ``adjusted_variogram`` switches the change/Tmask denominator floor to
    the reconstructed pyccd adjusted-variogram rule (docs/DIVERGENCE.md #1);
    ``None`` (the default) follows FIREBIRD_VARIOGRAM exactly as the kernel
    does (params.variogram_adjusted_default), so oracle and kernel can
    never disagree on the mode by default.
    """
    Y_in = np.stack([np.asarray(b, dtype=np.float64)
                     for b in (blues, greens, reds, nirs, swir1s, swir2s,
                               thermals)])
    return detect_sensor(dates, Y_in, qas, LANDSAT_ARD,
                         adjusted_variogram=adjusted_variogram)


def detect_sensor(dates, spectra, qas, sensor, adjusted_variogram=None) -> dict:
    """Sensor-generic oracle: ``spectra`` is [B, T] in the sensor's band
    order.  Same algorithm and result contract as :func:`detect`; the
    sensor supplies band roles and the chi2 thresholds' degrees of
    freedom, exactly as the kernel's static ``sensor`` argument does
    (kernel._detect_core)."""
    t_in = np.asarray(dates, dtype=np.int64)
    Y_in = np.asarray(spectra, dtype=np.float64)
    qa_in = np.asarray(qas)

    order = np.argsort(t_in, kind="stable")
    t, Y, qa = t_in[order], Y_in[:, order], qa_in[order]

    fill = qa_fill(qa)
    clear = (qa_clear(qa) | qa_water(qa)) & ~fill
    snow = qa_snow(qa) & ~fill

    n_nonfill = int(np.sum(~fill))
    n_clear = int(np.sum(clear))
    n_snow = int(np.sum(snow))

    if n_nonfill == 0:
        return {"change_models": [],
                "processing_mask": [0] * t_in.shape[0],
                "algorithm": ALGORITHM,
                "procedure": "no-data"}

    clear_pct = n_clear / n_nonfill
    snow_pct = n_snow / (n_clear + n_snow) if (n_clear + n_snow) else 0.0

    rng_ok = in_range(Y, sensor)
    if clear_pct >= params.CLEAR_PCT_THRESHOLD:
        usable = dedup_first(t, clear & rng_ok)
        models, mask = _standard_procedure(
            t, Y, usable, sensor, adjusted_variogram=adjusted_variogram)
        procedure = "standard"
    elif snow_pct > params.SNOW_PCT_THRESHOLD:
        usable = dedup_first(t, (clear | snow) & rng_ok)
        models, mask = _single_model_procedure(t, Y, usable,
                                               params.CURVE_QA_PERSIST_SNOW,
                                               sensor)
        procedure = "permanent-snow"
    else:
        cand = ~fill & rng_ok
        blue = Y[sensor.blue_band]
        if cand.any():
            blue_med = float(np.median(blue[cand]))
            cand = cand & (blue < blue_med + params.INSUF_CLEAR_BLUE_DELTA)
        usable = dedup_first(t, cand)
        models, mask = _single_model_procedure(t, Y, usable,
                                               params.CURVE_QA_INSUF_CLEAR,
                                               sensor)
        procedure = "insufficient-clear"

    # Map the (sorted-order) mask back to input order.
    mask_input = np.zeros(t_in.shape[0], dtype=np.int8)
    mask_input[order] = mask.astype(np.int8)

    return {"change_models": models,
            "processing_mask": mask_input.tolist(),
            "algorithm": ALGORITHM,
            "procedure": procedure}
