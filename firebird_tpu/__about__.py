__version__ = "0.2.0"
