"""Classification orchestration: train on the 3x3 neighborhood, classify
the tile, persist predictions and the model.

Replaces ccdc/core.py:156-251 **including the predict/persist path the
reference left commented out** (core.py:190-240) and the empty model
read/write stubs (ccdc/randomforest.py:17-22):

- training mirrors randomforest.train (randomforest.py:42-87): aux rows
  with trends[0] not in (0, 9), segments from the store windowed
  'sday >= msday AND eday <= meday', features joined per pixel;
- classification scores every real segment of the tile (the commented
  filter 'sday >= 0 AND eday >= 0'), joins rfrawp back into the segment
  rows by full key (ccdc/segment.py:103-116), and upserts them;
- the trained model is serialized into the tile table
  (tx, ty, name) -> model, updated (ccdc/tile.py:28-43).

Segments are read from the store, so change detection must have run for
the same keyspace first — the reference has the same dependency through
pyccd.read (randomforest.py:69).
"""

from __future__ import annotations

import datetime

import numpy as np

from firebird_tpu import grid
from firebird_tpu.config import Config
from firebird_tpu.obs import Counters, logger
from firebird_tpu.rf import features, forest
from firebird_tpu.store import AsyncWriter
from firebird_tpu.utils.fn import take

MODEL_NAME = "random-forest"


def _chip_segments(store, cx: int, cy: int) -> dict | None:
    seg = store.read("segment", where={"cx": int(cx), "cy": int(cy)})
    return seg if seg["sday"] else None


def training_data(cids, *, msday: int, meday: int, acquired: str,
                  aux_source, store, log=None):
    """Assemble (X [N, 33], y [N]) over a set of chip ids
    (ref randomforest.train, ccdc/randomforest.py:42-87)."""
    xs, ys = [], []
    # Distinct detected chips ∩ requested chips (ccdc/randomforest.py:67's
    # select(cx,cy).distinct()): skips the store scan for undetected chips.
    have = store.chip_ids("segment")
    for cx, cy in cids:
        if (int(cx), int(cy)) not in have:
            continue
        seg = _chip_segments(store, cx, cy)
        if seg is None:
            continue
        try:
            aux = aux_source.aux(cx, cy, acquired)
        except LookupError:
            continue
        mask = (features.real_rows(seg)
                & features.segment_window(seg, msday, meday))
        if not mask.any():
            continue
        X, meta = features.assemble(seg, aux, cx, cy, row_mask=mask)
        label = np.asarray(meta["label"])
        keep = ~np.isin(label, features.TRENDS_EXCLUDE)   # randomforest.py:63
        keep &= np.isfinite(X).all(axis=1)
        if keep.any():
            xs.append(X[keep])
            ys.append(label[keep])
    if not xs:
        return None, None
    X = np.concatenate(xs)
    y = np.concatenate(ys)
    if log:
        log.debug("feature row count:%d  feature columns:%d",
                  X.shape[0], X.shape[1])
    return X, y


def train_tile(x, y, *, msday: int, meday: int, acquired: str, aux_source,
               store, number: int | None = None, log=None,
               **train_kw) -> forest.RandomForest | None:
    """Train on the 3x3 tile neighborhood around (x, y); None when no
    features exist (ref core.training, core.py:127-153)."""
    log = log or logger("random-forest-training")
    cids = grid.training(x, y)
    if number is not None:
        cids = list(take(number, cids))
    X, yv = training_data(cids, msday=msday, meday=meday, acquired=acquired,
                          aux_source=aux_source, store=store, log=log)
    if X is None:
        log.info("No features found to train model")   # randomforest.py:76
        return None
    log.info("training random forest on %d rows", X.shape[0])
    return forest.train(X, yv, **train_kw)


def save_model(store, tx: int, ty: int, model: forest.RandomForest,
               name: str = MODEL_NAME) -> None:
    """Persist a model into the tile table (ccdc/tile.py:28-43)."""
    store.write("tile", {
        "tx": [int(tx)], "ty": [int(ty)], "name": [name],
        "model": [model.dumps()],
        "updated": [datetime.datetime.now(datetime.timezone.utc).isoformat()],
    })


def load_model(store, tx: int, ty: int,
               name: str = MODEL_NAME) -> forest.RandomForest | None:
    """Read a model back from the tile table (completes the reference's
    empty randomforest.read stub, ccdc/randomforest.py:21-22)."""
    rows = store.read("tile", where={"tx": int(tx), "ty": int(ty),
                                     "name": name})
    return forest.RandomForest.loads(rows["model"][0]) if rows["model"] else None


def classify_chip(model, seg: dict, aux: dict, cx: int, cy: int) -> dict | None:
    """Score one chip's real segments; returns the updated segment frame
    with rfrawp filled (ref randomforest.classify + segment.join,
    randomforest.py:90-103, segment.py:103-116)."""
    mask = features.real_rows(seg)
    if not mask.any():
        return None
    X, _ = features.assemble(seg, aux, cx, cy, row_mask=mask)
    raw = model.raw_predict(X)
    rfrawp = list(seg["rfrawp"])
    for k, i in enumerate(np.flatnonzero(mask)):
        rfrawp[i] = [float(v) for v in raw[k]]   # dedensify, randomforest.py:106-123
    out = dict(seg)
    out["rfrawp"] = rfrawp
    return out


def classify_tile(x, y, *, msday: int, meday: int, acquired: str,
                  cfg: Config | None = None, source=None, aux_source=None,
                  store=None, number: int | None = None, writer=None,
                  **train_kw):
    """Full classification driver (core.py:156-251, completed).

    Trains on the 3x3 neighborhood, persists the model under the tile key,
    scores every real segment of the center tile and upserts rfrawp.
    Returns the trained model, or None when no training features exist.

    ``writer`` lets a caller supply its own egress (a fleet classify job
    passes a retry-wrapped AsyncWriter over a fenced store, so a zombie
    worker's predictions reject like any other stale-fence write); the
    default builds a plain AsyncWriter over ``store`` and closes it.
    """
    name = "random-forest-classification"
    log = logger(name)
    counters = Counters()
    cfg = cfg or Config.from_env()

    log.info("beginning %s... x:%s y:%s acquired:%s", name, x, y, acquired)
    model = train_tile(x, y, msday=msday, meday=meday, acquired=acquired,
                       aux_source=aux_source, store=store, number=number,
                       **train_kw)
    if model is None:
        return None

    t = grid.tile(x, y)
    save_model(store, t["x"], t["y"], model)

    cids = grid.classification(x, y)
    if number is not None:
        cids = list(take(number, cids))
    own_writer = writer is None
    writer = writer if writer is not None else AsyncWriter(store)
    have = store.chip_ids("segment")
    try:
        for cx, cy in cids:
            if (int(cx), int(cy)) not in have:
                continue
            seg = _chip_segments(store, cx, cy)
            if seg is None:
                continue
            try:
                aux = aux_source.aux(cx, cy, acquired)
            except LookupError:
                continue
            updated = classify_chip(model, seg, aux, cx, cy)
            if updated is None:
                continue
            writer.write("segment", updated)
            counters.add("chips")
            counters.add("segments", len(updated["sday"]))
    finally:
        # A caller-supplied writer outlives this call (the fleet worker
        # closes it after the queue ack decision); flush so the rfrawp
        # upserts are landed — not merely queued — before returning.
        if own_writer:
            writer.close()
        else:
            writer.flush()
        log.info("classification complete: %s", counters.snapshot())
    return model
