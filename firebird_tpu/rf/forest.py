"""TPU-native random forest: histogram trees, level-wise, fully jittable.

Replaces Spark ML's distributed ``RandomForestClassifier(numTrees=500)``
(ccdc/randomforest.py:25-39).  Spark grows trees with distributed
findBestSplits passes over binned features; the TPU-native formulation keeps
the same statistical procedure — Poisson(1) bootstrap per tree (Spark's
bagging with subsamplingRate=1.0), quantile-binned features, per-node class
histograms, gini-gain splits over a sqrt(F) feature subset — but expresses
it as dense array ops so the whole forest trains under ``jit``:

- Trees are **complete binary trees of fixed depth** D.  A node that stops
  splitting (no gain / below min leaf size) gets threshold=+inf so samples
  fall through to its leftmost descendant; its class distribution is read at
  depth D.  Fixed shapes mean no data-dependent tree topology — the shape
  XLA wants.
- Growth is **level-wise**: at level d every sample carries its node index
  in [0, 2^d); one ``segment_sum`` scatter builds the [nodes, F, bins,
  classes] histogram for the whole level, cumulative sums over bins give
  every candidate split's left/right class counts at once.  This is the
  MXU/VPU-friendly reformulation of Spark's per-node aggregation shuffle.
- A chunk of trees trains at a time via ``vmap`` (bounded histogram
  memory); chunks loop on the host.

Inference walks all trees in lock-step (D gather steps, no branches) and
sums per-tree leaf class distributions — Spark ML's ``rawPrediction``
semantics (each tree contributes its leaf's normalized class distribution;
randomforest.py:90-103 renames it ``rfrawp``).

Label indexing follows StringIndexer(handleInvalid='keep'): classes ordered
by descending training frequency (randomforest.py:35).  VectorIndexer's
maxCategories=8 categorical detection (randomforest.py:36) is not
replicated: quantile binning handles low-cardinality features natively
(every distinct value gets its own bin edge), which is the same split
family without the indexing pass.
"""

from __future__ import annotations

import base64
import dataclasses
import json
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NUM_TREES = 500          # randomforest.py:38
DEFAULT_DEPTH = 8
DEFAULT_BINS = 64


@dataclasses.dataclass(frozen=True)
class RandomForest:
    """A trained forest in flat arrays (device- and serialization-friendly).

    Internal nodes use breadth-first indexing: level d occupies
    [2^d - 1, 2^(d+1) - 1); node i's children are 2i+1, 2i+2.  ``go right``
    iff x[feature] > threshold.
    """

    feature: np.ndarray      # [T, 2^D - 1] int32
    threshold: np.ndarray    # [T, 2^D - 1] float32 (+inf = always-left)
    leaf_proba: np.ndarray   # [T, 2^D, C] float32, rows sum to 1
    classes: np.ndarray      # [C] original label values, frequency-ordered

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def depth(self) -> int:
        return int(np.log2(self.feature.shape[1] + 1))

    @property
    def n_classes(self) -> int:
        return self.leaf_proba.shape[2]

    # -- persistence (the tile table's `model` TEXT column, ccdc/tile.py) --

    def dumps(self) -> str:
        def enc(a):
            a = np.ascontiguousarray(a)
            return {"dtype": str(a.dtype), "shape": list(a.shape),
                    "data": base64.b64encode(a.tobytes()).decode()}
        return json.dumps({"format": "firebird_tpu.rf.v1",
                           "feature": enc(self.feature),
                           "threshold": enc(self.threshold),
                           "leaf_proba": enc(self.leaf_proba),
                           "classes": enc(self.classes)})

    @classmethod
    def loads(cls, s: str) -> "RandomForest":
        d = json.loads(s)
        if d.get("format") != "firebird_tpu.rf.v1":
            raise ValueError(f"unknown model format: {d.get('format')!r}")
        def dec(e):
            a = np.frombuffer(base64.b64decode(e["data"]), dtype=e["dtype"])
            return a.reshape(e["shape"]).copy()
        return cls(feature=dec(d["feature"]), threshold=dec(d["threshold"]),
                   leaf_proba=dec(d["leaf_proba"]), classes=dec(d["classes"]))

    # -- inference --

    def raw_predict(self, X: np.ndarray, batch: int = 16384,
                    dense: bool | None = None) -> np.ndarray:
        """rawPrediction [N, C]: sum over trees of leaf class distributions.

        Batches are padded to a fixed size so XLA compiles once.  NaN
        features compare false and route left (deterministic).

        Two equivalent kernels (same decisions; sums differ only by f32
        accumulation order): accelerators run the dense leaf-reachability
        form (comparisons + matmul, MXU work); CPU runs the node walk
        (256x less arithmetic; gathers are cheap there).  ``dense``
        overrides the platform default.
        """
        if dense is None:
            dense = jax.default_backend() != "cpu"
        kern = _raw_predict_dense if dense else _raw_predict_walk
        X = np.asarray(X, np.float32)
        N = X.shape[0]
        if N == 0:
            return np.zeros((0, self.n_classes), np.float32)
        f = jnp.asarray(self.feature)
        t = jnp.asarray(self.threshold)
        lp = jnp.asarray(self.leaf_proba)
        out = np.empty((N, self.n_classes), np.float32)
        for i in range(0, N, batch):
            xb = X[i:i + batch]
            n = xb.shape[0]
            if n < batch:
                xb = np.pad(xb, ((0, batch - n), (0, 0)))
            out[i:i + batch] = np.asarray(
                kern(f, t, lp, jnp.asarray(xb), self.depth))[:n]
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Predicted original label values [N]."""
        raw = self.raw_predict(X)
        return self.classes[np.argmax(raw, axis=1)]


@partial(jax.jit, static_argnums=(4,))
def _raw_predict_walk(feature, threshold, leaf_proba, X, depth):
    """Node-walk inference: depth data-dependent gathers per tree.  The
    right shape for CPU, where gathers are cheap and arithmetic is not."""

    def one_tree(tf, tt, tl):
        node = jnp.zeros(X.shape[0], jnp.int32)
        for d in range(depth):
            nb = (2 ** d - 1) + node
            fidx = tf[nb]                                   # [N]
            xv = jnp.take_along_axis(X, fidx[:, None], axis=1)[:, 0]
            node = 2 * node + (xv > tt[nb]).astype(jnp.int32)
        return tl[node]                                     # [N, C]

    return jnp.sum(jax.vmap(one_tree)(feature, threshold, leaf_proba), axis=0)


@partial(jax.jit, static_argnums=(4,))
def _raw_predict_dense(feature, threshold, leaf_proba, X, depth):
    """[T,M] trees x [N,F] samples -> [N,C] summed leaf distributions.

    TPU-shaped: instead of walking each sample down its tree (depth
    data-dependent gathers per tree — gather-bound, the MXU idle), every
    node's comparison is evaluated at once ([N, M] from one column
    gather), leaf reachability is a chain of static broadcast-AND ops
    (leaf l is reached iff each level-d ancestor's bit equals bit
    depth-1-d of l), and the leaf lookup becomes a [N, L] x [L, C]
    matmul — MXU work.  Trees run in vmapped chunks under a scan to
    bound the [chunk, N, L] intermediates.
    """
    T, M = feature.shape
    L = M + 1
    N = X.shape[0]
    C = leaf_proba.shape[2]
    chunk = 8
    pad = -T % chunk
    if pad:
        # inert trees: all-left thresholds, zero leaf mass
        feature = jnp.pad(feature, ((0, pad), (0, 0)))
        threshold = jnp.pad(threshold, ((0, pad), (0, 0)),
                            constant_values=jnp.inf)
        leaf_proba = jnp.pad(leaf_proba, ((0, pad), (0, 0), (0, 0)))
    # direction bit of leaf l at level d (static)
    dirs = [((jnp.arange(L) >> (depth - 1 - d)) & 1).astype(bool)
            for d in range(depth)]

    def one_tree(tf, tt, tl):
        bits = jnp.take(X, tf, axis=1) > tt[None, :]        # [N, M]
        reached = jnp.ones((N, L), bool)
        for d in range(depth):
            lo = (1 << d) - 1
            bd = bits[:, lo:lo + (1 << d)]                  # level-d nodes
            reached &= jnp.repeat(bd, L >> d, axis=1) == dirs[d][None, :]
        return jnp.dot(reached.astype(tl.dtype), tl)        # [N, C]

    def step(acc, args):
        return acc + jnp.sum(jax.vmap(one_tree)(*args), axis=0), None

    acc, _ = jax.lax.scan(
        step, jnp.zeros((N, C), leaf_proba.dtype),
        (feature.reshape(-1, chunk, M), threshold.reshape(-1, chunk, M),
         leaf_proba.reshape(-1, chunk, L, C)))
    return acc


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------

def _bin_edges(X: np.ndarray, n_bins: int) -> np.ndarray:
    """Per-feature quantile edges [F, n_bins-1] (Spark's findSplits uses
    sampled quantiles per feature; maxBins analogue is n_bins)."""
    F = X.shape[1]
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.empty((F, n_bins - 1), np.float32)
    for f in range(F):
        col = X[:, f]
        col = col[np.isfinite(col)]
        if col.size == 0:
            edges[f] = np.arange(n_bins - 1, dtype=np.float32)
            continue
        e = np.quantile(col, qs).astype(np.float32)
        # Strictly increasing edges make bins well-defined; pad duplicates
        # with tiny increments far above float32 ulp at these magnitudes.
        e = np.maximum.accumulate(e)
        dup = np.concatenate([[False], np.diff(e) == 0])
        if dup.any():
            e = e + np.cumsum(dup) * np.float32(1e-6) * np.maximum(
                1.0, np.abs(e))
        edges[f] = e
    return edges


def _binize(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """bin(x) = #(x > edge) in [0, n_bins-1]; NaN -> bin 0 (routes left,
    matching inference where NaN > thr is false)."""
    b = (np.nan_to_num(X, nan=-np.inf)[:, :, None]
         > edges[None, :, :]).sum(axis=2)
    return b.astype(np.int32)


@partial(jax.jit, static_argnums=(3, 4, 5, 6, 7))
def _train_chunk(Xb, y, keys, depth, n_bins, n_classes, mtry, min_leaf):
    """Train a vmapped chunk of trees on binned features.

    Xb [N, F] int32 bins, y [N] int32 class indices, keys [Tc] PRNG keys.
    Returns (feature [Tc, 2^D-1], split_bin [Tc, 2^D-1], leaf_counts
    [Tc, 2^D, C]); split_bin -1 marks always-left nodes.
    """
    N, F = Xb.shape
    B, C = n_bins, n_classes

    def one_tree(key):
        kboot, knode = jax.random.split(key)
        w = jax.random.poisson(kboot, 1.0, (N,)).astype(jnp.float32)

        feats, bins = [], []
        node = jnp.zeros(N, jnp.int32)
        for d in range(depth):
            n_nodes = 2 ** d
            idx = ((node[:, None] * F + jnp.arange(F)[None, :]) * B + Xb)
            idx = idx * C + y[:, None]                         # [N, F]
            hist = jax.ops.segment_sum(
                jnp.broadcast_to(w[:, None], (N, F)).reshape(-1),
                idx.reshape(-1),
                num_segments=n_nodes * F * B * C,
            ).reshape(n_nodes, F, B, C)

            left = jnp.cumsum(hist, axis=2)                    # [n,F,B,C]
            total = left[:, :, -1:, :]
            right = total - left
            nl = left.sum(-1)                                  # [n,F,B]
            nr = right.sum(-1)
            # Maximizing sum_c l^2/nl + r^2/nr minimizes weighted gini.
            score = (jnp.sum(left * left, -1) / jnp.maximum(nl, 1e-9)
                     + jnp.sum(right * right, -1) / jnp.maximum(nr, 1e-9))
            valid = (nl >= min_leaf) & (nr >= min_leaf)
            # Last bin has no right side; exclude as a split point.
            valid = valid & (jnp.arange(B)[None, None, :] < B - 1)

            # sqrt(F) feature subset per node (featureSubsetStrategy='auto'
            # for classification): mask features outside the node's draw.
            u = jax.random.uniform(
                jax.random.fold_in(knode, d), (n_nodes, F))
            rank = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
            valid = valid & (rank[:, :, None] < mtry)

            score = jnp.where(valid, score, -jnp.inf)
            flat = score.reshape(n_nodes, F * B)
            best = jnp.argmax(flat, axis=1)
            bf = (best // B).astype(jnp.int32)                 # [n]
            bb = (best % B).astype(jnp.int32)
            best_score = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
            # No-gain guard: splitting must beat the parent's own purity
            # sum_c counts^2 / n (equality = pure node, nothing to gain).
            parent = hist.sum((1, 2)) / F                      # [n, C]
            pn = parent.sum(-1)
            pscore = jnp.sum(parent * parent, -1) / jnp.maximum(pn, 1e-9)
            use = jnp.isfinite(best_score) & (best_score > pscore + 1e-6)
            bf = jnp.where(use, bf, 0)
            bb = jnp.where(use, bb, -1)                        # -1: stay left
            feats.append(bf)
            bins.append(bb)

            xb = jnp.take_along_axis(Xb, bf[node][:, None], 1)[:, 0]
            go_right = (bb[node] >= 0) & (xb > bb[node])
            node = 2 * node + go_right.astype(jnp.int32)

        leaf_idx = node * C + y
        leaf = jax.ops.segment_sum(
            w, leaf_idx, num_segments=(2 ** depth) * C
        ).reshape(2 ** depth, C)
        return jnp.concatenate(feats), jnp.concatenate(bins), leaf

    return jax.vmap(one_tree)(keys)


def train(X: np.ndarray, y: np.ndarray, *, n_trees: int = NUM_TREES,
          max_depth: int = DEFAULT_DEPTH, n_bins: int = DEFAULT_BINS,
          min_leaf: int = 1, seed: int = 0,
          trees_per_chunk: int = 16) -> RandomForest:
    """Train a forest on host arrays X [N, F] (float), y [N] (labels).

    Rows with any non-finite feature are dropped (the reference's join
    produces only complete rows; sentinel segments never reach training).
    """
    X = np.asarray(X, np.float32)
    y = np.asarray(y)
    ok = np.isfinite(X).all(axis=1)
    X, y = X[ok], y[ok]
    if X.shape[0] == 0:
        raise ValueError("no finite training rows")

    # StringIndexer semantics: classes by descending frequency
    # (ties broken by value for determinism).
    vals, counts = np.unique(y, return_counts=True)
    order = np.lexsort((vals, -counts))
    classes = vals[order]
    lut = {v: i for i, v in enumerate(classes)}
    y_idx = np.array([lut[v] for v in y], np.int32)
    C = len(classes)

    edges = _bin_edges(X, n_bins)
    Xb = jnp.asarray(_binize(X, edges))
    yj = jnp.asarray(y_idx)
    mtry = max(1, int(np.sqrt(X.shape[1])))

    feats, bins, leaves = [], [], []
    root = jax.random.PRNGKey(seed)
    for c0 in range(0, n_trees, trees_per_chunk):
        tc = min(trees_per_chunk, n_trees - c0)
        keys = jax.vmap(jax.random.fold_in, (None, 0))(
            root, jnp.arange(c0, c0 + tc))
        f, b, l = _train_chunk(Xb, yj, keys, max_depth, n_bins, C,
                               mtry, min_leaf)
        feats.append(np.asarray(f))
        bins.append(np.asarray(b))
        leaves.append(np.asarray(l))
    feature = np.concatenate(feats).astype(np.int32)
    split_bin = np.concatenate(bins)
    leaf = np.concatenate(leaves)

    # bin threshold -> raw threshold: right iff bin > b iff x > edges[f, b];
    # b == n_bins-1 can't occur (excluded above); b == -1 -> +inf.
    thr = np.where(
        split_bin >= 0,
        edges[feature, np.clip(split_bin, 0, n_bins - 2)],
        np.inf).astype(np.float32)

    norm = leaf.sum(axis=2, keepdims=True)
    leaf_proba = (leaf / np.maximum(norm, 1e-9)).astype(np.float32)
    return RandomForest(feature=feature, threshold=thr,
                        leaf_proba=leaf_proba, classes=classes)
