"""Classification feature assembly (replaces ccdc/features.py + ccdc/udfs.py).

The 33-column contract is the reference's exactly (ccdc/features.py:20-37 —
"Altering this list invalidates all persisted models"): 7 magnitudes,
7 rmses, 7 first harmonic coefficients, 7 intercepts, then dem, aspect,
slope, mpw, posidex.  The reference's ``densify`` UDF takes ``first(x)`` of
any list-valued column (ccdc/udfs.py:19-21) — hence *first* coefficient
only, and element 0 of each length-1 aux array.  Label = ``trends[0]``
(ccdc/features.py:40-50).

The reference assembles rows via a Spark inner join of the aux and segment
dataframes on (cx, cy, px, py) (ccdc/features.py:6-17).  Here the join is a
direct array gather: aux layers are dense [100, 100] chip rasters and
segment rows carry (px, py), so ``aux[py - cy-edge, ...]`` indexing replaces
the shuffle.
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.ccd.format import BAND_PREFIX
from firebird_tpu.ingest.packer import CHIP_SIDE, PIXEL_SIZE_M
from firebird_tpu.utils import dates as dt

AUX_FEATURES = ("dem", "aspect", "slope", "mpw", "posidex")

COLUMNS = (
    tuple(f"{p}mag" for p in BAND_PREFIX)
    + tuple(f"{p}rmse" for p in BAND_PREFIX)
    + tuple(f"{p}coef" for p in BAND_PREFIX)
    + tuple(f"{p}int" for p in BAND_PREFIX)
    + AUX_FEATURES
)

TRENDS_EXCLUDE = (0, 9)      # ccdc/randomforest.py:63 'trends[0] NOT IN (0, 9)'


def pixel_index(cx: int, cy: int, px: np.ndarray, py: np.ndarray):
    """(px, py) projection coords -> (row, col) into a [100, 100] chip
    raster.  px increases east from cx; py decreases south from cy."""
    col = ((np.asarray(px) - cx) // PIXEL_SIZE_M).astype(np.int64)
    row = ((cy - np.asarray(py)) // PIXEL_SIZE_M).astype(np.int64)
    if ((col < 0) | (col >= CHIP_SIDE) | (row < 0) | (row >= CHIP_SIDE)).any():
        raise ValueError("pixel coords outside chip")
    return row, col


def _first(v):
    """densify's first(x)-if-sequence rule (ccdc/udfs.py:19-21)."""
    if isinstance(v, (list, tuple, np.ndarray)):
        return v[0] if len(v) else np.nan
    return v


def segment_window(seg: dict, msday: int, meday: int) -> np.ndarray:
    """Row mask: training window 'sday >= msday AND eday <= meday'
    (ccdc/randomforest.py:69), on ISO-string day columns."""
    lo, hi = dt.to_iso(msday), dt.to_iso(meday)
    sday = np.asarray(seg["sday"], object)
    eday = np.asarray(seg["eday"], object)
    return np.array([s >= lo and e <= hi for s, e in zip(sday, eday)], bool)


def real_rows(seg: dict) -> np.ndarray:
    """Mask off sentinel rows (sday == eday == 0001-01-01,
    ccdc/pyccd.py:99-103): they carry no model and can't be featurized."""
    return np.array([s != "0001-01-01" for s in seg["sday"]], bool)


def assemble(seg: dict, aux: dict, cx: int, cy: int,
             row_mask: np.ndarray | None = None):
    """Segment rows + aux chip rasters -> (X [N, 33], meta dict).

    ``seg`` is a segment-table frame (dict of columns) for one chip;
    ``aux`` maps layer name -> [100, 100] array.  Mirrors
    features.dataframe (ccdc/features.py:66-82): the output meta carries
    (cx, cy, px, py, sday, eday) and, when ``trends`` is present in aux,
    a ``label`` column.
    """
    n = len(seg["sday"])
    mask = np.ones(n, bool) if row_mask is None else np.asarray(row_mask)
    idx = np.flatnonzero(mask)
    px = np.asarray(seg["px"], np.int64)[idx]
    py = np.asarray(seg["py"], np.int64)[idx]
    row, col = pixel_index(cx, cy, px, py)

    X = np.empty((idx.size, len(COLUMNS)), np.float32)
    for j, name in enumerate(COLUMNS):
        if name in AUX_FEATURES:
            X[:, j] = np.asarray(aux[name], np.float32)[row, col]
        else:
            colv = seg[name]
            X[:, j] = [np.float32(_first(colv[i])) if colv[i] is not None
                       else np.nan for i in idx]

    meta = {k: [seg[k][i] for i in idx]
            for k in ("cx", "cy", "px", "py", "sday", "eday")}
    if "trends" in aux:
        meta["label"] = np.asarray(aux["trends"])[row, col]
    return X, meta
