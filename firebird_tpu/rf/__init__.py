"""Random-forest land-cover classification (replaces ccdc/randomforest.py,
ccdc/features.py, ccdc/udfs.py and the predict/persist path the reference
left commented out at ccdc/core.py:190-240).

- :mod:`firebird_tpu.rf.features` — the 33-column feature contract.
- :mod:`firebird_tpu.rf.forest` — TPU-native random forest: histogram-based
  level-wise training and batched inference, both jittable.
- :mod:`firebird_tpu.rf.pipeline` — train / classify orchestration against
  the keyed store.
"""

from firebird_tpu.rf.forest import RandomForest, train  # noqa: F401
