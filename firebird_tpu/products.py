"""Product rasters derived from stored segments.

This completes the reference 0.5 ``ccdc-save`` capability that was dropped
by 1.0 and survives only in its docs (docs/faq.rst:38-109; SURVEY.md §2.5
"behavior the rebuild must complete"): per-pixel product rasters
(``seglength``, ``ccd``, ``curveqa``) computed for query dates over areas
given as ``--bounds`` points, with whole-chip or clipped (``--clip``)
output, and ``ccdc-products`` listing what can be run.

The reference never shipped the implementation (only the CLI transcript in
the FAQ), so the product semantics are re-derived from the LCMAP product
definitions and pinned here:

- ``seglength``: days of continuity at date D — ``D - sday`` of the segment
  containing D; if D falls after a segment's confirmed break, days since
  that break (``D - bday`` of the most recent ``bday <= D``); 0 before the
  first segment or when the pixel has no models.
- ``ccd``: day-of-year (1..366) of a confirmed change (``chprob >= 1``)
  whose break day falls in the same calendar year as D, else 0.
- ``curveqa``: the ``curqa`` flag of the segment containing D, else 0.
- ``cover`` (beyond the reference list): the predicted land-cover label of
  the segment containing D — the stored ``rfrawp`` vote vector's argmax
  mapped through the tile model's class order; 0 when the segment was
  never classified or no model is stored for the tile.

Run modes (faq.rst examples): every chip intersecting the bounding box of
the ``bounds`` points is produced; ``clip`` masks pixels outside the
polygon of the points (two points: their bounding box; one point: the
single pixel containing it) to FILL (-9999).  Results land in the keyed
``product`` table (store.schema) so reruns upsert idempotently.
"""

from __future__ import annotations

import datetime

import numpy as np

from firebird_tpu import grid
from firebird_tpu.ccd.params import FILL_VALUE
from firebird_tpu.config import Config
from firebird_tpu.ingest.packer import CHIP_SIDE, PIXEL_SIZE_M, PIXELS
from firebird_tpu.obs import logger
from firebird_tpu.store import open_store
from firebird_tpu.utils import dates as dt

log = logger("products")

PRODUCTS = ("seglength", "ccd", "curveqa", "cover")


def available() -> tuple[str, ...]:
    """Products that can be run (the ``ccdc-products`` listing)."""
    return PRODUCTS


# ---------------------------------------------------------------------------
# Per-chip product math (vectorized over segment rows)
# ---------------------------------------------------------------------------

def _ordinals(iso_col) -> np.ndarray:
    return np.array([dt.to_ordinal(s[:10]) for s in iso_col], np.int64)


class ChipSegmentArrays:
    """A chip's segment rows parsed once (ISO dates -> ordinals, pixel
    indices bounds-checked) and shared by every (product, date) raster."""

    def __init__(self, cx: int, cy: int, seg: dict):
        from firebird_tpu.rf.features import pixel_index

        px = np.asarray(seg["px"], np.int64)
        py = np.asarray(seg["py"], np.int64)
        if px.size:
            row, col = pixel_index(cx, cy, px, py)
            self.pix = row * CHIP_SIDE + col
        else:
            self.pix = np.zeros(0, np.int64)
        self.sday = _ordinals(seg["sday"])
        self.eday = _ordinals(seg["eday"])
        self.bday = _ordinals(seg["bday"])
        self.chprob = np.array([0.0 if v is None else float(v)
                                for v in seg["chprob"]])
        self.curqa = np.array([0 if v is None else int(v)
                               for v in seg["curqa"]], np.int32)
        # argmax class index of each row's rfrawp vote vector (-1 when the
        # segment was never classified) — the cover product's input
        raw = seg.get("rfrawp")
        if raw is None or len(raw) == 0:
            raw = [None] * len(seg["sday"])
        # `v is not None and len(v)` rather than truthiness: rfrawp columns
        # may hold numpy arrays (no store round-trip), whose bool() raises.
        self.rfidx = np.array(
            [int(np.argmax(v)) if v is not None and len(v) else -1
             for v in raw], np.int64)
        self.real = self.sday > 1


def chip_product(name: str, date_ord: int, cx: int, cy: int,
                 seg: dict | ChipSegmentArrays,
                 classes: np.ndarray | None = None) -> np.ndarray:
    """One product raster for one chip.

    ``seg`` is the segment-table frame for the chip (dict of columns, as
    returned by ``store.read('segment', {'cx':…, 'cy':…})``) or an already
    parsed :class:`ChipSegmentArrays`.  Returns a flat [10000] int32 array
    in the packer's row-major pixel order.  Sentinel rows (sday ==
    0001-01-01, ccdc/pyccd.py:99-103) contribute nothing: their ordinals
    (1) never contain or precede a real query date with chprob/curqa set.

    ``cover`` (the predicted land-cover label of the segment containing D,
    from the stored rfrawp vote vectors) additionally needs ``classes`` —
    the trained model's label order (forest.RandomForest.classes) that
    maps vote argmax to the original label values.
    """
    if name not in PRODUCTS:
        raise ValueError(f"unknown product {name!r}; available: {PRODUCTS}")
    a = seg if isinstance(seg, ChipSegmentArrays) \
        else ChipSegmentArrays(cx, cy, seg)
    out = np.zeros(PIXELS, np.int32)
    if a.pix.size == 0:
        return out
    contains = a.real & (a.sday <= date_ord) & (date_ord <= a.eday)

    if name == "cover":
        if classes is None:
            raise ValueError("the cover product needs the trained model's "
                             "class order (classes=)")
        classes = np.asarray(classes)
        stale = contains & (a.rfidx >= classes.shape[0])
        if np.any(stale):
            log.warning(
                "cover chip (%d, %d): %d segments hold vote vectors longer "
                "than the stored model's %d classes (stale rfrawp vs a "
                "retrained model?) — emitted as 0", cx, cy,
                int(np.sum(stale)), classes.shape[0])
        hit = contains & (a.rfidx >= 0) & (a.rfidx < classes.shape[0])
        out[a.pix[hit]] = classes[a.rfidx[hit]].astype(np.int32)
        return out

    if name == "seglength":
        # Most recent confirmed break at or before D, per pixel.
        broke = a.real & (a.chprob >= 1.0) & (a.bday <= date_ord)
        last_brk = np.zeros(PIXELS, np.int64)
        np.maximum.at(last_brk, a.pix[broke], a.bday[broke])
        since_start = np.zeros(PIXELS, np.int64)
        np.maximum.at(since_start, a.pix[contains],
                      date_ord - a.sday[contains])
        has = np.zeros(PIXELS, bool)
        has[a.pix[contains]] = True
        out = np.where(has, since_start,
                       np.where(last_brk > 0, date_ord - last_brk, 0))
        return out.astype(np.int32)

    if name == "ccd":
        year = datetime.date.fromordinal(int(date_ord)).year
        y0 = datetime.date(year, 1, 1).toordinal()
        y1 = datetime.date(year, 12, 31).toordinal()
        hit = a.real & (a.chprob >= 1.0) & (a.bday >= y0) & (a.bday <= y1)
        np.maximum.at(out, a.pix[hit], (a.bday[hit] - y0 + 1).astype(np.int32))
        return out

    # curveqa
    out[a.pix[contains]] = a.curqa[contains]
    return out


# ---------------------------------------------------------------------------
# Area selection (bounds / clip)
# ---------------------------------------------------------------------------

def covering_chips(bounds: list[tuple[float, float]]) -> list[tuple[int, int]]:
    """Chip ids intersecting the bounding box of the bounds points
    (faq.rst "run a bigger area": several --bounds extend the area)."""
    g = grid.CONUS.chip
    return [tuple(int(c) for c in grid.proj_pt(h, v, g))
            for h, v in grid.cells_for_bounds(bounds, g)]


def _point_in_poly(px: np.ndarray, py: np.ndarray, poly) -> np.ndarray:
    """Vectorized ray-casting point-in-polygon (boundary-exclusive on the
    upper edge, standard even-odd rule)."""
    inside = np.zeros(px.shape, bool)
    n = len(poly)
    j = n - 1
    for i in range(n):
        xi, yi = poly[i]
        xj, yj = poly[j]
        cross = (yi > py) != (yj > py)
        xint = (xj - xi) * (py - yi) / ((yj - yi) or 1e-30) + xi
        inside ^= cross & (px < xint)
        j = i
    return inside


def clip_mask(cx: int, cy: int, bounds: list[tuple[float, float]]) -> np.ndarray:
    """[10000] bool: pixels of chip (cx, cy) kept under --clip.

    Three or more points clip to their polygon (faq.rst "run a triangle"),
    two points to their bounding box, one point to the single containing
    pixel (faq.rst "run a single point").
    """
    col = np.tile(np.arange(CHIP_SIDE), CHIP_SIDE)
    row = np.repeat(np.arange(CHIP_SIDE), CHIP_SIDE)
    # pixel centers
    px = cx + col * PIXEL_SIZE_M + PIXEL_SIZE_M / 2.0
    py = cy - row * PIXEL_SIZE_M - PIXEL_SIZE_M / 2.0
    if len(bounds) == 1:
        x, y = bounds[0]
        ux = cx + (np.floor((x - cx) / PIXEL_SIZE_M)) * PIXEL_SIZE_M
        uy = cy - (np.floor((cy - y) / PIXEL_SIZE_M)) * PIXEL_SIZE_M
        return ((px > ux) & (px < ux + PIXEL_SIZE_M)
                & (py < uy) & (py > uy - PIXEL_SIZE_M))
    if len(bounds) == 2:
        (x0, y0), (x1, y1) = bounds
        return ((px >= min(x0, x1)) & (px <= max(x0, x1))
                & (py >= min(y0, y1)) & (py <= max(y0, y1)))
    return _point_in_poly(px, py, bounds)


# ---------------------------------------------------------------------------
# The save run
# ---------------------------------------------------------------------------

def tile_classes(store, cx: int, cy: int,
                 cache: dict | None = None) -> np.ndarray | None:
    """The trained model's class order for the tile containing chip
    (cx, cy), or None when no model is stored — the ``cover`` product's
    vote-argmax -> label mapping.  ``cache`` (a caller-held dict) keeps
    one store lookup per tile across a chip loop; models are persisted
    per tile (tile table), so chips of one tile share the entry."""
    t = grid.tile(cx, cy)
    key = (int(t["x"]), int(t["y"]))
    if cache is None:
        cache = {}
    if key not in cache:
        from firebird_tpu.rf import pipeline as rf_pipeline

        m = rf_pipeline.load_model(store, key[0], key[1])
        cache[key] = None if m is None else m.classes
        if m is None:
            log.warning("cover: no trained model stored for tile "
                        "(%d, %d); run `firebird classification` first",
                        *key)
    return cache[key]


def save_chip_raster(store, name: str, date: str, date_ord: int,
                     cx: int, cy: int, seg: "dict | ChipSegmentArrays",
                     classes: np.ndarray | None = None,
                     keep: np.ndarray | None = None) -> np.ndarray:
    """Compute ONE (product, date, chip) raster and persist it to the
    keyed product table — the unit of work of the ``save`` run, shared
    verbatim by the serving layer's compute-on-miss path
    (serve/api.py), so a raster served cold is byte-identical to one a
    batch ``firebird save`` would have produced.  Returns the flat
    [10000] int32 cells as written (clip mask applied)."""
    vals = chip_product(name, date_ord, cx, cy, seg, classes=classes)
    if keep is not None:
        vals = np.where(keep, vals, FILL_VALUE).astype(np.int32)
    cells = np.empty(1, object)
    cells[0] = vals.tolist()
    store.write("product", {
        "name": np.array([name], object),
        "date": np.array([date], object),
        "cx": np.array([cx], np.int64),
        "cy": np.array([cy], np.int64),
        "cells": cells,
    })
    return vals


def save(bounds, products, product_dates, acquired: str | None = None,
         clip: bool = False, cfg: Config | None = None, store=None,
         source=None) -> list[tuple[str, str, int, int]]:
    """Compute and persist product rasters (the ``ccdc-save`` run).

    For chips in the area with no stored segments, change detection is run
    first over ``acquired`` (that is what made the reference's ccdc-save
    self-contained; pass ``acquired=None`` to derive strictly from the
    store).  Returns the (name, date, cx, cy) keys written.
    """
    for p in products:
        if p not in PRODUCTS:
            raise ValueError(f"unknown product {p!r}; available: {PRODUCTS}")
    # Dates parse before any work: a malformed date must fail in
    # milliseconds, not after the detection phase.
    date_ords = {d: dt.to_ordinal(d) for d in product_dates}
    cfg = cfg or Config.from_env()
    store = store or open_store(cfg.store_backend, cfg.store_path,
                                cfg.keyspace())
    cids = covering_chips(bounds)
    log.info("products %s at %s over %d chips (clip=%s)",
             list(products), list(product_dates), len(cids), clip)

    detected: list[tuple[int, int]] = []
    if acquired:
        have = store.chip_ids("segment")
        missing = [c for c in cids if c not in have]
        if missing:
            from firebird_tpu.driver import core
            from firebird_tpu.obs import Counters
            from firebird_tpu.store import AsyncWriter

            log.info("detecting %d chips with no stored segments", len(missing))
            writer = AsyncWriter(store)
            try:
                processed = core.detect_chunk(
                    missing, source=source or core.make_source(cfg),
                    writer=writer, acquired=acquired, cfg=cfg,
                    counters=Counters(), log=log)
            finally:
                writer.close()
            # detect_chunk isolates failures per chip (returning only the
            # survivors); a product raster computed over silently missing
            # segments would be wrong without looking wrong, so here —
            # with no quarantine/resume loop to drain into — absence must
            # stay loud, the pre-quarantine behavior.
            lost = [c for c in missing if c not in set(processed)]
            if lost:
                raise RuntimeError(
                    f"products: {len(lost)} chips failed detection "
                    f"(first: {lost[0]}); rerun once ingest recovers")
            detected = list(processed)

    # The cover product maps stored rfrawp votes through the trained
    # model's class order; tile_classes keeps one tile-table lookup per
    # tile across the chip loop via this shared dict.
    model_classes: dict[tuple[int, int], np.ndarray | None] = {}

    written = []
    for cx, cy in cids:
        seg = store.read("segment", {"cx": cx, "cy": cy})
        if not seg["px"]:
            log.warning("no segments stored for chip (%d, %d); skipping",
                        cx, cy)
            continue
        keep = clip_mask(cx, cy, bounds) if clip else None
        arrays = ChipSegmentArrays(cx, cy, seg)
        for name in products:
            classes = tile_classes(store, cx, cy, model_classes) \
                if name == "cover" else None
            if name == "cover" and classes is None:
                continue
            for d in product_dates:
                save_chip_raster(store, name, d, date_ords[d], cx, cy,
                                 arrays, classes=classes, keep=keep)
                written.append((name, d, cx, cy))
    log.info("products complete: %d rasters written", len(written))
    # Cross-process coherence (serve/changefeed.py): a batch save is
    # exactly the "non-alert mutation" the serve replicas cannot see
    # through the alert log — append one product_writes record per
    # touched chip (and per chip the self-contained acquired path
    # re-detected) AFTER the rows land, so a replica that applies the
    # record is guaranteed to read the new rows.
    from firebird_tpu.serve.changefeed import append_product_writes

    if written:
        append_product_writes(cfg, "product",
                              {(cx, cy) for _, _, cx, cy in written})
    if detected:
        append_product_writes(cfg, "segment", detected)
    return written
