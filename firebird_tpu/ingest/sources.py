"""Chip sources: synthetic, file-backed, and Chipmunk HTTP.

The reference's only source is the Chipmunk raster service reached through
merlin (`merlin.create`, driven at ccdc/timeseries.py:120-123; chip payloads
are base64 int16 rasters per (ubid, acquisition) — test/data/chip_response.json).
Tests there inject canned responses by swapping the merlin cfg functions
(test/conftest.py:20-37).  Here the seam is the source object itself.

All sources produce :class:`~firebird_tpu.ingest.packer.ChipData` (ARD) and
aux dicts (AUX layers: dem, trends, aspect, posidex, slope, mpw —
ccdc/timeseries.py:46-56).
"""

from __future__ import annotations

import json
import os
import urllib.parse
import urllib.request

import numpy as np

from firebird_tpu import native
from firebird_tpu.ccd import harmonic, params, synthetic
from firebird_tpu.ingest.packer import CHIP_SIDE, ChipData
from firebird_tpu.obs import logger
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.utils import dates as dt

log = logger("timeseries")

AUX_NAMES = ("dem", "trends", "aspect", "posidex", "slope", "mpw")


def _slice_acquired(t, spectra, qas, acquired):
    """Restrict a chip archive to an ISO8601 acquired range.

    The window is consistently HALF-OPEN: ``[start, end)`` — an
    observation dated exactly ``end`` belongs to the NEXT window, never
    to both or neither.  The acquisition watcher's ``since`` cursor
    (streamops/watcher.py) and the stream driver's horizon both slice
    the archive into adjacent windows; inclusive ends would
    double-deliver a boundary scene to two windows, and an exclusive
    start would skip it entirely (tests/test_ingest.py pins the
    partition property)."""
    if not acquired:
        return t, spectra, qas
    lo, hi = dt.acquired_range(acquired)
    keep = (t >= lo) & (t < hi)
    return t[keep], spectra[:, keep], qas[keep]


# ---------------------------------------------------------------------------
# Synthetic source (tests + bench; no reference analogue — closes the
# "no numerical fixtures" gap, SURVEY.md §4)
# ---------------------------------------------------------------------------

class SyntheticSource:
    """Deterministic synthetic ARD + AUX per chip id.

    Each chip gets a harmonic landscape with per-pixel level offsets; a
    rectangular patch of ``change_frac`` of the area undergoes a step change
    at a chip-specific date.  QA marks a fraction of acquisitions cloudy.
    Fully determined by (seed, cx, cy).
    """

    def __init__(self, seed: int = 0, *, start="1995-01-01", end="2005-01-01",
                 cadence_days: int = 16, change_frac: float = 0.25,
                 cloud_frac: float = 0.15, sensor=None, n_changes: int = 1,
                 seasonal_gap_frac: float = 0.0):
        from firebird_tpu.ccd.sensor import LANDSAT_ARD

        self.seed = seed
        self.start, self.end = start, end
        self.cadence_days = cadence_days
        self.change_frac = change_frac
        self.cloud_frac = cloud_frac
        self.sensor = sensor or LANDSAT_ARD
        # Break-dense / gap-dense knobs (the bench's hard rung): several
        # well-separated step changes per affected patch, and winter
        # acquisitions dropped with the given probability (seasonal gaps
        # — the case pyccd's adjusted variogram exists for).
        self.n_changes = n_changes
        self.seasonal_gap_frac = seasonal_gap_frac

    def _rng(self, cx: int, cy: int, salt: int = 0) -> np.random.Generator:
        return np.random.default_rng(
            abs(hash((int(self.seed), int(cx), int(cy), salt))) % (2**63))

    def chip(self, cx: int, cy: int, acquired: str | None = None) -> ChipData:
        # Generate the full archive first, slice at the end: the same chip
        # queried with different acquired windows must agree on overlapping
        # dates (like FileSource slicing a fixed archive).
        rng = self._rng(cx, cy)
        sn = self.sensor
        B, csd = sn.n_bands, sn.chip_side
        t = synthetic.acquisition_dates(self.start, self.end, self.cadence_days)
        T = t.shape[0]
        ph = harmonic.day_phase(t).astype(np.float32)

        means, amps = synthetic.means_amps(sn)
        means = means.astype(np.float32)
        amps = amps.astype(np.float32)
        # Per-pixel level field (spatially smooth-ish random offsets).
        level = rng.normal(0, 60, size=(csd, csd)).astype(np.float32)

        spectra = np.empty((B, T, csd, csd), np.int16)
        noise_scale = 30.0
        for b in range(B):
            base = (means[b] + amps[b] * np.cos(ph))[:, None, None]
            series = base + level[None, :, :] + rng.normal(
                0, noise_scale, size=(T, csd, csd)).astype(np.float32)
            spectra[b] = np.clip(series, -32768, 32767).astype(np.int16)

        # Step changes in a patch, at chip-specific dates.  n_changes > 1
        # spaces the change dates evenly through the middle of the archive
        # (each segment must still span INIT_DAYS with MEOW_SIZE obs to
        # re-initialize, so breaks land >= ~2 years apart for the default
        # grids).
        if self.change_frac > 0:
            side = max(1, int(csd * np.sqrt(self.change_frac)))
            r0 = int(rng.integers(0, csd - side + 1))
            c0 = int(rng.integers(0, csd - side + 1))
            nch = max(1, int(self.n_changes))
            lo, hi = T // 6, 5 * T // 6
            ks = (lo + (np.arange(nch) + rng.uniform(0.2, 0.8, nch))
                  * (hi - lo) / nch).astype(int) if nch > 1 \
                else np.array([int(rng.integers(T // 4, 3 * T // 4))])
            cum = np.zeros(B)
            for k in ks:
                delta = rng.uniform(500, 1000)
                # Keep shifted values inside the valid data ranges (params
                # OPTICAL/THERMAL): a negative step is only allowed when
                # the band's seasonal low PLUS the offset accumulated by
                # earlier changes still clears the range floor — otherwise
                # in_range() would discard every post-change observation.
                # (cum starts at 0, so the first change reduces to the
                # original single-change guard.)
                sign = np.where(rng.random(B) < 0.5, -1.0, 1.0)
                seasonal_low = means - amps
                sign = np.where(seasonal_low + cum < delta + 300, 1.0, sign)
                for b in range(B):
                    spectra[b, k:, r0:r0 + side, c0:c0 + side] = np.clip(
                        spectra[b, k:, r0:r0 + side, c0:c0 + side]
                        + np.int16(sign[b] * delta), -32768, 32767)
                cum += sign * delta

        qas = np.full((T, csd, csd), synthetic.QA_CLEAR, np.uint16)
        cloudy = rng.random(T) < self.cloud_frac
        if self.seasonal_gap_frac > 0:
            doy = np.mod(t.astype(np.float64), 365.25)
            winter = (doy < 75) | (doy > 320)
            cloudy = cloudy | (winter
                               & (rng.random(T) < self.seasonal_gap_frac))
        qas[cloudy] = synthetic.QA_CLOUD

        t, spectra, qas = _slice_acquired(t, spectra, qas, acquired)
        return ChipData(cx=int(cx), cy=int(cy), dates=t, spectra=spectra,
                        qas=qas, sensor=sn)

    def list_acquisitions(self, since: float = 0.0) -> list[dict]:
        """The acquisition manifest (streamops/watcher.py contract):
        ``[{scene_id, published, date, bbox}, ...]`` with ``published >
        since``.  One deterministic scene per cadence date covering the
        whole grid (bbox None); ``published`` is the fabricated
        timestamp ``ordinal * 86400`` — monotone in acquisition date,
        so cursor tests are reproducible (the dir-backed FileSource
        manifest carries real wall-clock publish times)."""
        t = synthetic.acquisition_dates(self.start, self.end,
                                        self.cadence_days)
        out = []
        for d in t:
            published = float(d) * 86400.0
            if published <= since:
                continue
            iso = dt.to_iso(int(d))
            out.append({"scene_id": f"synthetic-{self.seed}-{iso}",
                        "published": published, "date": iso,
                        "bbox": None})
        return out

    def aux(self, cx: int, cy: int, acquired: str | None = None) -> dict:
        """AUX layers: one [100,100] array per AUX_NAMES entry."""
        rng = self._rng(cx, cy, salt=1)
        row = np.arange(CHIP_SIDE, dtype=np.float32)
        grad = row[None, :] + row[:, None]
        out = {
            "dem": (300 + 5 * grad + rng.normal(0, 20, (CHIP_SIDE, CHIP_SIDE))).astype(np.float32),
            "aspect": rng.integers(0, 360, (CHIP_SIDE, CHIP_SIDE)).astype(np.int16),
            "posidex": rng.random((CHIP_SIDE, CHIP_SIDE)).astype(np.float32),
            "slope": np.abs(rng.normal(5, 3, (CHIP_SIDE, CHIP_SIDE))).astype(np.float32),
            "mpw": (rng.random((CHIP_SIDE, CHIP_SIDE)) < 0.1).astype(np.uint8),
            # Land-cover training labels in blobs; 0 and 9 are the values the
            # reference filters out of training (randomforest.py:63).
            "trends": (1 + (grad // 50) % 8).astype(np.uint8),
        }
        return out


# ---------------------------------------------------------------------------
# File-backed fixture source
# ---------------------------------------------------------------------------

class FileSource:
    """Chips stored as .npz files in a directory: chip_{cx}_{cy}.npz with
    arrays dates/spectra/qas, aux_{cx}_{cy}.npz with the AUX names.

    The directory doubles as a landing zone for the acquisition
    watcher: a ``scenes.jsonl`` manifest next to the chips records each
    delivered scene (one JSON line: scene_id, published, date, bbox),
    appended by :meth:`append_scene` after the chip archives are
    updated — so a watcher listing the manifest never sees a scene
    whose pixels have not landed yet."""

    SCENES_FILE = "scenes.jsonl"

    def __init__(self, root: str):
        self.root = root

    def _path(self, prefix: str, cx: int, cy: int) -> str:
        return f"{self.root}/{prefix}_{int(cx)}_{int(cy)}.npz"

    def chip(self, cx: int, cy: int, acquired: str | None = None) -> ChipData:
        z = np.load(self._path("chip", cx, cy))
        t, spectra, qas = _slice_acquired(z["dates"], z["spectra"], z["qas"],
                                          acquired)
        return ChipData(cx=int(cx), cy=int(cy), dates=t, spectra=spectra, qas=qas)

    def aux(self, cx: int, cy: int, acquired: str | None = None) -> dict:
        z = np.load(self._path("aux", cx, cy))
        return {k: z[k] for k in AUX_NAMES}

    def save_chip(self, c: ChipData) -> None:
        """Atomic archive write (tmp + rename): a reader fetching the
        chip mid-landing sees the previous archive, never a torn one."""
        path = self._path("chip", c.cx, c.cy)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, dates=c.dates, spectra=c.spectra,
                                qas=c.qas)
        os.replace(tmp, path)

    def save_aux(self, cx: int, cy: int, aux: dict) -> None:
        np.savez_compressed(self._path("aux", cx, cy), **aux)

    def append_scene(self, scene_id: str, *, date: str,
                     published: float | None = None, bbox=None) -> dict:
        """Publish one scene on the manifest (AFTER its chip archives
        landed — see class docstring).  Returns the manifest record."""
        import time as _time

        rec = {"scene_id": str(scene_id),
               "published": float(published if published is not None
                                  else _time.time()),
               "date": str(date),
               "bbox": None if bbox is None else [float(v) for v in bbox]}
        with open(os.path.join(self.root, self.SCENES_FILE), "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec

    def list_acquisitions(self, since: float = 0.0) -> list[dict]:
        """The acquisition manifest (streamops/watcher.py contract):
        scenes with ``published > since`` from ``scenes.jsonl``.  A
        truncated trailing line (a writer mid-append) is skipped — it
        re-lists complete on the next poll."""
        path = os.path.join(self.root, self.SCENES_FILE)
        out = []
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            return out
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue            # torn tail append; next poll has it
            if float(rec.get("published", 0.0)) > since:
                out.append(rec)
        return out


# ---------------------------------------------------------------------------
# Chipmunk HTTP source
# ---------------------------------------------------------------------------

class UnsupportedWireError(ValueError):
    """A service registry declares band dtypes the packed kernel wire format
    (int16 spectra / uint16 QA) cannot carry.  Deliberately NOT swallowed by
    the registry='auto' fallback: falling back to the built-in Collection-01
    tables against such a service would just query ubids it doesn't serve."""

# LCMAP ARD Collection-01 ubid layout: logical band -> ubids across
# platforms (merlin's chipmunk-ard profile; ubid example 'le07_srb1' in
# test/data/chip_response.json).
ARD_UBIDS = {
    "blues":    ("lt04_srb1", "lt05_srb1", "le07_srb1", "lc08_srb2"),
    "greens":   ("lt04_srb2", "lt05_srb2", "le07_srb2", "lc08_srb3"),
    "reds":     ("lt04_srb3", "lt05_srb3", "le07_srb3", "lc08_srb4"),
    "nirs":     ("lt04_srb4", "lt05_srb4", "le07_srb4", "lc08_srb5"),
    "swir1s":   ("lt04_srb5", "lt05_srb5", "le07_srb5", "lc08_srb6"),
    "swir2s":   ("lt04_srb7", "lt05_srb7", "le07_srb7", "lc08_srb7"),
    "thermals": ("lt04_btb6", "lt05_btb6", "le07_btb6", "lc08_btb10"),
    "qas":      ("lt04_pixelqa", "lt05_pixelqa", "le07_pixelqa", "lc08_pixelqa"),
}
BAND_ORDER = params.BAND_NAMES_PLURAL

AUX_UBIDS = {
    "dem": ("AUX_DEM",), "trends": ("AUX_TRENDS",), "aspect": ("AUX_ASPECT",),
    "posidex": ("AUX_POSIDEX",), "slope": ("AUX_SLOPE",), "mpw": ("AUX_MPW",),
}

# Fallback wire dtypes when no /registry is reachable (values transcribed
# from the reference's recorded registry, test/data/registry_response.json:
# SR/BT INT16, PIXELQA UINT16, ASPECT INT16, DEM/POSIDEX/SLOPE FLOAT32,
# MPW/TRENDS BYTE).
_FALLBACK_AUX_WIRE = {"dem": np.float32, "trends": np.uint8,
                      "aspect": np.int16, "posidex": np.float32,
                      "slope": np.float32, "mpw": np.uint8}


def _fallback_wire_dtypes() -> dict[str, np.dtype]:
    out = {}
    for name in BAND_ORDER:
        for u in ARD_UBIDS[name]:
            out[u] = np.dtype(np.int16)
    for u in ARD_UBIDS["qas"]:
        out[u] = np.dtype(np.uint16)
    for name, ubids in AUX_UBIDS.items():
        for u in ubids:
            out[u] = np.dtype(_FALLBACK_AUX_WIRE[name])
    return out


def decode_raster(rec: dict, dtype=np.int16, side: int = CHIP_SIDE) -> np.ndarray:
    """Decode one chip record's base64 payload to a [side,side] array.

    Payload is little-endian (int16 spectra, uint16 QA, float32/byte AUX) —
    the wire format seen in test/data/chip_response.json.  The decode runs
    in the native data plane, straight into the result buffer.
    """
    data = rec["data"]
    wire = np.dtype(dtype).newbyteorder("<")
    out = np.empty(len(data) * 3 // 4 // wire.itemsize + 1, wire)
    n = native.b64_decode_into(data, out)
    if n % wire.itemsize:
        raise ValueError(
            f"chip payload of {n} bytes is not a multiple of the "
            f"{wire.itemsize}-byte wire dtype — truncated or corrupt")
    a = out[:n // wire.itemsize]
    if wire != np.dtype(dtype):  # big-endian host: swap to native order
        a = a.astype(dtype)
    return a.reshape(side, side)


DEFAULT_HTTP_TIMEOUT = 60.0


def _default_http_get(url: str, timeout: float = DEFAULT_HTTP_TIMEOUT) \
        -> list | dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


class ChipmunkSource:
    """HTTP client for the Chipmunk raster service.

    ``http_get`` is injectable (url -> parsed JSON) so tests run without a
    network, mirroring the reference's function-injection seam; it is
    called from ``band_parallelism`` threads concurrently and MUST be
    thread-safe.  ``band_parallelism`` fans the 8 logical bands of one
    chip out over a thread pool — a chip is 32 HTTP requests (8 bands x 4
    platform ubids), and fetching them serially leaves the request latency
    unamortized (the reference's INPUT_PARTITIONS only parallelizes across
    chips); total in-flight requests = input_parallelism x
    band_parallelism (Config.band_parallelism; 1 restores the strict
    INPUT_PARTITIONS ceiling).

    ``timeout`` bounds each HTTP request of the default client
    (``FIREBIRD_HTTP_TIMEOUT`` via Config.http_timeout — previously a
    hardcoded 60 s).

    ``registry='auto'`` (default) fetches ``/registry`` once, lazily, and
    derives the ubid maps, wire dtypes, and chip side from it (merlin's
    registry_fn role, SURVEY.md §2.2); on failure it falls back to the
    built-in Collection-01 tables with a warning.  Pass a
    :class:`~firebird_tpu.ingest.registry.Registry` to pin one, or ``None``
    to force the built-in tables.
    """

    def __init__(self, url: str, http_get=None, band_parallelism: int = 8,
                 registry="auto", timeout: float = DEFAULT_HTTP_TIMEOUT):
        import threading

        if timeout <= 0:
            raise ValueError(f"http timeout must be > 0 s, got {timeout}")
        self.url = url.rstrip("/")
        self.timeout = float(timeout)
        # The timeout binds only when the default urllib client is in
        # play; an injected http_get owns its own transport policy.
        self.http_get = http_get or (
            lambda u: _default_http_get(u, timeout=self.timeout))
        self.band_parallelism = max(int(band_parallelism), 1)
        self._registry = registry
        self._resolved = None
        self._resolve_lock = threading.Lock()
        # Case-resolution memo (see _band_series): ubid -> casing the
        # service actually answers; _prefer_lower flips after the first
        # successful lowercase retry so later ubids query lowercase first.
        # GIL-atomic dict/flag writes; worst case under a race is one
        # redundant HTTP request.
        self._ubid_case: dict[str, str] = {}
        self._prefer_lower = False

    @staticmethod
    def _derive(reg):
        """(ard_ubids, aux_ubids, {ubid: wire dtype}, sensor) from a
        Registry.  A split deployment serves ARD and AUX from different
        services (Config.ard_url / aux_url), so a registry listing only one
        half is valid: the missing half keeps the built-in tables."""
        import dataclasses

        from firebird_tpu.ccd.sensor import LANDSAT_ARD

        try:
            ard = reg.ard_ubids()
        except LookupError as e:
            log.warning("registry ARD half unusable (%s); keeping the "
                        "built-in Collection-01 ARD tables", e)
            ard = None
        try:
            aux = reg.aux_ubids()
        except LookupError as e:
            log.warning("registry AUX half unusable (%s); keeping the "
                        "built-in Collection-01 AUX tables", e)
            aux = None
        if ard is None and aux is None:
            raise LookupError("registry has neither ARD nor AUX bands")
        used = [u for ubids in (*(ard or {}).values(), *(aux or {}).values())
                for u in ubids]
        dtypes = {u: reg.wire_dtype(u) for u in used}
        if ard is not None:
            # The packed kernel wire format is int16 spectra / uint16 QA
            # (PackedChips contract); a registry declaring float spectra
            # must fail loudly, not truncate on assignment.
            for band, ubids in ard.items():
                want = np.uint16 if band == "qas" else np.int16
                bad = [u for u in ubids if dtypes[u] != want]
                if bad:
                    raise UnsupportedWireError(
                        f"registry band {band!r} ubids {bad} declare wire "
                        f"dtypes {[str(dtypes[u]) for u in bad]}; the packed "
                        f"kernel wire format requires {np.dtype(want).name}")
        side = reg.chip_side(used)
        if (ard is None or aux is None) and side != CHIP_SIDE:
            # The built-in tables describe the fixed 100x100 Collection-01
            # service; mixing them with a different registry geometry would
            # decode the fallback half at the wrong shape.
            raise LookupError(
                f"partial registry declares chip side {side}, but the "
                f"built-in tables covering its missing half are "
                f"{CHIP_SIDE}x{CHIP_SIDE}")
        fallback = _fallback_wire_dtypes()
        if ard is None:
            ard = ARD_UBIDS
            dtypes.update((u, fallback[u])
                          for us in ARD_UBIDS.values() for u in us)
        if aux is None:
            aux = AUX_UBIDS
            dtypes.update((u, fallback[u])
                          for us in AUX_UBIDS.values() for u in us)
        sensor = LANDSAT_ARD
        if side != sensor.chip_side:
            # Chip extent is the grid's 3 km; a denser registry shape
            # means finer pixels (e.g. side 300 -> 10 m).
            sensor = dataclasses.replace(
                sensor, name=f"{sensor.name}-{side}", chip_side=side,
                pixel_size_m=max(1, (sensor.chip_side *
                                     sensor.pixel_size_m) // side))
        log.info("chipmunk registry: %d ubids across %d logical bands, "
                 "chip side %d", len(used), len(ard) + len(aux), side)
        return ard, aux, dtypes, sensor

    def _resolve(self):
        """(ard_ubids, aux_ubids, {ubid: wire dtype}, sensor) — from the
        service registry when reachable, built-in Collection-01 tables
        otherwise.  A pinned Registry propagates derivation errors; 'auto'
        falls back with a warning.  Locked: the driver calls chip() from
        input_parallelism threads, and every chip in a run must see one
        sensor spec (packer requires a single spec per batch)."""
        with self._resolve_lock:
            if self._resolved is None:
                from firebird_tpu.ccd.sensor import LANDSAT_ARD
                from firebird_tpu.ingest.registry import Registry

                reg = self._registry
                if isinstance(reg, str) and reg == "auto":
                    try:
                        self._resolved = self._derive(
                            Registry.fetch(self.http_get, self.url))
                    except UnsupportedWireError:
                        raise
                    except Exception as e:
                        log.warning(
                            "chipmunk /registry unusable at %s (%s); using "
                            "built-in Collection-01 ubid tables", self.url, e)
                        reg = None
                if self._resolved is None:
                    if reg is None:
                        self._resolved = (ARD_UBIDS, AUX_UBIDS,
                                          _fallback_wire_dtypes(), LANDSAT_ARD)
                    else:
                        self._resolved = self._derive(reg)
            return self._resolved

    def _chips(self, ubid: str, x: int, y: int, acquired: str) -> list:
        q = urllib.parse.urlencode(
            {"ubid": ubid, "x": x, "y": y, "acquired": acquired})
        with obs_metrics.timer() as tm:
            recs = self.http_get(f"{self.url}/chips?{q}") or []
        obs_metrics.histogram("ingest_http_seconds").observe(tm.elapsed)
        obs_metrics.counter("ingest_http_requests").inc()
        # Decoded payload size (base64 is 4/3 of the raster bytes) — the
        # only honest bytes-in figure available above the socket layer,
        # since http_get returns parsed JSON.
        obs_metrics.counter("ingest_bytes_in").inc(
            sum(len(r.get("data", "")) for r in recs
                if isinstance(r, dict)) * 3 // 4)
        return recs

    def _band_series(self, ubids, cx, cy, acquired, dtypes,
                     side) -> dict[int, np.ndarray]:
        """{ordinal_date: raster} merged across a logical band's ubids.

        The recorded service contract disagrees on ubid case (/registry
        serves 'LE07_SRB1', the working /chips capture uses 'le07_srb1' —
        reference test/data/{registry,chip}_response.json), so an empty
        result for a mixed-case ubid is retried lowercased before being
        treated as genuinely absent; the resolved casing is memoized per
        ubid (and as a source-wide preference) so absent-platform chips
        don't pay the double request on every query.
        """
        series: dict[int, np.ndarray] = {}
        for ubid in ubids:
            first = self._ubid_case.get(
                ubid, ubid.lower() if self._prefer_lower else ubid)
            recs = self._chips(first, cx, cy, acquired)
            if recs:
                self._ubid_case.setdefault(ubid, first)
            elif first != ubid.lower():
                recs = self._chips(ubid.lower(), cx, cy, acquired)
                if recs:
                    self._ubid_case[ubid] = ubid.lower()
                    self._prefer_lower = True
            for rec in recs:
                d = dt.to_ordinal(rec["acquired"][:10])
                if d not in series:  # first writer wins; skip wasted decodes
                    series[d] = decode_raster(rec, dtypes[ubid], side)
        return series

    def chip(self, cx: int, cy: int, acquired: str | None = None) -> ChipData:
        import concurrent.futures as cf

        acquired = acquired or dt.default_acquired()
        ard, _aux, dtypes, sensor = self._resolve()
        side = sensor.chip_side
        bands = sensor.band_names_plural
        names = list(bands) + ["qas"]
        with cf.ThreadPoolExecutor(self.band_parallelism) as ex:
            series = dict(zip(names, ex.map(
                lambda n: self._band_series(ard[n], cx, cy, acquired,
                                            dtypes, side), names)))
        per_band = {n: series[n] for n in bands}
        qa_series = series["qas"]
        # Date alignment: keep acquisitions present in every band + QA
        # (merlin's alignment step, SURVEY.md §3.3).
        common = set(qa_series)
        for s in per_band.values():
            common &= set(s)
        t = np.array(sorted(common), dtype=np.int64)
        # The service's own acquired filter is inclusive; re-apply the
        # half-open [start, end) window here so every source agrees on
        # boundary ownership (_slice_acquired docstring).
        lo, hi = dt.acquired_range(acquired)
        t = t[(t >= lo) & (t < hi)]
        T = t.shape[0]
        spectra = np.empty((sensor.n_bands, T, side, side), np.int16)
        for b, name in enumerate(bands):
            for k, d in enumerate(t):
                spectra[b, k] = per_band[name][int(d)]
        qas = np.stack([qa_series[int(d)] for d in t]) if T else \
            np.zeros((0, side, side), np.uint16)
        log.debug("chipmunk chip (%s,%s): %d aligned acquisitions", cx, cy, T)
        return ChipData(cx=int(cx), cy=int(cy), dates=t, spectra=spectra,
                        qas=qas, sensor=sensor)

    def aux(self, cx: int, cy: int, acquired: str | None = None) -> dict:
        acquired = acquired or dt.default_acquired()
        _ard, auxm, dtypes, sensor = self._resolve()
        side = sensor.chip_side
        out = {}
        for name, ubids in auxm.items():
            series = self._band_series(ubids, cx, cy, acquired, dtypes, side)
            if not series:
                raise LookupError(f"no AUX {name} at ({cx},{cy})")
            out[name] = series[min(series)]
        return out
