"""Ingest: chip sources and dense device packing.

Replaces the reference's merlin/Chipmunk data plane (ccdc/timeseries.py +
the external merlin package).  The reference fans one chip id out to 10,000
per-pixel Python rows inside a Spark flatMap (timeseries.py:120-125) and
repartitions them over the cluster; here a chip stays a dense array — the
packer emits device-ready batches ``[chips, bands, pixels, time]`` and the
TPU kernel vmaps over the pixel axis.  No shuffle exists because sharding is
a static, even split of the chip batch (SURVEY.md §2.4).

Sources are pluggable (the reference's test seam is merlin cfg function
injection, test/conftest.py:20-37; ours is the :class:`ChipSource`
protocol): synthetic (deterministic, for tests/bench), file-backed
fixtures, and a Chipmunk HTTP client.
"""

from firebird_tpu.ingest.packer import ChipData, PackedChips, pack, pixel_timeseries
from firebird_tpu.ingest.sources import SyntheticSource, FileSource, ChipmunkSource

__all__ = [
    "ChipData", "PackedChips", "pack", "pixel_timeseries",
    "SyntheticSource", "FileSource", "ChipmunkSource",
]
