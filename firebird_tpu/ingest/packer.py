"""Dense packing of chip time series for device dispatch.

The unit of I/O is the chip: 100x100 pixels x 7 spectral bands + QA over T
acquisitions (SURVEY.md §0).  A :class:`ChipData` holds one chip's aligned
arrays; :func:`pack` batches several into a :class:`PackedChips` with the
time axis padded to a bucket size so XLA sees few distinct shapes
(SURVEY.md §7 "ragged time dimension -> padding/bucketing policy").

Padding convention: padded observations carry QA = fill (bit 0 set) and
spectra = FILL_VALUE, so the kernel's QA triage drops them with no special
cases — padding is indistinguishable from fill data, which the algorithm
already handles.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from firebird_tpu import native
from firebird_tpu.ccd import params
from firebird_tpu.ccd.sensor import LANDSAT_ARD, Sensor

CHIP_SIDE = 100          # pixels per chip side (registry data_shape [100,100])
PIXELS = CHIP_SIDE * CHIP_SIDE
PIXEL_SIZE_M = 30        # Landsat ARD pixel, meters

QA_FILL_PACKED = np.uint16(1 << params.QA_FILL_BIT)


@dataclasses.dataclass
class ChipData:
    """One chip's date-aligned time series.

    dates:   [T] ordinal days, ascending.
    spectra: [B, T, side, side] int16 (sensor band order; Landsat ARD:
             blue..thermal, [7, T, 100, 100]).
    qas:     [T, side, side] uint16 bit-packed QA.
    sensor:  the band/geometry spec (default: the reference's Landsat ARD).
    """

    cx: int
    cy: int
    dates: np.ndarray
    spectra: np.ndarray
    qas: np.ndarray
    sensor: Sensor = LANDSAT_ARD

    def __post_init__(self):
        T = self.dates.shape[0]
        side = self.sensor.chip_side
        assert self.spectra.shape == (self.sensor.n_bands, T, side, side), \
            (self.spectra.shape, self.sensor.name)
        assert self.qas.shape == (T, side, side), self.qas.shape
        assert T < 2 or bool(np.all(np.diff(self.dates) >= 0)), "dates must ascend"


@dataclasses.dataclass
class PackedChips:
    """A device-ready batch of chips.

    cids:    [C, 2] int64 chip ids (cx, cy).
    dates:   [C, T] int32, ascending within the valid prefix, 0-padded.
    spectra: [C, B, P, T] int16, FILL_VALUE-padded.
    qas:     [C, P, T] uint16, fill-bit padded.
    n_obs:   [C] int32 valid observation count per chip.
    sensor:  the shared band/geometry spec of every chip in the batch.

    P = side*side pixels in row-major order: pixel index p = row*side + col
    where (row, col) counts from the chip's upper-left, so the pixel's
    projection coordinate is (px, py) = (cx + col*psz, cy - row*psz).
    Landsat ARD: P = 10000, psz = 30 m.
    """

    cids: np.ndarray
    dates: np.ndarray
    spectra: np.ndarray
    qas: np.ndarray
    n_obs: np.ndarray
    sensor: Sensor = LANDSAT_ARD

    @property
    def n_chips(self) -> int:
        return self.cids.shape[0]

    @property
    def capacity(self) -> int:
        return self.dates.shape[1]

    def pixel_coords(self, c: int) -> np.ndarray:
        """[P, 2] (px, py) projection coordinates of chip c's pixels."""
        cx, cy = self.cids[c]
        side, psz = self.sensor.chip_side, self.sensor.pixel_size_m
        cols = np.arange(side) * psz
        rows = np.arange(side) * psz
        px = cx + np.tile(cols, side)
        py = cy - np.repeat(rows, side)
        return np.stack([px, py], axis=1).astype(np.int64)


def bucket_capacity(T: int, bucket: int, max_obs: int) -> int:
    """Round T up to a bucket multiple, capped at max_obs."""
    cap = ((max(T, 1) + bucket - 1) // bucket) * bucket
    return min(cap, max_obs) if max_obs else cap


def pack(chips: list[ChipData], *, bucket: int = 64, max_obs: int = 0) -> PackedChips:
    """Pack chips into one padded batch.

    If a chip has more observations than max_obs (when nonzero), the oldest
    are kept and the newest truncated — logged as a warning here, because
    truncation loses data: max_obs (FIREBIRD_MAX_OBS) should be sized to
    the archive (a 40-year Landsat series at 16-day cadence with two
    platforms is ~1800 acquisitions).
    """
    assert chips, "cannot pack zero chips"
    sensor = chips[0].sensor
    assert all(c.sensor == sensor for c in chips), \
        "all chips in a batch must share one sensor spec"
    B, npix = sensor.n_bands, sensor.pixels
    T_max = max(c.dates.shape[0] for c in chips)
    cap = bucket_capacity(T_max, bucket, max_obs)
    if T_max > cap:
        from firebird_tpu.obs import logger

        logger("timeseries").warning(
            "archive exceeds the packed capacity: a chip has %d "
            "acquisitions but max_obs caps the time axis at %d — the "
            "newest %d are DROPPED; raise FIREBIRD_MAX_OBS to cover the "
            "archive", T_max, cap, T_max - cap)

    C = len(chips)
    cids = np.zeros((C, 2), np.int64)
    dates = np.zeros((C, cap), np.int32)
    # The transpose-with-padding writes every cell, so plain empty buffers;
    # the heavy [B,T,side,side] -> [B,P,cap] layout change runs in the
    # native data plane when available (firebird_tpu/native/fastpack.cpp).
    spectra = np.empty((C, B, npix, cap), np.int16)
    qas = np.empty((C, npix, cap), np.uint16)
    n_obs = np.zeros(C, np.int32)

    for i, c in enumerate(chips):
        T = min(c.dates.shape[0], cap)
        cids[i] = (c.cx, c.cy)
        dates[i, :T] = c.dates[:T]
        native.pack_spectra(c.spectra[:, :T].reshape(B, T, npix),
                            cap, params.FILL_VALUE, out=spectra[i])
        native.pack_qa(c.qas[:T].reshape(T, npix), cap,
                       int(QA_FILL_PACKED), out=qas[i])
        n_obs[i] = T
    return PackedChips(cids=cids, dates=dates, spectra=spectra, qas=qas,
                       n_obs=n_obs, sensor=sensor)


def pixel_timeseries(p: PackedChips, c: int, pix: int) -> dict:
    """Extract one pixel as the detect() keyword contract — the bridge to
    the per-pixel oracle and the reference's row shape
    (ccdc/timeseries.py:104-115)."""
    T = int(p.n_obs[c])
    d = {n: p.spectra[c, b, pix, :T].copy()
         for b, n in enumerate(p.sensor.band_names_plural)}
    d["dates"] = p.dates[c, :T].astype(np.int64)
    d["qas"] = p.qas[c, pix, :T].copy()
    return d
