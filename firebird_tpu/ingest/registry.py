"""Chipmunk ``/registry``-driven band discovery.

The reference resolves band ubids and chip geometry from the Chipmunk
``/registry`` endpoint through merlin's ``registry_fn`` (profile wiring at
ccdc/__init__.py:25-26; the recorded service contract is
test/data/registry_response.json — 97 entries of
``{ubid, data_type, data_shape, tags, ...}``).  Round 1 hardcoded the
Collection-01 ubid maps (:data:`sources.ARD_UBIDS` / :data:`sources.AUX_UBIDS`);
this module derives them from the service so a Collection-2 or new-sensor
deployment is configuration, not code edits (VERDICT.md round-1 missing #4).

Selection rules, golden-tested against the reference's recorded registry
(tests/test_registry.py):

- spectral band -> entries tagged ``{'sr', <color>}`` for color in
  blue / green / red / nir / swir1 / swir2
- QA            -> entries tagged ``{'pixelqa'}``
- thermal       -> entries tagged ``{'bt'}``; when one platform exposes
  several brightness-temperature bands (LC08 BTB10 + BTB11) the
  lowest-numbered wins — reproducing merlin's chipmunk-ard choice of
  ``lc08_btb10``
- AUX layer     -> entries tagged with the layer name (``dem``, ``trends``,
  ``aspect``, ``posidex``, ``slope``, ``mpw``)

Platforms are grouped by the ubid prefix before ``_`` (``lc08``, ``le07``,
``lt05``, ``lt04``) so each platform contributes at most one ubid per
logical band.
"""

from __future__ import annotations

import re

import numpy as np

from firebird_tpu.obs import logger

log = logger("timeseries")

#: Chipmunk data_type strings -> numpy wire dtypes (registry fixture uses
#: INT16 / UINT16 / UINT8 / BYTE / FLOAT32).
DATA_TYPES = {
    "INT8": np.int8, "UINT8": np.uint8, "BYTE": np.uint8,
    "INT16": np.int16, "UINT16": np.uint16,
    "INT32": np.int32, "UINT32": np.uint32,
    "FLOAT32": np.float32, "FLOAT64": np.float64,
}

#: Logical ARD band -> tag query (every tag must be present).
ARD_TAG_RULES = {
    "blues": ("sr", "blue"),
    "greens": ("sr", "green"),
    "reds": ("sr", "red"),
    "nirs": ("sr", "nir"),
    "swir1s": ("sr", "swir1"),
    "swir2s": ("sr", "swir2"),
    "thermals": ("bt",),
    "qas": ("pixelqa",),
}

AUX_TAG_RULES = {
    "dem": ("dem",), "trends": ("trends",), "aspect": ("aspect",),
    "posidex": ("posidex",), "slope": ("slope",), "mpw": ("mpw",),
}


def _natural_key(s: str):
    """Case-insensitive natural sort key: 'BTB10' after 'BTB6'."""
    return [int(p) if p.isdigit() else p
            for p in re.split(r"(\d+)", s.lower())]


class Registry:
    """Parsed ``/registry`` response with band/dtype/geometry lookups."""

    def __init__(self, entries: list[dict]):
        self.entries = list(entries)
        self._by_ubid = {e["ubid"]: e for e in self.entries}

    @classmethod
    def fetch(cls, http_get, url: str) -> "Registry":
        """GET ``{url}/registry`` with an injectable url->JSON callable."""
        entries = http_get(url.rstrip("/") + "/registry") or []
        if not entries:
            raise LookupError(f"empty /registry at {url}")
        return cls(entries)

    @property
    def ubids(self) -> tuple[str, ...]:
        return tuple(self._by_ubid)

    def select(self, *tags: str) -> tuple[str, ...]:
        """ubids whose tag set contains every query tag (case-insensitive),
        natural-sorted for determinism."""
        want = {t.lower() for t in tags}
        hit = [e["ubid"] for e in self.entries
               if want <= {str(t).lower() for t in e.get("tags", ())}]
        return tuple(sorted(hit, key=_natural_key))

    @staticmethod
    def _platform(ubid: str) -> str:
        return ubid.split("_", 1)[0].lower()

    @staticmethod
    def _platform_key(platform: str):
        """Order platforms by trailing mission number (lt04 < lt05 < le07 <
        lc08): the downstream date-collision merge is first-writer-wins
        (sources._band_series), and the built-in Collection-01 tables give
        the older platform priority — the registry-derived order must not
        silently flip that."""
        m = re.search(r"(\d+)$", platform)
        return (int(m.group(1)) if m else -1, platform)

    def _one_per_platform(self, ubids) -> tuple[str, ...]:
        """Keep the lowest-numbered ubid per platform (LC08 BTB10 < BTB11),
        platforms in mission order."""
        best: dict[str, str] = {}
        for u in ubids:
            p = self._platform(u)
            if p not in best or _natural_key(u) < _natural_key(best[p]):
                best[p] = u
        return tuple(best[p] for p in sorted(best, key=self._platform_key))

    def ard_ubids(self) -> dict[str, tuple[str, ...]]:
        """Logical ARD band -> per-platform ubids (sources.ARD_UBIDS shape)."""
        out = {}
        for band, tags in ARD_TAG_RULES.items():
            ubids = self._one_per_platform(self.select(*tags))
            if not ubids:
                raise LookupError(f"registry has no ubids for band {band!r} "
                                  f"(tags {tags})")
            out[band] = ubids
        return out

    def aux_ubids(self) -> dict[str, tuple[str, ...]]:
        out = {}
        for name, tags in AUX_TAG_RULES.items():
            ubids = self.select(*tags)
            if not ubids:
                raise LookupError(f"registry has no AUX ubids for {name!r}")
            out[name] = ubids
        return out

    def entry(self, ubid: str) -> dict:
        try:
            return self._by_ubid[ubid]
        except KeyError:
            raise LookupError(f"ubid {ubid!r} not in registry") from None

    def wire_dtype(self, ubid: str) -> np.dtype:
        dt = str(self.entry(ubid).get("data_type", "")).upper()
        try:
            return np.dtype(DATA_TYPES[dt])
        except KeyError:
            raise LookupError(
                f"ubid {ubid!r} has unknown data_type {dt!r}") from None

    def data_shape(self, ubid: str) -> tuple[int, int]:
        shape = self.entry(ubid).get("data_shape") or None
        if not shape or len(shape) != 2:
            raise LookupError(f"ubid {ubid!r} has no data_shape")
        return int(shape[0]), int(shape[1])

    def chip_side(self, ubids=None) -> int:
        """The common square chip side across `ubids` (default: all entries
        that declare a shape).  Mixed or non-square shapes are an error —
        the packer requires one geometry per campaign."""
        sides = set()
        for u in (ubids if ubids is not None else self.ubids):
            try:
                h, w = self.data_shape(u)
            except LookupError:
                continue
            if h != w:
                raise ValueError(f"non-square chip {u!r}: {h}x{w}")
            sides.add(h)
        if not sides:
            raise LookupError("registry declares no data_shape")
        if len(sides) > 1:
            raise ValueError(f"mixed chip sides in registry: {sorted(sides)}")
        return sides.pop()
