"""Deterministic fault injection: the testable half of graceful degradation.

The reference delegated every transient-failure path to Spark's task retry
(driver/core.py:482-484 notes this explicitly), which meant its failure
handling was *exercised in production only*.  This module makes the
replacement's failure paths drillable: a seeded fault plan
(``FIREBIRD_FAULTS`` / ``Config.faults``) wraps the ingest source, aux
source, store backend, and async writer in thin proxies that raise
realistic errors on a deterministic schedule — so ``make chaos-smoke``
(tools/chaos_soak.py) can prove that an ingest brownout or a store blip
costs retries, never results.

Plan grammar (scopes separated by ``;``, options by ``,``)::

    FIREBIRD_FAULTS="ingest:p=0.05,seed=7;store:after=40,brownout=3"

======================  =====================================================
scope target            what the injector wraps
======================  =====================================================
``ingest``              ``source.chip`` (and ``source.aux`` when the same
                        object serves both)
``aux``                 ``aux_source.aux``
``store``               ``store.write`` (the backend, under the writer)
``writer``              ``AsyncWriter.write`` (the enqueue seam)
``lease``               fleet-worker lease heartbeats (fleet/worker.py): an
                        injected failure drops the beat, so the lease ages
                        toward expiry — ``lease:p=1`` models a worker
                        partitioned from the queue (a zombie)
``serve``               ``/v1`` request handling (serve/api.py): an injected
                        failure answers 503 — ``serve:after=K,brownout=M``
                        models a serving brownout the black-box prober
                        (obs/prober.py) must detect from outside
``watch``               ``watcher.poll_once`` (streamops/watcher.py): an
                        injected failure aborts the poll before any scene
                        is mapped, so the landing zone backs up — a stalled
                        watcher the prober's alert probe sees as missed
                        end-to-end deadlines
``object``              every object-tier operation
                        (store/objectstore.py): puts/gets/heads/lists
                        fail per the schedule; the ``torn`` kind (puts
                        only) additionally leaves a *torn upload* behind
                        — see below.  ``chip=`` is rejected here: object
                        ops carry no chip identity
======================  =====================================================

======================  =====================================================
option                  meaning
======================  =====================================================
``p=<float>``           each operation fails independently with probability p
``after=<int>``         operations ``after+1 .. after+brownout`` fail — a
                        one-shot brownout window (brownout defaults to 1)
``brownout=<int>``      window length for ``after``; with ``p``, each
                        triggered failure extends to that many consecutive ops
``chip=<cx>:<cy>``      poison one chip id: every op for it fails
                        (ingest/aux scopes only; repeatable)
``seed=<int>``          RNG seed for ``p`` (default 0) — the plan is fully
                        deterministic given the seed and call order
``timeout``             raise :class:`InjectedTimeout` (TimeoutError)
``conn``                raise :class:`InjectedConnError` (ConnectionError)
``ioerror``             raise :class:`InjectedFault` (OSError) — the default
``torn``                object scope only: raise :class:`TornUpload` AND
                        leave a genuinely torn upload on disk — occurrences
                        alternate deterministically between committing a
                        truncated chunk (the manifest promises bytes that
                        are not there) and dropping the manifest write (the
                        chunks upload, the object never becomes visible).
                        NonRetryable by design: the damage must persist for
                        the reader-side recovery drills, not be healed by
                        the retry wrapper
======================  =====================================================

With ``FIREBIRD_FAULTS`` unset, :func:`wrap_source` / :func:`wrap_store` /
:func:`wrap_writer` return their argument unchanged — no proxy object, no
per-call overhead, nothing on the hot path.  Every injected failure
increments ``faults_injected`` (and ``faults_injected_<scope>``) so a chaos
run's telemetry shows exactly how much adversity it absorbed.
"""

from __future__ import annotations

import random
import threading
import zlib

from firebird_tpu import retry as retrylib
from firebird_tpu.obs import metrics as obs_metrics

TARGETS = ("ingest", "aux", "store", "writer", "lease", "serve", "watch",
           "object")
_KINDS = ("ioerror", "timeout", "conn", "torn")


class InjectedFault(OSError):
    """A fault-plan-injected I/O error (the default kind)."""


class InjectedTimeout(TimeoutError):
    """A fault-plan-injected timeout."""


class InjectedConnError(ConnectionError):
    """A fault-plan-injected connection failure."""


class TornUpload(OSError, retrylib.NonRetryable):
    """A fault-plan-injected torn object upload (``object`` scope).

    NonRetryable on purpose: the proxy has already left real damage on
    disk (a truncated chunk under a committed manifest, or uploaded
    chunks with the manifest write dropped), and the drill is the
    *reader's* recovery path — a retry wrapper silently re-putting would
    erase the very state under test."""


_ERRORS = {"ioerror": InjectedFault, "timeout": InjectedTimeout,
           "conn": InjectedConnError, "torn": TornUpload}


class FaultSpec:
    """One scope's parsed options (see the module grammar table)."""

    def __init__(self, target: str, *, p: float = 0.0,
                 after: int | None = None, brownout: int = 1,
                 seed: int = 0, kind: str = "ioerror",
                 chips: frozenset | None = None):
        if target not in TARGETS:
            raise ValueError(
                f"fault scope target must be one of {TARGETS}, got "
                f"{target!r}")
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"fault p must be in [0, 1], got {p}")
        if after is not None and after < 0:
            raise ValueError(f"fault after must be >= 0, got {after}")
        if brownout < 1:
            raise ValueError(f"fault brownout must be >= 1, got {brownout}")
        if kind not in _KINDS:
            raise ValueError(f"fault kind must be one of {_KINDS}, got "
                             f"{kind!r}")
        if kind == "torn" and target != "object":
            # A torn upload is an object-tier phenomenon (chunks vs
            # manifest); on any other scope it would be a misspelled
            # ioerror that silently changed semantics.
            raise ValueError(
                f"fault kind 'torn' only applies to the object scope, "
                f"not {target!r}")
        if chips and target not in ("ingest", "aux"):
            # store/writer ops carry no chip identity, so chip= there
            # would validate yet never fire — the silent-no-op chaos run
            # the config-time parse exists to prevent.
            raise ValueError(
                f"chip= poisoning only applies to ingest/aux scopes, not "
                f"{target!r}")
        if p <= 0 and after is None and not chips:
            raise ValueError(
                f"fault scope {target!r} injects nothing: set p=, after=, "
                "or chip=")
        self.target = target
        self.p = float(p)
        self.after = after
        self.brownout = int(brownout)
        self.seed = int(seed)
        self.kind = kind
        self.chips = chips or frozenset()


def _parse_scope(scope: str) -> FaultSpec:
    target, sep, body = scope.partition(":")
    target = target.strip()
    if not sep or not body.strip():
        raise ValueError(
            f"fault scope {scope!r} must be '<target>:<opt>[,<opt>...]'")
    kw: dict = {"chips": set()}
    for raw in body.split(","):
        opt = raw.strip()
        if not opt:
            continue
        if opt in _KINDS:
            kw["kind"] = opt
            continue
        key, sep, val = opt.partition("=")
        if not sep:
            raise ValueError(
                f"unknown fault option {opt!r} in scope {target!r} "
                f"(flags: {_KINDS})")
        key = key.strip()
        try:
            if key == "p":
                kw["p"] = float(val)
            elif key == "after":
                kw["after"] = int(val)
            elif key == "brownout":
                kw["brownout"] = int(val)
            elif key == "seed":
                kw["seed"] = int(val)
            elif key == "chip":
                cx, _, cy = val.partition(":")
                kw["chips"].add((int(cx), int(cy)))
            else:
                raise ValueError(
                    f"unknown fault option key {key!r} in scope {target!r}")
        except ValueError as e:
            if "unknown fault option" in str(e):
                raise
            raise ValueError(
                f"bad value for fault option {key!r}: {val!r}") from e
    kw["chips"] = frozenset(kw["chips"])
    return FaultSpec(target, **kw)


class FaultInjector:
    """One scope's live failure schedule.  Thread-safe: the driver calls
    ingest ops from ``input_parallelism`` threads and store ops from the
    writer pool, and the op counter / RNG / brownout window must agree on
    one call order to stay deterministic per seed."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self._lock = threading.Lock()
        # random.Random, not numpy: one bounded uniform draw per op, and
        # the stdlib generator is cheap to seed per scope.  crc32, not
        # hash(): str hashing is salted per process and the whole point
        # is a plan that replays identically across runs.
        self._rng = random.Random(spec.seed ^ zlib.crc32(
            spec.target.encode()))
        self._ops = 0
        self._brownout_until = 0      # ops <= this value fail (window)
        self._after_fired = False

    def fire(self, chip=None) -> None:
        """Count one operation; raise the scope's error when the schedule
        says this op fails.  ``chip`` is the (cx, cy) the op serves, for
        ``chip=`` poisoning."""
        spec = self.spec
        with self._lock:
            self._ops += 1
            n = self._ops
            fail = False
            if chip is not None and tuple(int(v) for v in chip) in spec.chips:
                fail = True
            elif n <= self._brownout_until:
                fail = True
            elif spec.after is not None and not self._after_fired \
                    and n > spec.after:
                self._after_fired = True
                self._brownout_until = n + spec.brownout - 1
                fail = True
            elif spec.p > 0 and self._rng.random() < spec.p:
                if spec.brownout > 1:
                    self._brownout_until = n + spec.brownout - 1
                fail = True
        if fail:
            obs_metrics.counter(
                "faults_injected",
                help="failures raised by the FIREBIRD_FAULTS plan").inc()
            obs_metrics.counter(f"faults_injected_{spec.target}").inc()
            raise _ERRORS[spec.kind](
                f"injected {spec.kind} fault ({spec.target} op {n}"
                f"{f', chip {chip}' if chip is not None else ''})")

    def snapshot(self) -> dict:
        with self._lock:
            return {"target": self.spec.target, "ops": self._ops}


class FaultPlan:
    """The parsed ``FIREBIRD_FAULTS`` spec: one injector per scope."""

    def __init__(self, specs: list[FaultSpec], spec_text: str = ""):
        seen = set()
        for s in specs:
            if s.target in seen:
                raise ValueError(
                    f"duplicate fault scope {s.target!r} in plan")
            seen.add(s.target)
        self.spec_text = spec_text
        self._injectors = {s.target: FaultInjector(s) for s in specs}

    @classmethod
    def parse(cls, text: str | None) -> "FaultPlan | None":
        """Plan from the env-spec string; None when unset/empty (the
        zero-cost default — callers skip wrapping entirely)."""
        if not text or not text.strip():
            return None
        specs = [_parse_scope(s) for s in text.split(";") if s.strip()]
        if not specs:
            return None
        return cls(specs, spec_text=text)

    @classmethod
    def from_config(cls, cfg) -> "FaultPlan | None":
        return cls.parse(getattr(cfg, "faults", ""))

    def injector(self, target: str) -> FaultInjector | None:
        return self._injectors.get(target)


# ---------------------------------------------------------------------------
# Proxies: thin, explicit seams; identity when the plan has no scope
# ---------------------------------------------------------------------------

class FaultySource:
    """Source proxy: injects before ``chip``/``aux`` delegation.  ``chip``
    fires the wrapping scope's injector with the chip id (so ``chip=``
    poisoning works); ``aux`` fires the plan's ``aux`` scope when present,
    else this scope.  Either injector may be None (an aux-only plan still
    wraps the source so its ``aux`` calls inject)."""

    def __init__(self, inner, injector: FaultInjector | None,
                 aux_injector: FaultInjector | None = None):
        self._inner = inner
        self._inj = injector
        self._aux_inj = aux_injector or injector

    def chip(self, cx, cy, acquired=None):
        if self._inj is not None:
            self._inj.fire(chip=(cx, cy))
        return self._inner.chip(cx, cy, acquired)

    def aux(self, cx, cy, acquired=None):
        if self._aux_inj is not None:
            self._aux_inj.fire(chip=(cx, cy))
        return self._inner.aux(cx, cy, acquired)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyStore:
    """Store-backend proxy: injects before ``write``; reads pass through
    (the durability model is write-side — a read failure is a different
    campaign's problem)."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._inj = injector

    def write(self, table: str, frame: dict) -> int:
        self._inj.fire()
        return self._inner.write(table, frame)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyWriter:
    """AsyncWriter proxy: injects at the enqueue seam (``write``) — the
    failure mode where the *host-side* egress path dies rather than the
    backend behind it."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._inj = injector

    def write(self, table: str, frame: dict, key=None) -> None:
        self._inj.fire()
        return self._inner.write(table, frame, key=key)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyObjectStore:
    """Object-store proxy (store/objectstore.py protocol).

    ``put`` always rides the injector; the read-side ops (get/head/list/
    delete) ride it only for the transient kinds — a ``torn`` schedule
    is about *uploads*, and firing it on reads would raise TornUpload
    from operations that cannot tear anything.

    On a TornUpload the proxy first performs the damaged put for real —
    alternating deterministically between a truncated final chunk
    (``_torn="chunk"``: manifest commits over missing bytes) and a
    dropped manifest (``_torn="manifest"``: chunks land, the object
    never becomes visible) — then re-raises, so the on-disk state
    matches what a crashed uploader leaves behind."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._inj = injector
        self._torn_lock = threading.Lock()
        self._torn_count = 0

    def _fire_transient(self):
        if self._inj.spec.kind != "torn":
            self._inj.fire()

    def put(self, key, data, **kw):
        try:
            self._inj.fire()
        except TornUpload:
            with self._torn_lock:
                mode = "chunk" if self._torn_count % 2 == 0 else "manifest"
                self._torn_count += 1
            self._inner.put(key, data, **{**kw, "_torn": mode})
            raise
        return self._inner.put(key, data, **kw)

    def get(self, key):
        self._fire_transient()
        return self._inner.get(key)

    def head(self, key):
        self._fire_transient()
        return self._inner.head(key)

    def list(self, prefix=""):
        self._fire_transient()
        return self._inner.list(prefix)

    def delete(self, key):
        self._fire_transient()
        return self._inner.delete(key)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def wrap_source(source, plan: FaultPlan | None, scope: str = "ingest"):
    """Source under the plan's ``scope`` injector; the source itself
    (zero indirection) when no plan covers either the scope or ``aux``
    (an aux-only plan still needs the proxy for its ``aux`` calls)."""
    if plan is None:
        return source
    inj = plan.injector(scope)
    aux_inj = plan.injector("aux")
    if inj is None and aux_inj is None:
        return source
    return FaultySource(source, inj, aux_injector=aux_inj)


def wrap_store(store, plan: FaultPlan | None):
    if plan is None:
        return store
    inj = plan.injector("store")
    return store if inj is None else FaultyStore(store, inj)


def wrap_writer(writer, plan: FaultPlan | None):
    if plan is None:
        return writer
    inj = plan.injector("writer")
    return writer if inj is None else FaultyWriter(writer, inj)


def wrap_objectstore(store, plan: FaultPlan | None):
    if plan is None:
        return store
    inj = plan.injector("object")
    return store if inj is None else FaultyObjectStore(store, inj)
