"""Observability: logging, counters, and timers.

The reference logs exclusively through JVM log4j over the py4j bridge
(ccdc/__init__.py:60-76 "the jvm is what is actually doing all the logging"),
with per-subsystem categories configured in resources/log4j.properties:48-53
(`ids`, `change-detection`, `random-forest-training`,
`random-forest-classification`, `timeseries`, `pyccd`).

Here there is no JVM: plain Python logging with the same category names, an
ISO8601 stderr format mirroring log4j.properties:20-24, plus the metrics the
reference lacks (SURVEY.md §5): chip/pixel/segment throughput counters.
"""

from __future__ import annotations

import logging
import sys
import threading
import time

# Per-subsystem categories, mirroring resources/log4j.properties:48-53.
CATEGORIES = (
    "ids",
    "change-detection",
    "random-forest-training",
    "random-forest-classification",
    "timeseries",
    "pyccd",
)

_configured = False
_lock = threading.Lock()


def configure(level: int | None = None) -> None:
    """Install the ISO8601 stderr handler once (idempotent).

    Levels mirror the reference's per-subsystem log4j categories
    (log4j.properties:48-53): FIREBIRD_LOG_LEVEL sets the root, and
    FIREBIRD_LOG_LEVELS="pyccd=DEBUG,timeseries=WARNING" overrides
    individual categories.
    """
    import os

    global _configured
    with _lock:
        if _configured:
            return
        root = logging.getLogger("firebird")
        if not root.handlers:      # never stack duplicate handlers
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(
                logging.Formatter(
                    fmt="%(asctime)s %(levelname)s %(name)s: %(message)s",
                    datefmt="%Y-%m-%dT%H:%M:%S",
                )
            )
            root.addHandler(handler)
        if level is None:
            level = _parse_level(os.environ.get("FIREBIRD_LOG_LEVEL", "INFO"),
                                 logging.INFO)
        root.setLevel(level)
        root.propagate = False
        for spec in os.environ.get("FIREBIRD_LOG_LEVELS", "").split(","):
            if "=" in spec:
                name, _, lv = spec.partition("=")
                logging.getLogger(f"firebird.{name.strip()}").setLevel(
                    _parse_level(lv, logging.INFO))
        _configured = True


def _parse_level(name: str, default: int) -> int:
    """Level name -> int; log4j's TRACE maps to DEBUG; unknown names fall
    back to the default with a stderr warning instead of silently lying
    about (or crashing on) the requested level."""
    n = name.strip().upper()
    levels = dict(logging.getLevelNamesMapping())
    levels["TRACE"] = logging.DEBUG
    if n in levels:
        return levels[n]
    print(f"firebird: unknown log level {name!r}, using "
          f"{logging.getLevelName(default)}", file=sys.stderr)
    return default


def logger(name: str) -> logging.Logger:
    """Get a per-subsystem logger (replaces ccdc.logger(ctx, name))."""
    configure()
    return logging.getLogger(f"firebird.{name}")


class Counters:
    """Thread-safe throughput counters.

    The reference has no metrics system (SURVEY.md §5); these close that gap.
    Typical keys: chips, pixels, segments, bytes_in, bytes_out.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._t0 = time.monotonic()

    def add(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def get(self, key: str) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def snapshot(self) -> dict:
        with self._lock:
            elapsed = time.monotonic() - self._t0
            out = dict(self._counts)
        out["elapsed_sec"] = elapsed
        for k in list(out):
            if k != "elapsed_sec" and elapsed > 0:
                out[f"{k}_per_sec"] = out[k] / elapsed
        return out


class timer:
    """Context manager measuring wall time in seconds (``.elapsed``)."""

    def __enter__(self):
        self._t0 = time.monotonic()
        self.elapsed = 0.0
        return self

    def __exit__(self, *exc):
        self.elapsed = time.monotonic() - self._t0
        return False
