"""Observability: logging, counters, and timers.

The reference logs exclusively through JVM log4j over the py4j bridge
(ccdc/__init__.py:60-76 "the jvm is what is actually doing all the logging"),
with per-subsystem categories configured in resources/log4j.properties:48-53
(`ids`, `change-detection`, `random-forest-training`,
`random-forest-classification`, `timeseries`, `pyccd`).

Here there is no JVM: plain Python logging with the same category names, an
ISO8601 stderr format mirroring log4j.properties:20-24, plus the metrics the
reference lacks (SURVEY.md §5): chip/pixel/segment throughput counters.
"""

from __future__ import annotations

import logging
import sys
import threading
import time

# Per-subsystem categories, mirroring resources/log4j.properties:48-53.
CATEGORIES = (
    "ids",
    "change-detection",
    "random-forest-training",
    "random-forest-classification",
    "timeseries",
    "pyccd",
)

_configured = False
_lock = threading.Lock()


def configure(level: int = logging.INFO) -> None:
    """Install the ISO8601 stderr handler once (idempotent)."""
    global _configured
    with _lock:
        if _configured:
            return
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                fmt="%(asctime)s %(levelname)s %(name)s: %(message)s",
                datefmt="%Y-%m-%dT%H:%M:%S",
            )
        )
        root = logging.getLogger("firebird")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True


def logger(name: str) -> logging.Logger:
    """Get a per-subsystem logger (replaces ccdc.logger(ctx, name))."""
    configure()
    return logging.getLogger(f"firebird.{name}")


class Counters:
    """Thread-safe throughput counters.

    The reference has no metrics system (SURVEY.md §5); these close that gap.
    Typical keys: chips, pixels, segments, bytes_in, bytes_out.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._t0 = time.monotonic()

    def add(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def get(self, key: str) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def snapshot(self) -> dict:
        with self._lock:
            elapsed = time.monotonic() - self._t0
            out = dict(self._counts)
        out["elapsed_sec"] = elapsed
        for k in list(out):
            if k != "elapsed_sec" and elapsed > 0:
                out[f"{k}_per_sec"] = out[k] / elapsed
        return out


class timer:
    """Context manager measuring wall time in seconds (``.elapsed``)."""

    def __enter__(self):
        self._t0 = time.monotonic()
        self.elapsed = 0.0
        return self

    def __exit__(self, *exc):
        self.elapsed = time.monotonic() - self._t0
        return False
