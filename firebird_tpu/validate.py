"""Kernel-vs-oracle parity audit on live data.

The framework's correctness claim is *bit-identical break dates* against
the per-pixel CPU reference implementation (BASELINE.md north star).  The
test suite pins that on fixtures; this module makes it an operational
check a user can run against any chip — synthetic, file-backed, or a real
Chipmunk endpoint — and any dtype:

    firebird validate -x 542000 -y 1650000 -n 200 --dtype float64

runs the accelerator kernel over the chip, replays ``n`` sampled pixels
through the float64 NumPy oracle (the pyccd stand-in,
firebird_tpu.ccd.reference), and prints a JSON agreement report.  Exit
status is non-zero when structural agreement (procedures, model counts,
break/start/end days, processing masks) is not 100%, so the command slots
into smoke suites as-is.
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.ccd import kernel
from firebird_tpu.ccd.reference import detect_sensor
from firebird_tpu.config import Config
from firebird_tpu.ingest import pack
from firebird_tpu.obs import logger

log = logger("validate")

STRUCTURAL = ("procedure", "n_models", "break_day", "start_day", "end_day",
              "processing_mask", "curve_qa", "observation_count")


def _rel_err(a: float, b: float) -> float:
    return abs(a - b) / max(abs(a), abs(b), 1e-9)


def validate_chip(packed, n_pixels: int = 100, dtype="float64",
                  seed: int = 0) -> dict:
    """Audit one packed chip: kernel at ``dtype`` vs the float64 oracle on
    ``n_pixels`` sampled pixels.  Returns the report dict."""
    import jax.numpy as jnp

    if n_pixels <= 0:
        raise ValueError("n_pixels must be positive — auditing zero pixels "
                         "would report vacuous agreement")
    dtype = jnp.dtype(dtype)
    seg = kernel.detect_packed(packed, dtype=dtype)
    one = kernel.chip_slice(seg, 0, to_host=True)
    T = int(packed.n_obs[0])
    dates = packed.dates[0][:T]

    P = one.n_segments.shape[0]
    rng = np.random.default_rng(seed)
    pix = rng.permutation(P)[: min(n_pixels, P)]

    mismatch = {f: 0 for f in STRUCTURAL}
    chprob_max = 0.0
    numeric = {"coefficients": 0.0, "intercept": 0.0, "rmse": 0.0,
               "magnitude": 0.0}
    bands_checked = 0
    for p_ in pix:
        # the sensor-generic oracle, so non-Landsat sources audit too
        o = detect_sensor(dates, packed.spectra[0, :, int(p_), :T],
                          packed.qas[0, int(p_), :T], packed.sensor)
        k = kernel.segments_to_records(one, dates, int(p_),
                                       sensor=packed.sensor)
        if k["procedure"] != o["procedure"]:
            mismatch["procedure"] += 1
            continue
        if k["processing_mask"] != o["processing_mask"]:
            mismatch["processing_mask"] += 1
        om_, km_ = o["change_models"], k["change_models"]
        if len(om_) != len(km_):
            mismatch["n_models"] += 1
            continue
        pixel_bad = set()
        for om, km in zip(om_, km_):
            for f in ("break_day", "start_day", "end_day", "curve_qa",
                      "observation_count"):
                if om[f] != km[f]:
                    pixel_bad.add(f)
            chprob_max = max(chprob_max, abs(om["change_probability"]
                                             - km["change_probability"]))
            for name in packed.sensor.band_names:
                bands_checked += 1
                numeric["rmse"] = max(numeric["rmse"],
                                      _rel_err(om[name]["rmse"],
                                               km[name]["rmse"]))
                numeric["magnitude"] = max(numeric["magnitude"],
                                           _rel_err(om[name]["magnitude"],
                                                    km[name]["magnitude"]))
                numeric["intercept"] = max(numeric["intercept"],
                                           _rel_err(om[name]["intercept"],
                                                    km[name]["intercept"]))
                for a, b in zip(om[name]["coefficients"],
                                km[name]["coefficients"]):
                    numeric["coefficients"] = max(numeric["coefficients"],
                                                  _rel_err(a, b))
        for f in pixel_bad:  # count mismatching *pixels*, not models —
            mismatch[f] += 1  # the agreement ratio denominator is pixels

    n = int(len(pix))
    structural_ok = not any(mismatch.values())
    return {
        "pixels_audited": n,
        "dtype": str(dtype),
        "obs_per_pixel": int(packed.n_obs[0]),
        "structural_agreement": structural_ok,
        "mismatches": mismatch,
        "break_day_agreement": (n - mismatch["procedure"]
                                - mismatch["n_models"]
                                - mismatch["break_day"]) / max(n, 1),
        "change_probability_max_abs_err": chprob_max,
        "numeric_max_rel_err": numeric,
        "band_segments_checked": bands_checked,
    }


def validate(x=None, y=None, acquired: str | None = None,
             n_pixels: int = 100, dtype: str = "float64", seed: int = 0,
             cfg: Config | None = None, source=None) -> dict:
    """Fetch one chip (the chip containing (x, y), or a default synthetic
    chip) and audit it.  See :func:`validate_chip`."""
    from firebird_tpu import grid
    from firebird_tpu.driver.core import make_source
    from firebird_tpu.utils import dates as dt

    cfg = cfg or Config.from_env()
    if (x is None) != (y is None):
        raise ValueError("validate needs both x and y (or neither, for "
                         "the default synthetic chip)")
    if x is None and source is None:
        # No location given: audit the documented default *synthetic* chip
        # regardless of the configured source — chip (100, 200) is not a
        # grid-aligned id a real endpoint could serve.
        from firebird_tpu.ingest import SyntheticSource

        source = SyntheticSource(seed=0)
    source = source or make_source(cfg)
    if x is None:
        cx, cy = 100, 200
    else:
        cx, cy = (int(v) for v in
                  grid.snap(float(x), float(y))["chip"]["proj-pt"])
    acquired = acquired or dt.default_acquired()
    log.info("validate: chip (%d, %d), %d pixels, dtype %s",
             cx, cy, n_pixels, dtype)
    packed = pack([source.chip(cx, cy, acquired)], bucket=cfg.obs_bucket,
                  max_obs=cfg.max_obs)
    report = validate_chip(packed, n_pixels=n_pixels, dtype=dtype, seed=seed)
    report["chip"] = [cx, cy]
    return report
