"""Async host-side writer: egress overlaps device compute.

The reference throttles spark-cassandra concurrent writes from executors
(CASSANDRA_OUTPUT_CONCURRENT_WRITES, ccdc/__init__.py:20); here a bounded
queue + worker pool drains table frames while the TPU crunches the next
batch.  ``flush()`` blocks until everything queued has landed and raises
any pending write error (once — the error is cleared so the driver's
per-chunk isolation can continue with later chunks, ccdc/core.py:115-124
semantics).  ``close()`` never raises: a terminal error is logged and the
workers are always shut down.

Ordering: frames written with the same ``key`` drain through the same
worker in submission order — the driver keys by chip id so the resume
invariant holds (the segment frame lands last per chip, driver/core.py).
Keyless writes round-robin and carry no ordering guarantee beyond a
single worker.
"""

from __future__ import annotations

import itertools
import queue
import threading

from firebird_tpu.obs import logger
from firebird_tpu.obs import metrics as obs_metrics
from firebird_tpu.obs import tracing

log = logger("change-detection")


def _frame_rows(frame: dict) -> int:
    """Row count of a table frame (all columns share one length)."""
    for v in frame.values():
        try:
            return len(v)
        except TypeError:
            continue
    return 0


class AsyncWriter:
    """``retry`` is an optional :class:`firebird_tpu.retry.RetryPolicy`
    applied around each backend ``store.write`` — a store brownout of a
    few ops heals inline (counted as ``store_write_retries``) instead of
    poisoning the writer and failing the whole chunk's flush."""

    def __init__(self, store, max_queue: int = 16, workers: int = 1,
                 retry=None):
        self.store = store
        self.retry = retry
        n = max(int(workers), 1)
        self._qs = [queue.Queue(maxsize=max_queue) for _ in range(n)]
        self._lock = threading.Lock()
        # First pending write error: set by any worker, popped (and
        # cleared) by the caller thread in write()/flush().
        self._error: Exception | None = None  # guarded-by: _lock
        self._rr = itertools.count()
        self._threads = []
        for q in self._qs:
            t = threading.Thread(target=self._run, args=(q,), daemon=True)
            t.start()
            self._threads.append(t)

    def _run(self, q: queue.Queue):
        while True:
            item = q.get()
            if item is None:
                q.task_done()
                return
            table, frame, ctx = item
            try:
                with self._lock:
                    poisoned = self._error is not None
                if not poisoned:
                    # The enqueueing thread's TraceContext rides the
                    # queue item: this write's span, exemplar, and any
                    # log line parent to the BATCH that produced the
                    # frame, not to an anonymous writer thread.  The
                    # observe stays INSIDE the activation so the
                    # histogram exemplar sees the batch id.
                    with tracing.activate(ctx):
                        with tracing.span("store_write", table=table), \
                                obs_metrics.timer() as tm:
                            if self.retry is not None:
                                self.retry.run(
                                    log, f"store write to {table}",
                                    lambda: self.store.write(table, frame))
                            else:
                                self.store.write(table, frame)
                        obs_metrics.histogram(
                            "store_write_seconds").observe(tm.elapsed)
                    obs_metrics.counter(
                        "store_rows_written",
                        help="rows landed in the results store").inc(
                        _frame_rows(frame))
            except BaseException as e:  # incl. KeyboardInterrupt: a dead
                # worker with un-acked items would hang flush() forever
                log.error("async write to %s failed: %s", table, e)
                obs_metrics.counter("store_write_errors").inc()
                with self._lock:
                    self._error = e if isinstance(e, Exception) \
                        else RuntimeError(f"writer interrupted: {e!r}")
            finally:
                # Depth BEFORE task_done: the ack releases flush()'s
                # join(), and the gauge must already reflect the drain
                # (success or failure alike) when flush returns — a
                # failing backend must not leave a phantom backlog.
                self._update_depth()
                q.task_done()

    def _pop_error(self) -> Exception | None:
        with self._lock:
            err, self._error = self._error, None
        return err

    def peek_error(self) -> Exception | None:
        """The pending write error WITHOUT clearing it (write()/flush()
        still raise it).  The driver's chunk loop polls this between
        batches (driver/core.py detect_chunk): a stale-fence rejection
        (retry.NonRetryable) sitting here means a fleet job's lease is
        gone and every further write will reject, so the loop abandons
        the remaining compute instead of discovering the loss at the
        final flush."""
        with self._lock:
            return self._error

    def _check_alive(self) -> None:
        if not all(t.is_alive() for t in self._threads):
            raise RuntimeError("async writer thread is dead")

    def _update_depth(self) -> None:
        # Egress backpressure signal: total frames queued across workers.
        # Gate BEFORE the qsize sweep — each qsize takes that queue's
        # mutex, and the per-frame cost must vanish when metrics are off.
        if obs_metrics.metrics_enabled():
            obs_metrics.gauge("store_queue_depth").set(
                sum(q.qsize() for q in self._qs))

    def write(self, table: str, frame: dict, key=None) -> None:
        """Queue a frame.  Frames sharing ``key`` keep submission order.
        The caller's TraceContext (if any) is captured with the frame and
        re-activated around the backend write on the worker thread."""
        err = self._pop_error()
        if err is not None:
            raise err
        self._check_alive()
        i = (hash(key) if key is not None else next(self._rr)) % len(self._qs)
        self._qs[i].put((table, frame, tracing.current_context()))
        self._update_depth()

    def flush(self) -> None:
        self._check_alive()
        with tracing.span("store_flush"), obs_metrics.timer() as tm:
            for q in self._qs:
                q.join()
        obs_metrics.histogram("store_flush_seconds").observe(tm.elapsed)
        # Authoritative sweep AFTER the joins and BEFORE any raise: all
        # acks happened-before this point, so even if worker-side updates
        # interleaved badly the gauge lands at the true (empty) depth on
        # the failure path too — not just when every write succeeded.
        self._update_depth()
        err = self._pop_error()
        if err is not None:
            raise err

    def close(self) -> None:
        try:
            self.flush()
        except Exception as e:
            log.error("async writer closed with pending error: %s", e)
        for q in self._qs:
            q.put(None)
        for t in self._threads:
            t.join(timeout=30)
