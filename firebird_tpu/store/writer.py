"""Async host-side writer: egress overlaps device compute.

The reference throttles spark-cassandra concurrent writes from executors
(CASSANDRA_OUTPUT_CONCURRENT_WRITES, ccdc/__init__.py:20); here a bounded
queue + worker thread drains table frames while the TPU crunches the next
batch.  ``flush()`` blocks until everything queued has landed and raises
any pending write error (once — the error is cleared so the driver's
per-chunk isolation can continue with later chunks, ccdc/core.py:115-124
semantics).  ``close()`` never raises: a terminal error is logged and the
worker is always shut down.
"""

from __future__ import annotations

import queue
import threading

from firebird_tpu.obs import logger

log = logger("change-detection")


class AsyncWriter:
    def __init__(self, store, max_queue: int = 16):
        self.store = store
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._error: Exception | None = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            table, frame = item
            try:
                if self._error is None:
                    self.store.write(table, frame)
            except BaseException as e:  # incl. KeyboardInterrupt: a dead
                # worker with un-acked items would hang flush() forever
                log.error("async write to %s failed: %s", table, e)
                self._error = e if isinstance(e, Exception) \
                    else RuntimeError(f"writer interrupted: {e!r}")
            finally:
                self._q.task_done()

    def _pop_error(self) -> Exception | None:
        err, self._error = self._error, None
        return err

    def _check_alive(self) -> None:
        if not self._thread.is_alive():
            raise RuntimeError("async writer thread is dead")

    def write(self, table: str, frame: dict) -> None:
        err = self._pop_error()
        if err is not None:
            raise err
        self._check_alive()
        self._q.put((table, frame))

    def flush(self) -> None:
        self._check_alive()
        self._q.join()
        err = self._pop_error()
        if err is not None:
            raise err

    def close(self) -> None:
        try:
            self.flush()
        except Exception as e:
            log.error("async writer closed with pending error: %s", e)
        self._q.put(None)
        self._thread.join(timeout=30)
