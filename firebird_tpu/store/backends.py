"""Store backends: memory, sqlite, parquet.

The Store interface: ``write(table, frame)`` upserts a dict-of-columns
frame; ``read(table, where=None)`` returns a dict of columns (optionally
filtered by exact-match key values).  Frames are dicts of equal-length numpy
arrays / lists, as produced by firebird_tpu.ccd.format.chip_frames.

Idempotence: rows are keyed by the table's primary key (schema.py);
re-writing the same key replaces the row — the reference's rerun-upsert
semantics (mode('append') onto Cassandra PKs, ccdc/cassandra.py:62-63,
SURVEY.md §5).
"""

from __future__ import annotations

import json
import math
import os
import sqlite3
import threading
import time

import numpy as np

from firebird_tpu.store import schema


def _retry_locked(fn, attempts: int = 240, delay: float = 0.25):
    """Run fn, retrying while sqlite reports the database locked.

    The WAL-conversion pragma and schema DDL need exclusive access for an
    instant; when several processes open the same store simultaneously
    (multi-host runs sharing one sqlite file) the loser gets 'database is
    locked' immediately rather than waiting on the busy handler.  Setup is
    the only place this can happen — writes ride the busy timeout.
    """
    for attempt in range(attempts):
        try:
            return fn()
        except sqlite3.OperationalError as e:
            if "locked" not in str(e) or attempt == attempts - 1:
                raise
            time.sleep(delay)


def _normalize(v):
    """Plain-Python cell values; NaN becomes None uniformly across backends
    (the reference stores NULL for absent model fields, schema.cql)."""
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        v = float(v)
    if isinstance(v, float) and math.isnan(v):
        return None
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def _col_types(table: str) -> dict[str, str]:
    return dict(schema.TABLES[table]["columns"])


def _encode_cell(v, typ: str):
    """One frame cell -> wire value for the sqlite/cassandra backends:
    JSON columns serialize, packed-array columns become raw little-endian
    bytes, scalars normalize with NaN -> NULL."""
    if typ in schema.PACKED_DTYPES:
        # Pack ndarrays directly — normalizing first would round-trip
        # every row through a Python list on the host-bound egress path.
        if v is None:
            return None
        return np.asarray(v, schema.PACKED_DTYPES[typ]).tobytes()
    v = _normalize(v)
    if v is None:
        return None
    if typ == "JSON":
        return json.dumps(v)
    return v


def _encode_column(frame: dict, c: str, typ: str, n: int) -> list:
    """A whole column encoded at once — the per-cell Python of a naive
    encode loop dominates chip egress (38 cols x ~12k rows per chip)."""
    if c not in frame:
        return [None] * n
    vals = frame[c]
    if typ == "JSON" or typ in schema.PACKED_DTYPES:
        return [_encode_cell(v, typ) for v in vals]
    a = np.asarray(vals)
    if a.dtype == object or a.dtype.kind in "US":
        return [_normalize(v) for v in vals]
    out = a.tolist()
    if a.dtype.kind == "f" and np.isnan(a).any():
        out = [None if v != v else v for v in out]
    return out


def _decode_cell(v, typ: str):
    if v is None:
        return None
    if typ == "JSON":
        return json.loads(v)
    if typ in schema.PACKED_DTYPES:
        return np.frombuffer(v, schema.PACKED_DTYPES[typ]).tolist()
    return v


class MemoryStore:
    """Dict-backed store for tests: {table: {key_tuple: row_dict}}."""

    def __init__(self, keyspace: str = "default"):
        self.keyspace = keyspace
        self._tables: dict[str, dict] = {t: {} for t in schema.TABLES}
        self._lock = threading.Lock()

    def write(self, table: str, frame: dict) -> int:
        key = schema.primary_key(table)
        cols = list(frame.keys())
        n = len(next(iter(frame.values())))
        with self._lock:
            for i in range(n):
                row = {c: _normalize(frame[c][i]) for c in cols}
                self._tables[table][tuple(row[k] for k in key)] = row
        return n

    def read(self, table: str, where: dict | None = None) -> dict:
        with self._lock:
            rows = [r for r in self._tables[table].values()
                    if not where or all(r.get(k) == v for k, v in where.items())]
        cols = schema.columns(table)
        return {c: [r.get(c) for r in rows] for c in cols}

    def count(self, table: str) -> int:
        return len(self._tables[table])

    def chip_ids(self, table: str = "segment") -> set[tuple[int, int]]:
        """Distinct (cx, cy) present in a table (the reference's
        select(cx, cy).distinct(), ccdc/randomforest.py:67)."""
        with self._lock:
            return {k[:2] for k in self._tables[table]}

    def close(self):
        pass


class SqliteStore:
    """Sqlite-backed store with INSERT OR REPLACE upserts.

    One database file per keyspace (the reference namespaces by Cassandra
    keyspace derived from inputs+version, ccdc/__init__.py:29-44; here the
    keyspace is part of the filename).

    ``read_only=True`` opens a **replica connection**: a ``mode=ro`` URI
    open plus ``PRAGMA query_only=ON``, so the handle can never take the
    write lock — N serve replicas tailing one WAL database read
    concurrently with the writer's AsyncWriter and never contend on its
    lock (WAL readers see the last committed transaction; they block
    nothing and nothing blocks them).  Schema DDL is skipped (the writer
    owns it) and ``write`` refuses loudly before sqlite would.
    """

    def __init__(self, path: str, keyspace: str = "default",
                 read_only: bool = False):
        self.read_only = bool(read_only)
        if not self.read_only:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        root, ext = os.path.splitext(path)
        self.path = f"{root}.{keyspace}{ext or '.db'}"
        self.keyspace = keyspace
        if self.read_only and not os.path.exists(self.path):
            raise FileNotFoundError(
                f"read-only replica open of {self.path}: the database "
                "does not exist (the writer creates it; replicas only "
                "ever attach)")
        self._local = threading.local()
        self._all_conns: list[sqlite3.Connection] = []
        self._conns_lock = threading.Lock()
        if not self.read_only:
            self._create()

    def _conn(self) -> sqlite3.Connection:
        if not hasattr(self._local, "conn"):
            # check_same_thread=False so close() can shut every thread's
            # connection down; each thread still only *uses* its own.
            if self.read_only:
                # mode=ro refuses the write lock at the VFS layer;
                # query_only refuses at the SQL layer — defense in
                # depth, and neither converts journal modes (a replica
                # must never run the WAL-conversion DDL the writer owns).
                conn = sqlite3.connect(
                    f"file:{self.path}?mode=ro", uri=True, timeout=60,
                    check_same_thread=False)
                conn.execute("PRAGMA query_only=ON")
            else:
                conn = sqlite3.connect(self.path, timeout=60,
                                       check_same_thread=False)
                _retry_locked(
                    lambda: conn.execute("PRAGMA journal_mode=WAL"))
                # WAL + NORMAL is durable to application crash (not OS
                # crash); the durability model is rerun-idempotence
                # (keyed upserts), so trading fsync-per-commit for write
                # throughput is right.
                conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
            with self._conns_lock:
                self._all_conns.append(conn)
        return self._local.conn

    def _create(self):
        con = self._conn()
        sql_type = lambda typ: ("TEXT" if typ == "JSON" else
                                "BLOB" if typ in schema.PACKED_DTYPES else typ)
        for t, spec in schema.TABLES.items():
            cols = ", ".join(
                f'"{c}" {sql_type(typ)}' for c, typ in spec["columns"])
            pk = ", ".join(spec["key"])
            sql = (f'CREATE TABLE IF NOT EXISTS "{t}" '
                   f'({cols}, PRIMARY KEY ({pk}))')
            _retry_locked(lambda: con.execute(sql))
        # Secondary (cx, cy) index for the serve-path point reads.  The
        # segment PK's autoindex already leads with (cx, cy), but the
        # product PK leads with (name, date) — a `WHERE cx=? AND cy=?`
        # chip read there (serve cache fills, chip_ids) would scan the
        # whole table.  Explicit on both so the serving layer's access
        # pattern is index-backed regardless of which table it reads;
        # tests pin the query plan (tests/test_store.py).
        for t in ("segment", "product"):
            sql = (f'CREATE INDEX IF NOT EXISTS "idx_{t}_chip" '
                   f'ON "{t}" (cx, cy)')
            _retry_locked(lambda: con.execute(sql))
        con.commit()

    def write(self, table: str, frame: dict) -> int:
        if self.read_only:
            raise RuntimeError(
                f"write to {table!r} on a read-only replica connection "
                f"({self.path}): writes belong to the writer process "
                "(open_store(..., read_only=False))")
        types = _col_types(table)
        cols = list(types)
        n = len(next(iter(frame.values())))
        rows = list(zip(*(_encode_column(frame, c, types[c], n)
                          for c in cols)))
        ph = ", ".join("?" * len(cols))
        con = self._conn()
        con.executemany(
            f'INSERT OR REPLACE INTO "{table}" ({", ".join(cols)}) VALUES ({ph})',
            rows)
        con.commit()
        return n

    def read(self, table: str, where: dict | None = None) -> dict:
        types = _col_types(table)
        cols = list(types)
        sql = f'SELECT {", ".join(cols)} FROM "{table}"'
        args: list = []
        if where:
            sql += " WHERE " + " AND ".join(f'"{k}" = ?' for k in where)
            args = list(where.values())
        cur = self._conn().execute(sql, args)
        out: dict[str, list] = {c: [] for c in cols}
        for row in cur:
            for c, v in zip(cols, row):
                out[c].append(_decode_cell(v, types[c]))
        return out

    def count(self, table: str) -> int:
        return self._conn().execute(
            f'SELECT COUNT(*) FROM "{table}"').fetchone()[0]

    def chip_ids(self, table: str = "segment") -> set[tuple[int, int]]:
        k1, k2 = schema.primary_key(table)[:2]
        cur = self._conn().execute(
            f'SELECT DISTINCT "{k1}", "{k2}" FROM "{table}"')
        return {(r[0], r[1]) for r in cur}

    def close(self):
        with self._conns_lock:
            conns, self._all_conns = self._all_conns, []
        for conn in conns:
            try:
                conn.close()
            except sqlite3.Error:
                pass
        if hasattr(self._local, "conn"):
            del self._local.conn


class ParquetStore:
    """Parquet-backed store: one file per (table, partition key prefix).

    Idempotence by construction — a rerun of the same chip rewrites the same
    file.  Suited to bulk analytics egress; requires pyarrow.
    """

    def __init__(self, path: str, keyspace: str = "default"):
        self.root = os.path.join(path, keyspace)
        os.makedirs(self.root, exist_ok=True)

    # Partition prefix per table: one file per chip (cx, cy) for the three
    # result tables; the full (tx, ty, name) key for tile so models with
    # different names never clobber each other.
    _PART = {"chip": 2, "pixel": 2, "segment": 2, "tile": 3, "product": 4}

    def _file(self, table: str, frame: dict) -> str:
        key = schema.primary_key(table)[: self._PART[table]]
        part = "_".join(str(_normalize(frame[k][0])) for k in key)
        d = os.path.join(self.root, table)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{part}.parquet")

    def write(self, table: str, frame: dict) -> int:
        import pyarrow as pa
        import pyarrow.parquet as pq
        # One frame = one partition: the file is named after row 0's key
        # prefix, so rows for a second chip would silently land in (and
        # clobber) the first chip's file.
        keyp = schema.primary_key(table)[: self._PART[table]]
        first = tuple(_normalize(frame[k][0]) for k in keyp)
        for i in range(1, len(frame[keyp[0]])):
            if tuple(_normalize(frame[k][i]) for k in keyp) != first:
                raise ValueError(
                    f"ParquetStore.write({table!r}): frame spans multiple "
                    f"partitions {first} vs row {i}; write one partition "
                    "per frame")
        cols = {c: [_normalize(v) for v in frame[c]] for c in frame}
        pq.write_table(pa.table(cols), self._file(table, frame))
        return len(next(iter(frame.values())))

    def read(self, table: str, where: dict | None = None) -> dict:
        import pyarrow.parquet as pq
        d = os.path.join(self.root, table)
        cols = schema.columns(table)
        out: dict[str, list] = {c: [] for c in cols}
        if not os.path.isdir(d):
            return out
        # When the filter pins the whole partition key prefix, only that
        # partition's file can match — skip the full-table scan (a per-chip
        # read over a tile would otherwise be O(chips^2) file reads).
        keyp = schema.primary_key(table)[: self._PART[table]]
        if where and all(k in where for k in keyp):
            part = "_".join(str(_normalize(where[k])) for k in keyp)
            files = [f"{part}.parquet"] if os.path.exists(
                os.path.join(d, f"{part}.parquet")) else []
        else:
            files = sorted(os.listdir(d))
        for f in files:
            t = pq.read_table(os.path.join(d, f)).to_pydict()
            n = len(next(iter(t.values()), []))
            for i in range(n):
                if where and any(t.get(k, [None] * n)[i] != v
                                 for k, v in where.items()):
                    continue
                for c in cols:
                    out[c].append(t.get(c, [None] * n)[i])
        return out

    def count(self, table: str) -> int:
        return len(self.read(table)["cx" if table != "tile" else "tx"])

    def chip_ids(self, table: str = "segment") -> set[tuple[int, int]]:
        d = os.path.join(self.root, table)
        if not os.path.isdir(d):
            return set()
        # One file per (cx, cy) partition: parse keys from filenames,
        # skipping anything that isn't a well-formed partition file.
        out = set()
        for f in os.listdir(d):
            stem, ext = os.path.splitext(f)
            parts = stem.split("_")
            if ext != ".parquet" or len(parts) < 2:
                continue
            try:
                out.add((int(parts[0]), int(parts[1])))
            except ValueError:
                continue
        return out

    def close(self):
        pass


def sanitize_keyspace(keyspace: str) -> str:
    """A valid unquoted CQL keyspace identifier (cqlstr semantics,
    ccdc/__init__.py:44; CQL's unquoted-identifier grammar requires a
    leading *letter*, so digit- and underscore-leading names are prefixed
    ``ks_``).  A non-letter-leading name could never have been created
    unquoted by Cassandra itself, so the prefix cannot orphan existing
    data; the mapping is called out in deploy/README.md regardless.
    """
    from firebird_tpu.config import _cqlstr

    ks = _cqlstr(keyspace) or "default"
    return ks if ks[0].isalpha() else f"ks_{ks}"


def cassandra_ddl(keyspace: str, replication: int = 1) -> list[str]:
    """The CQL DDL statements for the result tables — the reference ships
    these as resources/schema.cql and loads them with `make db-schema`
    (Makefile:24-39); here the single source of truth is schema.TABLES and
    this generator (printed by `firebird schema`, executed verbatim by
    CassandraStore._ensure_schema)."""
    ks = sanitize_keyspace(keyspace)
    stmts = [
        f"CREATE KEYSPACE IF NOT EXISTS {ks} WITH replication"
        f" = {{'class': 'SimpleStrategy', 'replication_factor': "
        f"{int(replication)}}}"]
    for t, spec in schema.TABLES.items():
        cols = ", ".join(f"{c} {CassandraStore._TYPES[typ]}"
                         for c, typ in spec["columns"])
        key = spec["key"]
        pk = (f"(({key[0]}, {key[1]})"
              + ("".join(f", {k}" for k in key[2:])) + ")")
        stmts.append(f"CREATE TABLE IF NOT EXISTS {ks}.{t} "
                     f"({cols}, PRIMARY KEY {pk})")
    return stmts


class CassandraStore:
    """Store over Apache Cassandra — the reference's production sink.

    Parity with ccdc/cassandra.py + resources/schema.cql:
    - same four (+product) tables; partition key = the first two key
      columns, remaining key columns clustering — the natural-key PKs that
      make rerun writes idempotent upserts (schema.cql:34,54,142;
      mode('append'), cassandra.py:62-63).
    - QUORUM consistency and bounded concurrent writes (cassandra.py:20-26,
      reference default 2 concurrent writes).
    - keyspace per inputs+version (ccdc/__init__.py:29-44 — Config.keyspace).

    Array-valued columns are JSON-encoded text (uniform with the sqlite
    backend) rather than frozen<list<...>>; the key design, not the cell
    encoding, carries the durability semantics.

    ``session`` is injectable (tests pass a fake; see tests/test_store.py).
    Without it, the DataStax ``cassandra-driver`` package is required and a
    clear error is raised when absent — the driver is not bundled.
    """

    _TYPES = {"INTEGER": "bigint", "REAL": "double", "TEXT": "text",
              "JSON": "text", "BITS": "blob", "F64S": "blob", "I32S": "blob"}

    def __init__(self, contact_points=("127.0.0.1",), port: int = 9042,
                 keyspace: str = "default", username: str = "",
                 password: str = "", concurrent_writes: int = 2,
                 replication: int = 1, session=None):
        self.keyspace = sanitize_keyspace(keyspace)
        self.concurrent_writes = max(int(concurrent_writes), 1)
        self._replication = int(replication)
        self._cluster = None
        if session is None:
            session = self._connect(contact_points, port, username, password)
        self.session = session
        self._prepared: dict[str, object] = {}
        self._ensure_schema()

    def _connect(self, contact_points, port, username, password):
        try:
            from cassandra.cluster import Cluster
        except ImportError as e:
            raise RuntimeError(
                "store backend 'cassandra' needs the cassandra-driver "
                "package (or pass an explicit session=); install it or use "
                "the sqlite/parquet backends") from e
        auth = None
        if username:
            from cassandra.auth import PlainTextAuthProvider
            auth = PlainTextAuthProvider(username=username, password=password)
        self._cluster = Cluster(list(contact_points), port=port,
                                auth_provider=auth)
        session = self._cluster.connect()
        from cassandra import ConsistencyLevel
        session.default_consistency_level = ConsistencyLevel.QUORUM
        return session

    def _ensure_schema(self):
        for stmt in cassandra_ddl(self.keyspace, self._replication):
            self.session.execute(stmt)

    def _prepare(self, table: str):
        if table not in self._prepared:
            cols = schema.columns(table)
            ph = ", ".join("?" * len(cols))
            self._prepared[table] = self.session.prepare(
                f"INSERT INTO {self.keyspace}.{table} "
                f"({', '.join(cols)}) VALUES ({ph})")
        return self._prepared[table]

    def write(self, table: str, frame: dict) -> int:
        types = _col_types(table)
        cols = list(types)
        stmt = self._prepare(table)
        n = len(next(iter(frame.values())))
        rows = zip(*(_encode_column(frame, c, types[c], n) for c in cols))
        # Bounded in-flight async writes (the reference's
        # spark.cassandra.output.concurrent.writes, ccdc/__init__.py:20).
        pending = []
        for row in rows:
            pending.append(self.session.execute_async(stmt, row))
            if len(pending) >= self.concurrent_writes:
                pending.pop(0).result()
        for f in pending:
            f.result()
        return n

    def read(self, table: str, where: dict | None = None) -> dict:
        types = _col_types(table)
        cols = list(types)
        cql = f"SELECT {', '.join(cols)} FROM {self.keyspace}.{table}"
        params: tuple = ()
        if where:
            cql += " WHERE " + " AND ".join(f"{k} = %s" for k in where)
            cql += " ALLOW FILTERING"
            params = tuple(_normalize(v) for v in where.values())
        out: dict[str, list] = {c: [] for c in cols}
        for row in self.session.execute(cql, params):
            for c, v in zip(cols, row):
                out[c].append(_decode_cell(v, types[c]))
        return out

    def count(self, table: str) -> int:
        rows = self.session.execute(
            f"SELECT COUNT(*) FROM {self.keyspace}.{table}", ())
        return int(next(iter(rows))[0])

    def chip_ids(self, table: str = "segment") -> set[tuple[int, int]]:
        # The first two key columns are exactly the partition key, so
        # DISTINCT reads only partition keys — a full-row scan here would
        # stream millions of segment rows just to dedupe chips (resume
        # path, driver/core.py).
        k1, k2 = schema.primary_key(table)[:2]
        rows = self.session.execute(
            f"SELECT DISTINCT {k1}, {k2} FROM {self.keyspace}.{table}", ())
        return {(r[0], r[1]) for r in rows}

    def close(self):
        if self._cluster is not None:
            self._cluster.shutdown()


def open_store(backend: str, path: str, keyspace: str,
               read_only: bool = False):
    """Factory used by the driver (cfg.store_backend).

    ``read_only=True`` opens a replica connection where the backend
    supports one (sqlite: ``mode=ro`` + ``PRAGMA query_only`` — the N
    serve replicas never touch the writer's lock); backends without a
    lock to contend on (memory, parquet, cassandra) reject it loudly
    rather than silently serving a writable handle as "read-only".

    For the 'cassandra' backend, connection settings come from the
    reference's env contract (ccdc/__init__.py:17-22): CASSANDRA
    (contact host[,host...]), CASSANDRA_PORT, CASSANDRA_USER,
    CASSANDRA_PASS, CASSANDRA_OUTPUT_CONCURRENT_WRITES — credentials stay
    in the environment, not in Config.
    """
    if read_only and backend not in ("sqlite", "object"):
        raise ValueError(
            f"read_only is a sqlite replica mode; backend {backend!r} "
            "has no writer lock for replicas to avoid")
    if backend == "object":
        # Object-native: shards, manifests, and fencing all live in the
        # object tier (FIREBIRD_OBJECT_ROOT); ``path`` only scopes the
        # key prefix so distinct logical stores share one root safely.
        from firebird_tpu.store import objectstore as objlib
        return objlib.ObjectBackedStore(
            objlib.open_object_root(), objlib.scope_for_path(path),
            keyspace, read_only=read_only)
    if backend == "sqlite":
        store = SqliteStore(path, keyspace, read_only=read_only)
        return _maybe_mirror(store, path, keyspace, read_only)
    if backend == "cassandra":
        hosts = os.environ.get("CASSANDRA", "127.0.0.1").split(",")
        return CassandraStore(
            contact_points=[h.strip() for h in hosts if h.strip()],
            port=int(os.environ.get("CASSANDRA_PORT", "9042")),
            keyspace=keyspace,
            username=os.environ.get("CASSANDRA_USER", ""),
            password=os.environ.get("CASSANDRA_PASS", ""),
            concurrent_writes=int(
                os.environ.get("CASSANDRA_OUTPUT_CONCURRENT_WRITES", "2")))
    if backend == "memory":
        return _maybe_mirror(MemoryStore(keyspace), path, keyspace, False)
    if backend == "parquet":
        return _maybe_mirror(ParquetStore(path, keyspace), path, keyspace,
                             False)
    raise ValueError(f"unknown store backend: {backend!r}")


def _maybe_mirror(store, path: str, keyspace: str, read_only: bool):
    """Wrap a local-file store in the object-tier write-through mirror
    when FIREBIRD_OBJECT_ROOT is set (store/objectstore.MirroredStore).

    Env-driven on purpose: every existing open_store call site — driver,
    fleet workers, CLI — inherits the mirror just by running with the
    knob exported, which is how `make fleet-smoke` reruns UNCHANGED
    against the object backend.  Local files stay read-authoritative;
    writes publish to the object tier FIRST so a zombie's stale-fence
    write is rejected at the object layer before any local byte lands.
    Replica (read-only) handles never write, so they skip the wrap.
    """
    from firebird_tpu.config import env_knob
    if read_only or not env_knob("FIREBIRD_OBJECT_ROOT"):
        return store
    from firebird_tpu.store import objectstore as objlib
    mirror = objlib.ObjectBackedStore(
        objlib.open_object_root(), objlib.scope_for_path(path), keyspace)
    return objlib.MirroredStore(store, mirror)
