"""Logical table schemas and key design.

Mirrors the reference's Cassandra schema (resources/schema.cql) and table
modules:

- chip    (cx, cy) -> dates[]                 (schema.cql:30-34, ccdc/chip.py)
- pixel   (cx, cy, px, py) -> mask[]          (schema.cql:48-54, ccdc/pixel.py)
- segment (cx, cy, px, py, sday, eday) -> 33 model columns + rfrawp
                                              (schema.cql:103-142, ccdc/segment.py)
- tile    (tx, ty, name) -> model, updated    (schema.cql:13-19, ccdc/tile.py)

Column types: INTEGER/REAL/TEXT scalars; JSON for irregular values (ISO
date lists); and packed-array types for the hot egress columns — BITS
(uint8, the per-pixel processing mask), F64S (float64 vectors: model
coefficients, rfrawp), I32S (int32 rasters: product cells).  Packed
columns are raw little-endian bytes in sqlite/cassandra (the egress path
is host-bound: JSON-encoding a 10k-pixel chip's masks alone costs
seconds per chip) and plain lists in parquet/memory; every backend's
read() returns plain lists either way.
"""

from __future__ import annotations

import numpy as np

from firebird_tpu.ccd.format import BAND_PREFIX

# numpy dtypes of the packed-array column types (little-endian on the wire)
PACKED_DTYPES = {"BITS": np.uint8, "F64S": "<f8", "I32S": "<i4"}

_SEG_BANDS: list[tuple[str, str]] = []
for _p in BAND_PREFIX:
    _SEG_BANDS += [(f"{_p}mag", "REAL"), (f"{_p}rmse", "REAL"),
                   (f"{_p}coef", "F64S"), (f"{_p}int", "REAL")]

TABLES: dict[str, dict] = {
    "chip": {
        "columns": [("cx", "INTEGER"), ("cy", "INTEGER"), ("dates", "JSON")],
        "key": ("cx", "cy"),
    },
    "pixel": {
        "columns": [("cx", "INTEGER"), ("cy", "INTEGER"), ("px", "INTEGER"),
                    ("py", "INTEGER"), ("mask", "BITS")],
        "key": ("cx", "cy", "px", "py"),
    },
    "segment": {
        "columns": ([("cx", "INTEGER"), ("cy", "INTEGER"), ("px", "INTEGER"),
                     ("py", "INTEGER"), ("sday", "TEXT"), ("eday", "TEXT"),
                     ("bday", "TEXT"), ("chprob", "REAL"),
                     ("curqa", "INTEGER")]
                    + _SEG_BANDS + [("rfrawp", "F64S")]),
        "key": ("cx", "cy", "px", "py", "sday", "eday"),
    },
    "tile": {
        "columns": [("tx", "INTEGER"), ("ty", "INTEGER"), ("name", "TEXT"),
                    ("model", "TEXT"), ("updated", "TEXT")],
        "key": ("tx", "ty", "name"),
    },
    # Derived product rasters (the reference 0.5 `ccdc-save` capability,
    # docs/faq.rst:38-109; dropped by 1.0 — completed here, SURVEY.md §2.5).
    # One row per (product, date, chip): row-major [100x100] cell values.
    "product": {
        "columns": [("name", "TEXT"), ("date", "TEXT"), ("cx", "INTEGER"),
                    ("cy", "INTEGER"), ("cells", "I32S")],
        "key": ("name", "date", "cx", "cy"),
    },
}


def primary_key(table: str) -> tuple[str, ...]:
    return TABLES[table]["key"]


def columns(table: str) -> list[str]:
    return [c for c, _ in TABLES[table]["columns"]]
