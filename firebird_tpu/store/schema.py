"""Logical table schemas and key design.

Mirrors the reference's Cassandra schema (resources/schema.cql) and table
modules:

- chip    (cx, cy) -> dates[]                 (schema.cql:30-34, ccdc/chip.py)
- pixel   (cx, cy, px, py) -> mask[]          (schema.cql:48-54, ccdc/pixel.py)
- segment (cx, cy, px, py, sday, eday) -> 33 model columns + rfrawp
                                              (schema.cql:103-142, ccdc/segment.py)
- tile    (tx, ty, name) -> model, updated    (schema.cql:13-19, ccdc/tile.py)

Array-valued columns (dates, mask, coefficients, rfrawp) are JSON-encoded in
sqlite and native lists in parquet/memory.
"""

from __future__ import annotations

from firebird_tpu.ccd.format import BAND_PREFIX

_SEG_BANDS: list[tuple[str, str]] = []
for _p in BAND_PREFIX:
    _SEG_BANDS += [(f"{_p}mag", "REAL"), (f"{_p}rmse", "REAL"),
                   (f"{_p}coef", "JSON"), (f"{_p}int", "REAL")]

TABLES: dict[str, dict] = {
    "chip": {
        "columns": [("cx", "INTEGER"), ("cy", "INTEGER"), ("dates", "JSON")],
        "key": ("cx", "cy"),
    },
    "pixel": {
        "columns": [("cx", "INTEGER"), ("cy", "INTEGER"), ("px", "INTEGER"),
                    ("py", "INTEGER"), ("mask", "JSON")],
        "key": ("cx", "cy", "px", "py"),
    },
    "segment": {
        "columns": ([("cx", "INTEGER"), ("cy", "INTEGER"), ("px", "INTEGER"),
                     ("py", "INTEGER"), ("sday", "TEXT"), ("eday", "TEXT"),
                     ("bday", "TEXT"), ("chprob", "REAL"),
                     ("curqa", "INTEGER")]
                    + _SEG_BANDS + [("rfrawp", "JSON")]),
        "key": ("cx", "cy", "px", "py", "sday", "eday"),
    },
    "tile": {
        "columns": [("tx", "INTEGER"), ("ty", "INTEGER"), ("name", "TEXT"),
                    ("model", "TEXT"), ("updated", "TEXT")],
        "key": ("tx", "ty", "name"),
    },
    # Derived product rasters (the reference 0.5 `ccdc-save` capability,
    # docs/faq.rst:38-109; dropped by 1.0 — completed here, SURVEY.md §2.5).
    # One row per (product, date, chip): row-major [100x100] cell values.
    "product": {
        "columns": [("name", "TEXT"), ("date", "TEXT"), ("cx", "INTEGER"),
                    ("cy", "INTEGER"), ("cells", "JSON")],
        "key": ("name", "date", "cx", "cy"),
    },
}


def primary_key(table: str) -> tuple[str, ...]:
    return TABLES[table]["key"]


def columns(table: str) -> list[str]:
    return [c for c, _ in TABLES[table]["columns"]]
